"""Histogram construction — the hottest op in GBDT training.

The reference builds per-leaf feature histograms with cache-tuned scatter-adds
(``src/io/dense_bin.hpp:66-132``) or an OpenCL local-memory atomic kernel
(``src/treelearner/ocl/histogram256.cl``).  TPUs have no fast random scatter,
so the native formulations here are:

* ``child_histograms_onehot`` — one-hot × weights matmul on the MXU,
  row-chunked so the one-hot tensor never materialises in HBM.  This is the
  default TPU path (and the shape the Pallas kernel mirrors).
* ``child_histograms_segsum`` — ``jax.ops.segment_sum`` per feature.  Scatter
  based; used as the debugging / parity oracle (the reference's
  GPU_DEBUG_COMPARE discipline, ``gpu_tree_learner.cpp:1018-1043``).

Both compute histograms for the *two children of a split in one pass*: rows
carry a segment id (0 = left child, 1 = right child, >=2 = other leaves), so a
single sweep yields both children — which replaces the reference's
"smaller-child + parent-subtraction" trick without giving up any work: a
masked TPU sweep touches every row regardless of how many segments it bins.

Each histogram entry is ``(sum_gradients, sum_hessians, count)`` exactly like
the reference ``HistogramBinEntry`` (``include/LightGBM/bin.h:27-56``).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NUM_CHILDREN = 2  # left/right of the split being evaluated
NUM_STATS = 3     # (sum_grad, sum_hess, count)


def child_histograms_segsum(bins: jnp.ndarray, seg: jnp.ndarray,
                            grad: jnp.ndarray, hess: jnp.ndarray,
                            cnt: jnp.ndarray, num_bins: int) -> jnp.ndarray:
    """Scatter-add path. bins: [N, F] int; seg: [N] int in {0,1,2}.

    Returns [2, F, B, 3] with B = ``num_bins``.
    """
    bins = bins.astype(jnp.int32)
    n, f = bins.shape
    b = num_bins
    # combined id per (row, feature): seg * B + bin ; segment 2 is a trash slot
    ids = seg[:, None] * b + bins                      # [N, F]
    data = jnp.stack([grad, hess, cnt], axis=-1)       # [N, 3]

    def per_feature(ids_f):
        return jax.ops.segment_sum(data, ids_f, num_segments=3 * b)  # [3B, 3]

    hist = jax.vmap(per_feature, in_axes=1)(ids)       # [F, 3B, 3]
    hist = hist.reshape(f, 3, b, NUM_STATS)
    return jnp.moveaxis(hist, 1, 0)[:NUM_CHILDREN]     # [2, F, B, 3]


def child_histograms_onehot(bins: jnp.ndarray, seg: jnp.ndarray,
                            grad: jnp.ndarray, hess: jnp.ndarray,
                            cnt: jnp.ndarray, num_bins: int,
                            rows_per_chunk: int = 16384) -> jnp.ndarray:
    """MXU path: per row-chunk, build a one-hot of the bin index in registers/
    VMEM and contract it against the 6 per-row weight channels
    (g,h,c for each child).  [N, F] x chunking keeps peak memory at
    ``chunk * F * B`` for the fused one-hot, which XLA materialises only
    tile-by-tile inside the fused matmul loop.
    """
    bins = bins.astype(jnp.int32)
    n, f = bins.shape
    b = num_bins
    left = (seg == 0)
    right = (seg == 1)
    w = jnp.stack([
        jnp.where(left, grad, 0.0), jnp.where(left, hess, 0.0),
        jnp.where(left, cnt, 0.0),
        jnp.where(right, grad, 0.0), jnp.where(right, hess, 0.0),
        jnp.where(right, cnt, 0.0),
    ], axis=-1)                                        # [N, 6]

    chunk = min(rows_per_chunk, n)
    pad = (-n) % chunk
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        w = jnp.pad(w, ((0, pad), (0, 0)))
    n_chunks = (n + pad) // chunk
    bins_c = bins.reshape(n_chunks, chunk, f)
    w_c = w.reshape(n_chunks, chunk, 2 * NUM_STATS)

    def body(acc, args):
        bc, wc = args                                   # [chunk, F], [chunk, 6]
        onehot = (bc[:, :, None] == lax.broadcasted_iota(jnp.int32, (1, 1, b), 2))
        onehot = onehot.astype(wc.dtype)                # [chunk, F, B]
        part = jnp.einsum("cfb,ck->fbk", onehot, wc,
                          precision=lax.Precision.HIGHEST)  # [F, B, 6]
        return acc + part, None

    acc0 = jnp.zeros((f, b, 2 * NUM_STATS), dtype=w.dtype)
    acc, _ = lax.scan(body, acc0, (bins_c, w_c))
    return jnp.moveaxis(acc.reshape(f, b, NUM_CHILDREN, NUM_STATS), 2, 0)


def child_histograms(bins: jnp.ndarray, seg: jnp.ndarray,
                     grad: jnp.ndarray, hess: jnp.ndarray,
                     cnt: jnp.ndarray, num_bins: int,
                     method: str = "auto",
                     rows_per_chunk: int = 16384) -> jnp.ndarray:
    """Dispatch histogram construction by method: auto|onehot|segsum|pallas."""
    if method == "auto":
        method = "onehot" if any(d.platform == "tpu" for d in jax.devices()) else "segsum"
    if method == "segsum":
        return child_histograms_segsum(bins, seg, grad, hess, cnt, num_bins)
    if method == "onehot":
        return child_histograms_onehot(bins, seg, grad, hess, cnt, num_bins,
                                       rows_per_chunk)
    if method == "pallas":
        try:
            from .pallas_hist import child_histograms_pallas
        except ImportError:
            return child_histograms_onehot(bins, seg, grad, hess, cnt, num_bins,
                                           rows_per_chunk)
        return child_histograms_pallas(bins, seg, grad, hess, cnt, num_bins)
    raise ValueError(f"unknown histogram method {method}")
