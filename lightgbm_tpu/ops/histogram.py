"""Histogram construction — the hottest op in GBDT training.

The reference builds per-leaf feature histograms with cache-tuned scatter-adds
(``src/io/dense_bin.hpp:66-132``) or an OpenCL local-memory atomic kernel
(``src/treelearner/ocl/histogram256.cl``).  TPUs have no fast random scatter,
so the native formulation is a one-hot × weights contraction on the MXU over a
*gathered row subset* — the grower gathers only the smaller child of each
split through its leaf-contiguous ``order`` array (the reference's
smaller-child trick, ``serial_tree_learner.cpp:326-404``), so the work per
split is proportional to the smaller child, not to the dataset:

* ``subset_histogram_fused`` (-> ``pallas_hist.hist6_fused``) — THE Pallas
  rung: the row gather happens INSIDE the kernel (per-tile DMA of indexed
  panel rows into VMEM) and the contraction is nibble-factorized, so
  neither the gathered [M, F] matrix nor the one-hot ever exists in HBM.
  Takes the leaf's ``order`` window + offset, not gathered rows.
  ``subset_histogram_fused_local`` is the same rung entered from inside
  the GSPMD shard_map island (per-shard row -> leaf partition instead of
  an order window).
* ``subset_histogram_segment`` — one ``segment_sum`` scatter-add over the
  combined (feature, bin) index; the default CPU path (fallback rungs,
  test mesh), where scatter lowers well.  ``subset_histogram_flat`` is
  its unchunked GSPMD sibling.
* ``subset_histogram_einsum`` — chunked f32 one-hot einsum; the
  MXU-shaped debug/parity oracle (``use_pallas=false`` on TPU).

The ladder is fused vs the XLA reference paths — the gen-1 pre-gathered
Pallas kernels (onehot/nibble over a staged [M, F] buffer) were retired in
round 9 when they stopped Mosaic-lowering and the fused kernel subsumed
their role (see pallas_hist.py).

Each histogram entry is ``(sum_gradients, sum_hessians, count)`` exactly like
the reference ``HistogramBinEntry`` (``include/LightGBM/bin.h:27-56``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..obs.counters import counters as obs_counters
from ..utils import faults as faults_mod

NUM_STATS = 3     # (sum_grad, sum_hess, count)


def _maybe_inject_hist_fault(method: str, site: str) -> None:
    """Armed ``hist_fail`` injection point: dispatch (host/trace time)
    raises deterministically so the error-surface of the hottest op is
    testable on CPU (utils/faults.py)."""
    fi = faults_mod.get_faults()
    if fi.enabled and fi.fire("hist_fail"):
        raise faults_mod.InjectedFault(
            f"hist_fail: injected histogram dispatch failure "
            f"(method={method}, site={site})")


def on_tpu() -> bool:
    """Whether the default jax backend is a TPU (shared platform probe —
    hist-method and gather-words 'auto' resolution must agree)."""
    return any(d.platform == "tpu" for d in jax.devices())


def _split_hi_lo(x: jnp.ndarray):
    """Split f32 into a (bf16 hi, bf16 lo) pair so a single-pass bf16 MXU
    matmul accumulates with ~f32 accuracy (hi + lo recombined after the dot).
    The one-hot operand is exact in bf16, so only the weights need splitting."""
    hi = x.astype(jnp.bfloat16)
    lo = (x - hi.astype(x.dtype)).astype(jnp.bfloat16)
    return hi, lo


def subset_histogram_einsum(rows: jnp.ndarray, g: jnp.ndarray, h: jnp.ndarray,
                            c: jnp.ndarray, num_bins: int,
                            rows_per_chunk: int = 8192) -> jnp.ndarray:
    """Histogram of a gathered row subset: rows [M, F] int, g/h/c [M] f32
    (weights must be 0 for padding rows) -> [F, B, 3].

    f32 one-hot x weights einsum, chunked over rows so the one-hot tensor
    stays small.  This is the CPU / debugging path; the TPU path is the
    fused Pallas kernel (``pallas_hist.hist6_fused``)."""
    rows = rows.astype(jnp.int32)
    m, f = rows.shape
    b = num_bins
    w = jnp.stack([g, h, c], axis=-1)                   # [M, 3]
    chunk = min(rows_per_chunk, m)
    pad = (-m) % chunk
    if pad:
        rows = jnp.pad(rows, ((0, pad), (0, 0)))
        w = jnp.pad(w, ((0, pad), (0, 0)))
    n_chunks = (m + pad) // chunk
    rows_c = rows.reshape(n_chunks, chunk, f)
    w_c = w.reshape(n_chunks, chunk, NUM_STATS)

    def body(acc, args):
        rc, wc = args
        onehot = (rc[:, :, None] == lax.broadcasted_iota(jnp.int32, (1, 1, b), 2))
        part = jnp.einsum("mfb,mk->fbk", onehot.astype(wc.dtype), wc,
                          precision=lax.Precision.HIGHEST)
        return acc + part, None

    acc0 = jnp.zeros((f, b, NUM_STATS), dtype=w.dtype)
    acc, _ = lax.scan(body, acc0, (rows_c, w_c))
    return acc


def subset_histogram_segment(rows: jnp.ndarray, g: jnp.ndarray,
                             h: jnp.ndarray, c: jnp.ndarray,
                             num_bins: int,
                             rows_per_chunk: int = 2048) -> jnp.ndarray:
    """Histogram via scatter-add (``segment_sum``) over the combined
    (feature, bin) index — O(M·F) adds instead of the einsum's O(M·F·B)
    MACs.  This IS the reference's dense_bin.hpp:66-132 accumulation in
    XLA form; scatter lowers well on CPU (where the fallback rungs run)
    but poorly on TPU, which is exactly why the TPU path is the MXU
    one-hot contraction instead.  Chunked over rows (like the einsum
    path) so the transient [chunk·F, 3] update buffer stays cache-sized:
    measured on the 1-core bench host at 256k x 28 x 255, 2048 rows/chunk
    runs 1.6x faster than 16384 (95 vs 152 ns/row — the [chunk*F, 3]
    scatter source fits L2 next to the 85 KB accumulator; 4096 already
    regresses)."""
    rows = rows.astype(jnp.int32)
    m, f = rows.shape
    w = jnp.stack([g, h, c], axis=-1)                    # [M, 3]
    chunk = min(rows_per_chunk, m)
    pad = (-m) % chunk
    if pad:
        # padding rows: weight 0 into bin 0 — contributes nothing
        rows = jnp.pad(rows, ((0, pad), (0, 0)))
        w = jnp.pad(w, ((0, pad), (0, 0)))
    n_chunks = (m + pad) // chunk
    offsets = jnp.arange(f, dtype=jnp.int32)[None, :] * num_bins
    rows_c = rows.reshape(n_chunks, chunk, f)
    w_c = w.reshape(n_chunks, chunk, NUM_STATS)

    def body(acc, args):
        rc, wc = args
        idx = (rc + offsets).reshape(-1)
        vals = jnp.broadcast_to(wc[:, None, :], (chunk, f, NUM_STATS))
        part = jax.ops.segment_sum(vals.reshape(-1, NUM_STATS), idx,
                                   num_segments=f * num_bins)
        return acc + part, None

    acc0 = jnp.zeros((f * num_bins, NUM_STATS), dtype=w.dtype)
    if n_chunks == 1:
        # single-chunk windows (every sub-2048-row bucket of the deep-tree
        # tail): the scan machinery is pure overhead — unroll it.  The
        # ``acc0 +`` is kept so the float results stay bit-identical to
        # the scanned form (dropping it would turn a -0.0 bin sum into
        # the raw part's -0.0 vs the scan's 0.0 + -0.0 == 0.0).
        hist, _ = body(acc0, (rows_c[0], w_c[0]))
    else:
        hist, _ = lax.scan(body, acc0, (rows_c, w_c))
    return hist.reshape(f, num_bins, NUM_STATS)


def subset_histogram_flat(rows: jnp.ndarray, g: jnp.ndarray, h: jnp.ndarray,
                          c: jnp.ndarray, num_bins: int,
                          site: str = "split") -> jnp.ndarray:
    """UNCHUNKED scatter-add histogram — the GSPMD formulation
    (``parallel/gspmd.py``; docs/DISTRIBUTED.md).

    Same math as :func:`subset_histogram_segment` minus the row-chunking
    scan: under ``NamedSharding`` the scan's carried accumulator makes
    the XLA SPMD partitioner ALL-GATHER the row shards (measured: a
    ``s32[4,2048,8]`` all-gather at 8k x 8), while the flat single
    ``segment_sum`` partitions cleanly — each device scatters its own
    row shard into the (feature-sharded) output slice and the compiler
    inserts one shard-sized reduction.  The [M·F, 3] transient this
    re-widens is per DEVICE (M = rows/shard), which is exactly the
    regime the GSPMD path runs in."""
    obs_counters.inc("hist_dispatch", method="segment", site=site,
                     interpret=False)
    _maybe_inject_hist_fault("segment", site)
    rows = rows.astype(jnp.int32)
    m, f = rows.shape
    w = jnp.stack([g, h, c], axis=-1)                    # [M, 3]
    idx = (rows + jnp.arange(f, dtype=jnp.int32)[None, :] * num_bins)
    vals = jnp.broadcast_to(w[:, None, :], (m, f, NUM_STATS))
    hist = jax.ops.segment_sum(vals.reshape(-1, NUM_STATS),
                               idx.reshape(-1),
                               num_segments=f * num_bins)
    return hist.reshape(f, num_bins, NUM_STATS)


def subset_histogram_fused(order: jnp.ndarray, panel: jnp.ndarray,
                           start, cnt, n_cols: int, words_per: int,
                           num_bins: int, row_tile: int = 512,
                           num_row_tiles=None,
                           interpret: bool = False,
                           site: str = "split") -> jnp.ndarray:
    """Fused rung: histogram a leaf's ``order`` window WITHOUT a separate
    gather pass — the kernel DMAs the indexed panel rows itself.

    order [NO] i32 (window at [start, start + cnt); see hist6_fused for
    the tail-padding contract), panel [N + 1, W + 3] u32
    (data/packing.py:pack_fused_panel) -> [n_cols, num_bins, 3] f32 with
    the reference (sum_grad, sum_hess, count) layout; gradients/hessians
    carry the bf16 hi/lo accuracy contract (counts exact)."""
    from .pallas_hist import hist6_fused
    # dispatch-identity evidence (trace-time, per call site): bench rungs
    # and decide_flips verify the label against this counter
    obs_counters.inc("hist_dispatch", method="fused", site=site,
                     interpret=bool(interpret))
    _maybe_inject_hist_fault("fused", site)
    h6 = hist6_fused(order, panel, start, cnt, n_cols, words_per, num_bins,
                     row_tile=row_tile, num_row_tiles=num_row_tiles,
                     interpret=interpret)
    return jnp.stack([h6[0] + h6[1], h6[2] + h6[3], h6[4]], axis=-1)


def subset_histogram_fused_local(row_leaf: jnp.ndarray, leaf_id,
                                 panel: jnp.ndarray, n_cols: int,
                                 words_per: int, num_bins: int,
                                 row_tile: int = 512,
                                 interpret: bool = False,
                                 site: str = "split") -> jnp.ndarray:
    """Fused rung, shard-local form for the GSPMD hybrid: the same kernel
    as :func:`subset_histogram_fused`, but entered from INSIDE a shard_map
    island where the leaf's membership lives as the row -> leaf partition
    (``row_leaf``) instead of a maintained order window.

    Returns the [n_cols, num_bins, 3] PARTIAL histogram over this shard's
    rows matching ``leaf_id``; the caller (parallel/gspmd.py) hands the
    cross-shard reduction to the SPMD partitioner."""
    from .pallas_hist import hist6_fused_local
    # dispatch-identity evidence: under shard_map this traces once for the
    # whole mesh, same as any other trace-time counter — observed_kernel()
    # and the census must still attribute the hybrid to the fused kernel
    obs_counters.inc("hist_dispatch", method="fused", site=site,
                     interpret=bool(interpret))
    _maybe_inject_hist_fault("fused", site)
    h6 = hist6_fused_local(row_leaf, leaf_id, panel, n_cols, words_per,
                           num_bins, row_tile=row_tile, interpret=interpret)
    return jnp.stack([h6[0] + h6[1], h6[2] + h6[3], h6[4]], axis=-1)


def subset_histogram(rows: jnp.ndarray, g: jnp.ndarray, h: jnp.ndarray,
                     c: jnp.ndarray, num_bins: int,
                     method: str = "auto",
                     site: str = "split") -> jnp.ndarray:
    """Dispatch a PRE-GATHERED subset histogram: rows [M, F] int, g/h/c [M]
    -> [F, B, 3].

    Only the XLA reference formulations live here (segment | einsum |
    auto): the fused Pallas rung takes an order window or a row -> leaf
    partition, not gathered rows, so it enters through
    :func:`subset_histogram_fused` / :func:`subset_histogram_fused_local`
    — by the time rows are gathered there is nothing left to fuse."""
    if method == "auto":
        method = "segment"
    # the RESOLVED method, per call site — trace-time counts that the
    # rung-honesty checks (bench.py / decide_flips.py) read back
    obs_counters.inc("hist_dispatch", method=method, site=site,
                     interpret=False)
    _maybe_inject_hist_fault(method, site)
    if method == "einsum":
        return subset_histogram_einsum(rows, g, h, c, num_bins)
    if method == "segment":
        return subset_histogram_segment(rows, g, h, c, num_bins)
    raise ValueError(f"unknown histogram method {method!r}")
