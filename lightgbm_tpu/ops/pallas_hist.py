"""Pallas TPU histogram kernel: the fused-gather, nibble-factorized form.

The TPU answer to the reference's OpenCL histogram kernels
(``src/treelearner/ocl/histogram256.cl`` — per-workgroup local-memory
histograms with hand-rolled atomic float adds).  TPUs have no fast random
scatter, so the native formulation is a one-hot x weights contraction on
the MXU — and this module holds the one kernel that survived two
generations of that idea: ``hist6_fused``, which DMAs the leaf's indexed
panel rows into VMEM itself (no separate gather pass, no staging buffer)
and contracts through the hi/lo nibble factorization.

The gen-1 kernels (a combined-index one-hot dot and a standalone nibble
form, both over PRE-GATHERED ``[M, F]`` rows) lived here until round 9.
They stopped Mosaic-lowering on the current jax/libtpu (the quarantine
that used to sit in tests/test_mosaic_aot.py), the fused kernel subsumed
both their roles, and they were deleted — the dispatch ladder is now
fused vs the XLA reference paths (ops/histogram.py).  Their hard-won
Mosaic lessons survive as the fused kernel's design notes below.

``hist6_fused_local`` is the shard-local entry for the GSPMD hybrid
(parallel/gspmd.py): inside a ``shard_map`` island it derives the leaf's
LOCAL order window from the row->leaf partition and runs the same kernel
over the device's row shard — one kernel from laptop CPU (interpret mode)
to pod slice.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_compat import CompilerParams, MemorySpace

NUM_CH = 6   # weight channels: (g_hi, g_lo, h_hi, h_lo, c, unused)
LANES = 128  # TPU vector register lane width — bin axis is padded to this
NIB = 16     # nibble radix: bin = hi*16 + lo, each one-hot 16 wide


# ---------------------------------------------------------------------------
# The fused-gather, nibble-factorized histogram kernel.
#
# The retired gen-1 path paid two separately-measured costs per split
# (docs/PERF.md cost model): a random row gather through XLA (~12.6 ns/elem,
# staged into a pow2-padded [M, F] HBM buffer) and a one-hot MXU contraction
# whose 6-channel M dim padded to 128 (~21x slot waste).  This kernel is the
# same move the reference made when it fused gather+accumulate into one
# OpenCL pass (src/treelearner/ocl/histogram256.cl): the row gather happens
# INSIDE the kernel — per-tile, the window of the leaf's ``order`` indices is
# DMAd into SMEM and each indexed panel row is DMAd from HBM straight into
# VMEM, so the gathered [M, F] matrix never exists in HBM and the separate
# gather dispatch disappears — and the contraction is the nibble-factorized
# form (bin = hi*16 + lo, M = ch x hi = 96 rows, 16-wide lo one-hot) that
# cuts the MXU slot cost ~2x at B_pad = 256.  PERF.md projects the stack at
# ~8.5 ns/row vs the measured 22 + 12.6.
#
# Three structural points:
#
# * the input is the FUSED PANEL (data/packing.py:pack_fused_panel): packed
#   bin words + the three bitcast f32 weight columns in one u32 row, so the
#   per-row DMA is a single contiguous burst and the hi/lo bf16 weight
#   split happens on-chip, per tile;
# * the grid is 1-D over row tiles and may be DYNAMIC (a traced tile
#   count): the grower passes ceil(cnt / row_tile), so a small leaf costs
#   a small grid — this is what retires the gather-bucket ``lax.switch``
#   (no static pow2 staging buffer means no static bucket sizes);
# * rows at positions >= cnt are redirected to the panel's sentinel row
#   (all-zero words AND zero weights), so tile padding needs no masking
#   anywhere downstream.
#
# Mosaic surfaces kept deliberately boring (round-2/round-5 lessons): the
# output block is written in static 128-lane groups (8 features x 16 lo
# bins) via full-width concatenated stores — never a sub-lane-width partial
# store — and every reshape happens outside the kernel in XLA.
# ---------------------------------------------------------------------------

FUSED_GROUP = 8        # features per 128-lane output group (8 * NIB = 128)
FUSED_MAX_COLS = 512   # feature-loop unroll + VMEM output-block ceiling
IDX_ALIGN = 1024       # i32 1-D tile: dynamic slices of ``order`` must sit
#                        on this boundary AND have a multiple-of-it length
#                        (Mosaic "tile index divisible by tiling" / "slice
#                        shape aligned to tile boundaries", both proven by
#                        the v5e AOT probe), so the kernel over-fetches the
#                        enclosing aligned region


def fused_idx_fetch(row_tile: int) -> int:
    """Elements of ``order`` the kernel fetches per tile: the smallest
    IDX_ALIGN multiple covering a row_tile window at any residual offset
    (< IDX_ALIGN) inside an aligned region."""
    return -(-(row_tile + IDX_ALIGN - 1) // IDX_ALIGN) * IDX_ALIGN


def _hist_kernel_fused(sc_ref, order_ref, panel_ref, out_ref,
                       idx_smem, rows_vmem, idx_sem, row_sem, *,
                       sentinel: int, n_words: int, words_per: int,
                       n_cols_pad: int, row_tile: int):
    ri = pl.program_id(0)

    @pl.when(ri == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    start = sc_ref[0]
    cnt = sc_ref[1]
    # the tile's slice of the leaf's ``order`` window, HBM -> SMEM: these
    # are the row ids the per-row DMAs below need as scalars.  The window
    # position is arbitrary but the source slice must be IDX_ALIGN-aligned,
    # so fetch the enclosing aligned region and read at the residual
    # offset — 3x the index bytes, which is noise next to the panel rows.
    pos = start + ri * row_tile
    aligned = pl.multiple_of((pos // IDX_ALIGN) * IDX_ALIGN, IDX_ALIGN)
    off = pos - aligned
    idx_copy = pltpu.make_async_copy(
        order_ref.at[pl.ds(aligned, fused_idx_fetch(row_tile))], idx_smem,
        idx_sem)
    idx_copy.start()
    idx_copy.wait()

    base = ri * row_tile

    def _row_copy(i):
        # positions past the leaf's count read the sentinel row (zero
        # words, zero weights) — same contract as the gen-1 sentinel pad.
        # pl.ds(r, 1) keeps the slice 2-D: integer .at[r] indexing squeezes
        # the row dim and that squeeze is what the LLO lowering choked on
        # ("dynamic_dim_it != dynamic_sizes.end()", v5e AOT probe) — the
        # compact kernel's proven dynamic-offset DMAs are all pl.ds-shaped
        r = jnp.where(base + i < cnt, idx_smem[off + i], sentinel)
        return pltpu.make_async_copy(panel_ref.at[pl.ds(r, 1), :],
                                     rows_vmem.at[pl.ds(i, 1), :],
                                     row_sem)

    # start every row DMA, then drain: the copies are independent and tiny
    # (W+3 u32 words each), so queueing them all before the first wait is
    # what lets the DMA engines overlap them
    def _start(i, _):
        _row_copy(i).start()
        return 0
    lax.fori_loop(0, row_tile, _start, 0)

    def _wait(i, _):
        _row_copy(i).wait()
        return 0
    lax.fori_loop(0, row_tile, _wait, 0)

    # word rows on the sublane axis (same orientation trick as the gen-1
    # kernels' [F, N] layout): static sublane indexing below, no dynamic
    # lane slicing for Mosaic to reject.  The untransposed form stays live
    # too: the lo one-hot needs COLUMN-shaped bins, and Mosaic rejects the
    # [TR] -> [TR, 1] shape cast from a sublane-layout vector (v5e AOT
    # probe) — a static [TR, 1] lane slice of the row-major value is
    # column-shaped from birth.
    rows2d = rows_vmem[...]                          # [TR, n_words + 3] u32
    words_t = rows2d.T                               # [n_words + 3, TR] u32
    shift = 32 // words_per
    wmask = jnp.uint32((1 << shift) - 1)

    # on-chip hi/lo weight split (the _split_hi_lo contract): channels
    # (g_hi, g_lo, h_hi, h_lo, c, 0), the retired gen-1 kernels' layout.
    # NO bf16 values exist below full-tile width: Mosaic rejected both the
    # gen-1 nibble form's [6, 1, TR] broadcast-multiply (vector.shape_cast)
    # and a [1, TR] bf16 sublane broadcast (vector.broadcast) — bf16's
    # packed (16, 128) tiling makes narrow bf16 vectors a hostile surface
    # (both caught by the v5e AOT probe).  So the hi half is computed IN
    # f32 via integer round-to-nearest-even on the raw bits (bit-identical
    # to an f32->bf16->f32 round-trip), everything stays f32 through the
    # broadcasts, and the one cast to bf16 happens on the full [96, TR]
    # tile right before the MXU.
    def _bf16_round_f32(wf):
        """f32 value of bf16(wf), without materializing a bf16 vector."""
        u = lax.bitcast_convert_type(wf, jnp.uint32)
        r = (u + jnp.uint32(0x7fff) + ((u >> 16) & jnp.uint32(1))) \
            & jnp.uint32(0xffff0000)
        return lax.bitcast_convert_type(r, jnp.float32)

    chans32 = []
    for k in range(2):
        wf = lax.bitcast_convert_type(words_t[n_words + k], jnp.float32)
        w_hi = _bf16_round_f32(wf)
        chans32 += [w_hi, wf - w_hi]
    chans32.append(lax.bitcast_convert_type(words_t[n_words + 2],
                                            jnp.float32))
    chans32.append(jnp.zeros_like(chans32[-1]))

    tr = row_tile
    # U's weight factor, feature-independent, built once per row tile —
    # strictly 2-D f32: each channel row broadcast to its 16-row band
    w_rep = jnp.concatenate(
        [jnp.broadcast_to(ch[None, :], (NIB, tr)) for ch in chans32],
        axis=0)                                      # [96, TR] f32
    for g0 in range(0, n_cols_pad, FUSED_GROUP):
        blocks = []
        for k in range(FUSED_GROUP):
            c = g0 + k
            w_i = c // words_per
            sh = (c % words_per) * shift
            binc = ((words_t[w_i] >> sh) & wmask).astype(jnp.int32)
            hi = binc >> 4                           # [TR], < 16
            oh_hi = (hi[None, :] ==
                     lax.broadcasted_iota(jnp.int32, (NIB, tr), 0)
                     ).astype(jnp.float32)           # [16, TR]
            # masked weights in f32, ONE full-tile bf16 cast before the
            # dot (oh is 0/1, so bf16(w * oh) == bf16(w) * oh exactly)
            u = (w_rep * jnp.concatenate([oh_hi] * NUM_CH, axis=0)
                 ).astype(jnp.bfloat16)              # [96, TR]
            lo_col = ((rows2d[:, w_i:w_i + 1] >> sh)
                      & wmask).astype(jnp.int32) & 15  # [TR, 1]
            oh_lo = (lo_col ==
                     lax.broadcasted_iota(jnp.int32, (tr, NIB), 1)
                     ).astype(jnp.bfloat16)          # [TR, 16]
            blocks.append(jnp.dot(u, oh_lo,
                                  preferred_element_type=jnp.float32))
        # one concatenated 128-lane-aligned store per feature group — the
        # masked sub-lane partial stores Mosaic has mislowered never happen
        out_ref[:, g0 * NIB:(g0 + FUSED_GROUP) * NIB] += jnp.concatenate(
            blocks, axis=1)                          # [96, 128]


def hist6_fused(order: jnp.ndarray, panel: jnp.ndarray, start, cnt,
                n_cols: int, words_per: int, num_bins: int,
                row_tile: int = 512, num_row_tiles=None,
                interpret: bool = False) -> jnp.ndarray:
    """Fused-gather nibble histogram: order [NO] i32 row ids (the leaf's
    window lives at [start, start + cnt)), panel [N + 1, n_words + 3] u32
    (pack_fused_panel layout, last row = sentinel) -> [6, n_cols, num_bins]
    f32.

    ``num_row_tiles`` is the grid length: a python int for a static grid,
    or a traced i32 scalar >= 1 (must equal ceil(max(cnt, 1) / row_tile))
    for the grower's dynamic-grid form.  ``start``/``cnt`` may be traced
    scalars either way.  The caller guarantees NO >= max(start + cnt)
    rounded down to IDX_ALIGN, plus fused_idx_fetch(row_tile): the aligned
    over-fetch may read that far past the window (the grower pads
    ``order`` with sentinel tail accordingly).
    """
    assert 1 < num_bins <= NIB * NIB, num_bins
    assert n_cols <= FUSED_MAX_COLS, (n_cols, FUSED_MAX_COLS)
    assert order.shape[0] >= fused_idx_fetch(row_tile), order.shape
    n_cols_pad = -(-n_cols // FUSED_GROUP) * FUSED_GROUP
    # the panel's word region covers exactly the group-padded columns
    # (pack_fused_panel layout); everything beyond words + 3 weight
    # columns is DMA-alignment padding, never read
    n_words = n_cols_pad // words_per
    assert panel.shape[1] >= n_words + 3, (panel.shape, n_words)
    sentinel = panel.shape[0] - 1
    if num_row_tiles is None:
        num_row_tiles = 1
    sc = jnp.stack([jnp.asarray(start, jnp.int32),
                    jnp.asarray(cnt, jnp.int32)])
    out2d = pl.pallas_call(
        functools.partial(_hist_kernel_fused, sentinel=sentinel,
                          n_words=n_words, words_per=words_per,
                          n_cols_pad=n_cols_pad, row_tile=row_tile),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(num_row_tiles,),
            in_specs=[pl.BlockSpec(memory_space=MemorySpace.ANY),
                      pl.BlockSpec(memory_space=MemorySpace.ANY)],
            out_specs=pl.BlockSpec((NUM_CH * NIB, n_cols_pad * NIB),
                                   lambda ri, sc: (0, 0)),
            scratch_shapes=[pltpu.SMEM((fused_idx_fetch(row_tile),),
                                       jnp.int32),
                            pltpu.VMEM((row_tile, panel.shape[1]),
                                       jnp.uint32),
                            pltpu.SemaphoreType.DMA,
                            pltpu.SemaphoreType.DMA],
        ),
        out_shape=jax.ShapeDtypeStruct((NUM_CH * NIB, n_cols_pad * NIB),
                                       jnp.float32),
        interpret=interpret,
        compiler_params=CompilerParams(dimension_semantics=("arbitrary",)),
    )(sc, order, panel)
    # [(ch, hi), (f, lo)] -> [ch, f, hi*16+lo], all in XLA (the same
    # epilogue the retired gen-1 nibble form used)
    out4 = out2d.reshape(NUM_CH, NIB, n_cols_pad, NIB)
    return out4.transpose(0, 2, 1, 3).reshape(
        NUM_CH, n_cols_pad, NIB * NIB)[:, :n_cols, :num_bins]


def hist6_fused_local(row_leaf: jnp.ndarray, leaf_id, panel: jnp.ndarray,
                      n_cols: int, words_per: int, num_bins: int,
                      row_tile: int = 512,
                      interpret: bool = False) -> jnp.ndarray:
    """Shard-local fused histogram for the GSPMD hybrid: derive the leaf's
    LOCAL order window from the row -> leaf partition, then run the same
    ``hist6_fused`` kernel over this device's row shard.

    row_leaf [n_loc] i32 (this shard's row -> leaf ids), leaf_id traced i32
    scalar, panel the shard's pack_fused_panel output (sentinel row
    appended by the caller before packing) -> [6, n_cols, num_bins] f32
    partial histogram (sums over the local rows only; the caller reduces
    across shards).

    The serial grower keeps ``order`` incrementally via its partition
    switch; under GSPMD the row -> leaf map IS the state, so the window is
    rebuilt per call with a cumsum compaction — O(n_loc) work, and the
    kernel's dynamic grid still makes the gather cost leaf-sized
    (ceil(cnt / row_tile) tiles, not n_loc / row_tile).
    """
    n_loc = row_leaf.shape[0]
    match = row_leaf == jnp.asarray(leaf_id, row_leaf.dtype)
    pos = jnp.cumsum(match.astype(jnp.int32)) - 1      # rank among matches
    cnt = jnp.sum(match.astype(jnp.int32))
    tail = fused_idx_fetch(row_tile)
    # compaction scatter: matching rows land at their rank, the rest are
    # routed out of bounds and dropped.  The tail (and any slot past cnt)
    # is never USED — the kernel redirects positions >= cnt to the panel's
    # sentinel row — it only has to exist for the aligned over-fetch.
    order = jnp.full((n_loc + tail,), n_loc, jnp.int32)
    order = order.at[jnp.where(match, pos, n_loc + tail)].set(
        jnp.arange(n_loc, dtype=jnp.int32), mode="drop")
    num_row_tiles = jnp.maximum(1, -(-cnt // row_tile)).astype(jnp.int32)
    return hist6_fused(order, panel, 0, cnt, n_cols, words_per, num_bins,
                       row_tile=row_tile, num_row_tiles=num_row_tiles,
                       interpret=interpret)
