"""Pallas TPU histogram kernel.

The TPU answer to the reference's OpenCL histogram kernels
(``src/treelearner/ocl/histogram256.cl`` — per-workgroup local-memory
histograms with hand-rolled atomic float adds): instead of scatter-adds,
each grid step builds a one-hot of the combined (feature, bin) index for a
row tile *in VMEM* and contracts it against the per-row weight channels on
the MXU.  The [rows, features*bins] one-hot never exists in HBM — only the
[feature_tile * B] accumulator block does, revisited across row tiles.

Layout: bins come in transposed ``[F, N]`` so the row dimension is the lane
axis of each block.  Weights ``w_t [6, N]`` carry the bf16 channels
``(g_hi, g_lo, h_hi, h_lo, c, 0)`` — gradients/hessians are hi/lo-split so a
single-pass bf16 MXU dot accumulates with ~f32 accuracy (recombined by the
caller, ``subset_histogram_pallas``).

Mosaic constraints shape two choices here (round-2 lesson: the kernel failed
`infer-vector-layout: unsupported shape cast` on a `vector<512x8x255xi1>`
reshape):

* the per-bin axis is padded up to a multiple of the 128-wide lane register
  (255 -> 256) so every reshape keeps the lane dimension aligned; the caller
  slices the phantom bins off (they are provably zero: bin ids < num_bins);
* the boolean one-hot is cast to the matmul dtype *before* the
  [TR, TF, B] -> [TR, TF*B] collapse, so Mosaic never has to lay out an i1
  vector across a shape cast — and the kernel's output block stays 2D
  ([6, TF*B]); the reshape to [6, F, B] happens outside Pallas in XLA.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..obs.counters import counters as obs_counters
from ..utils import log
from .pallas_compat import CompilerParams, MemorySpace

NUM_CH = 6   # weight channels: (g_hi, g_lo, h_hi, h_lo, c, unused)
LANES = 128  # TPU vector register lane width — bin axis is padded to this
# warn-once registry for the nibble fallback, keyed by the unsupported
# histogram width: a second model in the same process with a DIFFERENT
# unsupported width must still warn (a bare process-global bool silently
# suppressed it), while the grower's dozen-plus traces of one model at one
# width still produce a single line.
_nibble_warned_widths: set = set()


def _hist_kernel(bins_ref, w_ref, out_ref, *, num_bins: int, feat_tile: int):
    r = pl.program_id(1)

    @pl.when(r == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    bins = bins_ref[...].astype(jnp.int32)          # [TF, TR]
    w = w_ref[...]                                  # [6, TR]
    tr = bins.shape[1]
    # one-hot of the bin index per (row, feature-in-tile): [TR, TF, B];
    # flattened over (feature, bin) it is the combined-index one-hot.
    # num_bins is lane-aligned and the cast precedes the collapse (see
    # module docstring for the Mosaic rationale).
    onehot = (bins.T[:, :, None] ==
              lax.broadcasted_iota(jnp.int32, (tr, feat_tile, num_bins), 2)
              ).astype(w.dtype)
    onehot2d = onehot.reshape(tr, feat_tile * num_bins)
    # channels on the SUBLANE axis: [6, TR] @ [TR, TF*B] pads 6 -> 8 rows
    # instead of 6 -> 128 lanes (16x less MXU waste than the transposed form)
    out_ref[...] += jnp.dot(w, onehot2d,
                            preferred_element_type=jnp.float32)  # [6, TF*B]


NIB = 16     # nibble radix: bin = hi*16 + lo, each one-hot 16 wide


def _hist_kernel_nibble(bins_ref, w_ref, out_ref, *, feat_tile: int):
    """Nibble-factorized histogram block: bin = hi*16 + lo.

    The plain one-hot kernel's dot is [6, TR] @ [TR, TF*256]; on the MXU
    the 6-channel M dim pads to 128, so the slot cost per row is
    128 * 256 lanes per feature.  Factoring the one-hot through the two
    nibbles moves the hi one-hot INTO the M dim — U = (channel x hi_onehot)
    is 96 rows, padding 128 with only 1.3x waste — and shrinks the lane
    side to the 16-wide lo one-hot (padded to the 128 floor): per row per
    feature 128 * 128 slots, half the plain kernel, and ~3x less VPU work
    building one-hots (2x16 instead of 256 compares+casts).  Only pays
    when B_pad = 256, i.e. num_bins > 128; below that the plain kernel
    already sits on the 128-lane floor.

    Output block [96, TF*16]: rows are (ch, hi) ch-major, columns (f, lo);
    the lane dim is exactly 128 at feat_tile=8 so no kernel-side reshape
    ever crosses the lane boundary (the round-2 Mosaic lesson); the
    unfold to [6, F, 256] happens outside in XLA."""
    r = pl.program_id(1)

    @pl.when(r == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    bins = bins_ref[...].astype(jnp.int32)          # [TF, TR]
    w = w_ref[...]                                  # [6, TR]
    tr = bins.shape[1]
    hi = bins >> 4                                  # [TF, TR], < 16
    lo = bins & 15
    # per-feature [96, 16] dots are CONCATENATED along lanes and stored
    # once as the full [96, TF*16] block: sub-lane-width (16 < 128) slice
    # writes into out_ref are the kind of masked partial store Mosaic has
    # historically mislowered, so the kernel never does one
    blocks = []
    for f in range(feat_tile):
        oh_hi = (hi[f][None, :] ==
                 lax.broadcasted_iota(jnp.int32, (NIB, tr), 0)
                 ).astype(w.dtype)                  # [16, TR]
        u = (w[:, None, :] * oh_hi[None, :, :]).reshape(NUM_CH * NIB, tr)
        oh_lo = (lo[f][:, None] ==
                 lax.broadcasted_iota(jnp.int32, (tr, NIB), 1)
                 ).astype(w.dtype)                  # [TR, 16]
        blocks.append(jnp.dot(u, oh_lo,
                              preferred_element_type=jnp.float32))  # [96,16]
    out_ref[...] += jnp.concatenate(blocks, axis=1)   # [96, TF*16]


def hist6_pallas(bins_t: jnp.ndarray, w_t: jnp.ndarray, num_bins: int,
                 feat_tile: int = 8, row_tile: int = 512,
                 interpret: bool = False, impl: str = "auto") -> jnp.ndarray:
    """bins_t: [F, N] int; w_t: [6, N] f32 -> hist [6, F, B] f32.

    F must be a multiple of feat_tile and N of row_tile (pad at the caller;
    padded rows must carry w = 0, padded features are sliced off).

    ``impl``: 'onehot' (single combined-index one-hot dot), 'nibble'
    (hi/lo factorized, B_pad = 256 only), or 'auto' — which currently
    resolves to 'onehot' unconditionally: the nibble form is the
    projected winner at B_pad = 256 but stays opt-in until the on-chip
    tier (test_pallas_nibble_*) proves its Mosaic lowering.
    """
    f, n = bins_t.shape
    assert f % feat_tile == 0 and n % row_tile == 0, (f, n, feat_tile, row_tile)
    b_pad = -(-num_bins // LANES) * LANES
    grid = (f // feat_tile, n // row_tile)
    if impl == "auto":
        # the nibble form is the projected 2x winner at B_pad = 256; its
        # Mosaic LOWERING is proven offline (tests/test_mosaic_aot.py AOT-
        # compiles it for v5e), but 'auto' stays on the hardware-proven
        # kernel until an on-chip A/B confirms the throughput win
        # (bench_1m_nibble.json in the capture playbook — then flip here)
        impl = "onehot"
    if impl == "nibble" and b_pad != 2 * LANES:
        # the config gate is optimistic about bin packing widening the
        # axis to 256; when no pack plan materialized the effective width
        # stays < 129 and the factorization has nothing to win — fall
        # back instead of tripping the shape assert inside tracing.
        # Warn once per WIDTH: the grower traces one call per gather
        # bucket, which would repeat the identical line a dozen-plus times
        # — but a second model with a different unsupported width still
        # warns (the A/B harness must never silently mislabel a run)
        if num_bins not in _nibble_warned_widths:
            _nibble_warned_widths.add(num_bins)
            log.warning("pallas_hist_impl=nibble needs a 256-wide histogram "
                        "axis (got %d bins); using the one-hot kernel",
                        num_bins)
            obs_counters.event("layout_downgrade", stage="pallas_hist",
                               requested="nibble", resolved="onehot",
                               reason=f"histogram axis pads to {b_pad}, "
                                      "nibble needs 256")
        impl = "onehot"
    # resolved kernel FORM (onehot vs nibble) — the fine-grained identity
    # under hist_dispatch's method=pallas (trace-time, per call site)
    obs_counters.inc("pallas_impl", impl=impl)
    if impl == "nibble":
        assert b_pad == 2 * LANES and (feat_tile * NIB) % LANES == 0, \
            (num_bins, feat_tile)
        out2d = pl.pallas_call(
            functools.partial(_hist_kernel_nibble, feat_tile=feat_tile),
            grid=grid,
            in_specs=[
                pl.BlockSpec((feat_tile, row_tile), lambda fi, ri: (fi, ri)),
                pl.BlockSpec((NUM_CH, row_tile), lambda fi, ri: (0, ri)),
            ],
            out_specs=pl.BlockSpec((NUM_CH * NIB, feat_tile * NIB),
                                   lambda fi, ri: (0, fi)),
            out_shape=jax.ShapeDtypeStruct((NUM_CH * NIB, f * NIB),
                                           jnp.float32),
            interpret=interpret,
        )(bins_t, w_t)
        # [(ch, hi), (f, lo)] -> [ch, f, hi*16+lo], all in XLA
        out4 = out2d.reshape(NUM_CH, NIB, f, NIB)
        return out4.transpose(0, 2, 1, 3).reshape(
            NUM_CH, f, NIB * NIB)[:, :, :num_bins]
    out2d = pl.pallas_call(
        functools.partial(_hist_kernel, num_bins=b_pad,
                          feat_tile=feat_tile),
        grid=grid,
        in_specs=[
            pl.BlockSpec((feat_tile, row_tile), lambda fi, ri: (fi, ri)),
            pl.BlockSpec((NUM_CH, row_tile), lambda fi, ri: (0, ri)),
        ],
        out_specs=pl.BlockSpec((NUM_CH, feat_tile * b_pad),
                               lambda fi, ri: (0, fi)),
        out_shape=jax.ShapeDtypeStruct((NUM_CH, f * b_pad), jnp.float32),
        interpret=interpret,
    )(bins_t, w_t)
    # un-flatten and drop the lane-padding bins outside the kernel (plain XLA)
    return out2d.reshape(NUM_CH, f, b_pad)[:, :, :num_bins]


def subset_histogram_pallas(rows: jnp.ndarray, g: jnp.ndarray, h: jnp.ndarray,
                            c: jnp.ndarray, num_bins: int,
                            feat_tile: int = 8, row_tile: int = 512,
                            interpret: bool = False,
                            impl: str = "auto") -> jnp.ndarray:
    """Histogram of a gathered row subset: rows [M, F] int, g/h/c [M] f32
    (0 for padding rows) -> [F, B, 3].

    Single-pass bf16 MXU matmul with hi/lo-split weights for ~f32 accuracy:
    channels are (g_hi, g_lo, h_hi, h_lo, c, 0); the f32 histogram is
    recombined as hi + lo after the f32-accumulated dot."""
    from .histogram import _split_hi_lo
    m, f = rows.shape
    g_hi, g_lo = _split_hi_lo(g.astype(jnp.float32))
    h_hi, h_lo = _split_hi_lo(h.astype(jnp.float32))
    w_t = jnp.stack([g_hi, g_lo, h_hi, h_lo,
                     c.astype(jnp.bfloat16),
                     jnp.zeros_like(c, jnp.bfloat16)], axis=0)   # [6, M] bf16
    bins_t = rows.astype(jnp.int32).T                            # [F, M]
    pad_f = (-f) % feat_tile
    pad_m = (-m) % row_tile
    if pad_f:
        bins_t = jnp.pad(bins_t, ((0, pad_f), (0, 0)))
    if pad_m:
        bins_t = jnp.pad(bins_t, ((0, 0), (0, pad_m)))
        w_t = jnp.pad(w_t, ((0, 0), (0, pad_m)))
    hist6 = hist6_pallas(bins_t, w_t, num_bins, feat_tile, row_tile,
                         interpret=interpret, impl=impl)[:, :f]  # [6, F, B]
    hist_g = hist6[0] + hist6[1]
    hist_h = hist6[2] + hist6[3]
    return jnp.stack([hist_g, hist_h, hist6[4]], axis=-1)        # [F, B, 3]


# ---------------------------------------------------------------------------
# Generation 2: fused-gather, nibble-factorized histogram kernel.
#
# The gen-1 path pays two separately-measured costs per split (docs/PERF.md
# cost model): a random row gather through XLA (~12.6 ns/elem, staged into a
# pow2-padded [M, F] HBM buffer) and the one-hot MXU contraction whose
# 6-channel M dim pads to 128 (~21x slot waste).  This kernel is the same
# move the reference made when it fused gather+accumulate into one OpenCL
# pass (src/treelearner/ocl/histogram256.cl): the row gather happens INSIDE
# the kernel — per-tile, the window of the leaf's ``order`` indices is DMAd
# into SMEM and each indexed panel row is DMAd from HBM straight into VMEM,
# so the gathered [M, F] matrix never exists in HBM and the separate gather
# dispatch disappears — and the contraction is the nibble-factorized form
# (bin = hi*16 + lo, M = ch x hi = 96 rows, 16-wide lo one-hot) that cuts
# the MXU slot cost ~2x at B_pad = 256.  PERF.md projects the stack at
# ~8.5 ns/row vs the measured 22 + 12.6.
#
# Three structural differences from the gen-1 kernels:
#
# * the input is the FUSED PANEL (data/packing.py:pack_fused_panel): packed
#   bin words + the three bitcast f32 weight columns in one u32 row, so the
#   per-row DMA is a single contiguous burst and the hi/lo bf16 weight
#   split happens on-chip, per tile;
# * the grid is 1-D over row tiles and may be DYNAMIC (a traced tile
#   count): the grower passes ceil(cnt / row_tile), so a small leaf costs
#   a small grid — this is what retires the gather-bucket ``lax.switch``
#   (no static pow2 staging buffer means no static bucket sizes);
# * rows at positions >= cnt are redirected to the panel's sentinel row
#   (all-zero words AND zero weights), so tile padding needs no masking
#   anywhere downstream.
#
# Mosaic surfaces kept deliberately boring (round-2/round-5 lessons): the
# output block is written in static 128-lane groups (8 features x 16 lo
# bins) via full-width concatenated stores — never a sub-lane-width partial
# store — and every reshape happens outside the kernel in XLA.
# ---------------------------------------------------------------------------

FUSED_GROUP = 8        # features per 128-lane output group (8 * NIB = 128)
FUSED_MAX_COLS = 512   # feature-loop unroll + VMEM output-block ceiling
IDX_ALIGN = 1024       # i32 1-D tile: dynamic slices of ``order`` must sit
#                        on this boundary AND have a multiple-of-it length
#                        (Mosaic "tile index divisible by tiling" / "slice
#                        shape aligned to tile boundaries", both proven by
#                        the v5e AOT probe), so the kernel over-fetches the
#                        enclosing aligned region


def fused_idx_fetch(row_tile: int) -> int:
    """Elements of ``order`` the kernel fetches per tile: the smallest
    IDX_ALIGN multiple covering a row_tile window at any residual offset
    (< IDX_ALIGN) inside an aligned region."""
    return -(-(row_tile + IDX_ALIGN - 1) // IDX_ALIGN) * IDX_ALIGN


def _hist_kernel_fused(sc_ref, order_ref, panel_ref, out_ref,
                       idx_smem, rows_vmem, idx_sem, row_sem, *,
                       sentinel: int, n_words: int, words_per: int,
                       n_cols_pad: int, row_tile: int):
    ri = pl.program_id(0)

    @pl.when(ri == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    start = sc_ref[0]
    cnt = sc_ref[1]
    # the tile's slice of the leaf's ``order`` window, HBM -> SMEM: these
    # are the row ids the per-row DMAs below need as scalars.  The window
    # position is arbitrary but the source slice must be IDX_ALIGN-aligned,
    # so fetch the enclosing aligned region and read at the residual
    # offset — 3x the index bytes, which is noise next to the panel rows.
    pos = start + ri * row_tile
    aligned = pl.multiple_of((pos // IDX_ALIGN) * IDX_ALIGN, IDX_ALIGN)
    off = pos - aligned
    idx_copy = pltpu.make_async_copy(
        order_ref.at[pl.ds(aligned, fused_idx_fetch(row_tile))], idx_smem,
        idx_sem)
    idx_copy.start()
    idx_copy.wait()

    base = ri * row_tile

    def _row_copy(i):
        # positions past the leaf's count read the sentinel row (zero
        # words, zero weights) — same contract as the gen-1 sentinel pad.
        # pl.ds(r, 1) keeps the slice 2-D: integer .at[r] indexing squeezes
        # the row dim and that squeeze is what the LLO lowering choked on
        # ("dynamic_dim_it != dynamic_sizes.end()", v5e AOT probe) — the
        # compact kernel's proven dynamic-offset DMAs are all pl.ds-shaped
        r = jnp.where(base + i < cnt, idx_smem[off + i], sentinel)
        return pltpu.make_async_copy(panel_ref.at[pl.ds(r, 1), :],
                                     rows_vmem.at[pl.ds(i, 1), :],
                                     row_sem)

    # start every row DMA, then drain: the copies are independent and tiny
    # (W+3 u32 words each), so queueing them all before the first wait is
    # what lets the DMA engines overlap them
    def _start(i, _):
        _row_copy(i).start()
        return 0
    lax.fori_loop(0, row_tile, _start, 0)

    def _wait(i, _):
        _row_copy(i).wait()
        return 0
    lax.fori_loop(0, row_tile, _wait, 0)

    # word rows on the sublane axis (same orientation trick as the gen-1
    # kernels' [F, N] layout): static sublane indexing below, no dynamic
    # lane slicing for Mosaic to reject.  The untransposed form stays live
    # too: the lo one-hot needs COLUMN-shaped bins, and Mosaic rejects the
    # [TR] -> [TR, 1] shape cast from a sublane-layout vector (v5e AOT
    # probe) — a static [TR, 1] lane slice of the row-major value is
    # column-shaped from birth.
    rows2d = rows_vmem[...]                          # [TR, n_words + 3] u32
    words_t = rows2d.T                               # [n_words + 3, TR] u32
    shift = 32 // words_per
    wmask = jnp.uint32((1 << shift) - 1)

    # on-chip hi/lo weight split (the _split_hi_lo contract): channels
    # (g_hi, g_lo, h_hi, h_lo, c, 0) exactly like subset_histogram_pallas.
    # NO bf16 values exist below full-tile width: Mosaic rejected both the
    # gen-1 nibble form's [6, 1, TR] broadcast-multiply (vector.shape_cast)
    # and a [1, TR] bf16 sublane broadcast (vector.broadcast) — bf16's
    # packed (16, 128) tiling makes narrow bf16 vectors a hostile surface
    # (both caught by the v5e AOT probe).  So the hi half is computed IN
    # f32 via integer round-to-nearest-even on the raw bits (bit-identical
    # to an f32->bf16->f32 round-trip), everything stays f32 through the
    # broadcasts, and the one cast to bf16 happens on the full [96, TR]
    # tile right before the MXU.
    def _bf16_round_f32(wf):
        """f32 value of bf16(wf), without materializing a bf16 vector."""
        u = lax.bitcast_convert_type(wf, jnp.uint32)
        r = (u + jnp.uint32(0x7fff) + ((u >> 16) & jnp.uint32(1))) \
            & jnp.uint32(0xffff0000)
        return lax.bitcast_convert_type(r, jnp.float32)

    chans32 = []
    for k in range(2):
        wf = lax.bitcast_convert_type(words_t[n_words + k], jnp.float32)
        w_hi = _bf16_round_f32(wf)
        chans32 += [w_hi, wf - w_hi]
    chans32.append(lax.bitcast_convert_type(words_t[n_words + 2],
                                            jnp.float32))
    chans32.append(jnp.zeros_like(chans32[-1]))

    tr = row_tile
    # U's weight factor, feature-independent, built once per row tile —
    # strictly 2-D f32: each channel row broadcast to its 16-row band
    w_rep = jnp.concatenate(
        [jnp.broadcast_to(ch[None, :], (NIB, tr)) for ch in chans32],
        axis=0)                                      # [96, TR] f32
    for g0 in range(0, n_cols_pad, FUSED_GROUP):
        blocks = []
        for k in range(FUSED_GROUP):
            c = g0 + k
            w_i = c // words_per
            sh = (c % words_per) * shift
            binc = ((words_t[w_i] >> sh) & wmask).astype(jnp.int32)
            hi = binc >> 4                           # [TR], < 16
            oh_hi = (hi[None, :] ==
                     lax.broadcasted_iota(jnp.int32, (NIB, tr), 0)
                     ).astype(jnp.float32)           # [16, TR]
            # masked weights in f32, ONE full-tile bf16 cast before the
            # dot (oh is 0/1, so bf16(w * oh) == bf16(w) * oh exactly)
            u = (w_rep * jnp.concatenate([oh_hi] * NUM_CH, axis=0)
                 ).astype(jnp.bfloat16)              # [96, TR]
            lo_col = ((rows2d[:, w_i:w_i + 1] >> sh)
                      & wmask).astype(jnp.int32) & 15  # [TR, 1]
            oh_lo = (lo_col ==
                     lax.broadcasted_iota(jnp.int32, (tr, NIB), 1)
                     ).astype(jnp.bfloat16)          # [TR, 16]
            blocks.append(jnp.dot(u, oh_lo,
                                  preferred_element_type=jnp.float32))
        # one concatenated 128-lane-aligned store per feature group — the
        # masked sub-lane partial stores Mosaic has mislowered never happen
        out_ref[:, g0 * NIB:(g0 + FUSED_GROUP) * NIB] += jnp.concatenate(
            blocks, axis=1)                          # [96, 128]


def hist6_fused(order: jnp.ndarray, panel: jnp.ndarray, start, cnt,
                n_cols: int, words_per: int, num_bins: int,
                row_tile: int = 512, num_row_tiles=None,
                interpret: bool = False) -> jnp.ndarray:
    """Fused-gather nibble histogram: order [NO] i32 row ids (the leaf's
    window lives at [start, start + cnt)), panel [N + 1, n_words + 3] u32
    (pack_fused_panel layout, last row = sentinel) -> [6, n_cols, num_bins]
    f32.

    ``num_row_tiles`` is the grid length: a python int for a static grid,
    or a traced i32 scalar >= 1 (must equal ceil(max(cnt, 1) / row_tile))
    for the grower's dynamic-grid form.  ``start``/``cnt`` may be traced
    scalars either way.  The caller guarantees NO >= max(start + cnt)
    rounded down to IDX_ALIGN, plus fused_idx_fetch(row_tile): the aligned
    over-fetch may read that far past the window (the grower pads
    ``order`` with sentinel tail accordingly).
    """
    assert 1 < num_bins <= NIB * NIB, num_bins
    assert n_cols <= FUSED_MAX_COLS, (n_cols, FUSED_MAX_COLS)
    assert order.shape[0] >= fused_idx_fetch(row_tile), order.shape
    n_cols_pad = -(-n_cols // FUSED_GROUP) * FUSED_GROUP
    # the panel's word region covers exactly the group-padded columns
    # (pack_fused_panel layout); everything beyond words + 3 weight
    # columns is DMA-alignment padding, never read
    n_words = n_cols_pad // words_per
    assert panel.shape[1] >= n_words + 3, (panel.shape, n_words)
    sentinel = panel.shape[0] - 1
    if num_row_tiles is None:
        num_row_tiles = 1
    sc = jnp.stack([jnp.asarray(start, jnp.int32),
                    jnp.asarray(cnt, jnp.int32)])
    out2d = pl.pallas_call(
        functools.partial(_hist_kernel_fused, sentinel=sentinel,
                          n_words=n_words, words_per=words_per,
                          n_cols_pad=n_cols_pad, row_tile=row_tile),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(num_row_tiles,),
            in_specs=[pl.BlockSpec(memory_space=MemorySpace.ANY),
                      pl.BlockSpec(memory_space=MemorySpace.ANY)],
            out_specs=pl.BlockSpec((NUM_CH * NIB, n_cols_pad * NIB),
                                   lambda ri, sc: (0, 0)),
            scratch_shapes=[pltpu.SMEM((fused_idx_fetch(row_tile),),
                                       jnp.int32),
                            pltpu.VMEM((row_tile, panel.shape[1]),
                                       jnp.uint32),
                            pltpu.SemaphoreType.DMA,
                            pltpu.SemaphoreType.DMA],
        ),
        out_shape=jax.ShapeDtypeStruct((NUM_CH * NIB, n_cols_pad * NIB),
                                       jnp.float32),
        interpret=interpret,
        compiler_params=CompilerParams(dimension_semantics=("arbitrary",)),
    )(sc, order, panel)
    # [(ch, hi), (f, lo)] -> [ch, f, hi*16+lo], all in XLA (the same
    # epilogue as the gen-1 nibble form)
    out4 = out2d.reshape(NUM_CH, NIB, n_cols_pad, NIB)
    return out4.transpose(0, 2, 1, 3).reshape(
        NUM_CH, n_cols_pad, NIB * NIB)[:, :n_cols, :num_bins]
