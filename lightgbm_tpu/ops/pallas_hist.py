"""Pallas TPU histogram kernel.

The TPU answer to the reference's OpenCL histogram kernels
(``src/treelearner/ocl/histogram256.cl`` — per-workgroup local-memory
histograms with hand-rolled atomic float adds): instead of scatter-adds,
each grid step builds a one-hot of the combined (feature, bin) index for a
row tile *in VMEM* and contracts it against the per-row weight channels on
the MXU.  The [rows, features*bins] one-hot never exists in HBM — only the
[feature_tile, B, 6] accumulator block does, revisited across row tiles.

Layout: bins come in transposed ``[F, N]`` so the row dimension is the lane
axis of each block.  Weights ``w [N, 6]`` carry (g, h, c) for the left and
right child, premasked by segment outside the kernel (fused by XLA).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NUM_CH = 6  # (g, h, c) x (left child, right child)


def _hist_kernel(bins_ref, w_ref, out_ref, *, num_bins: int, feat_tile: int):
    r = pl.program_id(1)

    @pl.when(r == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    bins = bins_ref[...].astype(jnp.int32)          # [TF, TR]
    w = w_ref[...]                                  # [6, TR]
    tr = bins.shape[1]
    # one-hot of the bin index per (row, feature-in-tile): [TR, TF, B];
    # flattened over (feature, bin) it is the combined-index one-hot.
    onehot = (bins.T[:, :, None] ==
              lax.broadcasted_iota(jnp.int32, (tr, feat_tile, num_bins), 2))
    onehot2d = onehot.reshape(tr, feat_tile * num_bins).astype(w.dtype)
    # channels on the SUBLANE axis: [6, TR] @ [TR, TF*B] pads 6 -> 8 rows
    # instead of 6 -> 128 lanes (16x less MXU waste than the transposed form)
    part = jnp.dot(w, onehot2d,
                   preferred_element_type=jnp.float32)  # [6, TF*B]
    out_ref[...] += part.reshape(NUM_CH, feat_tile, num_bins)


def hist6_pallas(bins_t: jnp.ndarray, w_t: jnp.ndarray, num_bins: int,
                 feat_tile: int = 8, row_tile: int = 512,
                 interpret: bool = False) -> jnp.ndarray:
    """bins_t: [F, N] int; w_t: [6, N] f32 -> hist [6, F, B] f32.

    F must be a multiple of feat_tile and N of row_tile (pad at the caller;
    padded rows must carry w = 0, padded features are sliced off).
    """
    f, n = bins_t.shape
    assert f % feat_tile == 0 and n % row_tile == 0, (f, n, feat_tile, row_tile)
    grid = (f // feat_tile, n // row_tile)
    return pl.pallas_call(
        functools.partial(_hist_kernel, num_bins=num_bins,
                          feat_tile=feat_tile),
        grid=grid,
        in_specs=[
            pl.BlockSpec((feat_tile, row_tile), lambda fi, ri: (fi, ri)),
            pl.BlockSpec((NUM_CH, row_tile), lambda fi, ri: (0, ri)),
        ],
        out_specs=pl.BlockSpec((NUM_CH, feat_tile, num_bins),
                               lambda fi, ri: (0, fi, 0)),
        out_shape=jax.ShapeDtypeStruct((NUM_CH, f, num_bins), jnp.float32),
        interpret=interpret,
    )(bins_t, w_t)


def child_histograms_pallas(bins: jnp.ndarray, seg: jnp.ndarray,
                            grad: jnp.ndarray, hess: jnp.ndarray,
                            cnt: jnp.ndarray, num_bins: int,
                            feat_tile: int = 8,
                            row_tile: int = 1024,
                            interpret: bool = False) -> jnp.ndarray:
    """Drop-in for ops.histogram.child_histograms: [2, F, B, 3]."""
    n, f = bins.shape
    left = (seg == 0)
    right = (seg == 1)
    w_t = jnp.stack([
        jnp.where(left, grad, 0.0), jnp.where(left, hess, 0.0),
        jnp.where(left, cnt, 0.0),
        jnp.where(right, grad, 0.0), jnp.where(right, hess, 0.0),
        jnp.where(right, cnt, 0.0),
    ], axis=0).astype(jnp.float32)                  # [6, N]

    pad_n = (-n) % row_tile
    pad_f = (-f) % feat_tile
    bins_t = bins.astype(jnp.int32).T               # [F, N]
    if pad_f:
        bins_t = jnp.pad(bins_t, ((0, pad_f), (0, 0)))
    if pad_n:
        bins_t = jnp.pad(bins_t, ((0, 0), (0, pad_n)))
        w_t = jnp.pad(w_t, ((0, 0), (0, pad_n)))

    hist6 = hist6_pallas(bins_t, w_t, num_bins, feat_tile, row_tile,
                         interpret=interpret)[:, :f]      # [6, F, B]
    # [6, F, B] -> [2, F, B, 3]
    return jnp.moveaxis(hist6.reshape(2, 3, f, num_bins), 1, 3)
