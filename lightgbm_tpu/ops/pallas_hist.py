"""Pallas TPU histogram kernel.

The TPU answer to the reference's OpenCL histogram kernels
(``src/treelearner/ocl/histogram256.cl`` — per-workgroup local-memory
histograms with hand-rolled atomic float adds): instead of scatter-adds,
each grid step builds a one-hot of the combined (feature, bin) index for a
row tile *in VMEM* and contracts it against the per-row weight channels on
the MXU.  The [rows, features*bins] one-hot never exists in HBM — only the
[feature_tile * B] accumulator block does, revisited across row tiles.

Layout: bins come in transposed ``[F, N]`` so the row dimension is the lane
axis of each block.  Weights ``w_t [6, N]`` carry the bf16 channels
``(g_hi, g_lo, h_hi, h_lo, c, 0)`` — gradients/hessians are hi/lo-split so a
single-pass bf16 MXU dot accumulates with ~f32 accuracy (recombined by the
caller, ``subset_histogram_pallas``).

Mosaic constraints shape two choices here (round-2 lesson: the kernel failed
`infer-vector-layout: unsupported shape cast` on a `vector<512x8x255xi1>`
reshape):

* the per-bin axis is padded up to a multiple of the 128-wide lane register
  (255 -> 256) so every reshape keeps the lane dimension aligned; the caller
  slices the phantom bins off (they are provably zero: bin ids < num_bins);
* the boolean one-hot is cast to the matmul dtype *before* the
  [TR, TF, B] -> [TR, TF*B] collapse, so Mosaic never has to lay out an i1
  vector across a shape cast — and the kernel's output block stays 2D
  ([6, TF*B]); the reshape to [6, F, B] happens outside Pallas in XLA.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..utils import log

NUM_CH = 6   # weight channels: (g_hi, g_lo, h_hi, h_lo, c, unused)
LANES = 128  # TPU vector register lane width — bin axis is padded to this
_nibble_warned = False


def _hist_kernel(bins_ref, w_ref, out_ref, *, num_bins: int, feat_tile: int):
    r = pl.program_id(1)

    @pl.when(r == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    bins = bins_ref[...].astype(jnp.int32)          # [TF, TR]
    w = w_ref[...]                                  # [6, TR]
    tr = bins.shape[1]
    # one-hot of the bin index per (row, feature-in-tile): [TR, TF, B];
    # flattened over (feature, bin) it is the combined-index one-hot.
    # num_bins is lane-aligned and the cast precedes the collapse (see
    # module docstring for the Mosaic rationale).
    onehot = (bins.T[:, :, None] ==
              lax.broadcasted_iota(jnp.int32, (tr, feat_tile, num_bins), 2)
              ).astype(w.dtype)
    onehot2d = onehot.reshape(tr, feat_tile * num_bins)
    # channels on the SUBLANE axis: [6, TR] @ [TR, TF*B] pads 6 -> 8 rows
    # instead of 6 -> 128 lanes (16x less MXU waste than the transposed form)
    out_ref[...] += jnp.dot(w, onehot2d,
                            preferred_element_type=jnp.float32)  # [6, TF*B]


NIB = 16     # nibble radix: bin = hi*16 + lo, each one-hot 16 wide


def _hist_kernel_nibble(bins_ref, w_ref, out_ref, *, feat_tile: int):
    """Nibble-factorized histogram block: bin = hi*16 + lo.

    The plain one-hot kernel's dot is [6, TR] @ [TR, TF*256]; on the MXU
    the 6-channel M dim pads to 128, so the slot cost per row is
    128 * 256 lanes per feature.  Factoring the one-hot through the two
    nibbles moves the hi one-hot INTO the M dim — U = (channel x hi_onehot)
    is 96 rows, padding 128 with only 1.3x waste — and shrinks the lane
    side to the 16-wide lo one-hot (padded to the 128 floor): per row per
    feature 128 * 128 slots, half the plain kernel, and ~3x less VPU work
    building one-hots (2x16 instead of 256 compares+casts).  Only pays
    when B_pad = 256, i.e. num_bins > 128; below that the plain kernel
    already sits on the 128-lane floor.

    Output block [96, TF*16]: rows are (ch, hi) ch-major, columns (f, lo);
    the lane dim is exactly 128 at feat_tile=8 so no kernel-side reshape
    ever crosses the lane boundary (the round-2 Mosaic lesson); the
    unfold to [6, F, 256] happens outside in XLA."""
    r = pl.program_id(1)

    @pl.when(r == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    bins = bins_ref[...].astype(jnp.int32)          # [TF, TR]
    w = w_ref[...]                                  # [6, TR]
    tr = bins.shape[1]
    hi = bins >> 4                                  # [TF, TR], < 16
    lo = bins & 15
    # per-feature [96, 16] dots are CONCATENATED along lanes and stored
    # once as the full [96, TF*16] block: sub-lane-width (16 < 128) slice
    # writes into out_ref are the kind of masked partial store Mosaic has
    # historically mislowered, so the kernel never does one
    blocks = []
    for f in range(feat_tile):
        oh_hi = (hi[f][None, :] ==
                 lax.broadcasted_iota(jnp.int32, (NIB, tr), 0)
                 ).astype(w.dtype)                  # [16, TR]
        u = (w[:, None, :] * oh_hi[None, :, :]).reshape(NUM_CH * NIB, tr)
        oh_lo = (lo[f][:, None] ==
                 lax.broadcasted_iota(jnp.int32, (tr, NIB), 1)
                 ).astype(w.dtype)                  # [TR, 16]
        blocks.append(jnp.dot(u, oh_lo,
                              preferred_element_type=jnp.float32))  # [96,16]
    out_ref[...] += jnp.concatenate(blocks, axis=1)   # [96, TF*16]


def hist6_pallas(bins_t: jnp.ndarray, w_t: jnp.ndarray, num_bins: int,
                 feat_tile: int = 8, row_tile: int = 512,
                 interpret: bool = False, impl: str = "auto") -> jnp.ndarray:
    """bins_t: [F, N] int; w_t: [6, N] f32 -> hist [6, F, B] f32.

    F must be a multiple of feat_tile and N of row_tile (pad at the caller;
    padded rows must carry w = 0, padded features are sliced off).

    ``impl``: 'onehot' (single combined-index one-hot dot), 'nibble'
    (hi/lo factorized, B_pad = 256 only), or 'auto' — which currently
    resolves to 'onehot' unconditionally: the nibble form is the
    projected winner at B_pad = 256 but stays opt-in until the on-chip
    tier (test_pallas_nibble_*) proves its Mosaic lowering.
    """
    f, n = bins_t.shape
    assert f % feat_tile == 0 and n % row_tile == 0, (f, n, feat_tile, row_tile)
    b_pad = -(-num_bins // LANES) * LANES
    grid = (f // feat_tile, n // row_tile)
    if impl == "auto":
        # the nibble form is the projected 2x winner at B_pad = 256; its
        # Mosaic LOWERING is proven offline (tests/test_mosaic_aot.py AOT-
        # compiles it for v5e), but 'auto' stays on the hardware-proven
        # kernel until an on-chip A/B confirms the throughput win
        # (bench_1m_nibble.json in the capture playbook — then flip here)
        impl = "onehot"
    if impl == "nibble" and b_pad != 2 * LANES:
        # the config gate is optimistic about bin packing widening the
        # axis to 256; when no pack plan materialized the effective width
        # stays < 129 and the factorization has nothing to win — fall
        # back instead of tripping the shape assert inside tracing.
        # Warn once per process: the grower traces one call per gather
        # bucket, which would repeat the identical line a dozen-plus times
        global _nibble_warned
        if not _nibble_warned:
            _nibble_warned = True
            log.warning("pallas_hist_impl=nibble needs a 256-wide histogram "
                        "axis (got %d bins); using the one-hot kernel",
                        num_bins)
        impl = "onehot"
    if impl == "nibble":
        assert b_pad == 2 * LANES and (feat_tile * NIB) % LANES == 0, \
            (num_bins, feat_tile)
        out2d = pl.pallas_call(
            functools.partial(_hist_kernel_nibble, feat_tile=feat_tile),
            grid=grid,
            in_specs=[
                pl.BlockSpec((feat_tile, row_tile), lambda fi, ri: (fi, ri)),
                pl.BlockSpec((NUM_CH, row_tile), lambda fi, ri: (0, ri)),
            ],
            out_specs=pl.BlockSpec((NUM_CH * NIB, feat_tile * NIB),
                                   lambda fi, ri: (0, fi)),
            out_shape=jax.ShapeDtypeStruct((NUM_CH * NIB, f * NIB),
                                           jnp.float32),
            interpret=interpret,
        )(bins_t, w_t)
        # [(ch, hi), (f, lo)] -> [ch, f, hi*16+lo], all in XLA
        out4 = out2d.reshape(NUM_CH, NIB, f, NIB)
        return out4.transpose(0, 2, 1, 3).reshape(
            NUM_CH, f, NIB * NIB)[:, :, :num_bins]
    out2d = pl.pallas_call(
        functools.partial(_hist_kernel, num_bins=b_pad,
                          feat_tile=feat_tile),
        grid=grid,
        in_specs=[
            pl.BlockSpec((feat_tile, row_tile), lambda fi, ri: (fi, ri)),
            pl.BlockSpec((NUM_CH, row_tile), lambda fi, ri: (0, ri)),
        ],
        out_specs=pl.BlockSpec((NUM_CH, feat_tile * b_pad),
                               lambda fi, ri: (0, fi)),
        out_shape=jax.ShapeDtypeStruct((NUM_CH, f * b_pad), jnp.float32),
        interpret=interpret,
    )(bins_t, w_t)
    # un-flatten and drop the lane-padding bins outside the kernel (plain XLA)
    return out2d.reshape(NUM_CH, f, b_pad)[:, :, :num_bins]


def subset_histogram_pallas(rows: jnp.ndarray, g: jnp.ndarray, h: jnp.ndarray,
                            c: jnp.ndarray, num_bins: int,
                            feat_tile: int = 8, row_tile: int = 512,
                            interpret: bool = False,
                            impl: str = "auto") -> jnp.ndarray:
    """Histogram of a gathered row subset: rows [M, F] int, g/h/c [M] f32
    (0 for padding rows) -> [F, B, 3].

    Single-pass bf16 MXU matmul with hi/lo-split weights for ~f32 accuracy:
    channels are (g_hi, g_lo, h_hi, h_lo, c, 0); the f32 histogram is
    recombined as hi + lo after the f32-accumulated dot."""
    from .histogram import _split_hi_lo
    m, f = rows.shape
    g_hi, g_lo = _split_hi_lo(g.astype(jnp.float32))
    h_hi, h_lo = _split_hi_lo(h.astype(jnp.float32))
    w_t = jnp.stack([g_hi, g_lo, h_hi, h_lo,
                     c.astype(jnp.bfloat16),
                     jnp.zeros_like(c, jnp.bfloat16)], axis=0)   # [6, M] bf16
    bins_t = rows.astype(jnp.int32).T                            # [F, M]
    pad_f = (-f) % feat_tile
    pad_m = (-m) % row_tile
    if pad_f:
        bins_t = jnp.pad(bins_t, ((0, pad_f), (0, 0)))
    if pad_m:
        bins_t = jnp.pad(bins_t, ((0, 0), (0, pad_m)))
        w_t = jnp.pad(w_t, ((0, 0), (0, pad_m)))
    hist6 = hist6_pallas(bins_t, w_t, num_bins, feat_tile, row_tile,
                         interpret=interpret, impl=impl)[:, :f]  # [6, F, B]
    hist_g = hist6[0] + hist6[1]
    hist_h = hist6[2] + hist6[3]
    return jnp.stack([hist_g, hist_h, hist6[4]], axis=-1)        # [F, B, 3]
