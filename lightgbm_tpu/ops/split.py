"""Vectorized best-split search over feature histograms.

Reproduces ``FeatureHistogram::FindBestThresholdNumerical`` /
``FindBestThresholdSequence`` (``src/treelearner/feature_histogram.hpp:82-418``)
as one tensor program over all features at once — no per-feature loop:

* two scan directions become two cumulative-sum families over the bin axis;
* the reference's ``continue``/``break`` constraint guards become masks (all
  guarded quantities are monotone along the scan, so masking is equivalent);
* missing-value handling (``MissingType`` none/zero/nan) selects which bins
  contribute to each side and which thresholds are candidates;
* tie-breaking matches the reference scan order: smallest feature index wins,
  then direction -1 (missing defaults left) before +1, then the -1 scan
  prefers the largest threshold and the +1 scan the smallest.

Gain = ``G(left) + G(right) - G(parent) - min_gain_to_split`` with the L1
soft-threshold regularizer ``G(s,h) = max(0, |s|-l1)^2 / (h+l2)``
(``feature_histogram.hpp:255-262``).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

K_EPSILON = 1e-15  # reference kEpsilon
MISSING_NONE, MISSING_ZERO, MISSING_NAN = 0, 1, 2


class SplitConfig(NamedTuple):
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3


class SplitResult(NamedTuple):
    """Best split of one leaf (scalar fields) — analogue of SplitInfo
    (src/treelearner/split_info.hpp:17-120)."""
    found: jnp.ndarray        # bool
    gain: jnp.ndarray         # f32, already reduced by gain_shift; -inf if none
    feature: jnp.ndarray      # i32 index into used features; -1 if none
    threshold: jnp.ndarray    # i32 bin threshold (left: bin <= threshold)
    default_left: jnp.ndarray # bool
    left_sum_g: jnp.ndarray
    left_sum_h: jnp.ndarray
    left_count: jnp.ndarray   # f32 count
    right_sum_g: jnp.ndarray
    right_sum_h: jnp.ndarray
    right_count: jnp.ndarray
    left_output: jnp.ndarray
    right_output: jnp.ndarray


def leaf_split_gain(sum_g, sum_h, l1, l2):
    """G(s, h) with L1 soft-thresholding (feature_histogram.hpp:255-262)."""
    reg = jnp.maximum(jnp.abs(sum_g) - l1, 0.0)
    return reg * reg / (sum_h + l2)


def leaf_output(sum_g, sum_h, l1, l2):
    """Leaf weight -sign(s)*max(0,|s|-l1)/(h+l2) (feature_histogram.hpp:269-274)."""
    reg = jnp.maximum(jnp.abs(sum_g) - l1, 0.0)
    return -jnp.sign(sum_g) * reg / (sum_h + l2)


def _candidate_arrays(hist, parent_g, parent_h, parent_c,
                      num_bin, missing_type, default_bin, feat_valid, cfg):
    """Packed per-candidate arrays [F, 2B] in reference tie-break order:
    per feature, dir=-1 candidates (largest threshold first) then dir=+1
    ascending.  Invalid candidates carry gain = -inf."""
    dtype = hist.dtype
    f, b, _ = hist.shape
    g = hist[:, :, 0]
    h = hist[:, :, 1]
    c = hist[:, :, 2]
    bins = lax.broadcasted_iota(jnp.int32, (f, b), 1)
    nb = num_bin[:, None]
    mt = missing_type[:, None]
    db = default_bin[:, None]
    nan_bin = nb - 1

    l1 = jnp.asarray(cfg.lambda_l1, dtype)
    l2 = jnp.asarray(cfg.lambda_l2, dtype)
    min_data = jnp.asarray(cfg.min_data_in_leaf, dtype)
    min_hess = jnp.asarray(cfg.min_sum_hessian_in_leaf, dtype)

    tot_h = parent_h + 2.0 * K_EPSILON
    gain_shift = leaf_split_gain(parent_g, tot_h, l1, l2)
    min_gain_shift = gain_shift + cfg.min_gain_to_split

    two_dir = (nb > 2) & (mt != MISSING_NONE)
    na_excl = two_dir & (mt == MISSING_NAN)    # dir=-1 keeps NaN bin out of right
    zero_skip = two_dir & (mt == MISSING_ZERO)

    neg_inf = jnp.asarray(-jnp.inf, dtype)

    def eval_candidates(left_g, left_h, left_c, cand):
        right_g = parent_g - left_g
        right_h = tot_h - left_h
        right_c = parent_c - left_c
        ok = (cand
              & (left_c >= min_data) & (right_c >= min_data)
              & (left_h >= min_hess) & (right_h >= min_hess))
        gain = (leaf_split_gain(left_g, left_h, l1, l2)
                + leaf_split_gain(right_g, right_h, l1, l2))
        ok = ok & (gain > min_gain_shift)
        return jnp.where(ok, gain, neg_inf), left_g, left_h, left_c

    # ---- dir = -1 : accumulate from the right; missing defaults LEFT --------
    keep_m1 = ~((zero_skip & (bins == db)) | (na_excl & (bins == nan_bin)))
    gk = jnp.where(keep_m1, g, 0.0)
    hk = jnp.where(keep_m1, h, 0.0)
    ck = jnp.where(keep_m1, c, 0.0)
    # right side at threshold t = sum of kept bins strictly above t
    right_g_m1 = jnp.sum(gk, axis=1, keepdims=True) - jnp.cumsum(gk, axis=1)
    right_h_m1 = (jnp.sum(hk, axis=1, keepdims=True) - jnp.cumsum(hk, axis=1)
                  + K_EPSILON)
    right_c_m1 = jnp.sum(ck, axis=1, keepdims=True) - jnp.cumsum(ck, axis=1)
    left_g_m1 = parent_g - right_g_m1
    left_h_m1 = tot_h - right_h_m1
    left_c_m1 = parent_c - right_c_m1
    cand_m1 = (feat_valid[:, None]
               & (bins <= nb - 2 - na_excl.astype(jnp.int32))
               & ~(zero_skip & (bins == db - 1)))
    gain_m1, lg_m1, lh_m1, lc_m1 = eval_candidates(left_g_m1, left_h_m1,
                                                   left_c_m1, cand_m1)

    # ---- dir = +1 : accumulate from the left; missing defaults RIGHT --------
    keep_p1 = ~(zero_skip & (bins == db))
    gk = jnp.where(keep_p1, g, 0.0)
    hk = jnp.where(keep_p1, h, 0.0)
    ck = jnp.where(keep_p1, c, 0.0)
    left_g_p1 = jnp.cumsum(gk, axis=1)
    left_h_p1 = jnp.cumsum(hk, axis=1) + K_EPSILON
    left_c_p1 = jnp.cumsum(ck, axis=1)
    cand_p1 = (feat_valid[:, None] & two_dir
               & (bins <= nb - 2)
               & ~(zero_skip & (bins == db)))
    gain_p1, lg_p1, lh_p1, lc_p1 = eval_candidates(left_g_p1, left_h_p1,
                                                   left_c_p1, cand_p1)

    # ---- combine with reference tie-break order -----------------------------
    # [F, 2B]: dir=-1 flipped (largest threshold first), then dir=+1 ascending
    def pack(a_m1, a_p1):
        return jnp.concatenate([jnp.flip(a_m1, axis=1), a_p1], axis=1)

    gains = pack(gain_m1, gain_p1)
    lg = pack(lg_m1, lg_p1)
    lh = pack(lh_m1, lh_p1)
    lc = pack(lc_m1, lc_p1)
    thr = pack(bins, bins)  # pack() flips the dir=-1 half itself
    is_m1 = pack(jnp.ones_like(bins, dtype=bool), jnp.zeros_like(bins, dtype=bool))
    return gains, lg, lh, lc, thr, is_m1, min_gain_shift, tot_h, l1, l2


def _result_from_index(idx, gains_flat, lg, lh, lc, thr, is_m1,
                       parent_g, parent_c, num_bin, missing_type,
                       min_gain_shift, tot_h, l1, l2, nf, b, feature_base=0):
    """Assemble a SplitResult from a flat candidate index into [F, 2B]."""
    neg_inf = jnp.asarray(-jnp.inf, gains_flat.dtype)
    best_gain = gains_flat[idx]
    found = best_gain > neg_inf
    feature_local = (idx // (2 * b)).astype(jnp.int32)
    feature = jnp.where(found, feature_local + feature_base, -1)
    threshold = jnp.where(found, thr.reshape(-1)[idx], 0)
    default_left = jnp.where(found, is_m1.reshape(-1)[idx], True)
    # 2-bin NaN features always default right (feature_histogram.hpp:97-100)
    fi = jnp.clip(feature_local, 0, nf - 1)
    force_right = (num_bin[fi] <= 2) & (missing_type[fi] == MISSING_NAN)
    default_left = jnp.where(found & force_right, False, default_left)

    left_sum_g = lg.reshape(-1)[idx]
    left_sum_h_raw = lh.reshape(-1)[idx]
    left_count = lc.reshape(-1)[idx]
    right_sum_g = parent_g - left_sum_g
    right_sum_h_raw = tot_h - left_sum_h_raw
    right_count = parent_c - left_count

    return SplitResult(
        found=found,
        gain=jnp.where(found, best_gain - min_gain_shift, neg_inf),
        feature=feature,
        threshold=threshold.astype(jnp.int32),
        default_left=default_left,
        left_sum_g=left_sum_g,
        left_sum_h=left_sum_h_raw - K_EPSILON,
        left_count=left_count,
        right_sum_g=right_sum_g,
        right_sum_h=right_sum_h_raw - K_EPSILON,
        right_count=right_count,
        left_output=leaf_output(left_sum_g, left_sum_h_raw, l1, l2),
        right_output=leaf_output(right_sum_g, right_sum_h_raw, l1, l2),
    )


def best_split(hist: jnp.ndarray,
               parent_g: jnp.ndarray, parent_h: jnp.ndarray, parent_c: jnp.ndarray,
               num_bin: jnp.ndarray, missing_type: jnp.ndarray,
               default_bin: jnp.ndarray, feat_valid: jnp.ndarray,
               cfg: SplitConfig, feature_base: int = 0) -> SplitResult:
    """Best numerical split across all features of one leaf.

    hist: [F, B, 3] (sum_g, sum_h, count); num_bin/missing_type/default_bin:
    [F] i32; feat_valid: [F] bool (feature_fraction & non-trivial &
    non-categorical).  parent_*: scalars for the leaf.  ``feature_base``
    offsets the reported feature index (feature-parallel shards).
    """
    f, b, _ = hist.shape
    (gains, lg, lh, lc, thr, is_m1,
     min_gain_shift, tot_h, l1, l2) = _candidate_arrays(
        hist, parent_g, parent_h, parent_c, num_bin, missing_type,
        default_bin, feat_valid, cfg)
    flat = gains.reshape(-1)
    idx = jnp.argmax(flat)
    return _result_from_index(idx, flat, lg, lh, lc, thr, is_m1,
                              parent_g, parent_c, num_bin, missing_type,
                              min_gain_shift, tot_h, l1, l2, f, b,
                              feature_base)


def per_feature_best_gain(hist: jnp.ndarray,
                          parent_g, parent_h, parent_c,
                          num_bin, missing_type, default_bin, feat_valid,
                          cfg: SplitConfig) -> jnp.ndarray:
    """Best gain per feature [F] (gain - gain_shift; -inf if unsplittable).

    Used by the voting-parallel learner to pick each worker's top-k vote
    features (voting_parallel_tree_learner.cpp:255-330)."""
    (gains, _, _, _, _, _, min_gain_shift, _, _, _) = _candidate_arrays(
        hist, parent_g, parent_h, parent_c, num_bin, missing_type,
        default_bin, feat_valid, cfg)
    best = jnp.max(gains, axis=1)
    # parent sums may be per-feature [F, 1] (voting learner's local stats)
    shift = jnp.asarray(min_gain_shift)
    if shift.ndim:
        shift = shift.reshape(-1)
    return jnp.where(best > -jnp.inf, best - shift, -jnp.inf)
