"""Vectorized best-split search over feature histograms.

Reproduces ``FeatureHistogram::FindBestThresholdNumerical`` /
``FindBestThresholdSequence`` (``src/treelearner/feature_histogram.hpp:82-418``)
as one tensor program over all features at once — no per-feature loop:

* two scan directions become two cumulative-sum families over the bin axis;
* the reference's ``continue``/``break`` constraint guards become masks (all
  guarded quantities are monotone along the scan, so masking is equivalent);
* missing-value handling (``MissingType`` none/zero/nan) selects which bins
  contribute to each side and which thresholds are candidates;
* tie-breaking matches the reference scan order: smallest feature index wins,
  then direction -1 (missing defaults left) before +1, then the -1 scan
  prefers the largest threshold and the +1 scan the smallest.

Gain = ``G(left) + G(right) - G(parent) - min_gain_to_split`` with the L1
soft-threshold regularizer ``G(s,h) = max(0, |s|-l1)^2 / (h+l2)``
(``feature_histogram.hpp:255-262``).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

K_EPSILON = 1e-15  # reference kEpsilon
MISSING_NONE, MISSING_ZERO, MISSING_NAN = 0, 1, 2


class SplitConfig(NamedTuple):
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    # categorical split search (feature_histogram.hpp:104-223)
    has_categorical: bool = False   # static: skip the cat path entirely if off
    has_missing: bool = True        # static: False skips the dir=+1 scan —
    #                                 without missing values no feature is
    #                                 two_dir (feature_histogram.hpp runs a
    #                                 single direction then too)
    max_cat_threshold: int = 256
    max_cat_group: int = 64
    cat_smooth_ratio: float = 0.01
    min_cat_smooth: float = 5.0
    max_cat_smooth: float = 100.0
    split_find: str = "chain"       # static: fused (per-direction reductions
    #                                 straight off the hot histogram — no
    #                                 packed [F, 2B, 4] candidate arrays) |
    #                                 chain (the historical pack+argmax
    #                                 formulation, the forced A/B baseline).
    #                                 Both produce bit-identical SplitResults.


class SplitResult(NamedTuple):
    """Best split of one leaf (scalar fields) — analogue of SplitInfo
    (src/treelearner/split_info.hpp:17-120)."""
    found: jnp.ndarray        # bool
    gain: jnp.ndarray         # f32, already reduced by gain_shift; -inf if none
    feature: jnp.ndarray      # i32 index into used features; -1 if none
    threshold: jnp.ndarray    # i32 bin threshold (left: bin <= threshold)
    default_left: jnp.ndarray # bool
    left_sum_g: jnp.ndarray
    left_sum_h: jnp.ndarray
    left_count: jnp.ndarray   # f32 count
    right_sum_g: jnp.ndarray
    right_sum_h: jnp.ndarray
    right_count: jnp.ndarray
    left_output: jnp.ndarray
    right_output: jnp.ndarray
    is_cat: jnp.ndarray       # bool: categorical split (bitset, not threshold)
    cat_bins: jnp.ndarray     # [B] bool: bins routed LEFT (cat splits only)


def leaf_split_gain(sum_g, sum_h, l1, l2):
    """G(s, h) with L1 soft-thresholding (feature_histogram.hpp:255-262)."""
    reg = jnp.maximum(jnp.abs(sum_g) - l1, 0.0)
    return reg * reg / (sum_h + l2)


def leaf_output(sum_g, sum_h, l1, l2):
    """Leaf weight -sign(s)*max(0,|s|-l1)/(h+l2) (feature_histogram.hpp:269-274)."""
    reg = jnp.maximum(jnp.abs(sum_g) - l1, 0.0)
    return -jnp.sign(sum_g) * reg / (sum_h + l2)


def _candidate_arrays(hist, parent_g, parent_h, parent_c,
                      num_bin, missing_type, default_bin, feat_valid, cfg):
    """Packed per-candidate arrays [F, 2B] in reference tie-break order:
    per feature, dir=-1 candidates (largest threshold first) then dir=+1
    ascending.  Invalid candidates carry gain = -inf."""
    dtype = hist.dtype
    f, b, _ = hist.shape
    bins = lax.broadcasted_iota(jnp.int32, (f, b), 1)
    nb = num_bin[:, None]
    mt = missing_type[:, None]
    db = default_bin[:, None]
    nan_bin = nb - 1

    l1 = jnp.asarray(cfg.lambda_l1, dtype)
    l2 = jnp.asarray(cfg.lambda_l2, dtype)
    min_data = jnp.asarray(cfg.min_data_in_leaf, dtype)
    min_hess = jnp.asarray(cfg.min_sum_hessian_in_leaf, dtype)

    tot_h = parent_h + 2.0 * K_EPSILON
    gain_shift = leaf_split_gain(parent_g, tot_h, l1, l2)
    min_gain_shift = gain_shift + cfg.min_gain_to_split

    two_dir = (nb > 2) & (mt != MISSING_NONE)
    na_excl = two_dir & (mt == MISSING_NAN)    # dir=-1 keeps NaN bin out of right
    zero_skip = two_dir & (mt == MISSING_ZERO)

    neg_inf = jnp.asarray(-jnp.inf, dtype)

    def eval_candidates(left_g, left_h, left_c, cand):
        right_g = parent_g - left_g
        right_h = tot_h - left_h
        right_c = parent_c - left_c
        ok = (cand
              & (left_c >= min_data) & (right_c >= min_data)
              & (left_h >= min_hess) & (right_h >= min_hess))
        gain = (leaf_split_gain(left_g, left_h, l1, l2)
                + leaf_split_gain(right_g, right_h, l1, l2))
        ok = ok & (gain > min_gain_shift)
        return jnp.where(ok, gain, neg_inf), left_g, left_h, left_c

    # ---- dir = -1 : accumulate from the right; missing defaults LEFT --------
    # channel-stacked: ONE masked [F, B, 3] cumsum/sum per direction
    # instead of three — the find chain runs twice per split inside the
    # grow loop, where op LAUNCH count is the cost that matters on TPU
    keep_m1 = ~((zero_skip & (bins == db)) | (na_excl & (bins == nan_bin)))
    kept = jnp.where(keep_m1[:, :, None], hist, 0.0)
    # right side at threshold t = sum of kept bins strictly above t
    right_m1 = (jnp.sum(kept, axis=1, keepdims=True)
                - jnp.cumsum(kept, axis=1))
    right_g_m1 = right_m1[:, :, 0]
    right_h_m1 = right_m1[:, :, 1] + K_EPSILON
    right_c_m1 = right_m1[:, :, 2]
    left_g_m1 = parent_g - right_g_m1
    left_h_m1 = tot_h - right_h_m1
    left_c_m1 = parent_c - right_c_m1
    cand_m1 = (feat_valid[:, None]
               & (bins <= nb - 2 - na_excl.astype(jnp.int32))
               & ~(zero_skip & (bins == db - 1)))
    gain_m1, lg_m1, lh_m1, lc_m1 = eval_candidates(left_g_m1, left_h_m1,
                                                   left_c_m1, cand_m1)

    # ---- dir = +1 : accumulate from the left; missing defaults RIGHT --------
    # without missing values NO feature is two_dir, so the whole +1 half
    # is statically skipped (candidate width B instead of 2B) — exactly
    # the reference's single-direction scan for missing-free features
    stk_m1 = jnp.stack([gain_m1, lg_m1, lh_m1, lc_m1], axis=-1)
    if not cfg.has_missing:
        packed = jnp.flip(stk_m1, axis=1)
        thr = jnp.flip(bins, axis=1)
        is_m1 = jnp.ones_like(bins, dtype=bool)
        return packed, thr, is_m1, min_gain_shift, tot_h, l1, l2

    keep_p1 = ~(zero_skip & (bins == db))
    kept = jnp.where(keep_p1[:, :, None], hist, 0.0)
    left_p1 = jnp.cumsum(kept, axis=1)
    left_g_p1 = left_p1[:, :, 0]
    left_h_p1 = left_p1[:, :, 1] + K_EPSILON
    left_c_p1 = left_p1[:, :, 2]
    cand_p1 = (feat_valid[:, None] & two_dir
               & (bins <= nb - 2)
               & ~(zero_skip & (bins == db)))
    gain_p1, lg_p1, lh_p1, lc_p1 = eval_candidates(left_g_p1, left_h_p1,
                                                   left_c_p1, cand_p1)

    # ---- combine with reference tie-break order -----------------------------
    # [F, 2B]: dir=-1 flipped (largest threshold first), then dir=+1
    # ascending.  The four per-candidate arrays travel as ONE stacked
    # [F, 2B, 4] tensor (gain, lg, lh, lc): one flip + one concat instead
    # of four of each, and the assembly reads all four with one gather.
    def pack(a_m1, a_p1):
        return jnp.concatenate([jnp.flip(a_m1, axis=1), a_p1], axis=1)

    stk_p1 = jnp.stack([gain_p1, lg_p1, lh_p1, lc_p1], axis=-1)
    packed = jnp.concatenate([jnp.flip(stk_m1, axis=1), stk_p1], axis=1)
    thr = pack(bins, bins)  # pack() flips the dir=-1 half itself
    is_m1 = pack(jnp.ones_like(bins, dtype=bool), jnp.zeros_like(bins, dtype=bool))
    return packed, thr, is_m1, min_gain_shift, tot_h, l1, l2


def _categorical_candidates(hist, parent_g, parent_h, parent_c,
                            num_bin, is_cat, feat_valid, missing_type,
                            cfg: SplitConfig):
    """Categorical split candidates (FindBestThresholdCategorical,
    feature_histogram.hpp:104-223), vectorized over features.

    Bins of each categorical feature are sorted by smoothed grad/hess ratio;
    candidates are prefixes of the sorted order (dir=+1) and of the reversed
    order (dir=-1), up to ``max_cat_threshold`` positions, gated by the
    ``max_cat_group`` accounting which is a short ``lax.scan``.

    Returns (gains [F, 2T], lg, lh, lc, pos [F, 2T], is_p1 [F, 2T],
    order [F, B], used_bin [F]) with candidate order: dir=+1 ascending i,
    then dir=-1 ascending i (the reference's dirs = {1, -1} loop).
    """
    dtype = hist.dtype
    f, b, _ = hist.shape
    T = min(int(cfg.max_cat_threshold), b)
    g = hist[:, :, 0]
    h = hist[:, :, 1]
    nb = num_bin                                  # [F]
    # used_bin = num_bin - 1 + (missing == None): the overflow/NaN bin is
    # excluded from the scan unless the mapper saw every category
    used_bin = nb - 1 + (missing_type == MISSING_NONE).astype(jnp.int32)

    l1 = jnp.asarray(cfg.lambda_l1, dtype)
    l2 = jnp.asarray(cfg.lambda_l2, dtype)
    min_data = jnp.asarray(cfg.min_data_in_leaf, dtype)
    min_hess = jnp.asarray(cfg.min_sum_hessian_in_leaf, dtype)

    pg = jnp.broadcast_to(jnp.asarray(parent_g, dtype), (f, 1))[:, 0] \
        if jnp.ndim(parent_g) else jnp.full((f,), parent_g, dtype)
    ph = jnp.broadcast_to(jnp.asarray(parent_h, dtype), (f, 1))[:, 0] \
        if jnp.ndim(parent_h) else jnp.full((f,), parent_h, dtype)
    pc = jnp.broadcast_to(jnp.asarray(parent_c, dtype), (f, 1))[:, 0] \
        if jnp.ndim(parent_c) else jnp.full((f,), parent_c, dtype)
    tot_h = ph + 2.0 * K_EPSILON
    gain_shift = leaf_split_gain(pg, tot_h, l1, l2)
    min_gain_shift = gain_shift + cfg.min_gain_to_split      # [F]

    # smoothing (feature_histogram.hpp:122-126)
    smooth_hess = jnp.minimum(
        cfg.max_cat_smooth,
        jnp.maximum(cfg.cat_smooth_ratio * pc / jnp.maximum(nb, 1),
                    cfg.min_cat_smooth))
    smooth_grad = smooth_hess * pg / jnp.where(ph == 0, 1.0, ph)

    bins_iota = lax.broadcasted_iota(jnp.int32, (f, b), 1)
    in_scan = bins_iota < used_bin[:, None]
    key = (g + smooth_grad[:, None]) / (h + smooth_hess[:, None])
    key = jnp.where(in_scan, key, jnp.inf)        # invalid bins sort last
    order = jnp.argsort(key, axis=1)              # [F, B] bin ids, ascending

    # channel-stacked: ONE sorted gather / cumsum / prefix read over
    # [F, B, 3] instead of three of each (same op-launch rationale as the
    # numerical scan above)
    shist = jnp.take_along_axis(hist, order[:, :, None], axis=1)
    cs = jnp.cumsum(shist, axis=1)                # [F, B, 3]
    last = jnp.clip(used_bin - 1, 0, b - 1)[:, None]
    tot = jnp.take_along_axis(cs, last[:, :, None], axis=1)[:, 0]  # [F, 3]
    tg, th_, tc = tot[:, 0], tot[:, 1], tot[:, 2]

    pos = jnp.arange(T, dtype=jnp.int32)[None, :]            # [1, T]
    # dir=+1: prefix of the sorted order
    take_p1 = jnp.minimum(pos, b - 1)
    pre_p1 = jnp.take_along_axis(cs, take_p1[:, :, None], axis=1)  # [F, T, 3]
    lg_p1 = pre_p1[:, :, 0]
    lh_p1 = pre_p1[:, :, 1]
    lc_p1 = pre_p1[:, :, 2]
    csc_sorted_c = jnp.take_along_axis(shist[:, :, 2], take_p1, axis=1)
    # dir=-1: prefix of the reversed order = totals minus cumsum at ub-2-i
    idx_m1 = used_bin[:, None] - 2 - pos                     # may be < 0
    clip_m1 = jnp.clip(idx_m1, 0, b - 1)
    pre_m1 = jnp.where((idx_m1 >= 0)[:, :, None],
                       jnp.take_along_axis(cs, clip_m1[:, :, None], axis=1),
                       0.0)                                  # [F, T, 3]
    lg_m1 = tg[:, None] - pre_m1[:, :, 0]
    lh_m1 = th_[:, None] - pre_m1[:, :, 1]
    lc_m1 = tc[:, None] - pre_m1[:, :, 2]
    step_m1 = jnp.clip(used_bin[:, None] - 1 - pos, 0, b - 1)
    sc_m1 = jnp.take_along_axis(shist[:, :, 2], step_m1, axis=1)

    # dir=-1 skipped when full-categorical and 2*max_cat_threshold covers all
    # bins (feature_histogram.hpp:134-138)
    dir_m1_on = ~((missing_type == MISSING_NONE)
                  & (2 * cfg.max_cat_threshold >= nb))

    cat_ok = feat_valid & is_cat                             # [F]
    base_valid = cat_ok[:, None] & (pos < used_bin[:, None]) # [F, T]

    def stack2(p1, m1):                                      # → [F, 2, T]
        return jnp.stack([p1, m1], axis=1)

    lg2 = stack2(lg_p1, lg_m1)
    lh2 = stack2(lh_p1, lh_m1) + K_EPSILON
    lc2 = stack2(lc_p1, lc_m1)
    step_c = stack2(csc_sorted_c, sc_m1)
    valid2 = stack2(base_valid, base_valid & dir_m1_on[:, None])

    rg2 = pg[:, None, None] - lg2
    rh2 = tot_h[:, None, None] - lh2
    rc2 = pc[:, None, None] - lc2
    cont_ok = (lc2 >= min_data) & (lh2 >= min_hess)
    right_ok = (rc2 >= min_data) & (rh2 >= min_hess)

    # max_cat_group gating: sequential accounting over candidate positions
    # (feature_histogram.hpp:142-147,169-177) — a T-step scan over [F, 2]
    rest0 = jnp.full((f, 2), cfg.max_cat_group, dtype)
    mdpg0 = jnp.maximum(1.0, jnp.floor(pc / cfg.max_cat_group))[:, None] \
        * jnp.ones((1, 2), dtype)
    cnt0 = jnp.zeros((f, 2), dtype)

    def group_step(state, xs):
        cnt, rest, mdpg = state
        step_cnt, cont, rok, rcnt = xs
        cnt = cnt + step_cnt
        accept = cont & rok & (cnt >= mdpg)
        new_rest = jnp.where(accept, rest - 1.0, rest)
        new_mdpg = jnp.where(
            accept & (new_rest > 0),
            jnp.maximum(1.0, jnp.floor(rcnt / jnp.maximum(new_rest, 1.0))),
            mdpg)
        new_cnt = jnp.where(accept, 0.0, cnt)
        return (new_cnt, new_rest, new_mdpg), accept

    xs = (jnp.moveaxis(step_c, 2, 0), jnp.moveaxis(cont_ok, 2, 0),
          jnp.moveaxis(right_ok, 2, 0), jnp.moveaxis(rc2, 2, 0))
    _, accepts = lax.scan(group_step, (cnt0, rest0, mdpg0), xs)
    accept2 = jnp.moveaxis(accepts, 0, 2)                    # [F, 2, T]

    gain2 = (leaf_split_gain(lg2, lh2, l1, l2)
             + leaf_split_gain(rg2, rh2, l1, l2))
    ok = valid2 & cont_ok & right_ok & accept2 \
        & (gain2 > min_gain_shift[:, None, None])
    gain2 = jnp.where(ok, gain2, -jnp.inf)

    def flat(a):                                             # [F, 2, T] → [F, 2T]
        return a.reshape(f, 2 * T)

    pos2 = jnp.broadcast_to(pos[None, :, :], (f, 2, T))
    is_p1 = jnp.broadcast_to(
        jnp.asarray([True, False])[None, :, None], (f, 2, T))
    return (flat(gain2), flat(lg2), flat(lh2), flat(lc2),
            flat(pos2), flat(is_p1), order, used_bin, min_gain_shift, tot_h,
            l1, l2)


class FusedSplitCtx(NamedTuple):
    """Loop-invariant precomputation of the fused split-find scan.

    Every field depends only on feature metadata + static config — constant
    across a tree's ~L splits — so the grower builds it ONCE per grow call
    (strategy ``setup``) and the while body stops re-deriving the bin iota
    and keep/candidate masks every split the way the chain formulation
    does.  ``keep_p1``/``cand_p1``/``force_right`` are ``None`` when the
    dataset has no missing values (the dir=+1 scan is statically skipped,
    exactly like the chain path)."""
    bins: jnp.ndarray           # [F, B] i32 bin iota
    keep_m1: jnp.ndarray        # [F, B] bool: bins feeding the dir=-1 scan
    cand_m1: jnp.ndarray        # [F, B] bool: dir=-1 candidacy (sans
    #                             feat_valid, which changes per leaf)
    keep_p1: jnp.ndarray        # [F, B] bool | None
    cand_p1: jnp.ndarray        # [F, B] bool | None
    force_right: jnp.ndarray    # [F] bool | None: 2-bin NaN features
    #                             always default right


def make_fused_ctx(num_bin, missing_type, default_bin, num_bins: int,
                   cfg: SplitConfig) -> FusedSplitCtx:
    """Build the loop-invariant fused-scan masks (same boolean algebra as
    ``_candidate_arrays`` — booleans are exact, so hoisting them out of the
    loop body is trivially bit-neutral)."""
    f = num_bin.shape[0]
    b = num_bins
    bins = lax.broadcasted_iota(jnp.int32, (f, b), 1)
    nb = num_bin[:, None]
    mt = missing_type[:, None]
    db = default_bin[:, None]
    nan_bin = nb - 1
    two_dir = (nb > 2) & (mt != MISSING_NONE)
    na_excl = two_dir & (mt == MISSING_NAN)
    zero_skip = two_dir & (mt == MISSING_ZERO)
    keep_m1 = ~((zero_skip & (bins == db)) | (na_excl & (bins == nan_bin)))
    cand_m1 = ((bins <= nb - 2 - na_excl.astype(jnp.int32))
               & ~(zero_skip & (bins == db - 1)))
    if not cfg.has_missing:
        return FusedSplitCtx(bins, keep_m1, cand_m1, None, None, None)
    keep_p1 = ~(zero_skip & (bins == db))
    cand_p1 = two_dir & (bins <= nb - 2) & ~(zero_skip & (bins == db))
    force_right = (num_bin <= 2) & (missing_type == MISSING_NAN)
    return FusedSplitCtx(bins, keep_m1, cand_m1, keep_p1, cand_p1,
                         force_right)


def _fused_numerical(hist, parent_g, parent_h, parent_c,
                     num_bin, missing_type, default_bin, feat_valid,
                     cfg: SplitConfig, feature_base, ctx: FusedSplitCtx):
    """Fused best-split scan: per-direction reductions straight off the
    (still hot) histogram, emitting only the winning ``SplitResult`` —
    the packed ``[F, 2B, 4]`` candidate array, its flip/concat assembly,
    and the candidate-order ``thr``/``is_m1`` tables of the chain path
    never materialize.

    Bit-identity with the chain: every float value entering the selection
    (the masked cumulative sums and ``eval_candidates`` gain algebra) is
    computed by the SAME primitive sequence; only the selection is
    restructured — per-direction row argmax (over the dir=-1 gains
    REVERSED, preserving the largest-threshold-first tie-break) combined
    by the exact packed-order priority (dir=-1 block before dir=+1,
    smallest feature index first), which is equivalent to the chain's
    first-max flat argmax candidate for candidate.

    Returns ``(SplitResult, per_feature_ok [F])``."""
    dtype = hist.dtype
    f, b, _ = hist.shape
    if ctx is None:
        ctx = make_fused_ctx(num_bin, missing_type, default_bin, b, cfg)

    l1 = jnp.asarray(cfg.lambda_l1, dtype)
    l2 = jnp.asarray(cfg.lambda_l2, dtype)
    min_data = jnp.asarray(cfg.min_data_in_leaf, dtype)
    min_hess = jnp.asarray(cfg.min_sum_hessian_in_leaf, dtype)
    tot_h = parent_h + 2.0 * K_EPSILON
    gain_shift = leaf_split_gain(parent_g, tot_h, l1, l2)
    min_gain_shift = gain_shift + cfg.min_gain_to_split
    neg_inf = jnp.asarray(-jnp.inf, dtype)

    def eval_gains(left_g, left_h, left_c, cand):
        # identical arithmetic to the chain's eval_candidates
        right_g = parent_g - left_g
        right_h = tot_h - left_h
        right_c = parent_c - left_c
        ok = (cand
              & (left_c >= min_data) & (right_c >= min_data)
              & (left_h >= min_hess) & (right_h >= min_hess))
        gain = (leaf_split_gain(left_g, left_h, l1, l2)
                + leaf_split_gain(right_g, right_h, l1, l2))
        ok = ok & (gain > min_gain_shift)
        return jnp.where(ok, gain, neg_inf)

    # ---- dir = -1 : accumulate from the right; missing defaults LEFT ----
    # without missing values no bin is ever excluded (two_dir is all-False
    # so keep_m1 is all-True) — the masking select is the identity and is
    # statically skipped (where(True, hist, 0) == hist bit for bit)
    kept = (jnp.where(ctx.keep_m1[:, :, None], hist, 0.0)
            if cfg.has_missing else hist)
    right_m1 = (jnp.sum(kept, axis=1, keepdims=True)
                - jnp.cumsum(kept, axis=1))
    lg_m1 = parent_g - right_m1[:, :, 0]
    lh_m1 = tot_h - (right_m1[:, :, 1] + K_EPSILON)
    lc_m1 = parent_c - right_m1[:, :, 2]
    gains_m1 = eval_gains(lg_m1, lh_m1, lc_m1,
                          feat_valid[:, None] & ctx.cand_m1)
    # chain order puts dir=-1 candidates largest-threshold-first: the row
    # argmax over the REVERSED gains is exactly that order's first max
    flipped_m1 = gains_m1[:, ::-1]
    jm = jnp.argmax(flipped_m1, axis=1)
    gm = jnp.max(flipped_m1, axis=1)

    if cfg.has_missing:
        # ---- dir = +1 : accumulate from the left; missing defaults RIGHT
        kept = jnp.where(ctx.keep_p1[:, :, None], hist, 0.0)
        left_p1 = jnp.cumsum(kept, axis=1)
        lg_p1 = left_p1[:, :, 0]
        lh_p1 = left_p1[:, :, 1] + K_EPSILON
        lc_p1 = left_p1[:, :, 2]
        gains_p1 = eval_gains(lg_p1, lh_p1, lc_p1,
                              feat_valid[:, None] & ctx.cand_p1)
        jp = jnp.argmax(gains_p1, axis=1)
        gp = jnp.max(gains_p1, axis=1)
        best_f = jnp.maximum(gm, gp)     # per-feature winner, dir=-1 first
    else:
        best_f = gm

    # smallest feature index wins ties — argmax's first-max, like the
    # chain's feature-major flat argmax
    fi = jnp.argmax(best_f).astype(jnp.int32)
    best_gain = best_f[fi]
    found = best_gain > neg_inf

    bin_m1 = (b - 1 - jm[fi]).astype(jnp.int32)
    if cfg.has_missing:
        use_m1 = gm[fi] >= gp[fi]        # ties: dir=-1 precedes dir=+1
        pos_p1 = jp[fi].astype(jnp.int32)
        threshold = jnp.where(use_m1, bin_m1, pos_p1)
        left_sum_g = jnp.where(use_m1, lg_m1[fi, bin_m1], lg_p1[fi, pos_p1])
        left_sum_h_raw = jnp.where(use_m1, lh_m1[fi, bin_m1],
                                   lh_p1[fi, pos_p1])
        left_count = jnp.where(use_m1, lc_m1[fi, bin_m1], lc_p1[fi, pos_p1])
        default_left = jnp.where(found, use_m1, True)
        # 2-bin NaN features always default right (chain _result_from_index)
        default_left = jnp.where(found & ctx.force_right[fi], False,
                                 default_left)
    else:
        threshold = bin_m1
        left_sum_g = lg_m1[fi, bin_m1]
        left_sum_h_raw = lh_m1[fi, bin_m1]
        left_count = lc_m1[fi, bin_m1]
        default_left = jnp.ones((), bool)   # chain: is_m1 always True here

    right_sum_g = parent_g - left_sum_g
    right_sum_h_raw = tot_h - left_sum_h_raw
    right_count = parent_c - left_count

    res = SplitResult(
        found=found,
        gain=jnp.where(found, best_gain - min_gain_shift, neg_inf),
        feature=jnp.where(found, fi + feature_base, -1),
        threshold=jnp.where(found, threshold, 0).astype(jnp.int32),
        default_left=default_left,
        left_sum_g=left_sum_g,
        left_sum_h=left_sum_h_raw - K_EPSILON,
        left_count=left_count,
        right_sum_g=right_sum_g,
        right_sum_h=right_sum_h_raw - K_EPSILON,
        right_count=right_count,
        left_output=leaf_output(left_sum_g, left_sum_h_raw, l1, l2),
        right_output=leaf_output(right_sum_g, right_sum_h_raw, l1, l2),
        is_cat=jnp.zeros((), bool),
        cat_bins=jnp.zeros((b,), bool),
    )
    return res, best_f > neg_inf


def _result_from_index(idx, packed, thr, is_m1,
                       parent_g, parent_c, num_bin, missing_type,
                       min_gain_shift, tot_h, l1, l2, nf, b, feature_base=0):
    """Assemble a SplitResult from a flat candidate index into [F, 2B]
    (``packed`` stacks (gain, lg, lh, lc) on the last axis)."""
    neg_inf = jnp.asarray(-jnp.inf, packed.dtype)
    row = packed.reshape(-1, 4)[idx]          # one gather: all four values
    best_gain = row[0]
    found = best_gain > neg_inf
    # candidate width is B (single-direction, no missing) or 2B
    feature_local = (idx // packed.shape[1]).astype(jnp.int32)
    feature = jnp.where(found, feature_local + feature_base, -1)
    threshold = jnp.where(found, thr.reshape(-1)[idx], 0)
    default_left = jnp.where(found, is_m1.reshape(-1)[idx], True)
    # 2-bin NaN features always default right (feature_histogram.hpp:97-100)
    fi = jnp.clip(feature_local, 0, nf - 1)
    force_right = (num_bin[fi] <= 2) & (missing_type[fi] == MISSING_NAN)
    default_left = jnp.where(found & force_right, False, default_left)

    left_sum_g = row[1]
    left_sum_h_raw = row[2]
    left_count = row[3]
    right_sum_g = parent_g - left_sum_g
    right_sum_h_raw = tot_h - left_sum_h_raw
    right_count = parent_c - left_count

    return SplitResult(
        found=found,
        gain=jnp.where(found, best_gain - min_gain_shift, neg_inf),
        feature=feature,
        threshold=threshold.astype(jnp.int32),
        default_left=default_left,
        left_sum_g=left_sum_g,
        left_sum_h=left_sum_h_raw - K_EPSILON,
        left_count=left_count,
        right_sum_g=right_sum_g,
        right_sum_h=right_sum_h_raw - K_EPSILON,
        right_count=right_count,
        left_output=leaf_output(left_sum_g, left_sum_h_raw, l1, l2),
        right_output=leaf_output(right_sum_g, right_sum_h_raw, l1, l2),
        is_cat=jnp.zeros((), bool),
        cat_bins=jnp.zeros((b,), bool),
    )


def _cat_result_from_index(idx, gains_flat, lg, lh, lc, pos, is_p1,
                           order, used_bin, parent_g, parent_c,
                           min_gain_shift, tot_h, l1, l2, nf, b, t2,
                           feature_base=0) -> SplitResult:
    """Assemble a categorical SplitResult from a flat index into [F, 2T]."""
    neg_inf = jnp.asarray(-jnp.inf, gains_flat.dtype)
    best_gain = gains_flat[idx]
    found = best_gain > neg_inf
    feature_local = (idx // t2).astype(jnp.int32)
    fi = jnp.clip(feature_local, 0, nf - 1)
    p = pos.reshape(-1)[idx]
    p1 = is_p1.reshape(-1)[idx]
    ub = used_bin[fi]

    # bins routed left = sorted positions [0..p] (dir=+1) or
    # [ub-1-p..ub-1] (dir=-1); rank = inverse permutation of the sort
    order_row = lax.dynamic_index_in_dim(order, fi, axis=0, keepdims=False)
    rank = jnp.argsort(order_row)                 # rank[bin] = sorted position
    member = jnp.where(p1, rank <= p, rank >= ub - 1 - p) & (rank < ub)
    cat_bins = found & member

    shift = min_gain_shift[fi] if jnp.ndim(min_gain_shift) else min_gain_shift
    toth = tot_h[fi] if jnp.ndim(tot_h) else tot_h
    pg = parent_g[fi] if jnp.ndim(parent_g) else parent_g
    pc = parent_c[fi] if jnp.ndim(parent_c) else parent_c

    left_sum_g = lg.reshape(-1)[idx]
    left_sum_h_raw = lh.reshape(-1)[idx]
    left_count = lc.reshape(-1)[idx]
    right_sum_g = pg - left_sum_g
    right_sum_h_raw = toth - left_sum_h_raw
    right_count = pc - left_count

    return SplitResult(
        found=found,
        gain=jnp.where(found, best_gain - shift, neg_inf),
        feature=jnp.where(found, fi + feature_base, -1),
        threshold=jnp.zeros((), jnp.int32),
        default_left=jnp.zeros((), bool),        # cat splits default right
        left_sum_g=left_sum_g,
        left_sum_h=left_sum_h_raw - K_EPSILON,
        left_count=left_count,
        right_sum_g=right_sum_g,
        right_sum_h=right_sum_h_raw - K_EPSILON,
        right_count=right_count,
        left_output=leaf_output(left_sum_g, left_sum_h_raw, l1, l2),
        right_output=leaf_output(right_sum_g, right_sum_h_raw, l1, l2),
        is_cat=found,
        cat_bins=cat_bins,
    )


def best_split(hist: jnp.ndarray,
               parent_g: jnp.ndarray, parent_h: jnp.ndarray, parent_c: jnp.ndarray,
               num_bin: jnp.ndarray, missing_type: jnp.ndarray,
               default_bin: jnp.ndarray, feat_valid: jnp.ndarray,
               cfg: SplitConfig, feature_base: int = 0,
               is_cat: jnp.ndarray = None, with_feat_ok: bool = False,
               fused_ctx: FusedSplitCtx = None):
    """Best split (numerical or categorical) across all features of one leaf.

    hist: [F, B, 3] (sum_g, sum_h, count); num_bin/missing_type/default_bin:
    [F] i32; feat_valid: [F] bool (feature_fraction & non-trivial); is_cat:
    [F] bool (None ⇒ all numerical).  parent_*: scalars for the leaf.
    ``feature_base`` offsets the reported feature index (feature-parallel
    shards).

    ``with_feat_ok=True`` additionally returns the per-feature
    ``is_splittable`` flags [F] — True when the feature produced ANY
    candidate beating min_gain_shift on this leaf.  The reference prunes
    features whose parent leaf had no such candidate from the entire
    subtree (serial_tree_learner.cpp:406-417), so the grower records
    these flags per leaf and gates children's scans with them.

    ``cfg.split_find`` selects the numerical-scan formulation: ``fused``
    (per-direction reductions, no packed candidate arrays; optionally fed
    the loop-invariant ``fused_ctx`` the grower hoists) or ``chain`` (the
    historical pack+argmax form).  Both are bit-identical — pinned in
    tests/test_split_find.py; the categorical scan is shared.
    """
    f, b, _ = hist.shape
    use_cat = cfg.has_categorical and is_cat is not None
    num_valid = feat_valid & ~is_cat if use_cat else feat_valid
    if cfg.split_find == "fused":
        num_res, num_ok = _fused_numerical(
            hist, parent_g, parent_h, parent_c, num_bin, missing_type,
            default_bin, num_valid, cfg, feature_base, fused_ctx)
        if not use_cat:
            if with_feat_ok:
                return num_res, num_ok
            return num_res
        return _combine_categorical(
            hist, num_res, num_ok, parent_g, parent_h, parent_c, num_bin,
            missing_type, is_cat, feat_valid, cfg, feature_base, f, b,
            with_feat_ok)
    (packed, thr, is_m1,
     min_gain_shift, tot_h, l1, l2) = _candidate_arrays(
        hist, parent_g, parent_h, parent_c, num_bin, missing_type,
        default_bin, num_valid, cfg)
    gains = packed[:, :, 0]
    idx = jnp.argmax(gains.reshape(-1))
    num_res = _result_from_index(idx, packed, thr, is_m1,
                                 parent_g, parent_c, num_bin, missing_type,
                                 min_gain_shift, tot_h, l1, l2, f, b,
                                 feature_base)
    if not use_cat:
        if with_feat_ok:
            return num_res, jnp.max(gains, axis=1) > -jnp.inf
        return num_res
    return _combine_categorical(
        hist, num_res, jnp.max(gains, axis=1) > -jnp.inf, parent_g,
        parent_h, parent_c, num_bin, missing_type, is_cat, feat_valid, cfg,
        feature_base, f, b, with_feat_ok)


def _combine_categorical(hist, num_res, num_ok, parent_g, parent_h, parent_c,
                         num_bin, missing_type, is_cat, feat_valid,
                         cfg: SplitConfig, feature_base, f, b, with_feat_ok):
    """Categorical scan + numerical-vs-categorical combine, shared by the
    chain and fused numerical paths (the categorical candidate machinery is
    identical either way)."""
    dtype = hist.dtype
    l1 = jnp.asarray(cfg.lambda_l1, dtype)
    l2 = jnp.asarray(cfg.lambda_l2, dtype)
    (cgains, clg, clh, clc, cpos, cp1, order, used_bin,
     c_shift, c_tot_h, _, _) = _categorical_candidates(
        hist, parent_g, parent_h, parent_c, num_bin, is_cat, feat_valid,
        missing_type, cfg)
    cflat = cgains.reshape(-1)
    cidx = jnp.argmax(cflat)
    cat_res = _cat_result_from_index(cidx, cflat, clg, clh, clc, cpos, cp1,
                                     order, used_bin, parent_g, parent_c,
                                     c_shift, c_tot_h, l1, l2, f, b,
                                     cgains.shape[1], feature_base)
    # features are either numerical or categorical; reproduce the serial
    # learner's feature-major tie-break (smallest feature index wins)
    pick_cat = cat_res.found & (~num_res.found
                                | (cat_res.gain > num_res.gain)
                                | ((cat_res.gain == num_res.gain)
                                   & (cat_res.feature < num_res.feature)))
    res = jax.tree.map(lambda a, c: jnp.where(pick_cat, c, a),
                       num_res, cat_res)
    if with_feat_ok:
        ok = jnp.where(is_cat, jnp.max(cgains, axis=1) > -jnp.inf, num_ok)
        return res, ok
    return res


def per_feature_best_gain(hist: jnp.ndarray,
                          parent_g, parent_h, parent_c,
                          num_bin, missing_type, default_bin, feat_valid,
                          cfg: SplitConfig, is_cat: jnp.ndarray = None) -> jnp.ndarray:
    """Best gain per feature [F] (gain - gain_shift; -inf if unsplittable).

    Used by the voting-parallel learner to pick each worker's top-k vote
    features (voting_parallel_tree_learner.cpp:255-330)."""
    use_cat = cfg.has_categorical and is_cat is not None
    num_valid = feat_valid & ~is_cat if use_cat else feat_valid
    (packed, _, _, min_gain_shift, _, _, _) = _candidate_arrays(
        hist, parent_g, parent_h, parent_c, num_bin, missing_type,
        default_bin, num_valid, cfg)
    best = jnp.max(packed[:, :, 0], axis=1)
    # parent sums may be per-feature [F, 1] (voting learner's local stats)
    shift = jnp.asarray(min_gain_shift)
    if shift.ndim:
        shift = shift.reshape(-1)
    out = jnp.where(best > -jnp.inf, best - shift, -jnp.inf)
    if use_cat:
        (cgains, _, _, _, _, _, _, _, c_shift, _, _, _) = \
            _categorical_candidates(hist, parent_g, parent_h, parent_c,
                                    num_bin, is_cat, feat_valid,
                                    missing_type, cfg)
        cbest = jnp.max(cgains, axis=1)
        cout = jnp.where(cbest > -jnp.inf, cbest - c_shift, -jnp.inf)
        out = jnp.maximum(out, cout)
    return out
