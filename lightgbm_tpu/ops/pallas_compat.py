"""Version tolerance for the Pallas TPU API surface.

The Pallas TPU namespace renamed ``TPUMemorySpace`` -> ``MemorySpace`` and
``TPUCompilerParams`` -> ``CompilerParams`` across JAX releases; the
container images this repo runs in have carried BOTH generations (the
round-5 kernels were written against the new names and the whole
``tests/test_compact.py`` module failed with ``AttributeError`` on a
jax 0.4.x image).  Every kernel module imports the names from here so a
runtime jax downgrade/upgrade can never take out the kernel tier again.
"""
from jax.experimental.pallas import tpu as pltpu

MemorySpace = getattr(pltpu, "MemorySpace", None) \
    or getattr(pltpu, "TPUMemorySpace")
CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")
