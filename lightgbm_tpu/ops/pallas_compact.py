"""Pallas two-pass compaction partition kernel.

The TPU answer to the cost class the reference never pays: its
``DataPartition::Split`` (src/treelearner/data_partition.hpp:94-146) is a
cache-resident two-pointer sweep, ~1 ns/row on a Xeon core; the XLA
translations measured on the v5e all sit in the per-element-random class
(rank scatter ~20 ns/elem; payload sort is many full-window passes).  This
kernel is the designed escape (docs/ROUND4_NOTES.md "parked design"): a
stable two-way compaction expressed as block-local one-hot permutation
matmuls on the MXU plus manually-sequenced dynamic-offset DMA writes —
all sequential HBM traffic, projected ~5 ns/row.

Shape contract: the window is a [size, CP] f32 matrix (size % 512 == 0)
whose columns are [left_mask, right_mask, rank_left, rank_right, order,
*payload_halves]; every value must be exactly representable in f32
(masks 0/1, block-local ranks < 512, order < 2**24, u32 payload split
into u16 halves by :func:`compact_window`, which the grower's
``partition_branch`` drives with the same packed-word/bitcast payload
marshalling the sort path uses).  The stable ranks are precomputed in
XLA so the kernel body is pure compare + matmul + DMA.

Algorithm (grid = (2 phases, size/512 blocks), sequential on TPU):

* XLA pre-pass computes per-(phase, block) output BASES: exclusive cumsum
  of per-block left counts; right bases offset by the total left count.
  Bases ride in as scalar prefetch.
* Each grid step loads its [512, CP] block, reads the phase's
  precomputed stable rank column, applies it as a [512, 512] one-hot
  permutation matmul (stability = cumsum order; exactness = one nonzero
  per output row in f32), and DMAs the full 512-row result to
  ``out[base : base+512]``.
* Garbage tails: each step writes all 512 rows, but bases ascend within a
  phase and the right phase starts at the total left count, so every
  step's tail is overwritten by its successor; the final <=512-row spill
  lands in the +512 scratch margin of the output buffer, and rows past
  ``cnt`` are restored by the caller's ``where(j < cnt, ...)`` merge.

The kernel never scatters and never reads HBM at a random address: all
input blocks are sequential reads, all output DMAs are sequential bursts.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_compat import CompilerParams, MemorySpace

BLK = 512       # rows per block; every gather-bucket size divides it
LANES = 128     # output DMA width must be a multiple of this (Mosaic)


def _compact_kernel(bases_ref, blk_ref, out_ref, scratch, sem):
    p = pl.program_id(0)            # 0 = lefts, 1 = rights
    k = pl.program_id(1)
    nb = pl.num_programs(1)
    blk = blk_ref[...]                                   # [BLK, CP]
    mask = jnp.where(p == 0, blk[:, 0], blk[:, 1])       # [BLK] 0/1 f32
    # block-local stable ranks are PRECOMPUTED in XLA and ride as columns
    # 2/3 — the kernel body is pure compare + dot + DMA, with no in-kernel
    # scan to lower (one less Mosaic surface; round-2 lesson)
    rank = jnp.where(p == 0, blk[:, 2], blk[:, 3]).astype(jnp.int32)
    # one-hot permutation: P[o, i] = (rank[i] == o) & mask[i]
    onehot = ((rank[None, :] ==
               lax.broadcasted_iota(jnp.int32, (BLK, BLK), 0))
              & (mask[None, :] > 0)).astype(jnp.float32)
    # only the DATA columns (4:) are permuted and written out — the mask
    # and rank columns are kernel inputs nobody reads back.  The output
    # width is zero-padded to a 128-lane multiple IN the kernel: Mosaic
    # rejects HBM slices whose minor dim is not tile-aligned ("Slice
    # shape along dimension 1 must be aligned to tiling (128)", proven
    # via v5e AOT compile), so the narrower no-pad form cannot lower.
    # HIGHEST pins the MXU to true-f32 contraction: the default precision
    # may run bf16 passes, which would truncate order ids > 2^16 and
    # payload halves — exactness, not speed, is the contract here
    data = blk[:, 4:]
    out_w = scratch.shape[1]
    if data.shape[1] < out_w:
        data = jnp.concatenate(
            [data, jnp.zeros((BLK, out_w - data.shape[1]), data.dtype)],
            axis=1)
    scratch[...] = jnp.dot(onehot, data,
                           preferred_element_type=jnp.float32,
                           precision=lax.Precision.HIGHEST)
    base = bases_ref[p * nb + k]
    copy = pltpu.make_async_copy(
        scratch, out_ref.at[pl.ds(base, BLK), :], sem)
    copy.start()
    # wait inside the same sequential grid step: successor steps must
    # observe this write before issuing theirs (the overwrite cascade)
    copy.wait()


def compact_pallas(mat: jnp.ndarray, bases: jnp.ndarray,
                   interpret: bool = False) -> jnp.ndarray:
    """mat: [size, CP] f32 with columns [left_mask, right_mask, rank_left,
    rank_right, *data] (data = order + payload halves); bases:
    [2 * size/512] i32 output row offsets per (phase, block).
    Returns [size + 512, ceil((CP-4)/128)*128] f32 — the permuted DATA
    columns, zero-padded to a lane-aligned width (a Mosaic DMA
    requirement); caller slices [:size] rows, reads the first CP-4
    columns, and merges tails.
    """
    size, cp = mat.shape
    assert size % BLK == 0 and cp > 4, (size, cp)
    out_w = -(-(cp - 4) // LANES) * LANES
    nb = size // BLK
    return pl.pallas_call(
        _compact_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(2, nb),
            in_specs=[pl.BlockSpec((BLK, cp), lambda p, k, bases: (k, 0))],
            out_specs=pl.BlockSpec(memory_space=MemorySpace.ANY),
            scratch_shapes=[pltpu.VMEM((BLK, out_w), jnp.float32),
                            pltpu.SemaphoreType.DMA],
        ),
        out_shape=jax.ShapeDtypeStruct((size + BLK, out_w), jnp.float32),
        interpret=interpret,
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(bases, mat)


def compact_window(win: jnp.ndarray, goes_left: jnp.ndarray,
                   valid: jnp.ndarray, payload_u32=(),
                   interpret: bool = False):
    """Stable two-way partition of a window by ``goes_left``.

    win: [size] i32 (values < 2**24); goes_left/valid: [size] bool with
    ``valid`` a prefix mask (j < cnt) and goes_left False outside it;
    payload_u32: extra u32 [size] columns permuted identically.

    Returns (new_win, new_payload_tuple, nl) where rows past the valid
    prefix keep their original values and ``nl`` is the left count (the
    kernel's base computation already pays for it — callers must not
    re-reduce).  Stability and output order match the rank-scatter
    partition bit-for-bit.
    """
    size = win.shape[0]
    gl = goes_left & valid
    gr = valid & ~goes_left
    glf = gl.astype(jnp.float32)
    grf = gr.astype(jnp.float32)
    # per-(phase, block) output bases: lefts pack from 0, rights from nl
    nb = size // BLK
    lcnt = glf.reshape(nb, BLK).sum(axis=1).astype(jnp.int32)
    rcnt = grf.reshape(nb, BLK).sum(axis=1).astype(jnp.int32)
    nl = lcnt.sum()
    lbase = jnp.cumsum(lcnt) - lcnt
    rbase = nl + jnp.cumsum(rcnt) - rcnt
    bases = jnp.concatenate([lbase, rbase])
    # block-local stable ranks, precomputed here so the kernel has no
    # in-kernel scan: global inclusive cumsum minus the block's exclusive
    # prefix, minus 1 (values < 512, f32-exact; garbage on non-side rows
    # is masked by the kernel's mask columns)
    # int32 cumsum: exact at any window size (an f32 running sum would
    # round past 2^24 rows and silently collide two output rows)
    csl = jnp.cumsum(gl.astype(jnp.int32))
    csr = jnp.cumsum(gr.astype(jnp.int32))
    rank_l = csl - jnp.repeat(lbase, BLK) - 1
    rank_r = csr - jnp.repeat(rbase - nl, BLK) - 1
    cols = [glf, grf, rank_l.astype(jnp.float32),
            rank_r.astype(jnp.float32), win.astype(jnp.float32)]
    for c in payload_u32:
        cu = c.astype(jnp.uint32)
        cols.append((cu & 0xffff).astype(jnp.float32))
        cols.append((cu >> 16).astype(jnp.float32))
    # the INPUT matrix is unpadded (BlockSpec reads are block-granular and
    # Mosaic pads vregs internally); the OUTPUT is lane-padded to 128
    # inside the kernel because Mosaic requires DMA slice widths aligned
    # to the tiling — a real write-amplification cost (128 f32/row vs
    # cp-4) that the on-chip A/B prices; it is the cost of lowering, not
    # a choice
    mat = jnp.stack(cols, axis=1)
    out = compact_pallas(mat, bases, interpret=interpret)[:size]
    new_win = jnp.where(valid, out[:, 0].astype(jnp.int32), win)
    new_payload = []
    for i in range(len(payload_u32)):
        lo = out[:, 1 + 2 * i].astype(jnp.uint32)
        hi = out[:, 2 + 2 * i].astype(jnp.uint32)
        merged = lo | (hi << 16)
        new_payload.append(jnp.where(valid, merged,
                                     payload_u32[i].astype(jnp.uint32)))
    return new_win, tuple(new_payload), nl
