"""Objective functions as pure jnp gradient transforms.

The reference's ``ObjectiveFunction`` hierarchy (``src/objective/*.hpp``,
factory ``src/objective/objective_function.cpp:10-36``) becomes a registry of
classes whose ``get_gradients(score) -> (grad, hess)`` are traced into the
boosting step's jit program.  Host-side setup (label statistics, query
boundaries, lookup tables) happens once in ``init``.

Formulas follow the reference exactly:
* regression L2/L1/huber/fair/poisson — ``regression_objective.hpp``
  (incl. the Gaussian hessian approximation for the non-smooth losses,
  ``common.h:486-495``, and 2.0.5's linear-score Poisson variant);
* binary logloss with sigmoid scaling / is_unbalance / scale_pos_weight —
  ``binary_objective.hpp:13-157``;
* multiclass softmax (K trees per iteration, ``h = 2p(1-p)``) and OVA —
  ``multiclass_objective.hpp``;
* cross-entropy + weighted "xentlambda" — ``xentropy_objective.hpp:39-268``;
* LambdaRank with |ΔNDCG|-weighted pairwise lambdas —
  ``rank_objective.hpp:19-245`` (vectorized per-query pairwise tensors instead
  of the reference's per-query loops + sigmoid lookup table).

Score layout is ``[K, N]`` (K = trees per iteration), matching the reference's
flattened ``score[k * num_data + i]``.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .config import Config
from .data.metadata import Metadata
from .utils import log

K_MIN_SCORE = -np.inf
_GAUSS_C_MIN = 1.0e-10


class Objective:
    name = "base"
    is_constant_hessian = False
    boost_from_average = False
    need_accurate_prediction = True

    def __init__(self, config: Config):
        self.config = config
        self.num_tree_per_iteration = 1
        self.weights: Optional[jnp.ndarray] = None
        self.labels: Optional[jnp.ndarray] = None
        self.num_data = 0

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.num_data = num_data
        self.labels = jnp.asarray(metadata.label, jnp.float32)
        self.weights = (jnp.asarray(metadata.weight, jnp.float32)
                        if metadata.weight is not None else None)

    def get_gradients(self, score: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        raise NotImplementedError

    def convert_output(self, x):
        return x

    def average_stats(self) -> Tuple[float, float]:
        """(numerator, denominator) whose ratio is the label average that
        boost-from-average transforms.  Expressed as two plain sums so the
        multi-process driver can psum them globally before the transform —
        the reference's GlobalSyncUpByMean discipline."""
        label = np.asarray(self.labels)
        return float(label.sum()), float(len(label))

    def init_from_average(self, avg: float) -> float:
        """Init score from the (globally agreed) label average."""
        return float(avg)

    def to_string(self) -> str:
        return self.name

    def _w(self, g, h):
        if self.weights is None:
            return g, h
        return g * self.weights, h * self.weights


class RegressionL2(Objective):
    """regression_objective.hpp:11-76 (g = s - y, constant hessian)."""
    name = "regression"
    is_constant_hessian = True
    boost_from_average = True

    def get_gradients(self, score):
        g = score[0] - self.labels
        h = jnp.ones_like(g)
        g, h = self._w(g, h)
        return g[None], h[None]


def _gaussian_hessian(score, label, grad, eta, weight):
    """Common::ApproximateHessianWithGaussian (common.h:486-495)."""
    x = jnp.abs(score - label)
    a = 2.0 * jnp.abs(grad) * weight
    c = jnp.maximum((jnp.abs(score) + jnp.abs(label)) * eta, _GAUSS_C_MIN)
    return weight * jnp.exp(-x * x / (2.0 * c * c)) * a / (c * jnp.sqrt(2 * jnp.pi))


class RegressionL1(Objective):
    """regression_objective.hpp:78-156."""
    name = "regression_l1"
    boost_from_average = True

    def get_gradients(self, score):
        s = score[0]
        w = self.weights if self.weights is not None else jnp.ones_like(s)
        g = jnp.where(s > self.labels, 1.0, -1.0) * w
        h = _gaussian_hessian(s, self.labels, g, self.config.gaussian_eta, w)
        return g[None], h[None]


class RegressionHuber(Objective):
    """regression_objective.hpp:158-220 (quadratic inside delta, L1 outside
    with Gaussian-approximated hessian)."""
    name = "huber"
    boost_from_average = True

    def get_gradients(self, score):
        s = score[0]
        delta = self.config.huber_delta
        w = self.weights if self.weights is not None else jnp.ones_like(s)
        diff = s - self.labels
        inside = jnp.abs(diff) <= delta
        g_out = jnp.where(diff >= 0, delta, -delta) * w
        h_out = _gaussian_hessian(s, self.labels, g_out,
                                  self.config.gaussian_eta, w)
        g = jnp.where(inside, diff * w, g_out)
        h = jnp.where(inside, w, h_out)
        return g[None], h[None]


class RegressionFair(Objective):
    """regression_objective.hpp:233-293."""
    name = "fair"
    boost_from_average = True

    def get_gradients(self, score):
        c = self.config.fair_c
        x = score[0] - self.labels
        g = c * x / (jnp.abs(x) + c)
        h = c * c / (jnp.abs(x) + c) ** 2
        g, h = self._w(g, h)
        return g[None], h[None]


class RegressionPoisson(Objective):
    """regression_objective.hpp:298-358 — v2.0.5 linear-score form:
    g = s - y, h = s + max_delta_step."""
    name = "poisson"
    boost_from_average = True

    def get_gradients(self, score):
        s = score[0]
        g = s - self.labels
        h = s + self.config.poisson_max_delta_step
        g, h = self._w(g, h)
        return g[None], h[None]


class BinaryLogloss(Objective):
    """binary_objective.hpp:13-157."""
    name = "binary"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        label = np.asarray(metadata.label)
        cnt_pos = int((label > 0).sum())
        cnt_neg = num_data - cnt_pos
        if cnt_pos == 0 or cnt_neg == 0:
            log.warning("Only one class present in label")
        log.info("Number of positive: %d, number of negative: %d", cnt_pos, cnt_neg)
        lw = [1.0, 1.0]
        if self.config.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                lw[0] = cnt_pos / cnt_neg
            else:
                lw[1] = cnt_neg / cnt_pos
        lw[1] *= self.config.scale_pos_weight
        self._label_sign = jnp.where(self.labels > 0, 1.0, -1.0)
        self._label_weight = jnp.where(self.labels > 0, lw[1], lw[0])

    def get_gradients(self, score):
        sig = self.config.sigmoid
        ls = self._label_sign
        response = -ls * sig / (1.0 + jnp.exp(ls * sig * score[0]))
        abs_r = jnp.abs(response)
        g = response * self._label_weight
        h = abs_r * (sig - abs_r) * self._label_weight
        g, h = self._w(g, h)
        return g[None], h[None]

    def convert_output(self, x):
        return 1.0 / (1.0 + np.exp(-self.config.sigmoid * np.asarray(x)))

    def to_string(self):
        return f"binary sigmoid:{self.config.sigmoid:g}"


class MulticlassSoftmax(Objective):
    """multiclass_objective.hpp:16-136 — K trees/iteration."""
    name = "multiclass"

    def __init__(self, config):
        super().__init__(config)
        self.num_tree_per_iteration = config.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        li = np.asarray(metadata.label, dtype=np.int32)
        if li.min() < 0 or li.max() >= self.config.num_class:
            log.fatal("Label must be in [0, %d)", self.config.num_class)
        self._onehot = jnp.asarray(
            np.eye(self.config.num_class, dtype=np.float32)[:, li])  # [K, N]

    def get_gradients(self, score):
        p = jax.nn.softmax(score, axis=0)          # [K, N]
        g = p - self._onehot
        h = 2.0 * p * (1.0 - p)
        if self.weights is not None:
            g = g * self.weights[None]
            h = h * self.weights[None]
        return g, h

    def convert_output(self, x):
        x = np.asarray(x, dtype=np.float64)
        e = np.exp(x - x.max(axis=0, keepdims=True))
        return e / e.sum(axis=0, keepdims=True)

    def to_string(self):
        return f"multiclass num_class:{self.config.num_class}"


class MulticlassOVA(Objective):
    """multiclass_objective.hpp:139-210 — K independent binary classifiers."""
    name = "multiclassova"

    def __init__(self, config):
        super().__init__(config)
        self.num_tree_per_iteration = config.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        li = np.asarray(metadata.label, dtype=np.int32)
        self._sign = jnp.asarray(
            np.where(np.eye(self.config.num_class)[:, li] > 0, 1.0, -1.0)
            .astype(np.float32))

    def get_gradients(self, score):
        sig = self.config.sigmoid
        response = -self._sign * sig / (1.0 + jnp.exp(self._sign * sig * score))
        abs_r = jnp.abs(response)
        g = response
        h = abs_r * (sig - abs_r)
        if self.weights is not None:
            g = g * self.weights[None]
            h = h * self.weights[None]
        return g, h

    def convert_output(self, x):
        return 1.0 / (1.0 + np.exp(-self.config.sigmoid * np.asarray(x)))

    def to_string(self):
        return (f"multiclassova num_class:{self.config.num_class} "
                f"sigmoid:{self.config.sigmoid:g}")


class CrossEntropy(Objective):
    """xentropy_objective.hpp:39-137 (labels in [0,1])."""
    name = "xentropy"
    boost_from_average = True

    def get_gradients(self, score):
        z = 1.0 / (1.0 + jnp.exp(-score[0]))
        g = z - self.labels
        h = z * (1.0 - z)
        g, h = self._w(g, h)
        return g[None], h[None]

    def convert_output(self, x):
        return 1.0 / (1.0 + np.exp(-np.asarray(x)))

    def average_stats(self):
        label = np.asarray(self.labels)
        if self.weights is not None:
            w = np.asarray(self.weights)
            return float((label * w).sum()), float(w.sum())
        return float(label.sum()), float(len(label))

    def init_from_average(self, pavg):
        pavg = min(max(float(pavg), 1e-15), 1.0 - 1e-15)
        init = float(np.log(pavg / (1.0 - pavg)))
        log.info("[xentropy]: pavg=%f -> initscore=%f", pavg, init)
        return init


class CrossEntropyLambda(Objective):
    """xentropy_objective.hpp:139-268 ("xentlambda": intensity-weighted)."""
    name = "xentlambda"
    boost_from_average = True

    def get_gradients(self, score):
        s = score[0]
        y = self.labels
        if self.weights is None:
            z = 1.0 / (1.0 + jnp.exp(-s))
            g = z - y
            h = z * (1.0 - z)
        else:
            w = self.weights
            epf = jnp.exp(s)
            hhat = jnp.log1p(epf)
            z = 1.0 - jnp.exp(-w * hhat)
            enf = 1.0 / epf
            g = (1.0 - y / z) * w / (1.0 + enf)
            c = 1.0 / (1.0 - z)
            d = 1.0 + epf
            a = w * epf / (d * d)
            b = (c / (d * d)) * (1.0 + w * epf - c)
            h = a * (1.0 + y * b)
        return g[None], h[None]

    def convert_output(self, x):
        return np.log1p(np.exp(np.asarray(x)))

    def average_stats(self):
        label = np.asarray(self.labels)
        if self.weights is not None:
            w = np.asarray(self.weights)
            return float((label * w).sum()), float(w.sum())
        return float(label.sum()), float(len(label))

    def init_from_average(self, havg):
        init = float(np.log(np.expm1(max(float(havg), 1e-15))))
        log.info("[xentlambda]: havg=%f -> initscore=%f", havg, init)
        return init


def default_label_gain(max_label: int = 31):
    """2^i - 1 label gains (DCGCalculator::DefaultLabelGain)."""
    return [float((1 << i) - 1) for i in range(max_label)]


class LambdarankNDCG(Objective):
    """rank_objective.hpp:19-245.

    Vectorized: queries padded to the max query length D; per query the
    pairwise [D, D] lambda matrix is computed in one shot (sigmoid applied
    directly — no lookup table needed on TPU), processed in chunks of
    queries via ``lax.map`` to bound memory.
    """
    name = "lambdarank"
    need_accurate_prediction = False

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("Lambdarank tasks require query information")
        bounds = np.asarray(metadata.query_boundaries)
        self.num_queries = len(bounds) - 1
        sizes = np.diff(bounds)
        D = int(sizes.max())
        label = np.asarray(metadata.label)
        gains = np.asarray(self.config.label_gain or default_label_gain(),
                           dtype=np.float64)
        max_label = int(label.max())
        if max_label >= len(gains):
            log.fatal("Label %d exceeds label_gain size", max_label)

        # padded [Q, D] gather indices (N = padding slot) and validity
        qidx = np.full((self.num_queries, D), num_data, dtype=np.int32)
        for q in range(self.num_queries):
            qidx[q, :sizes[q]] = np.arange(bounds[q], bounds[q + 1])
        valid = qidx < num_data
        # truncated max DCG per query (CalMaxDCGAtK at max_position)
        k = min(self.config.max_position, D)
        discounts = 1.0 / np.log2(np.arange(D + 2, dtype=np.float64) + 2.0)
        inv_max_dcg = np.zeros(self.num_queries, dtype=np.float64)
        for q in range(self.num_queries):
            ls = np.sort(label[bounds[q]:bounds[q + 1]])[::-1][:k]
            mdcg = float((gains[ls.astype(np.int32)] * discounts[:len(ls)]).sum())
            inv_max_dcg[q] = 1.0 / mdcg if mdcg > 0 else 0.0

        self._qidx = jnp.asarray(qidx)
        self._valid = jnp.asarray(valid)
        self._inv_max_dcg = jnp.asarray(inv_max_dcg, jnp.float32)
        self._gains = jnp.asarray(gains, jnp.float32)
        self._label_pad = jnp.concatenate(
            [self.labels, jnp.zeros((1,), jnp.float32)])
        self._discount = jnp.asarray(discounts[:D], jnp.float32)
        self._D = D
        # chunk so chunk * D * D floats stays bounded (~64 MB)
        self._chunk = max(1, min(self.num_queries, int(16e6 // max(D * D, 1)) or 1))

    def get_gradients(self, score):
        s_pad = jnp.concatenate([score[0], jnp.full((1,), 0.0, score.dtype)])
        sigma = self.config.sigmoid

        def one_chunk(args):
            qidx, valid, inv_mdcg = args          # [C, D], [C, D], [C]
            s = jnp.where(valid, s_pad[qidx], -jnp.inf)
            y = jnp.where(valid, self._label_pad[qidx], -1.0)
            order = jnp.argsort(-s, axis=1)        # descending scores
            ss = jnp.take_along_axis(s, order, axis=1)
            sy = jnp.take_along_axis(y, order, axis=1).astype(jnp.int32)
            sval = jnp.take_along_axis(valid, order, axis=1)
            gain = self._gains[jnp.clip(sy, 0)]
            disc = jnp.where(sval, self._discount[None, :], 0.0)
            best = ss[:, :1]
            cnt = sval.sum(axis=1)
            worst = jnp.take_along_axis(
                ss, jnp.maximum(cnt - 1, 0)[:, None], axis=1)
            nondegen = best != worst               # [C, 1]

            ds = ss[:, :, None] - ss[:, None, :]   # s_high - s_low
            pair = ((sy[:, :, None] > sy[:, None, :])
                    & sval[:, :, None] & sval[:, None, :])
            dcg_gap = gain[:, :, None] - gain[:, None, :]
            paired_disc = jnp.abs(disc[:, :, None] - disc[:, None, :])
            delta_ndcg = dcg_gap * paired_disc * inv_mdcg[:, None, None]
            delta_ndcg = jnp.where(
                nondegen[:, :, None],
                delta_ndcg / (0.01 + jnp.abs(ds)), delta_ndcg)
            p = 2.0 / (1.0 + jnp.exp(2.0 * sigma * ds))
            lam = jnp.where(pair, -delta_ndcg * p, 0.0)
            hes = jnp.where(pair, p * (2.0 - p) * 2.0 * delta_ndcg, 0.0)
            lam_i = lam.sum(axis=2) - lam.sum(axis=1)   # high gets +, low gets -
            hes_i = hes.sum(axis=2) + hes.sum(axis=1)
            # scatter back from sorted positions to original rows
            rows = jnp.take_along_axis(qidx, order, axis=1)
            return rows, lam_i, hes_i

        Q, D = self._qidx.shape
        C = self._chunk
        pad_q = (-Q) % C
        qidx = jnp.pad(self._qidx, ((0, pad_q), (0, 0)),
                       constant_values=self.num_data)
        validp = jnp.pad(self._valid, ((0, pad_q), (0, 0)))
        inv = jnp.pad(self._inv_max_dcg, (0, pad_q))
        nchunks = (Q + pad_q) // C
        rows, lam, hes = lax.map(
            one_chunk,
            (qidx.reshape(nchunks, C, D), validp.reshape(nchunks, C, D),
             inv.reshape(nchunks, C)))
        g = jnp.zeros((self.num_data + 1,), jnp.float32)
        h = jnp.zeros((self.num_data + 1,), jnp.float32)
        g = g.at[rows.reshape(-1)].add(lam.reshape(-1))
        h = h.at[rows.reshape(-1)].add(hes.reshape(-1))
        g, h = g[:-1], h[:-1]
        if self.weights is not None:
            g = g * self.weights
            h = h * self.weights
        return g[None], h[None]


_REGISTRY = {
    "regression": RegressionL2,
    "regression_l2": RegressionL2,
    "mean_squared_error": RegressionL2,
    "mse": RegressionL2,
    "l2": RegressionL2,
    "regression_l1": RegressionL1,
    "l1": RegressionL1,
    "mean_absolute_error": RegressionL1,
    "mae": RegressionL1,
    "huber": RegressionHuber,
    "fair": RegressionFair,
    "poisson": RegressionPoisson,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "softmax": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "multiclass_ova": MulticlassOVA,
    "ova": MulticlassOVA,
    "ovr": MulticlassOVA,
    "xentropy": CrossEntropy,
    "cross_entropy": CrossEntropy,
    "xentlambda": CrossEntropyLambda,
    "cross_entropy_lambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
}


def create_objective(config: Config) -> Objective:
    """Factory (objective_function.cpp:10-36)."""
    name = config.objective.lower()
    if name not in _REGISTRY:
        log.fatal("Unknown objective type name: %s", name)
    return _REGISTRY[name](config)


def parse_objective_string(s: str, config: Config) -> Objective:
    """Parse a model-file objective line, e.g. 'binary sigmoid:1'."""
    toks = s.split()
    cfg = config.copy()
    cfg.objective = toks[0]
    for t in toks[1:]:
        if ":" in t:
            k, v = t.split(":", 1)
            if k == "sigmoid":
                cfg.sigmoid = float(v)
            elif k == "num_class":
                cfg.num_class = int(v)
    return create_objective(cfg)
