"""Async high-QPS serving loop: request coalescing, microbatch dispatch,
hot model swap.  ``python -m lightgbm_tpu.serving`` is the CLI.

The host side of the serving path (docs/SERVING.md; the device side is
:mod:`lightgbm_tpu.inference`):

* **Latency-budget batching** — concurrent requests land in one queue; a
  single dispatcher thread coalesces them into the largest
  ``serving_buckets`` ladder bucket reachable within ``latency_budget_ms``
  of the oldest waiting request, then runs ONE microbatch executable for
  the whole coalition.  Each request's rows stay contiguous, so a request
  is always answered by exactly one model — there is no torn read by
  construction.
* **Hot model swap** — with ``model_watch`` set, a watcher thread polls
  the checkpoint commit point of PR 6
  (:func:`lightgbm_tpu.checkpoint.latest_committed_iteration`: plain
  snapshots, or shard sets whose rank-0 manifest validates) and, when a
  trainer commits a newer iteration, loads the model, builds + pre-warms
  its engine OFF the serving path, and swaps it in atomically between
  microbatches.  In-flight microbatches hold a reference to the old
  engine and complete on it; the next dispatch uses the new one.  A
  same-bucket-shape swap reuses the compiled executables (zero
  recompiles — the kernels take every model array as an argument).
* **Observability** — every dispatch is an obs span + a
  ``predict_dispatch`` counter; the server keeps per-bucket latency
  reservoirs whose p50/p99/QPS summary lands in :meth:`ModelServer.stats`,
  as a ``serving stats`` telemetry summary in the trace file (rendered by
  ``python -m lightgbm_tpu.obs``), and in the bench JSON ``serving`` rung.
"""
from __future__ import annotations

import argparse
import collections
import json
import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import numpy as np

from . import checkpoint as checkpoint_mod
from .config import config_from_params, parse_serving_buckets
from .obs import metrics as obs_metrics
from .obs import trace as obs_trace
from .obs.counters import counters as obs_counters
from .utils import log

# per-bucket latency histogram edges (ms) for the obs report
_HIST_EDGES_MS = (0.5, 1, 2, 5, 10, 20, 50, 100, 500)


class _Request:
    __slots__ = ("x", "future", "t_enq", "raw_score", "n")

    def __init__(self, x: np.ndarray, raw_score: bool):
        self.x = x
        self.n = x.shape[0]
        self.raw_score = raw_score
        self.future: Future = Future()
        self.t_enq = time.perf_counter()


class ServingStats:
    """Per-bucket latency reservoirs + throughput counters (thread-safe)."""

    RESERVOIR = 4096

    def __init__(self):
        self._lock = threading.Lock()
        self._lat: Dict[int, collections.deque] = {}
        self._requests = 0
        self._rows = 0
        self._batches = 0
        self._swaps = 0
        self._t0 = time.perf_counter()

    def record_batch(self, bucket: int, request_latencies_ms: List[float],
                     rows: int) -> None:
        with self._lock:
            d = self._lat.setdefault(bucket,
                                     collections.deque(maxlen=self.RESERVOIR))
            d.extend(request_latencies_ms)
            self._requests += len(request_latencies_ms)
            self._rows += rows
            self._batches += 1

    def record_swap(self) -> None:
        with self._lock:
            self._swaps += 1

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            elapsed = max(time.perf_counter() - self._t0, 1e-9)
            buckets = {}
            for b, d in sorted(self._lat.items()):
                lat = np.asarray(d, np.float64)
                hist = {}
                lo = 0.0
                for edge in _HIST_EDGES_MS:
                    hist[f"<={edge}ms"] = int(((lat > lo)
                                               & (lat <= edge)).sum()
                                              + (lo == 0.0) * (lat == 0).sum())
                    lo = edge
                hist[f">{_HIST_EDGES_MS[-1]}ms"] = int(
                    (lat > _HIST_EDGES_MS[-1]).sum())
                buckets[str(b)] = {
                    "count": int(len(lat)),
                    "p50_ms": round(float(np.percentile(lat, 50)), 3),
                    "p99_ms": round(float(np.percentile(lat, 99)), 3),
                    "max_ms": round(float(lat.max()), 3),
                    "hist": hist,
                }
            return {"requests": self._requests, "rows": self._rows,
                    "batches": self._batches, "swaps": self._swaps,
                    "elapsed_s": round(elapsed, 3),
                    "qps": round(self._requests / elapsed, 2),
                    "rows_per_s": round(self._rows / elapsed, 1),
                    "buckets": buckets}


class ModelServer:
    """Queue + dispatcher + (optional) model watcher around one
    :class:`~lightgbm_tpu.inference.PredictEngine`.

    ``submit`` is the async API (returns a Future), ``predict`` the
    blocking convenience.  ``start()``/``stop()`` run the threads;
    constructing with ``autostart=False`` and enqueueing before
    ``start()`` makes coalescing deterministic (the tests use this)."""

    def __init__(self, booster=None, model_file: Optional[str] = None,
                 model_str: Optional[str] = None,
                 params: Optional[Dict[str, Any]] = None,
                 prewarm: bool = True, autostart: bool = True):
        from .basic import Booster
        self.params = dict(params or {})
        cfg = config_from_params(
            {k: v for k, v in self.params.items()})
        self.latency_budget_s = float(cfg.latency_budget_ms) / 1e3
        self.buckets = parse_serving_buckets(cfg.serving_buckets)
        self.watch_prefix = str(cfg.model_watch or "")
        self.watch_interval = float(cfg.model_watch_interval)
        self.drift_threshold = float(cfg.drift_threshold)
        self.drift_window_rows = int(cfg.drift_window_rows)
        self._drift = None
        if booster is None and model_file is None and model_str is None \
                and not self.watch_prefix:
            raise ValueError("ModelServer needs a booster, model_file, "
                             "model_str, or model_watch prefix")
        if booster is None and (model_file or model_str):
            booster = Booster(params=self.params, model_file=model_file,
                              model_str=model_str)
        self._lock = threading.Lock()
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._booster = None
        self._predictor = None
        self._engine = None
        self.loaded_iteration: Optional[int] = None
        self.stats_ = ServingStats()
        self._running = False
        self._threads: List[threading.Thread] = []
        # live metrics plane (docs/OBSERVABILITY.md "Live telemetry"):
        # the per-bucket latency stats become scrapeable families on
        # GET /metrics — on this server's HTTP front and, when
        # metrics_port is set, a standalone exporter thread
        obs_metrics.register_source(self._metrics_samples)
        self._own_exporter = None
        if int(cfg.metrics_port) > 0:
            self._own_exporter = obs_metrics.start_exporter(
                int(cfg.metrics_port))
        if booster is not None:
            self._install(booster, iteration=None, prewarm=prewarm)
        elif self.watch_prefix:
            # watch-only start: block until the trainer commits anything
            if not self._poll_model_watch(prewarm=prewarm):
                log.warning("model_watch: no committed checkpoint under %s "
                            "yet; serving starts after the first commit",
                            self.watch_prefix)
        if autostart:
            self.start()

    # ------------------------------------------------------------- install

    def _install(self, booster, iteration: Optional[int],
                 prewarm: bool) -> None:
        """Build engine + predictor for ``booster`` and swap them in
        atomically.  Everything expensive (flatten, compile warmup) runs
        BEFORE the swap — the dispatcher never blocks on it."""
        gbdt = getattr(booster, "inner", booster)
        engine = gbdt.predict_engine(prewarm=prewarm, buckets=self.buckets)
        predictor = gbdt.predictor()
        # serving-time drift watchdog (docs/OBSERVABILITY.md "Model
        # quality"): armed only when the model text carried a
        # feature_distribution section (written by a model-quality-armed
        # training) — attached BEFORE the swap so the first dispatched
        # batch is already counted
        drift = None
        dist = getattr(gbdt, "feature_distribution", None)
        if dist:
            from .obs import model_quality as obs_model_quality
            drift = obs_model_quality.DriftMonitor(
                engine.bundle, dist,
                feature_names=list(getattr(gbdt, "feature_names", []) or []),
                threshold=self.drift_threshold,
                window_rows=self.drift_window_rows)
            if drift.enabled:
                engine.drift = drift
            else:
                drift = None
        with self._lock:
            first = self._predictor is None
            self._booster = booster
            self._engine = engine
            self._predictor = predictor
            self._drift = drift
            self.loaded_iteration = iteration
        if not first:
            self.stats_.record_swap()
            obs_counters.inc("serving_model_swap")
        obs_counters.event("model_swap" if not first else "model_load",
                           iteration=iteration,
                           trees=engine.bundle.num_trees,
                           exec=engine.bundle.exec_id())
        log.info("serving: %s model%s (%d trees, exec %s)",
                 "swapped in" if not first else "loaded",
                 f" at iteration {iteration}" if iteration is not None
                 else "", engine.bundle.num_trees, engine.bundle.exec_id())

    def _poll_model_watch(self, prewarm: bool = True) -> bool:
        """One watcher step: load + install a newer committed checkpoint
        if the trainer published one.  Returns True when a swap (or the
        initial load) happened."""
        from .boosting import GBDT
        it = checkpoint_mod.latest_committed_iteration(self.watch_prefix)
        if it is None or it == self.loaded_iteration:
            return False
        plain = checkpoint_mod.snapshot_path(self.watch_prefix, it)
        if not os.path.exists(plain):
            # a coordinated shard set: rank 0's shard carries the model
            # text, the manifest is the commit point that admitted it
            plain = checkpoint_mod.shard_path(self.watch_prefix, it, 0)
        try:
            model_str, _ = checkpoint_mod.load_snapshot(plain)
            gbdt = GBDT.load_from_string(model_str,
                                         config_from_params(self.params))
        except (checkpoint_mod.CheckpointError, OSError, ValueError) as e:
            # a commit that validates at the manifest but fails to load is
            # surfaced, never served
            obs_counters.event("model_swap_failed", iteration=it,
                               reason=str(e)[:200])
            log.warning("model_watch: checkpoint at iteration %s failed to "
                        "load (%s); keeping the current model", it, e)
            return False
        self._install(gbdt, iteration=it, prewarm=prewarm)
        return True

    def _watch_loop(self) -> None:
        while self._running:
            time.sleep(self.watch_interval)
            if not self._running:
                return
            try:
                self._poll_model_watch()
            except Exception as e:   # watcher must never die silently
                obs_counters.event("model_swap_failed", iteration=None,
                                   reason=str(e)[:200])
                log.warning("model_watch poll failed: %s", e)

    # ------------------------------------------------------------ requests

    def submit(self, X, raw_score: bool = False) -> Future:
        x = np.atleast_2d(np.asarray(X, np.float64))
        req = _Request(x, raw_score)
        self._queue.put(req)
        return req.future

    def predict(self, X, raw_score: bool = False):
        return self.submit(X, raw_score).result()

    def stats(self) -> Dict[str, Any]:
        s = self.stats_.summary()
        s["loaded_iteration"] = self.loaded_iteration
        s["predict_jit_entries"] = _jit_entries_gauge()
        drift = self._drift
        if drift is not None:
            s["drift"] = drift.stats()
        return s

    def _metrics_samples(self) -> List[tuple]:
        """Live ``/metrics`` families of this server: throughput counters,
        the loaded iteration / jit-entry gauges, and per-bucket latency —
        p50/p99/max gauges plus a windowed Prometheus histogram derived
        from the reservoir's edge counts (the reservoir keeps the newest
        ``ServingStats.RESERVOIR`` latencies, so the histogram is a
        sliding window, not an all-time cumulative).  Host-side reads
        only."""
        from .inference import jit_entries
        s = self.stats_.summary()
        # the registry already carries serving_requests / serving_batches
        # / serving_model_swap counters from the dispatch path — this
        # source only adds what no counter records
        out = [
            ("serving_rows", {}, float(s["rows"]), "counter"),
            ("serving_loaded_iteration", {},
             float(-1 if self.loaded_iteration is None
                   else self.loaded_iteration), "gauge"),
            ("serving_jit_entries", {}, float(jit_entries()), "gauge"),
        ]
        for bucket, rec in s.get("buckets", {}).items():
            labels = {"bucket": bucket}
            for q in ("p50_ms", "p99_ms", "max_ms"):
                out.append((f"serving_{q}", labels, float(rec[q]), "gauge"))
            cum = 0.0
            for edge in _HIST_EDGES_MS:
                cum += float(rec["hist"].get(f"<={edge}ms", 0))
                out.append(("serving_latency_ms_bucket",
                            dict(labels, le=str(edge)), cum, "gauge"))
            out.append(("serving_latency_ms_bucket",
                        dict(labels, le="+Inf"), float(rec["count"]),
                        "gauge"))
            out.append(("serving_latency_ms_count", labels,
                        float(rec["count"]), "gauge"))
        drift = self._drift
        if drift is not None:
            out.extend(drift.samples())
        return out

    # ---------------------------------------------------------- dispatcher

    def _collect(self) -> Optional[List[_Request]]:
        """Block for the next request, then coalesce companions until the
        ladder's largest bucket is filled or ``latency_budget_ms`` from
        the FIRST queued request has elapsed."""
        try:
            first = self._queue.get(timeout=0.1)
        except queue.Empty:
            return None
        batch = [first]
        rows = first.n
        deadline = first.t_enq + self.latency_budget_s
        max_rows = self.buckets[-1]
        while rows < max_rows:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            try:
                nxt = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            batch.append(nxt)
            rows += nxt.n
        return batch

    def _serve_batch(self, batch: List[_Request], predictor) -> None:
        """Run one coalesced microbatch on a model SNAPSHOT (grabbed by
        the caller before any swap could land): every request in the
        coalition is answered by that one model."""
        rows = sum(r.n for r in batch)
        tracer = obs_trace.get_tracer()
        with tracer.span("serving_batch", requests=len(batch), rows=rows):
            x = batch[0].x if len(batch) == 1 else \
                np.concatenate([r.x for r in batch], axis=0)
            # raw and transformed coalesce together: predict() is a pure
            # host transform of predict_raw's margins
            raw = predictor.predict_raw(x)
            done_t = time.perf_counter()
            lo = 0
            lats = []
            for r in batch:
                sl = raw[:, lo:lo + r.n]
                lo += r.n
                try:
                    r.future.set_result(
                        predictor._transform(sl, raw_score=r.raw_score))
                except Exception as e:      # pragma: no cover - transform bug
                    r.future.set_exception(e)
                lats.append((done_t - r.t_enq) * 1e3)
        bucket = next((b for b in self.buckets if rows <= b),
                      self.buckets[-1])
        self.stats_.record_batch(bucket, lats, rows)
        obs_counters.inc("serving_requests", len(batch))
        obs_counters.inc("serving_batches", bucket=bucket)

    def _dispatch_loop(self) -> None:
        while self._running:
            batch = self._collect()
            if batch is None:
                continue
            with self._lock:          # model snapshot for this coalition
                predictor = self._predictor
            if predictor is None:
                for r in batch:
                    r.future.set_exception(
                        RuntimeError("no model loaded yet (model_watch saw "
                                     "no committed checkpoint)"))
                continue
            try:
                self._serve_batch(batch, predictor)
            except Exception as e:
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(e)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "ModelServer":
        if self._running:
            return self
        self._running = True
        t = threading.Thread(target=self._dispatch_loop,
                             name="lgbm-serving-dispatch", daemon=True)
        t.start()
        self._threads = [t]
        if self.watch_prefix:
            w = threading.Thread(target=self._watch_loop,
                                 name="lgbm-serving-watch", daemon=True)
            w.start()
            self._threads.append(w)
        return self

    def stop(self) -> Dict[str, Any]:
        """Stop threads, flush the ``serving stats`` telemetry summary,
        return the final stats."""
        self._running = False
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        if self._own_exporter is not None:
            # only the exporter THIS server armed — never one the engine
            # or supervisor owns in the same process
            if obs_metrics.get_exporter() is self._own_exporter:
                obs_metrics.stop_exporter()
            self._own_exporter = None
        s = self.stats()
        obs_trace.get_tracer().summary("serving stats", s)
        return s


def _jit_entries_gauge() -> int:
    from .inference import jit_entries
    n = jit_entries()
    obs_counters.gauge("predict_jit_entries", n)
    return n


# --------------------------------------------------------------------- CLI


def _run_http(server: ModelServer, port: int) -> None:
    """Minimal stdlib HTTP front: POST /predict {"data": [[...]...]} ->
    {"predictions": [...]}; GET /stats, GET /healthz, GET /metrics
    (Prometheus text — the live telemetry plane's scrape point)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def _json(self, code: int, payload) -> None:
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path.startswith("/healthz"):
                self._json(200, {"ok": server._predictor is not None,
                                 "loaded_iteration":
                                     server.loaded_iteration})
            elif self.path.startswith("/stats"):
                self._json(200, server.stats())
            elif self.path.startswith("/metrics"):
                obs_counters.inc("metrics_scrapes")
                body = obs_metrics.render_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type", obs_metrics.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._json(404, {"error": "unknown path"})

        def do_POST(self):
            if not self.path.startswith("/predict"):
                self._json(404, {"error": "unknown path"})
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(n) or b"{}")
                x = np.asarray(body["data"], np.float64)
                out = server.predict(x, raw_score=bool(
                    body.get("raw_score", False)))
                self._json(200, {"predictions": np.asarray(out).tolist()})
            except Exception as e:
                self._json(400, {"error": str(e)[:500]})

        def log_message(self, fmt, *args):   # route through our logger
            log.debug("serving http: " + fmt, *args)

    httpd = ThreadingHTTPServer(("", port), Handler)
    log.info("serving: HTTP on port %d (POST /predict, GET /stats, "
             "GET /healthz)", httpd.server_address[1])
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:         # pragma: no cover - interactive
        pass
    finally:
        httpd.server_close()


def _run_replay(server: ModelServer, n_requests: int, n_features: int,
                seed: int = 0) -> Dict[str, Any]:
    """Synthetic mixed-size request replay against a live server — the
    zero-recompile / latency smoke the capture playbook collects."""
    rng = np.random.RandomState(seed)
    sizes = rng.choice([1, 1, 3, 8, 17, 64, 200, 512, 1500, 4096],
                       size=n_requests)
    futures = [server.submit(rng.randn(int(s), n_features))
               for s in sizes]
    for f in futures:
        f.result(timeout=300)
    return server.stats()


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.serving",
        description="High-QPS model server (docs/SERVING.md)")
    ap.add_argument("--model", help="model text file to serve")
    ap.add_argument("--watch", default="",
                    help="checkpoint prefix (trainer output_model) to hot-"
                         "swap from (model_watch param)")
    ap.add_argument("--port", type=int, default=8080,
                    help="HTTP port (ignored under --replay)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="standalone Prometheus exporter port (the "
                         "metrics_port param; GET /metrics also rides "
                         "the main HTTP front)")
    ap.add_argument("--latency-budget-ms", type=float, default=None)
    ap.add_argument("--buckets", default=None,
                    help="serving_buckets ladder, e.g. 1,8,64,512,4096")
    ap.add_argument("--watch-interval", type=float, default=None)
    ap.add_argument("--replay", type=int, default=0, metavar="N",
                    help="serve N synthetic mixed-size requests, print the "
                         "stats JSON, exit")
    ap.add_argument("--features", type=int, default=28,
                    help="synthetic replay feature count")
    args = ap.parse_args(argv)
    if not args.model and not args.watch:
        ap.error("need --model and/or --watch")
    params: Dict[str, Any] = {"verbose": -1}
    if args.latency_budget_ms is not None:
        params["latency_budget_ms"] = args.latency_budget_ms
    if args.buckets:
        params["serving_buckets"] = args.buckets
    if args.watch:
        params["model_watch"] = args.watch
    if args.watch_interval is not None:
        params["model_watch_interval"] = args.watch_interval
    if args.metrics_port is not None:
        params["metrics_port"] = args.metrics_port
    server = ModelServer(model_file=args.model or None, params=params)
    if args.replay:
        stats = _run_replay(server, args.replay, args.features)
        server.stop()
        print(json.dumps(stats))
        return 0
    _run_http(server, args.port)
    server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
