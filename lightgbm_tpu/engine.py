"""Training entry points ``train`` and ``cv``.

Mirrors ``python-package/lightgbm/engine.py`` (train :18-229, cv :230-460):
callback-driven boosting loop, early stopping, evaluation recording,
stratified / grouped cross-validation folds.
"""
from __future__ import annotations

import collections
import copy
import os
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from . import callback as callback_mod
from . import checkpoint as checkpoint_mod
from .basic import Booster, Dataset
from .config import canonicalize_params
from .utils import faults as faults_mod
from .utils import log


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          fobj: Optional[Callable] = None, feval: Optional[Callable] = None,
          init_model: Optional[Union[str, Booster]] = None,
          feature_name: Union[str, List[str]] = "auto",
          categorical_feature: Union[str, List] = "auto",
          early_stopping_rounds: Optional[int] = None,
          evals_result: Optional[Dict] = None,
          verbose_eval: Union[bool, int] = True,
          learning_rates: Optional[Union[List[float], Callable]] = None,
          keep_training_booster: bool = True,
          callbacks: Optional[List[Callable]] = None,
          resume: Optional[Union[bool, str]] = None) -> Booster:
    """engine.py:18-229 analogue.

    ``resume`` (also the ``snapshot_resume`` param): ``True`` auto-detects
    the latest *valid* ``<output_model>.snapshot_iter_N`` checkpoint (a
    torn tail falls back to the previous good one) and continues training
    from it with bit-exact state — final model byte-identical to an
    uninterrupted run; a string resumes from that explicit checkpoint
    file.  See docs/ROBUSTNESS.md.
    """
    params = canonicalize_params(params)
    if "num_iterations" in params:
        num_boost_round = int(params.pop("num_iterations"))
    if "early_stopping_round" in params and params["early_stopping_round"]:
        early_stopping_rounds = int(params.pop("early_stopping_round"))
    # structured telemetry (lightgbm_tpu.obs): trace_path writes a
    # Chrome-trace span file; telemetry=true enables counters/spans without
    # a file.  The counter registry is reset per training so two runs in
    # one process never blur their kernel-identity evidence.
    from .obs import devprof as obs_devprof
    from .obs import memory as obs_memory
    from .obs import trace as obs_trace
    from .obs.counters import counters as obs_counters
    trace_path = str(params.get("trace_path", "") or "")
    # device-time attribution (obs/devprof.py): implies telemetry — the
    # attributor needs the TraceAnnotation phase windows the tracer mirrors
    # into every profiler capture
    devprof_on = str(params.get("device_profile", "")).strip().lower() \
        in ("true", "1", "yes", "on", "+")
    telemetry_on = bool(trace_path) or devprof_on or str(
        params.get("telemetry", "")).strip().lower() in ("true", "1", "yes",
                                                         "on", "+")
    if telemetry_on:
        obs_counters.reset()
        obs_trace.start(trace_path or None)
        # device-memory accounting rides the same switch: per-iteration /
        # per-phase samples are host-side reads (memory_stats on TPU, a
        # live-array census on CPU) — zero added device synchronizations
        obs_memory.start()
    if devprof_on:
        obs_devprof.start(
            profile_iters=int(params.get("profile_iters", 2) or 2))
    # deterministic fault injection (utils/faults.py): a param-armed plan is
    # scoped to THIS training; an env-armed plan stays process-wide
    fault_spec = str(params.get("fault_inject", "") or "")
    prev_faults = faults_mod.get_faults()
    if fault_spec:
        faults_mod.install(fault_spec)
    # host_lost fault, startup leg: in a RELAUNCHED incarnation (the
    # supervisor stamps its attempt counter into child env) the lost
    # rank dies again BEFORE its first heartbeat — the repeatable
    # startup failure the supervisor's world_shrink_after counter is
    # defined over.  targets() (not fire()) so the @K pin stays armed
    # for the mid-run death of attempt 0.
    try:
        _sup_attempt = int(
            os.environ.get("LGBM_TPU_SUPERVISOR_ATTEMPT", "0") or 0)
    except ValueError:
        _sup_attempt = 0
    if _sup_attempt > 0:
        _fi = faults_mod.get_faults()
        if _fi.enabled and _fi.targets("host_lost",
                                       faults_mod.current_rank()):
            log.warning("host_lost fault: rank %d's host never comes "
                        "back — dying at startup of attempt %d (before "
                        "the first heartbeat)",
                        faults_mod.current_rank(), _sup_attempt)
            os._exit(70)
    # host-object collective budget (parallel/sync.py recovery ladder)
    from .parallel import sync as sync_mod
    if params.get("collective_timeout") or params.get("collective_retries") \
            is not None:
        sync_mod.configure(
            timeout=float(params["collective_timeout"])
            if params.get("collective_timeout") else None,
            retries=int(params["collective_retries"])
            if params.get("collective_retries") is not None else None)
    # elastic relaunch override: after a degraded-world shrink the
    # supervisor stamps the CURRENT world size into child env; the
    # user-level num_machines still describes the LAUNCH topology, so
    # reduce it here (a world of 1 then skips distributed bring-up — and
    # its dead-peer rendezvous — entirely)
    _env_world = os.environ.get("LGBM_TPU_WORLD", "")
    if _env_world.strip():
        try:
            _w = int(_env_world)
        except ValueError:
            _w = 0
        if _w >= 1 and _w != int(params.get("num_machines", 1) or 1):
            log.info("LGBM_TPU_WORLD=%d overrides num_machines=%s "
                     "(elastic relaunch at a shrunk world)", _w,
                     params.get("num_machines", 1))
            params["num_machines"] = _w
    if int(params.get("num_machines", 1)) > 1:
        # multi-host bring-up from config (application.cpp:190-224 analogue)
        from .config import config_from_params
        from .parallel.mesh import init_distributed_from_config
        init_distributed_from_config(config_from_params(params))
    if fobj is not None:
        params.setdefault("objective", "regression")

    if feature_name != "auto":
        train_set.feature_name = feature_name
    if categorical_feature != "auto":
        train_set.categorical_feature = categorical_feature

    booster = Booster(params=params, train_set=train_set)
    if init_model is not None:
        # continued training: load old model, use it as init scores
        prev = init_model if isinstance(init_model, Booster) \
            else Booster(model_file=str(init_model), params=params)
        raw = train_set.ensure_raw()
        if raw is None:
            log.fatal("Continued training requires raw data "
                      "(set free_raw_data=False)")
        init_scores = prev.inner.predictor().predict_raw(np.asarray(raw))
        booster.inner.scores = booster.inner.scores + np.asarray(
            init_scores, np.float32)
        booster.inner.num_init_iteration = prev.inner.current_iteration()
        booster.inner.models = list(prev.inner.models) + booster.inner.models
        booster.inner.boost_from_average_ = prev.inner.boost_from_average_

    valid_sets = valid_sets or []
    if isinstance(valid_sets, Dataset):
        valid_sets = [valid_sets]    # bare Dataset (python-guide examples)
    valid_names = valid_names or [f"valid_{i}" for i in range(len(valid_sets))]
    is_valid_contain_train = False
    train_data_name = "training"
    for vs, name in zip(valid_sets, valid_names):
        if vs is train_set:
            is_valid_contain_train = True
            train_data_name = name
            continue
        booster.add_valid(vs, name)

    cbs = list(callbacks or [])
    if verbose_eval is True:
        cbs.append(callback_mod.print_evaluation())
    elif isinstance(verbose_eval, int) and verbose_eval > 0:
        cbs.append(callback_mod.print_evaluation(verbose_eval))
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.append(callback_mod.early_stopping(early_stopping_rounds,
                                               bool(verbose_eval)))
    if learning_rates is not None:
        # per-iteration schedule, list or function(iter) (reference
        # engine.py:167-168 routes it through reset_parameter)
        cbs.append(callback_mod.reset_parameter(learning_rate=learning_rates))
    if evals_result is not None:
        cbs.append(callback_mod.record_evaluation(evals_result))
    cbs_before = [cb for cb in cbs if getattr(cb, "before_iteration", False)]
    cbs_after = [cb for cb in cbs if not getattr(cb, "before_iteration", False)]
    cbs_before.sort(key=lambda cb: getattr(cb, "order", 0))
    cbs_after.sort(key=lambda cb: getattr(cb, "order", 0))

    snapshot_freq = int(params.get("snapshot_freq", -1) or -1)
    snapshot_keep = int(params.get("snapshot_keep", -1) or -1)
    snapshot_out = str(params.get("output_model", "LightGBM_model.txt"))
    world = sync_mod.process_count()
    # single-process identity: a supervisor may run several INDEPENDENT
    # single-process workers under one prefix (LGBM_TPU_RANK env), whose
    # liveness artifacts — heartbeats, crash reports, flight streams —
    # must stay per-rank; distributed runs keep the jax process index
    rank = sync_mod.process_index() if world > 1 \
        else faults_mod.current_rank()
    single_process = world == 1
    # ---- the live telemetry plane (docs/OBSERVABILITY.md) ----
    # Both legs are scoped to THIS training (armed here, disarmed in the
    # finally) and both are pure host-side observers: the flight recorder
    # appends unsynced JSONL lines, the exporter serves scrapes off a
    # daemon thread — zero added collectives / device syncs (pinned).
    from .obs import flight as obs_flight
    from .obs import metrics as obs_metrics
    obs_stream = str(params.get("obs_stream_path", "") or "")
    flight_armed = False
    if obs_stream:
        obs_flight.start(obs_flight.stream_path(obs_stream, rank), rank=rank)
        flight_armed = True
    metrics_port = int(params.get("metrics_port", 0) or 0)
    exporter_armed = False
    if metrics_port > 0:
        obs_metrics.start_exporter(metrics_port + rank)
        exporter_armed = True
    # ---- the model-quality plane (split audit + importance gauges) ----
    # A pure host-side fold over arrays the boosting loop has ALREADY
    # fetched (the tree finalize drain), so arming it adds zero device
    # syncs and zero collectives (pinned).  model_quality=auto follows
    # the telemetry switch; on/off force it.
    from .obs import model_quality as obs_model_quality
    mq_armed = obs_model_quality.resolve_armed(
        booster.inner.config.model_quality, telemetry_on)
    if mq_armed:
        obs_model_quality.start(list(booster.inner.feature_names))
    ckpt_callbacks = cbs_before + cbs_after   # stable capture/restore order
    # elastic groups (docs/ROBUSTNESS.md): opt-in acceptance of committed
    # sets written at a DIFFERENT process count
    elastic = str(params.get("elastic_resume", "")).strip().lower() \
        in ("true", "1", "yes", "on", "+")
    _elastic_cache: List[Optional[Dict[str, Any]]] = [None]

    def _elastic_meta() -> Dict[str, Any]:
        """Partition metadata each shard ships through the existing commit
        barrier so the manifest carries GLOBAL row boundaries.  Cached:
        the partition cannot change mid-training, so the offset exchange
        is one extra allgather per TRAINING, not per snapshot."""
        if _elastic_cache[0] is None:
            ts = booster.inner.train_set
            n_local = int(ts.num_data)
            views = sorted(
                sync_mod.allgather_object({"rank": rank,
                                           "num_data": n_local}),
                key=lambda v: int(v["rank"]))
            off = sum(int(v["num_data"]) for v in views
                      if int(v["rank"]) < rank)
            _elastic_cache[0] = {
                "num_data": n_local,
                "valid_num_data": [int(vs.data.num_data)
                                   for vs in booster.inner.valid_sets],
                "fp_partial": checkpoint_mod.elastic_fingerprint_partial(
                    np.asarray(ts.binned), n_local, off),
                "num_features": int(np.asarray(ts.binned).shape[1]),
                "num_class": int(booster.inner.num_class),
                # model-shape knobs for the supervisor's W-1 mesh
                # pre-flight (plan_mesh sizes the histogram pool from
                # leaves x bins)
                "num_leaves": int(booster.inner.config.num_leaves),
                "max_bin": int(booster.inner.config.max_bin),
            }
        return _elastic_cache[0]

    def _write_checkpoint(iteration: int) -> None:
        """One atomic snapshot at an iteration boundary: the single-file
        checkpoint when alone, the coordinated shard-set protocol (shards
        -> CRC barrier -> rank-0 manifest commit) across processes."""
        if single_process:
            checkpoint_mod.write_snapshot(
                checkpoint_mod.snapshot_path(snapshot_out, iteration),
                booster, iteration, ckpt_callbacks, evals_result)
            if snapshot_keep > 0:
                checkpoint_mod.prune_snapshots(snapshot_out, snapshot_keep)
            return
        state = checkpoint_mod.capture_state(booster, iteration,
                                             ckpt_callbacks, evals_result)
        checkpoint_mod.write_group_snapshot(
            snapshot_out, iteration,
            booster.model_to_string(-1) if rank == 0 else "", state,
            rank=rank, world=world,
            fingerprint=booster.inner.data_fingerprint(),
            elastic_meta=_elastic_meta())
        if snapshot_keep > 0 and rank == 0:
            # only after the manifest commit, and only on rank 0: the
            # barrier guarantees every shard of the new set is durable, so
            # pruning can never race a peer's in-flight write
            checkpoint_mod.prune_snapshots(snapshot_out, snapshot_keep)

    # ---- resume from the latest valid snapshot (docs/ROBUSTNESS.md) ----
    if resume is None:
        resume = params.get("snapshot_resume", False)
    if isinstance(resume, str):
        s = resume.strip().lower()
        if s in ("false", "0", "no", "off", "-", ""):
            resume = False
        elif s in ("true", "1", "yes", "on", "+", "auto"):
            resume = True
    start_iter = 0
    if resume:
        if elastic:
            # the ELASTIC resume barrier (docs/ROBUSTNESS.md "Elastic
            # groups"): agree on the newest committed artifact at ANY
            # topology this group can reassemble — a W-rank set spliced
            # at global row boundaries, or a plain snapshot as a 1-rank
            # set (W->1 and 1->W are first-class)
            ts = booster.inner.train_set

            def _fp_partial(global_offset: int) -> int:
                return checkpoint_mod.elastic_fingerprint_partial(
                    np.asarray(ts.binned), int(ts.num_data),
                    int(global_offset))

            found = checkpoint_mod.find_latest_valid_elastic(
                snapshot_out, rank=rank, world=world,
                num_data=int(ts.num_data),
                valid_num_data=[int(vs.data.num_data)
                                for vs in booster.inner.valid_sets],
                fingerprint_partial_fn=_fp_partial,
                only_iteration=(checkpoint_mod.iteration_from_path(resume)
                                if isinstance(resume, str) else None))
        elif single_process:
            if isinstance(resume, str):    # explicit checkpoint file
                _, state = checkpoint_mod.load_snapshot(resume)
                found = (int(state["iteration"]), resume, state)
            else:                          # auto-detect; torn tails skipped
                found = checkpoint_mod.find_latest_valid(snapshot_out)
        else:
            # the resume barrier: ranks agree on the newest set valid on
            # EVERY rank (a torn shard anywhere demotes the whole group);
            # topology/partition mismatches raise a CheckpointError on all
            # ranks together instead of hanging the fleet
            found = checkpoint_mod.find_latest_valid_group(
                snapshot_out, rank=rank, world=world,
                fingerprint=booster.inner.data_fingerprint(),
                only_iteration=(checkpoint_mod.iteration_from_path(resume)
                                if isinstance(resume, str) else None))
        if found is None:
            log.info("snapshot_resume: no valid snapshot for %s; "
                     "training from scratch", snapshot_out)
        else:
            _, ck_path, state = found
            start_iter = checkpoint_mod.restore_state(
                booster, state, ckpt_callbacks, evals_result)
            obs_counters.event(
                "checkpoint_resume", iteration=start_iter, path=ck_path,
                kind="single" if single_process else "group")
            log.info("Resumed training from %s (continuing at "
                     "iteration %d)", ck_path, start_iter)

    # jax.profiler trace of the boosting loop (the reference's TIMETAG deep
    # profile becomes an xprof trace; lightweight counters are always on)
    profile_dir = params.get("profile_dir")
    import contextlib
    profile_ctx = contextlib.nullcontext()
    if profile_dir:
        import jax
        profile_ctx = jax.profiler.trace(str(profile_dir))

    # preemption safety (docs/ROBUSTNESS.md): SIGTERM/SIGINT request a
    # coordinated checkpoint at the next iteration boundary + a clean
    # exit.  Installed HERE, immediately before the try whose finally
    # restores the previous handlers, so they can never leak.
    preempt_watch = checkpoint_mod.PreemptionWatch(
        str(params.get("preempt_signal", "") or "")).install()
    preempt_armed = preempt_watch.armed or \
        faults_mod.get_faults().has_point("preempt")

    # liveness heartbeats (docs/ROBUSTNESS.md "Self-healing training"):
    # stamp iteration + wall-time into <output_model>.heartbeat.rank_R at
    # each boundary — pure host-side file writes on the happy path (the
    # zero-collectives pin of PR 6 extends over this), read by the
    # supervisor's hang detection.  Arming heartbeats also arms the
    # per-rank crash report on abnormal exit.
    heartbeat_interval = float(params.get("heartbeat_interval", 0) or 0)
    heartbeat = None
    if heartbeat_interval > 0:
        heartbeat = checkpoint_mod.Heartbeat(
            checkpoint_mod.heartbeat_path(snapshot_out, rank),
            heartbeat_interval)
        heartbeat.stamp(start_iter, force=True)

    def _boundary_liveness(iteration: int) -> None:
        """Once per iteration boundary: the supervisor-matrix fault points
        (a hard rank death / a wedged rank), then the heartbeat stamp."""
        fi = faults_mod.get_faults()
        if fi.enabled and fi.fire("rank_crash", iteration):
            log.warning("rank_crash fault: rank %d dying hard at "
                        "iteration %d (os._exit, no checkpoint, no "
                        "goodbye)", rank, iteration)
            os._exit(70)
        if fi.enabled and fi.fire("host_lost", iteration):
            log.warning("host_lost fault: rank %d dying hard at iteration "
                        "%d — and its host will NOT come back (every "
                        "relaunched incarnation dies again at startup)",
                        rank, iteration)
            os._exit(70)
        if fi.enabled and fi.fire("rank_hang", iteration):
            log.warning("rank_hang fault: rank %d wedging at iteration %d "
                        "(stand-in for a stuck device collective; "
                        "heartbeats stop now)", rank, iteration)
            import time as _time
            while True:          # only SIGKILL — or the supervisor — ends this
                _time.sleep(3600)
        if heartbeat is not None:
            heartbeat.stamp(iteration)

    train_span = obs_trace.get_tracer().span(
        "train", num_boost_round=num_boost_round)
    try:
        with profile_ctx, train_span:
            for i in range(start_iter, num_boost_round):
                for cb in cbs_before:
                    cb(callback_mod.CallbackEnv(
                        model=booster, params=params,
                        iteration=i, begin_iteration=0,
                        end_iteration=num_boost_round,
                        evaluation_result_list=None))
                finished = booster.update(fobj=fobj)

                evaluation_result_list = []
                if valid_sets:
                    if is_valid_contain_train:
                        evaluation_result_list.extend(
                            (train_data_name, m, v, hib)
                            for (_, m, v, hib) in booster.eval_train(feval))
                    evaluation_result_list.extend(booster.eval_valid(feval))
                try:
                    for cb in cbs_after:
                        cb(callback_mod.CallbackEnv(
                            model=booster, params=params, iteration=i,
                            begin_iteration=0, end_iteration=num_boost_round,
                            evaluation_result_list=evaluation_result_list))
                except callback_mod.EarlyStopException as es:
                    booster.best_iteration = es.best_iteration + 1
                    for item in (es.best_score or []):
                        booster.best_score.setdefault(
                            item[0], {})[item[1]] = item[2]
                    break
                # BEFORE the snapshot block: a rank_crash/rank_hang at
                # boundary K dies with iterations since the last committed
                # set genuinely lost — the shape of a real mid-run death
                _boundary_liveness(i + 1)
                wrote_snapshot = False
                if snapshot_freq > 0 and (i + 1) % snapshot_freq == 0:
                    # gbdt.cpp:456-460's snapshot cadence, upgraded to an
                    # atomic resumable checkpoint (coordinated shard set
                    # across processes).  AFTER the callbacks so the
                    # captured eval/early-stop state matches iteration i.
                    _write_checkpoint(i + 1)
                    wrote_snapshot = True
                if preempt_armed:
                    fi = faults_mod.get_faults()
                    want = preempt_watch.requested or \
                        (fi.enabled and fi.fire("preempt", i + 1))
                    if not single_process:
                        # a preemption notice may land on ONE rank only;
                        # the group must agree before anyone checkpoints
                        # or exits (hardened ladder: a dead peer surfaces
                        # as a named CollectiveError, not a hang)
                        want = any(sync_mod.allgather_object(bool(want)))
                    if want:
                        if not wrote_snapshot:
                            _write_checkpoint(i + 1)
                        obs_counters.event("preempt_checkpoint",
                                           iteration=i + 1)
                        log.info("Preemption requested: coordinated "
                                 "checkpoint written at iteration %d; "
                                 "exiting the training loop cleanly "
                                 "(snapshot_resume continues from here)",
                                 i + 1)
                        break
                if finished:
                    break
        # drain pipelined tree materialization NOW: deferred guard trips
        # (non-finite raise) and late no-split rewinds must surface from
        # train() itself, not from a later .models access
        booster.inner.models
        if booster.best_iteration <= 0:
            booster.best_iteration = booster.current_iteration()
        booster.inner.timers.report("training phase timers")
        if heartbeat is not None:
            heartbeat.stamp(booster.current_iteration(), force=True)
    except BaseException as e:
        # abnormal exit with heartbeats armed (i.e. a supervised rank):
        # flush a per-rank crash report — exception, every thread's stack,
        # the obs event-ring tail — so the supervisor can say WHY this
        # rank died without anyone re-running under a debugger.
        # EarlyStopException never reaches here (handled at the boundary);
        # SystemExit from the double-signal path and SimulatedCrash from
        # the fault matrix are exactly the deaths worth a report.
        if heartbeat is not None:
            checkpoint_mod.write_crash_report(snapshot_out, rank, exc=e)
        raise
    finally:
        preempt_watch.restore()   # handlers are scoped to THIS training
        if devprof_on:
            # finalize BEFORE the trace writes: the device_profile block
            # rides the trace as a telemetry.summary event so one file
            # carries the host spans AND the device attribution
            dp_summary = obs_devprof.stop()
            if dp_summary is not None:
                obs_trace.get_tracer().summary("device_profile", dp_summary)
        if telemetry_on:
            # recompile evidence: how many distinct (shape, donation)
            # entries the grower jit accumulated this training — a number
            # above the expected pow2-bucket count means buffer-identity
            # churn forced recompiles
            grow = getattr(booster.inner, "grow", None)
            cache_size = getattr(grow, "_cache_size", None)
            if callable(cache_size):
                try:
                    obs_counters.gauge("grower_jit_entries",
                                       int(cache_size()))
                except (TypeError, ValueError) as e:
                    # a gauge is best-effort, but anything beyond a size
                    # that won't coerce to int is a real bug — let it raise
                    log.debug("grower_jit_entries gauge unavailable: %s", e)
            # GSPMD trainings: record the compiled-HLO collective census
            # (compiler-inserted collectives never hit a call-site
            # counter) so the trace's final snapshot carries the real
            # communication story (docs/DISTRIBUTED.md).  The lowering
            # re-hits the persistent compilation cache, so this is a
            # read, not a second compile, on any warm run.
            if getattr(booster.inner, "_gspmd_mesh", None) is not None:
                try:
                    booster.inner.grow_hlo_census()
                except Exception as e:   # telemetry is best-effort
                    log.debug("grow HLO census unavailable: %s", e)
            # flush the memory summary (peak gauge + top residents event)
            # BEFORE the trace writes its final counter snapshot, so the
            # trace file carries the whole memory story
            obs_memory.stop()
            if mq_armed:
                # model-quality summary (top features by gain, gain-decay
                # curve) rides the trace like the device_profile block so
                # one file carries the whole training story
                obs_trace.get_tracer().summary(
                    "model_quality",
                    obs_model_quality.get_tracker().summary())
            obs_trace.stop()
        if mq_armed:
            # cache the training bin distribution on the booster while
            # the plane is still armed — later model saves embed it for
            # the serving drift monitor (one host bincount pass)
            try:
                booster.inner._training_distribution()
            except Exception as e:   # telemetry is best-effort
                log.debug("training distribution unavailable: %s", e)
            # after the trace summary (needs the live tracker) but before
            # the flight stop — the tracker itself never writes at stop
            obs_model_quality.stop()
        if exporter_armed:
            obs_metrics.stop_exporter()
        if flight_armed:
            # after memory/trace teardown so their final events (the
            # memory_summary, late checkpoint events) still stream
            obs_flight.stop()
        if fault_spec:
            faults_mod.restore(prev_faults)
    return booster


class CVBooster:
    """All per-fold boosters of a cv run (reference engine.py:230-252):
    unknown attribute access dispatches the call to every fold's booster
    and returns the list of results."""

    def __init__(self, boosters=None):
        self.boosters = list(boosters or [])
        self.best_iteration = -1

    def append(self, booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)

        def handler(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs)
                    for b in self.boosters]
        return handler


def _make_n_folds(full_data: Dataset, nfold: int, params: Dict,
                  seed: int, stratified: bool, shuffle: bool,
                  group_info: Optional[np.ndarray]):
    full_data.construct()
    num_data = full_data.num_data()
    rng = np.random.RandomState(seed)
    if group_info is not None:
        # group-aware folds: split whole queries
        group_sizes = np.asarray(group_info, dtype=np.int64)
        ngroups = len(group_sizes)
        gidx = np.arange(ngroups)
        if shuffle:
            rng.shuffle(gidx)
        folds_groups = np.array_split(gidx, nfold)
        bounds = np.concatenate([[0], np.cumsum(group_sizes)])
        for fg in folds_groups:
            test_idx = np.concatenate(
                [np.arange(bounds[g], bounds[g + 1]) for g in fg]) \
                if len(fg) else np.empty(0, dtype=np.int64)
            yield np.setdiff1d(np.arange(num_data), test_idx), test_idx, fg
        return
    if stratified:
        label = full_data.get_label().astype(np.int64)
        folds = [[] for _ in range(nfold)]
        for cls in np.unique(label):
            idx = np.nonzero(label == cls)[0]
            if shuffle:
                rng.shuffle(idx)
            for f, part in enumerate(np.array_split(idx, nfold)):
                folds[f].append(part)
        for f in range(nfold):
            test_idx = np.concatenate(folds[f])
            yield np.setdiff1d(np.arange(num_data), test_idx), test_idx, None
        return
    idx = np.arange(num_data)
    if shuffle:
        rng.shuffle(idx)
    for part in np.array_split(idx, nfold):
        yield np.setdiff1d(np.arange(num_data), part), part, None


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True, shuffle: bool = True,
       metrics: Optional[Union[str, List[str]]] = None,
       fobj=None, feval=None, init_model=None,
       feature_name="auto", categorical_feature="auto",
       early_stopping_rounds: Optional[int] = None,
       verbose_eval=None, seed: int = 0,
       callbacks: Optional[List[Callable]] = None,
       eval_train_metric: bool = False) -> Dict[str, List[float]]:
    """engine.py:230-460 analogue; returns {metric-mean: [...], metric-stdv: [...]}."""
    params = canonicalize_params(params)
    if "num_iterations" in params:
        num_boost_round = int(params.pop("num_iterations"))
    if metrics is not None:
        params["metric"] = metrics
    if params.get("objective", "").startswith(("binary",)) is False \
            and params.get("objective") not in ("binary", "multiclass",
                                                "multiclassova"):
        stratified = False if params.get("objective") else stratified

    train_set.construct()
    raw = train_set.ensure_raw()
    if raw is None:
        log.fatal("cv requires raw data (set free_raw_data=False)")
    label = train_set.get_label()
    weight = train_set.get_weight()
    group = train_set.get_group()

    if folds is None:
        folds = list(_make_n_folds(train_set, nfold, params, seed,
                                   stratified and group is None, shuffle, group))
    else:
        folds = [(tr, te, None) if len(f) == 2 else f
                 for f in (tuple(f) for f in folds)]

    boosters: List[Booster] = []
    for train_idx, test_idx, fold_groups in folds:
        tr = Dataset(raw[train_idx], label=label[train_idx],
                     weight=None if weight is None else weight[train_idx],
                     params=dict(params))
        te_ref = tr.create_valid(
            raw[test_idx], label=label[test_idx],
            weight=None if weight is None else weight[test_idx])
        if group is not None:
            # recompute per-fold group sizes
            gsizes = np.asarray(group, dtype=np.int64)
            gid = np.repeat(np.arange(len(gsizes)), gsizes)
            tr.group = np.bincount(gid[train_idx])[np.unique(gid[train_idx])]
            te_ref.group = np.bincount(gid[test_idx])[np.unique(gid[test_idx])]
        booster = Booster(params=dict(params), train_set=tr)
        booster.add_valid(te_ref, "valid")
        boosters.append(booster)

    results: Dict[str, List[float]] = collections.defaultdict(list)
    es_cb = (callback_mod.early_stopping(early_stopping_rounds, False)
             if early_stopping_rounds else None)
    for i in range(num_boost_round):
        all_evals = []
        for booster in boosters:
            booster.update(fobj=fobj)
            evals = booster.eval_valid(feval)
            if eval_train_metric:
                evals = list(booster.eval_train(feval)) + list(evals)
            all_evals.append(evals)
        # aggregate across folds
        agg: Dict[tuple, List[float]] = collections.defaultdict(list)
        order: List[tuple] = []
        for evals in all_evals:
            for name, metric, value, hib in evals:
                key = (name, metric, hib)
                if key not in agg:
                    order.append(key)
                agg[key].append(value)
        merged = []
        for key in order:
            name, metric, hib = key
            vals = agg[key]
            mean, std = float(np.mean(vals)), float(np.std(vals))
            results[f"{metric}-mean"].append(mean)
            results[f"{metric}-stdv"].append(std)
            merged.append((f"cv_agg {name}", metric, mean, hib, std))
        if verbose_eval:
            log.info("[%d]\t%s", i + 1,
                     "\t".join(f"{m[1]}: {m[2]:g} + {m[4]:g}" for m in merged))
        if es_cb is not None:
            try:
                es_cb(callback_mod.CallbackEnv(
                    model=CVBooster(boosters), params=params, iteration=i,
                    begin_iteration=0, end_iteration=num_boost_round,
                    evaluation_result_list=merged))
            except callback_mod.EarlyStopException as es:
                for k in results:
                    results[k] = results[k][:es.best_iteration + 1]
                break
    return dict(results)
