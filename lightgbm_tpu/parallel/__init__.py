from . import mesh  # noqa: F401
from .learner import (DataParallelStrategy, FeatureParallelStrategy,  # noqa: F401
                      VotingStrategy, make_distributed_grower)
from .mesh import make_mesh  # noqa: F401
