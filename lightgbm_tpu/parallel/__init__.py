from . import mesh  # noqa: F401
from .gspmd import make_gspmd_grower  # noqa: F401
from .learner import (DataParallelStrategy, FeatureParallelStrategy,  # noqa: F401
                      VotingStrategy, make_distributed_grower)
from .mesh import (MeshPlan, MeshPlanError, make_mesh,  # noqa: F401
                   make_named_mesh, plan_mesh)
