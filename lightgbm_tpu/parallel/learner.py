"""Distributed tree-learner strategies over a jax device mesh.

Re-designs the reference's parallel tree learners
(``src/treelearner/*parallel*_tree_learner.cpp``) as shard_map programs:

* :class:`DataParallelStrategy` — rows sharded; local child histograms are
  ``lax.psum``-reduced over ICI, after which every device owns the global
  histograms and finds the identical best split.  This replaces the
  ReduceScatter + feature-ownership plan + best-split Allreduce of
  ``data_parallel_tree_learner.cpp:50-243`` (on TPU the full-histogram psum
  rides ICI; ownership bookkeeping buys nothing).
* :class:`FeatureParallelStrategy` — every device holds all rows (exactly the
  reference's feature-parallel contract, feature_parallel_tree_learner.cpp),
  histograms/scan run only on the device's feature slice, and the winning
  split is agreed with a gain-argmax sync (``SyncUpGlobalBestSplit``,
  parallel_tree_learner.h:184-207 → pmax + broadcast-from-winner).
* :class:`VotingStrategy` — data-parallel with PV-tree communication
  compression (voting_parallel_tree_learner.cpp): each shard votes its local
  top-k features, the global top-2k are selected from the gathered votes, and
  only those features' histograms are psum-reduced.

All strategies plug into ``make_grower`` and are wrapped in ``shard_map`` by
:func:`make_distributed_grower`.

Since the GSPMD rewrite (``parallel/gspmd.py``, docs/DISTRIBUTED.md) this
module is the FORCED A/B PARTNER (``parallel_impl=shardmap``), not the
default: the NamedSharding path lets the XLA partitioner insert and
overlap the same collectives this file issues by hand.  ``auto`` still
resolves here for multi-process training and for the voting learner
(PV-tree's vote compression is call-site collective machinery by nature)
— and the explicit choreography below remains the reference against
which the compiler-owned path is A/B'd until on-chip numbers land.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map
import inspect

# the replication-check kwarg was renamed check_rep -> check_vma across
# jax releases; resolve the spelling this runtime accepts once (same
# version-tolerance discipline as ops/pallas_compat.py)
_CHECK_KW = ("check_vma" if "check_vma"
             in inspect.signature(shard_map).parameters else "check_rep")

from ..grower import (FeatureMeta, GrowerConfig, SerialStrategy, TreeArrays,
                      expand_bundle_hist, make_expand_maps, make_grower)
from ..obs.collectives import note_collective
from ..ops.split import SplitResult, best_split, per_feature_best_gain


def _broadcast_from_winner(res: SplitResult, axis_name: str) -> SplitResult:
    """Gain-argmax sync across an axis (SyncUpGlobalBestSplit analogue):
    lowest-ranked shard with the maximal gain wins; its SplitResult is
    broadcast with a psum of masked fields."""
    # one accounting entry for the whole sync (its psums cover every
    # SplitResult field; pmax/pmin ride along at scalar cost)
    note_collective("psum", res, axis_name, site="best_split_sync")
    n_shards = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    gmax = lax.pmax(jnp.where(res.found, res.gain, -jnp.inf), axis_name)
    any_found = lax.pmax(res.found.astype(jnp.int32), axis_name) > 0
    winner = res.found & (res.gain == gmax)
    win_rank = lax.pmin(jnp.where(winner, rank, n_shards), axis_name)
    pick = (rank == win_rank) & any_found

    def bc(v):
        masked = jnp.where(pick, v, jnp.zeros_like(v))
        summed = lax.psum(masked.astype(jnp.float32)
                          if v.dtype == jnp.bool_ else masked, axis_name)
        return summed.astype(v.dtype) if v.dtype != jnp.bool_ \
            else summed > 0.5

    out = SplitResult(*[bc(v) for v in res])
    neg_inf = jnp.asarray(-jnp.inf, res.gain.dtype)
    return out._replace(
        found=any_found,
        gain=jnp.where(any_found, out.gain, neg_inf),
        feature=jnp.where(any_found, out.feature, -1))


class DataParallelStrategy(SerialStrategy):
    """Rows sharded over ``axis_name``; histograms psum-reduced.

    The smaller-child histogram measured by each shard over its local rows is
    psum-reduced (the ReduceScatter + ownership plan of
    ``data_parallel_tree_learner.cpp:148-163`` collapsed to one collective);
    the parent subtraction then happens on the already-global histograms, so
    the larger child is never communicated — exactly the reference's
    guarantee (``:246-252``)."""

    def __init__(self, cfg: GrowerConfig, axis_name: str = "data"):
        super().__init__(cfg)
        self.axis = axis_name

    def reduce_hist(self, hist):
        note_collective("psum", hist, self.axis, site="reduce_hist")
        return lax.psum(hist, self.axis)

    def reduce_scalar(self, x):
        note_collective("psum", x, self.axis, site="reduce_scalar")
        return lax.psum(x, self.axis)


class FeatureParallelStrategy(SerialStrategy):
    """All rows on every device; features sliced per shard.

    The physical column count must be padded to a multiple of the shard
    count (pad features are masked via feat_valid=False / absent from the
    bundle maps).  With EFB bundles the shard owns a window of physical
    columns and expands only the logical features living in that window
    (``make_expand_maps`` with a column window); without bundles the
    logical metadata is sliced directly.
    """

    def __init__(self, cfg: GrowerConfig, axis_name: str = "feature",
                 num_shards: int = 1):
        super().__init__(cfg)
        self.axis = axis_name
        self.num_shards = num_shards

    def setup(self, bins, meta: FeatureMeta, feat_valid):
        n, f = bins.shape
        fl = f // self.num_shards
        ax = lax.axis_index(self.axis)
        start = ax * fl
        bins_local = lax.dynamic_slice(bins, (0, start), (n, fl))
        if meta.col is not None:
            # bundled: logical meta stays global; expansion maps are local
            maps = make_expand_maps(meta, self.cfg.max_bin,
                                    col_start=start, col_count=fl)
            return (meta, feat_valid, bins_local, None, None, start, maps)
        meta_local = FeatureMeta(
            num_bin=lax.dynamic_slice(meta.num_bin, (start,), (fl,)),
            missing_type=lax.dynamic_slice(meta.missing_type, (start,), (fl,)),
            default_bin=lax.dynamic_slice(meta.default_bin, (start,), (fl,)),
            is_categorical=lax.dynamic_slice(
                meta.is_categorical, (start,), (fl,)))
        fv_local = lax.dynamic_slice(feat_valid, (start,), (fl,))
        return (meta, feat_valid, bins_local, meta_local, fv_local, start,
                None)

    def hist_bins(self, ctx, bins):
        return ctx[2]

    def find(self, ctx, hist_child, pg, ph, pc, feat_ok):
        meta, feat_valid, _, meta_local, fv_local, start, maps = ctx
        if maps is not None:
            # expand the local physical histograms into the (global) logical
            # feature space; features outside this shard's window are zeroed
            # and masked, so the global numbering needs no feature_base shift
            hist_log = expand_bundle_hist(hist_child, pg, ph, pc, maps)
            res, ok = best_split(hist_log, pg, ph, pc, meta.num_bin,
                                 meta.missing_type, meta.default_bin,
                                 feat_valid & maps[5] & feat_ok,
                                 self.cfg.split_config(),
                                 is_cat=meta.is_categorical,
                                 with_feat_ok=True)
            ok_global = ok & maps[5]
        else:
            fok_local = lax.dynamic_slice(feat_ok, (start,),
                                          (fv_local.shape[0],))
            # feature_base shifts to global numbering before the argmax sync
            res, ok = best_split(hist_child, pg, ph, pc, meta_local.num_bin,
                                 meta_local.missing_type,
                                 meta_local.default_bin,
                                 fv_local & fok_local,
                                 self.cfg.split_config(),
                                 feature_base=start,
                                 is_cat=meta_local.is_categorical,
                                 with_feat_ok=True)
            ok_global = lax.dynamic_update_slice(
                jnp.zeros_like(feat_ok), ok, (start,))
        # every shard owns a disjoint feature window: OR across shards
        # rebuilds the full is_splittable vector identically everywhere
        ok_i32 = ok_global.astype(jnp.int32)
        note_collective("psum", ok_i32, self.axis, site="feat_ok_sync")
        ok_global = lax.psum(ok_i32, self.axis) > 0
        return _broadcast_from_winner(res, self.axis), ok_global


class DataFeatureStrategy(FeatureParallelStrategy):
    """2-D hybrid: rows sharded over the ``data`` mesh axis, the split
    scan sharded over the ``feature`` axis.

    The composition the reference leaves to its template parameter
    (``data_parallel_tree_learner.cpp:255-256`` instantiates
    DataParallel<GPUTreeLearner> etc. but never ships a data x feature
    product): each (d, f) device histograms ITS row shard over ITS
    column slice; a psum over ``data`` makes the slice's histograms
    global, and the feature-axis argmax sync of the parent class agrees
    on the winning split.  Row routing happens on the data shard,
    replicated across the feature axis."""

    def __init__(self, cfg: GrowerConfig, data_axis: str = "data",
                 feat_axis: str = "feature", num_feat_shards: int = 1):
        super().__init__(cfg, feat_axis, num_feat_shards)
        self.data_axis = data_axis

    def reduce_hist(self, hist):
        note_collective("psum", hist, self.data_axis, site="reduce_hist")
        return lax.psum(hist, self.data_axis)

    def reduce_scalar(self, x):
        note_collective("psum", x, self.data_axis, site="reduce_scalar")
        return lax.psum(x, self.data_axis)


class VotingStrategy(SerialStrategy):
    """Data-parallel with top-k vote compression (PV-tree).

    ``hist`` returns the LOCAL histograms; ``find`` votes local top-k
    features, selects the global top-2k from the gathered votes, psums only
    the selected slices, and finds the best split on the reduced set.
    """

    def __init__(self, cfg: GrowerConfig, axis_name: str = "data",
                 top_k: int = 20, num_shards: int = 1):
        super().__init__(cfg)
        self.axis = axis_name
        self.top_k = top_k
        # the LOCAL vote scan sees ~1/S of every leaf's rows, so the data /
        # hessian gates must shrink with the shard count or features stop
        # voting long before the leaf is globally unsplittable
        # (voting_parallel_tree_learner.cpp:54-56 divides both by
        # num_machines; integer division for the count, float for the
        # hessian).  The GLOBAL find on the psum-reduced histograms keeps
        # the unscaled config.
        self.local_scfg = cfg.split_config()._replace(
            min_data_in_leaf=cfg.min_data_in_leaf // num_shards,
            min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf / num_shards)

    def reduce_scalar(self, x):
        note_collective("psum", x, self.axis, site="reduce_scalar")
        return lax.psum(x, self.axis)

    # reduce_hist stays identity: histograms remain LOCAL and only the
    # voted feature slices are psum-reduced inside ``find`` (PV-tree's
    # communication compression); the parent-minus-smaller subtraction in
    # the grower is therefore performed in each shard's local space.

    def find(self, ctx, hist_child, pg, ph, pc, feat_ok):
        # the voting scan runs on a SLICED feature subset, so the serial
        # strategy's full-width fused ctx does not apply (best_split
        # derives the masks inline on the fused path)
        meta, feat_valid, maps, _ = ctx
        feat_valid = feat_valid & feat_ok
        scfg = self.cfg.split_config()
        if maps is not None:
            # EFB: expand the LOCAL physical histograms with LOCAL parent
            # sums (every row lands in exactly one bin of physical column 0,
            # so its bin sums are the local leaf totals).  Expansion is
            # linear in the histogram given additive parents, so the psum of
            # locally-expanded slices below equals the expansion of the
            # psum-reduced histogram.
            pl = hist_child[0].sum(axis=0)                   # [3] local parent
            hist_child = expand_bundle_hist(hist_child, pl[0], pl[1], pl[2],
                                            maps)
        f = hist_child.shape[0]
        k = min(self.top_k, f)
        # local votes from local histograms with LOCAL parent sums (PV-tree
        # votes are defined on each worker's own leaf statistics,
        # voting_parallel_tree_learner.cpp:255-330); the per-feature bin sums
        # [F, 1] broadcast through the candidate arithmetic
        pg_loc = hist_child[:, :, 0].sum(axis=1, keepdims=True)
        ph_loc = hist_child[:, :, 1].sum(axis=1, keepdims=True)
        pc_loc = hist_child[:, :, 2].sum(axis=1, keepdims=True)
        local_gain = per_feature_best_gain(
            hist_child, pg_loc, ph_loc, pc_loc, meta.num_bin,
            meta.missing_type, meta.default_bin, feat_valid, self.local_scfg,
            is_cat=meta.is_categorical)
        _, local_top = lax.top_k(local_gain, k)
        votes_local = jnp.stack([local_gain[local_top],
                                 local_top.astype(local_gain.dtype)], axis=-1)
        note_collective("all_gather", votes_local, self.axis, site="votes")
        gathered = lax.all_gather(votes_local, self.axis)    # [S, k, 2]
        votes = gathered.reshape(-1, 2)
        # global top-2k by voted gain (GlobalVoting :165-195); duplicate
        # feature ids are harmless (redundant reduced slices)
        _, top_idx = lax.top_k(votes[:, 0], min(2 * k, votes.shape[0]))
        sel = votes[top_idx, 1].astype(jnp.int32)        # [2k]
        # reduce only the selected features' histograms (CopyLocalHistogram)
        hist_voted = hist_child[sel]
        note_collective("psum", hist_voted, self.axis, site="voted_hist")
        hist_sel = lax.psum(hist_voted, self.axis)       # [2k, B, 3]
        res, sel_ok = best_split(hist_sel, pg, ph, pc, meta.num_bin[sel],
                                 meta.missing_type[sel],
                                 meta.default_bin[sel],
                                 feat_valid[sel], scfg,
                                 is_cat=meta.is_categorical[sel],
                                 with_feat_ok=True)
        res = res._replace(feature=jnp.where(res.found, sel[jnp.clip(
            res.feature, 0, sel.shape[0] - 1)], -1))
        # is_splittable only from the GLOBALLY-reduced scan of the voted
        # features; features this round never examined globally stay
        # splittable.  (Local gains use per-shard counts, so deriving the
        # flag from them would freeze subtrees whose per-shard row counts
        # fall under min_data_in_leaf even though the leaf is globally
        # splittable.)  sel is identical on every shard, so the state
        # stays shard-consistent without a collective.
        ok = jnp.ones_like(feat_ok).at[sel].set(sel_ok)
        return res, ok


def make_distributed_grower(cfg: GrowerConfig, mesh: Mesh,
                            tree_learner: str = "data",
                            top_k: int = 20, bundled: bool = False,
                            pack_plan=None):
    """shard_map-wrapped grow function for a 1-D mesh.

    Returns ``fn(bins, gw, hw, cw, meta, feat_valid) -> (TreeArrays, row_leaf)``
    operating on global (host-level) arrays.  Rows (data/voting) or the
    feature scan (feature) are sharded over the mesh axis.  ``bundled``
    states whether the FeatureMeta carries EFB col/offset arrays (their
    specs must match the pytree).  ``pack_plan`` (data/packing.py) adds a
    second positional arg — the nibble-packed histogram matrix, sharded
    like ``bins`` (data/voting only; the feature learner's column
    slicing is incompatible with shared bytes and boosting gates it off).
    """
    axis = mesh.axis_names[0]
    n_shards = mesh.devices.size
    if tree_learner == "data":
        strategy = DataParallelStrategy(cfg, axis)
        in_row = P(axis)
        row_out = P(axis)
    elif tree_learner == "voting":
        strategy = VotingStrategy(cfg, axis, top_k, num_shards=n_shards)
        in_row = P(axis)
        row_out = P(axis)
    elif tree_learner == "feature":
        strategy = FeatureParallelStrategy(cfg, axis, n_shards)
        in_row = P()
        row_out = P()
    elif tree_learner == "data_feature":
        if len(mesh.axis_names) != 2:
            raise ValueError("data_feature needs a 2-D (data x feature) mesh")
        da, fa = mesh.axis_names
        strategy = DataFeatureStrategy(cfg, da, fa,
                                       int(mesh.shape[fa]))
        in_row = P(da)
        row_out = P(da)
    else:
        raise ValueError(f"unknown tree_learner {tree_learner}")

    if pack_plan is not None and tree_learner in ("feature", "data_feature"):
        raise ValueError("bin packing is incompatible with the "
                         "feature-parallel column slicing")
    grow = make_grower(cfg, strategy, pack_plan=pack_plan)
    if tree_learner in ("data", "voting"):
        bins_spec = P(axis, None)
    elif tree_learner == "data_feature":
        bins_spec = P(mesh.axis_names[0], None)   # rows sharded, cols whole
    else:
        bins_spec = P()
    meta_spec = (FeatureMeta(P(), P(), P(), P(), P(), P()) if bundled
                 else FeatureMeta(P(), P(), P(), P()))
    tree_spec = TreeArrays(*([P()] * len(TreeArrays._fields)))
    hist_spec = (bins_spec,) if pack_plan is not None else ()

    fn = shard_map(grow, mesh=mesh,
                   in_specs=(bins_spec, *hist_spec, in_row, in_row, in_row,
                             meta_spec, P()),
                   out_specs=(tree_spec, row_out),
                   **{_CHECK_KW: False})
    return jax.jit(fn)
