"""Device-mesh utilities.

The reference's distributed substrate is a hand-built TCP/MPI collective layer
(``src/network/``: Bruck allgather, recursive-halving reduce-scatter over a
machine-list file).  On TPU the entire layer collapses to ``jax.sharding.Mesh``
axes + XLA collectives over ICI/DCN: machine-list → mesh construction,
rank → ``lax.axis_index``, Allreduce/ReduceScatter → ``lax.psum`` /
``lax.psum_scatter``.  Multi-host initialization goes through
``jax.distributed.initialize`` (the analogue of ``Network::Init``).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
FEATURE_AXIS = "feature"


def distributed_is_initialized() -> bool:
    """Is the multi-process runtime up?  ``jax.distributed.is_initialized``
    is not present on every jax this repo supports (0.4.37 dropped it from
    the public module), so fall back to the distributed global state the
    way the ops/pallas_compat.py shim handles renamed Pallas API."""
    try:
        return bool(jax.distributed.is_initialized())
    except AttributeError:
        pass
    try:
        from jax._src import distributed as _dist
        return getattr(_dist.global_state, "client", None) is not None
    except Exception:       # pragma: no cover - future-jax defensive
        return False


def make_mesh(num_devices: int = 0, axis_name: str = DATA_AXIS,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the given axis (rows for data-parallel, columns for
    feature-parallel)."""
    devs = list(devices) if devices is not None else jax.devices()
    if num_devices and num_devices > 0:
        devs = devs[:num_devices]
    return Mesh(np.asarray(devs), (axis_name,))


def make_2d_mesh(data: int, feature: int) -> Mesh:
    """data x feature mesh for combined row/column sharding — the 2-D
    hybrid learner (``tree_learner=data_feature``,
    parallel/learner.py DataFeatureStrategy)."""
    devs = np.asarray(jax.devices()[:data * feature]).reshape(data, feature)
    return Mesh(devs, (DATA_AXIS, FEATURE_AXIS))


def _enable_cpu_collectives() -> None:
    """Multi-process CPU needs a cross-process collectives transport: jax
    0.4.37's default (``none``) makes every cross-host computation fail
    with "Multiprocess computations aren't implemented on the CPU
    backend".  Select gloo — but only when the job explicitly runs on CPU
    (the 2-process CI harness); TPU slices keep their ICI transport."""
    import os
    plats = jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")
    if "cpu" not in str(plats):
        return
    try:
        # flag-only option: no attribute access, go through the value table
        cur = jax.config.values.get("jax_cpu_collectives_implementation")
        if cur in (None, "none"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, KeyError):  # pragma: no cover - older/newer jax
        pass


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bring-up (Network::Init analogue; machine-list file →
    coordinator address)."""
    if coordinator_address is not None:
        _enable_cpu_collectives()
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)


def parse_machine_list(path: str):
    """Reference mlist format (``Network::Init``, src/network/linkers.cpp):
    one ``ip port`` pair per line."""
    machines = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2:
                machines.append((parts[0], int(parts[1])))
    return machines


def write_machine_list(path: str, machines) -> None:
    """Inverse of :func:`parse_machine_list` — the supervisor rewrites the
    list when it refreshes ports between group relaunches."""
    with open(path, "w") as f:
        for ip, port in machines:
            f.write(f"{ip} {port}\n")


def refresh_local_ports(path: str) -> None:
    """Re-point every loopback entry of a machine list at a freshly bound
    (and immediately released) port.  A restarted group reuses its machine
    list, but the dead coordinator's listen port can linger in TIME_WAIT —
    on a single-host group (the CI harness, local supervised runs) fresh
    ports per incarnation make relaunch deterministic.  Non-local entries
    (a real multi-host fleet) are left untouched: their ports are
    infrastructure, not ours to rebind."""
    import socket
    machines = parse_machine_list(path)
    out = []
    for ip, port in machines:
        if ip in ("127.0.0.1", "localhost"):
            s = socket.socket()
            s.bind((ip if ip != "localhost" else "127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
        out.append((ip, port))
    write_machine_list(path, out)


def _local_rank(machines) -> Optional[int]:
    """Find this host in the machine list by its addresses — the reference's
    rank discovery (linkers.cpp matches local interface IPs).  The
    ``LGBM_TPU_RANK`` env var overrides (containers often NAT their IPs)."""
    import os
    import socket
    env = os.environ.get("LGBM_TPU_RANK")
    if env is not None:
        return int(env)
    try:
        local = {"127.0.0.1", "localhost", socket.gethostname(),
                 socket.gethostbyname(socket.gethostname())}
    except OSError:
        local = {"127.0.0.1", "localhost"}
    matches = [i for i, (ip, _) in enumerate(machines) if ip in local]
    if len(matches) > 1:
        # several workers on one host (duplicate IPs in the list): address
        # matching cannot disambiguate — the caller must set LGBM_TPU_RANK
        return None
    return matches[0] if matches else None


def init_distributed_from_config(cfg) -> bool:
    """Wire ``machine_list_file`` / ``num_machines`` into
    ``jax.distributed.initialize`` — the analogue of the reference CLI's
    network bring-up (``src/application/application.cpp:190-224``).

    Machine 0 is the coordinator; its listed port doubles as the JAX
    coordination-service port.  Rank comes from ``LGBM_TPU_RANK`` or from
    matching local addresses against the list.  Returns True when running
    multi-process (freshly initialized or already up)."""
    from ..utils import log
    if getattr(cfg, "num_machines", 1) <= 1:
        return False
    # must not touch the backend (jax.devices/process_count) before
    # jax.distributed.initialize; use is_initialized to test idempotently
    if distributed_is_initialized():
        return True                      # already initialized
    if not cfg.machine_list_file:
        log.fatal("num_machines=%d but no machine_list_file given",
                  cfg.num_machines)
    machines = parse_machine_list(cfg.machine_list_file)[:cfg.num_machines]
    if len(machines) < cfg.num_machines:
        log.fatal("machine_list_file lists %d machines, num_machines=%d",
                  len(machines), cfg.num_machines)
    rank = _local_rank(machines)
    if rank is None:
        log.fatal("cannot determine this machine's rank: no local address in "
                  "%s (set LGBM_TPU_RANK)", cfg.machine_list_file)
    coordinator = f"{machines[0][0]}:{machines[0][1]}"
    log.info("Initializing distributed runtime: %d machines, rank %d, "
             "coordinator %s", len(machines), rank, coordinator)
    init_distributed(coordinator, len(machines), rank)
    return True


def pad_rows(n: int, shards: int) -> int:
    """Rows padded so every shard gets an equal static slice."""
    return (-n) % shards


def pad_features(f: int, shards: int) -> int:
    return (-f) % shards
