"""Device-mesh utilities.

The reference's distributed substrate is a hand-built TCP/MPI collective layer
(``src/network/``: Bruck allgather, recursive-halving reduce-scatter over a
machine-list file).  On TPU the entire layer collapses to ``jax.sharding.Mesh``
axes + XLA collectives over ICI/DCN: machine-list → mesh construction,
rank → ``lax.axis_index``, Allreduce/ReduceScatter → ``lax.psum`` /
``lax.psum_scatter``.  Multi-host initialization goes through
``jax.distributed.initialize`` (the analogue of ``Network::Init``).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
FEATURE_AXIS = "feature"


def make_mesh(num_devices: int = 0, axis_name: str = DATA_AXIS,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the given axis (rows for data-parallel, columns for
    feature-parallel)."""
    devs = list(devices) if devices is not None else jax.devices()
    if num_devices and num_devices > 0:
        devs = devs[:num_devices]
    return Mesh(np.asarray(devs), (axis_name,))


def make_2d_mesh(data: int, feature: int) -> Mesh:
    """data x feature mesh for combined row/column sharding (reserved for
    the 2-D hybrid learner; not yet wired into the boosting layer)."""
    devs = np.asarray(jax.devices()[:data * feature]).reshape(data, feature)
    return Mesh(devs, (DATA_AXIS, FEATURE_AXIS))


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bring-up (Network::Init analogue; machine-list file →
    coordinator address)."""
    if coordinator_address is not None:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)


def pad_rows(n: int, shards: int) -> int:
    """Rows padded so every shard gets an equal static slice."""
    return (-n) % shards


def pad_features(f: int, shards: int) -> int:
    return (-f) % shards
