"""Device-mesh utilities.

The reference's distributed substrate is a hand-built TCP/MPI collective layer
(``src/network/``: Bruck allgather, recursive-halving reduce-scatter over a
machine-list file).  On TPU the entire layer collapses to ``jax.sharding.Mesh``
axes + XLA collectives over ICI/DCN: machine-list → mesh construction,
rank → ``lax.axis_index``, Allreduce/ReduceScatter → ``lax.psum`` /
``lax.psum_scatter``.  Multi-host initialization goes through
``jax.distributed.initialize`` (the analogue of ``Network::Init``).
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
FEATURE_AXIS = "feature"

# GSPMD mesh axes (docs/DISTRIBUTED.md): rows shard over ``batch``, the
# histogram pool over ``feature``.  The shard_map learners keep the
# historical ``data`` spelling above; the named-sharding mesh follows the
# (batch, feature) convention of the block-distributed formulation.
BATCH_AXIS = "batch"


def distributed_is_initialized() -> bool:
    """Is the multi-process runtime up?  ``jax.distributed.is_initialized``
    is not present on every jax this repo supports (0.4.37 dropped it from
    the public module), so fall back to the distributed global state the
    way the ops/pallas_compat.py shim handles renamed Pallas API."""
    try:
        return bool(jax.distributed.is_initialized())
    except AttributeError:
        pass
    try:
        from jax._src import distributed as _dist
        return getattr(_dist.global_state, "client", None) is not None
    except Exception:       # pragma: no cover - future-jax defensive
        from ..obs.counters import counters
        counters.inc("distributed_probe_fallback")
        return False


def make_mesh(num_devices: int = 0, axis_name: str = DATA_AXIS,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the given axis (rows for data-parallel, columns for
    feature-parallel)."""
    devs = list(devices) if devices is not None else jax.devices()
    if num_devices and num_devices > 0:
        devs = devs[:num_devices]
    return Mesh(np.asarray(devs), (axis_name,))


def make_2d_mesh(data: int, feature: int) -> Mesh:
    """data x feature mesh for combined row/column sharding — the 2-D
    hybrid learner (``tree_learner=data_feature``,
    parallel/learner.py DataFeatureStrategy)."""
    devs = np.asarray(jax.devices()[:data * feature]).reshape(data, feature)
    return Mesh(devs, (DATA_AXIS, FEATURE_AXIS))


def make_named_mesh(data: int, feature: int,
                    devices: Optional[Sequence] = None) -> Mesh:
    """``(batch, feature)`` named mesh for the GSPMD learners
    (``parallel/gspmd.py``): rows shard over ``batch``, the histogram
    pool over ``feature``.  Either extent may be 1 (pure data- or pure
    feature-sharding); the product must not exceed the device count.

    Spans ALL processes' devices in process-major order: each process's
    local devices occupy a contiguous block of batch-axis rows, so one
    rank's row partition lands exactly on its own devices
    (``jax.make_array_from_process_local_data`` in
    ``boosting._setup_gspmd``) and the elastic shrink path re-cuts the
    same global row order at any world size."""
    devs = list(devices) if devices is not None else jax.devices()
    devs.sort(key=lambda d: (int(getattr(d, "process_index", 0)),
                             int(getattr(d, "id", 0))))
    need = data * feature
    if need > len(devs):
        raise MeshPlanError(
            f"mesh shape {data}x{feature} needs {need} devices; "
            f"{len(devs)} available")
    return Mesh(np.asarray(devs[:need]).reshape(data, feature),
                (BATCH_AXIS, FEATURE_AXIS))


class MeshPlanError(RuntimeError):
    """Structured pre-flight failure of the sharding planner: no mesh
    shape over the available devices fits the predicted per-device peak
    in the capacity budget (the message carries the best candidate's
    component breakdown so the fix — fewer leaves/bins, more chips, a
    bigger budget — is actionable without a debugger)."""


class MeshPlan(NamedTuple):
    """One planner decision (``plan_mesh``): mesh extents, whether the
    binned matrix itself is block-sharded over ``feature`` (vs replicated
    along that axis), and the evidence backing the choice."""
    data: int                  # batch-axis extent
    feature: int               # feature-axis extent
    block_shard_bins: bool     # bins P(batch, feature) vs P(batch, None)
    per_device_bytes: int      # predicted per-device peak at this shape
    capacity: Optional[int]    # budget the plan was judged against
    components: dict           # top per-device components {name: bytes}
    reason: str                # human-readable decision trail


def _mesh_factorizations(n: int):
    """(data, feature) candidates over exactly ``n`` devices, data-major
    first (pure data-parallel is the cheapest shape when it fits: routing
    and split-find stay collective-free)."""
    out = [(d, n // d) for d in range(n, 0, -1) if n % d == 0]
    return out


def mesh_shape_fits_processes(data: int, feature: int, procs: int,
                              local_devices: int) -> Optional[str]:
    """Can a ``(data, feature)`` mesh be laid out so every process's
    local devices tile whole batch-axis rows?  Returns None when it can,
    else the human-readable refusal.  Required for multi-process GSPMD:
    each rank holds its OWN row partition, so its devices must cover a
    contiguous block of batch rows across the FULL feature extent —
    ``data`` a multiple of the process count and the per-process device
    count a multiple of ``feature``."""
    procs = max(1, int(procs))
    if procs == 1:
        return None
    if data % procs != 0:
        return (f"batch extent {data} does not divide over {procs} "
                "processes (each rank's row partition needs whole "
                "batch-axis rows)")
    if local_devices and local_devices % feature != 0:
        return (f"{local_devices} local device(s) per process cannot "
                f"tile {feature} feature shard(s) per batch row")
    return None


def plan_mesh(n_devices: int, rows: int, features: int, bins: int = 255,
              leaves: int = 31, num_class: int = 1,
              bin_bytes: Optional[int] = None, packed_cols: int = 0,
              valid_rows: int = 0, capacity: Optional[int] = None,
              prefer: str = "data", gspmd_fused: bool = False,
              procs: int = 1, local_devices: int = 0) -> MeshPlan:
    """The memory-driven sharding planner (``mesh_shape=auto``).

    Evaluates ``obs/memory.predict_hbm`` per candidate ``(data,
    feature)`` factorization of ``n_devices`` and returns the first shape
    — in preference order — whose predicted per-device peak fits
    ``capacity``.  Preference: pure data-parallel first (``prefer="data"``,
    the shape with no cross-shard routing or split-find traffic), walking
    toward feature-heavy shapes only under memory pressure;
    ``prefer="feature"`` walks the other way (the feature-parallel
    learner's contract), ``prefer="square"`` starts at the most balanced
    factorization (the 2-D hybrid).  Replication is part of the decision:
    a shape is first tried with the binned matrix replicated along
    ``feature`` (cheap routing) and block-sharded over both axes only if
    replication alone does not fit.  With no capacity signal (CPU hosts
    report none) the preferred shape wins outright.

    Multi-process jobs (``procs`` > 1, ``local_devices`` per process):
    candidates that cannot map each process's row partition onto its own
    devices are skipped (:func:`mesh_shape_fits_processes`) — a
    feature-heavy shape a single process could serve may be
    unreachable for a partitioned group, and the planner must say so
    at pre-flight rather than let the array placement fail mid-setup.

    Raises :class:`MeshPlanError` when nothing fits — a structured
    pre-flight error in milliseconds instead of an on-chip OOM minutes
    into a capture window."""
    from ..obs.memory import predict_hbm
    n_devices = max(int(n_devices), 1)
    cands = _mesh_factorizations(n_devices)
    if procs > 1:
        fits = [(d, f) for d, f in cands
                if mesh_shape_fits_processes(d, f, procs,
                                             local_devices) is None]
        if not fits:
            raise MeshPlanError(
                f"no factorization of {n_devices} device(s) lays out over "
                f"{procs} processes x {local_devices or '?'} local "
                "device(s): every candidate leaves some rank's row "
                "partition straddling another process's devices")
        cands = fits
    if prefer == "feature":
        cands = cands[::-1]
    elif prefer == "square":
        cands.sort(key=lambda df: (abs(df[0] - df[1]), -df[0]))

    def per_device(d, f, block):
        p = predict_hbm(rows=rows, features=features, bins=bins,
                        leaves=leaves, num_class=num_class,
                        bin_bytes=bin_bytes, packed_cols=packed_cols,
                        valid_rows=valid_rows, data_shards=d,
                        feature_shards=f, block_shard_bins=block,
                        gspmd_fused=gspmd_fused)
        comps = dict(sorted({**p["residents"], **p["transients"]}.items(),
                            key=lambda kv: -kv[1])[:4])
        return int(p["peak_bytes"]), comps

    best = None            # smallest-peak candidate, for the error message
    for d, f in cands:
        for block in (False, True) if f > 1 else (False,):
            peak, comps = per_device(d, f, block)
            if best is None or peak < best[3]:
                best = (d, f, block, peak, comps)
            if capacity is None or peak <= capacity:
                why = (f"{d}x{f} mesh"
                       + (", bins block-sharded" if block
                          else (", bins replicated over feature"
                                if f > 1 else ""))
                       + (f": predicted per-device peak "
                          f"{peak / 1e9:.2f} GB fits capacity "
                          f"{capacity / 1e9:.2f} GB"
                          if capacity is not None else
                          ": no capacity signal, preferred shape"))
                return MeshPlan(d, f, block, peak, capacity, comps, why)
    d, f, block, peak, comps = best
    detail = ", ".join(f"{k}={v / 1e9:.2f} GB" for k, v in comps.items())
    raise MeshPlanError(
        f"no mesh shape over {n_devices} device(s) fits: best candidate "
        f"{d}x{f}{' (bins block-sharded)' if block else ''} still needs "
        f"{peak / 1e9:.2f} GB per device vs capacity "
        f"{(capacity or 0) / 1e9:.2f} GB (top components: {detail}) — "
        f"shrink the shape (num_leaves/max_bin/rows), add devices, or "
        f"raise hbm_budget")


class PlacementPlan(NamedTuple):
    """One data-placement decision (``resolve_placement``): where the
    binned training matrix lives for this run and the evidence backing
    the choice."""
    mode: str                  # resident | chunked | sharded
    chunk_rows: int            # streamed block size (0 unless chunked)
    mesh: Optional[MeshPlan]   # the mesh plan when mode == "sharded"
    peak_bytes: int            # predicted peak at the chosen placement
    capacity: Optional[int]    # budget the plan was judged against
    components: dict           # top predicted components {name: bytes}
    reason: str                # human-readable decision trail


def default_chunk_rows(rows: int, requested: int = 0) -> int:
    """Streamed block size: the explicit ``stream_chunk_rows`` when
    given (clamped to the row count), else 256k rows capped at
    ``ceil(rows / 2)`` so even a small dataset exercises at least two
    blocks — the double buffer is pointless with one."""
    rows = max(1, int(rows))
    if requested and int(requested) > 0:
        return min(int(requested), rows)
    return max(1, min(262144, -(-rows // 2)))


def resolve_placement(rows: int, features: int, bins: int = 255,
                      leaves: int = 31, num_class: int = 1,
                      bin_bytes: Optional[int] = None,
                      packed_cols: int = 0, valid_rows: int = 0,
                      capacity: Optional[int] = None,
                      data_stream: str = "auto",
                      stream_chunk_rows: int = 0,
                      n_devices: int = 1, prefer: str = "data",
                      gspmd_fused: bool = False, procs: int = 1,
                      local_devices: int = 0) -> PlacementPlan:
    """The unified capacity walk (``data_stream=auto``): decide where the
    binned matrix lives BEFORE anything compiles by evaluating
    ``obs/memory.predict_hbm`` per placement rung —

    1. **resident** — the classic whole-matrix-on-device layout;
    2. **chunked** — streamed out-of-core blocks (data/stream.py): the
       requested (or default) block size first, then halving blocks down
       to a 4096-row floor, since the double-buffer footprint is the
       planner's lever;
    3. **sharded** — hand the shape to :func:`plan_mesh` when more than
       one device is available.

    An explicit ``data_stream=resident|chunked`` pins the rung (the
    budget check still runs later in pre-flight, so a forced placement
    that does not fit fails with the component breakdown rather than an
    on-chip OOM).  Every decision lands as one structured
    ``placement_decision`` obs event; when NOTHING fits the walk raises
    :class:`MeshPlanError` naming the best candidate per rung."""
    from ..obs.counters import counters
    from ..obs.memory import predict_hbm

    def predict(chunk):
        p = predict_hbm(rows=rows, features=features, bins=bins,
                        leaves=leaves, num_class=num_class,
                        bin_bytes=bin_bytes, packed_cols=packed_cols,
                        valid_rows=valid_rows, stream_chunk_rows=chunk)
        comps = dict(sorted({**p["residents"], **p["transients"]}.items(),
                            key=lambda kv: -kv[1])[:4])
        return int(p["peak_bytes"]), comps

    def decide(plan: PlacementPlan) -> PlacementPlan:
        counters.event("placement_decision", mode=plan.mode,
                       chunk_rows=plan.chunk_rows,
                       predicted_peak_bytes=plan.peak_bytes,
                       capacity_bytes=plan.capacity,
                       data_stream=data_stream, reason=plan.reason)
        return plan

    res_peak, res_comps = predict(0)
    if data_stream == "resident":
        return decide(PlacementPlan(
            "resident", 0, None, res_peak, capacity, res_comps,
            "data_stream=resident pinned by config"))
    if data_stream == "auto" and (capacity is None
                                  or res_peak <= capacity):
        why = ("resident: no capacity signal" if capacity is None else
               f"resident: predicted peak {res_peak / 1e9:.2f} GB fits "
               f"capacity {capacity / 1e9:.2f} GB")
        return decide(PlacementPlan("resident", 0, None, res_peak,
                                    capacity, res_comps, why))

    chunk0 = default_chunk_rows(rows, stream_chunk_rows)
    forced_chunk = data_stream == "chunked"
    best_stream = None
    chunk = chunk0
    while True:
        peak, comps = predict(chunk)
        if best_stream is None or peak < best_stream[1]:
            best_stream = (chunk, peak, comps)
        if forced_chunk and stream_chunk_rows:
            # an explicit block size is a pin, not a starting point
            break
        if capacity is not None and peak > capacity and chunk > 4096:
            chunk = max(4096, chunk // 2)
            continue
        break
    chunk, peak, comps = best_stream
    if forced_chunk or capacity is None or peak <= capacity:
        why = (f"chunked: {chunk}-row blocks, predicted peak "
               f"{peak / 1e9:.2f} GB"
               + (" pinned by data_stream=chunked" if forced_chunk else
                  (f" fits capacity {capacity / 1e9:.2f} GB (resident "
                   f"needs {res_peak / 1e9:.2f} GB)"
                   if capacity is not None else "")))
        return decide(PlacementPlan("chunked", chunk, None, peak,
                                    capacity, comps, why))

    if n_devices > 1:
        try:
            mp = plan_mesh(n_devices, rows, features, bins=bins,
                           leaves=leaves, num_class=num_class,
                           bin_bytes=bin_bytes, packed_cols=packed_cols,
                           valid_rows=valid_rows, capacity=capacity,
                           prefer=prefer, gspmd_fused=gspmd_fused,
                           procs=procs, local_devices=local_devices)
        except MeshPlanError:
            mp = None
        if mp is not None:
            return decide(PlacementPlan(
                "sharded", 0, mp, mp.per_device_bytes, capacity,
                mp.components,
                f"sharded: {mp.reason} (resident needs "
                f"{res_peak / 1e9:.2f} GB, best streamed "
                f"{peak / 1e9:.2f} GB)"))

    detail = ", ".join(f"{k}={v / 1e9:.2f} GB" for k, v in comps.items())
    counters.event("placement_decision", mode="refused",
                   chunk_rows=chunk, predicted_peak_bytes=peak,
                   capacity_bytes=capacity, data_stream=data_stream,
                   reason="no placement fits")
    raise MeshPlanError(
        f"no data placement fits capacity "
        f"{(capacity or 0) / 1e9:.2f} GB: resident needs "
        f"{res_peak / 1e9:.2f} GB, best streamed candidate "
        f"({chunk}-row blocks) still needs {peak / 1e9:.2f} GB "
        f"(top components: {detail})"
        + ("" if n_devices > 1 else ", and only 1 device is available "
           "for sharding") +
        " — shrink the shape (num_leaves/max_bin), lower "
        "stream_chunk_rows, add devices, or raise hbm_budget")


def parse_mesh_shape(spec: str, n_devices: int, prefer: str = "data"):
    """``mesh_shape`` parameter -> (data, feature) extents or None for
    ``auto`` (planner decides).  Accepts ``DxF`` (``2x4``), ``data``
    (all devices on the batch axis) and ``feature`` (all on the feature
    axis); rejects shapes the device count cannot serve."""
    s = str(spec or "auto").strip().lower()
    if s in ("", "auto"):
        return None
    if s == "data":
        return (n_devices, 1)
    if s == "feature":
        return (1, n_devices)
    m = s.replace("*", "x").split("x")
    if len(m) == 2 and all(p.strip().isdigit() for p in m):
        d, f = int(m[0]), int(m[1])
        if d < 1 or f < 1:
            raise ValueError(f"mesh_shape extents must be >= 1; got {spec!r}")
        if d * f > n_devices:
            raise ValueError(
                f"mesh_shape {d}x{f} needs {d * f} devices; only "
                f"{n_devices} available")
        return (d, f)
    raise ValueError(
        f"mesh_shape must be 'auto', 'data', 'feature', or 'DxF' "
        f"(e.g. 2x4); got {spec!r}")


def _enable_cpu_collectives() -> None:
    """Multi-process CPU needs a cross-process collectives transport: jax
    0.4.37's default (``none``) makes every cross-host computation fail
    with "Multiprocess computations aren't implemented on the CPU
    backend".  Select gloo — but only when the job explicitly runs on CPU
    (the 2-process CI harness); TPU slices keep their ICI transport."""
    import os
    plats = jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")
    if "cpu" not in str(plats):
        return
    try:
        # flag-only option: no attribute access, go through the value table
        cur = jax.config.values.get("jax_cpu_collectives_implementation")
        if cur in (None, "none"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, KeyError):  # pragma: no cover - older/newer jax
        pass


# epoch the runtime was last initialized under (the incarnation fence,
# parallel/sync.py): a relaunched in-process training at a NEWER epoch
# tears the stale runtime down and re-initializes instead of rejoining a
# rendezvous its peers already abandoned
_init_epoch: Optional[int] = None


def shutdown_distributed() -> None:
    """Tear the distributed runtime down (idempotent).  The supervisor
    relaunch path spawns fresh processes — their runtimes die with them —
    but an in-process relaunch (tests, embedding hosts) must disconnect
    the dead incarnation's coordination client before the new epoch's
    barrier can form."""
    global _init_epoch
    if distributed_is_initialized():
        jax.distributed.shutdown()
    _init_epoch = None


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     timeout: Optional[float] = None) -> None:
    """Multi-host bring-up (Network::Init analogue; machine-list file →
    coordinator address).  The startup barrier is bounded: a dead peer
    (or a stale survivor holding the old port) surfaces as a catchable
    :class:`~..parallel.sync.CollectiveError` after ``timeout`` seconds
    — with a structured ``distributed_init_failed`` event — never as an
    indefinite hang the supervisor can only SIGKILL."""
    global _init_epoch
    if coordinator_address is None:
        return
    _enable_cpu_collectives()
    kwargs = {}
    if timeout and timeout > 0:
        kwargs["initialization_timeout"] = max(1, int(timeout))
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id, **kwargs)
    except TypeError:       # older jax: no initialization_timeout kwarg
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except RuntimeError as e:
        from ..obs.counters import counters
        from .sync import CollectiveError
        counters.event("distributed_init_failed",
                       coordinator=coordinator_address,
                       num_processes=num_processes, process_id=process_id,
                       timeout=timeout, error=str(e))
        raise CollectiveError(
            f"distributed startup barrier failed for process "
            f"{process_id}/{num_processes} (coordinator "
            f"{coordinator_address}, timeout {timeout}s): {e}") from e
    from ..checkpoint import group_epoch
    _init_epoch = group_epoch()


def parse_machine_list(path: str):
    """Reference mlist format (``Network::Init``, src/network/linkers.cpp):
    one ``ip port`` pair per line."""
    machines = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2:
                machines.append((parts[0], int(parts[1])))
    return machines


def write_machine_list(path: str, machines) -> None:
    """Inverse of :func:`parse_machine_list` — the supervisor rewrites the
    list when it refreshes ports between group relaunches."""
    with open(path, "w") as f:
        for ip, port in machines:
            f.write(f"{ip} {port}\n")


def refresh_local_ports(path: str) -> None:
    """Re-point every loopback entry of a machine list at a freshly bound
    (and immediately released) port.  A restarted group reuses its machine
    list, but the dead coordinator's listen port can linger in TIME_WAIT —
    on a single-host group (the CI harness, local supervised runs) fresh
    ports per incarnation make relaunch deterministic.  Non-local entries
    (a real multi-host fleet) are left untouched: their ports are
    infrastructure, not ours to rebind."""
    import socket
    machines = parse_machine_list(path)
    out = []
    for ip, port in machines:
        if ip in ("127.0.0.1", "localhost"):
            s = socket.socket()
            s.bind((ip if ip != "localhost" else "127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
        out.append((ip, port))
    write_machine_list(path, out)


def _local_rank(machines) -> Optional[int]:
    """Find this host in the machine list by its addresses — the reference's
    rank discovery (linkers.cpp matches local interface IPs).  The
    ``LGBM_TPU_RANK`` env var overrides (containers often NAT their IPs)."""
    import os
    import socket
    env = os.environ.get("LGBM_TPU_RANK")
    if env is not None:
        return int(env)
    try:
        local = {"127.0.0.1", "localhost", socket.gethostname(),
                 socket.gethostbyname(socket.gethostname())}
    except OSError:
        local = {"127.0.0.1", "localhost"}
    matches = [i for i, (ip, _) in enumerate(machines) if ip in local]
    if len(matches) > 1:
        # several workers on one host (duplicate IPs in the list): address
        # matching cannot disambiguate — the caller must set LGBM_TPU_RANK
        return None
    return matches[0] if matches else None


def init_distributed_from_config(cfg) -> bool:
    """Wire ``machine_list_file`` / ``num_machines`` into
    ``jax.distributed.initialize`` — the analogue of the reference CLI's
    network bring-up (``src/application/application.cpp:190-224``).

    Machine 0 is the coordinator; its listed port doubles as the JAX
    coordination-service port.  Rank comes from ``LGBM_TPU_RANK`` or from
    matching local addresses against the list.  Returns True when running
    multi-process (freshly initialized or already up).

    Epoch fence at the startup barrier: when a supervisor stamped the
    group's current incarnation into the epoch file
    (``checkpoint.group_epoch_path``), a worker launched under an OLDER
    epoch raises :class:`~.sync.StaleEpochError` before touching the
    rendezvous — the startup-barrier extension of the per-payload fence
    in parallel/sync.py.  A runtime initialized under a PREVIOUS epoch
    (in-process relaunch) is torn down and re-initialized rather than
    rejoined."""
    from ..utils import log
    from ..checkpoint import group_epoch, read_group_epoch_file
    if getattr(cfg, "num_machines", 1) <= 1:
        return False
    my_epoch = group_epoch()
    stamped = read_group_epoch_file(getattr(cfg, "output_model", "") or "")
    if stamped is not None and stamped > my_epoch:
        from ..obs.counters import counters
        from .sync import StaleEpochError
        counters.event("stale_epoch_rejected", op="distributed_init",
                       frame_epoch=my_epoch, group_epoch=stamped)
        raise StaleEpochError(
            f"startup barrier refused: this process was launched under "
            f"epoch {my_epoch} but the group is at epoch {stamped} — a "
            f"stale incarnation must not join the new rendezvous",
            frame_epoch=my_epoch, group_epoch=stamped)
    # must not touch the backend (jax.devices/process_count) before
    # jax.distributed.initialize; use is_initialized to test idempotently
    if distributed_is_initialized():
        if _init_epoch is not None and _init_epoch != my_epoch:
            # in-process relaunch under a new incarnation: the old
            # runtime's coordination client belongs to a dead group
            log.info("Distributed runtime is from epoch %s; re-initializing "
                     "under epoch %d", _init_epoch, my_epoch)
            shutdown_distributed()
        else:
            return True                  # already initialized, same epoch
    if not cfg.machine_list_file:
        log.fatal("num_machines=%d but no machine_list_file given",
                  cfg.num_machines)
    machines = parse_machine_list(cfg.machine_list_file)[:cfg.num_machines]
    if len(machines) < cfg.num_machines:
        log.fatal("machine_list_file lists %d machines, num_machines=%d",
                  len(machines), cfg.num_machines)
    rank = _local_rank(machines)
    if rank is None:
        log.fatal("cannot determine this machine's rank: no local address in "
                  "%s (set LGBM_TPU_RANK)", cfg.machine_list_file)
    coordinator = f"{machines[0][0]}:{machines[0][1]}"
    log.info("Initializing distributed runtime: %d machines, rank %d, "
             "coordinator %s", len(machines), rank, coordinator)
    init_distributed(coordinator, len(machines), rank,
                     timeout=getattr(cfg, "collective_timeout", 0.0))
    return True


def pad_rows(n: int, shards: int) -> int:
    """Rows padded so every shard gets an equal static slice."""
    return (-n) % shards


def pad_features(f: int, shards: int) -> int:
    return (-f) % shards
