"""Host-object synchronization across processes.

The reference's distributed ``FindBin`` ships serialized ``BinMapper`` blobs
through its Bruck allgather (``dataset_loader.cpp:737-816``: each machine
fits mappers for its feature slice, then ``Network::Allgather`` merges).
With jax the transport is the distributed runtime's allgather over a
length-then-payload two-phase pickle — no hand-rolled socket layer.
"""
from __future__ import annotations

import pickle
from typing import Any, List

import numpy as np


def process_count() -> int:
    """Number of participating processes; 1 when the distributed runtime is
    not initialized (safe to call before backend init)."""
    import jax
    try:
        if not jax.distributed.is_initialized():
            return 1
    except Exception:
        return 1
    return jax.process_count()


def allgather_object(obj: Any) -> List[Any]:
    """Gather one picklable host object from every process, in process-index
    order (Network::Allgather of serialized blobs)."""
    import jax
    from jax.experimental import multihost_utils
    if process_count() == 1:
        return [obj]
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    lens = np.asarray(multihost_utils.process_allgather(
        np.asarray([len(payload)], np.int64))).reshape(-1)
    buf = np.zeros(int(lens.max()), np.uint8)
    buf[:len(payload)] = payload
    gathered = np.asarray(multihost_utils.process_allgather(buf))
    return [pickle.loads(gathered[i, :int(lens[i])].tobytes())
            for i in range(len(lens))]


def broadcast_object(obj: Any) -> Any:
    """Every process receives process 0's object (rank-0 decision sync)."""
    return allgather_object(obj)[0]
