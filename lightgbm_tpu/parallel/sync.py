"""Host-object synchronization across processes — hardened.

The reference's distributed ``FindBin`` ships serialized ``BinMapper`` blobs
through its Bruck allgather (``dataset_loader.cpp:737-816``); with jax the
transport is the distributed runtime's allgather over a length-then-payload
two-phase pickle.  Block-distributed GBT work (PAPERS.md) shows workers +
collectives are exactly where distributed boosting fails in practice, so
every host-object collective here is wrapped in the same recovery ladder:

* **payload integrity** — each process ships ``[length, crc32]`` alongside
  its pickle; the receiver verifies every slice and an error names the
  *offending process index* instead of dying later in ``pickle.loads``;
* **timeout** — one attempt may block at most ``collective_timeout``
  seconds (the runtime's allgather has no deadline of its own: a dead peer
  used to hang the fleet silently);
* **bounded retry with backoff** — transient failures re-attempt up to
  ``collective_retries`` times (exponential backoff), each retry counted
  into the ``collective_retries`` obs counter and recorded as a
  ``collective_retry`` structured event, so recovery is visible, never
  silent;
* **fault injection** — the ``collective_fail`` / ``collective_corrupt``
  points (:mod:`lightgbm_tpu.utils.faults`) exercise the whole ladder on
  CPU in tier-1;
* **incarnation epoch fence** — every payload header carries the group
  epoch the sender was launched under (``LGBM_TPU_GROUP_EPOCH``, minted
  per (re)launch by the supervisor).  A frame from a PREVIOUS incarnation
  — a process that survived a teardown and tries to rejoin after the
  group relaunched, possibly at a different world size — raises
  :class:`StaleEpochError` naming both epochs.  The fence is terminal:
  a stale peer does not become current by retrying, so the retry ladder
  passes it straight through.  The ``stale_rejoin`` fault point replays
  exactly this on CPU at world=1 (zero hangs).

``broadcast_object`` is a real rank-0 length-then-payload broadcast: only
process 0 pickles and ships its object (it used to run a full allgather
and take element 0 — every process pickled and shipped a payload that was
thrown away).

The coordinated-checkpoint protocol (:mod:`lightgbm_tpu.checkpoint`) rides
``allgather_object`` for both of its rendezvous — the shard-CRC commit
barrier and the resume agreement — so a rank that dies mid-snapshot
surfaces as a named ``CollectiveError`` after ``collective_timeout``
seconds on its peers, never a silent fleet hang.

Division of labor under GSPMD (``parallel/gspmd.py``,
docs/DISTRIBUTED.md): the NamedSharding learners hand the DATA-plane
collectives (histogram reductions, split agreement) to the XLA
partitioner, but this module stays load-bearing as the CONTROL plane —
bin finding, checkpoint barriers, resume agreement and preemption
coordination are host-object exchanges that must survive peers dying
mid-call, which is exactly what the ladder above provides and a compiled
collective cannot.
"""
from __future__ import annotations

import pickle
import time
import zlib
from typing import Any, Callable, List, Optional

import numpy as np

from ..utils import faults as faults_mod
from ..utils import log

# module defaults; engine.train() re-configures them from params
_TIMEOUT = 120.0
_RETRIES = 2
_BACKOFF = 0.25     # seconds; doubles per retry


class CollectiveError(RuntimeError):
    """A host-object collective failed after exhausting its retries."""


class StaleEpochError(CollectiveError):
    """A collective frame arrived from a DEAD incarnation of the group.

    Carries both sides of the fence: ``frame_epoch`` (what the stale
    sender was launched under) and ``group_epoch`` (what this process was
    launched under).  Terminal by design — :func:`_retrying` never
    re-attempts it, because a process from a previous incarnation cannot
    become current by waiting; it must be swept."""

    def __init__(self, msg: str, *, frame_epoch: int, group_epoch: int):
        super().__init__(msg)
        self.frame_epoch = int(frame_epoch)
        self.group_epoch = int(group_epoch)


def _group_epoch() -> int:
    # function-local import: checkpoint.py reaches back into this module
    # (function-locally) for the resume barriers
    from ..checkpoint import group_epoch
    return group_epoch()


def _check_frame_epoch(frame_epoch: int, what: str, peer: Any = "?") -> None:
    """The incarnation fence itself: reject any frame whose stamped epoch
    differs from ours, with a structured event + error naming BOTH epochs
    and the offending process."""
    mine = _group_epoch()
    if int(frame_epoch) == mine:
        return
    from ..obs.counters import counters
    counters.event("stale_epoch_rejected", op=what, peer=str(peer),
                   frame_epoch=int(frame_epoch), group_epoch=mine)
    log.warning("%s: rejected frame from process %s at incarnation epoch "
                "%d (this group is epoch %d)", what, peer,
                int(frame_epoch), mine)
    raise StaleEpochError(
        f"{what}: frame from process {peer} carries incarnation epoch "
        f"{int(frame_epoch)} but this group is epoch {mine} — a process "
        "from a dead incarnation tried to rejoin; terminate it (it will "
        "not become current by retrying)",
        frame_epoch=int(frame_epoch), group_epoch=mine)


def _maybe_stale_rejoin(what: str) -> None:
    """``stale_rejoin`` fault point: simulate one frame from the previous
    incarnation arriving at this collective (fires BEFORE the world==1
    short-circuit so the fence is tier-1-testable with no peers)."""
    fi = faults_mod.get_faults()
    if fi.enabled and fi.fire("stale_rejoin"):
        _check_frame_epoch(_group_epoch() - 1, what, peer="injected-stale")


def configure(timeout: Optional[float] = None,
              retries: Optional[int] = None) -> None:
    """Set the module-wide timeout/retry budget (collective_timeout /
    collective_retries params; engine.train wires them per training)."""
    global _TIMEOUT, _RETRIES
    if timeout is not None:
        _TIMEOUT = float(timeout)
    if retries is not None:
        _RETRIES = int(retries)


def process_count() -> int:
    """Number of participating processes; 1 when the distributed runtime is
    not initialized (safe to call before backend init).

    Goes through the mesh.py ``distributed_is_initialized`` compat shim:
    the bare ``jax.distributed.is_initialized`` probe this used to do
    raises AttributeError on jax 0.4.37 — which the old ``except`` turned
    into a silent, WRONG "1 process" answer inside real multi-process
    runs."""
    import jax

    from .mesh import distributed_is_initialized
    if not distributed_is_initialized():
        return 1
    return jax.process_count()


def process_index() -> int:
    """This process's rank; 0 when the distributed runtime is not
    initialized (the single-process identity)."""
    import jax

    from .mesh import distributed_is_initialized
    if not distributed_is_initialized():
        return 0
    return jax.process_index()


def _with_timeout(fn: Callable[[], Any], timeout: float, what: str) -> Any:
    """Run ``fn`` with a deadline.  The underlying collective cannot be
    cancelled, but a named timeout beats an indefinite silent hang.

    A timed-out attempt is marked **abandoned** before the caller raises:
    the worker thread keeps running (nothing can cancel it), and when the
    collective eventually completes *late* its result is dropped — and
    the drop recorded as a ``collective_late_completion`` obs event —
    instead of mutating the result box after the caller already raised
    ``CollectiveError`` (or double-counting the ``collective_calls``
    accounting through a retry that is also in flight)."""
    import threading
    out: List[Any] = []
    err: List[BaseException] = []
    lock = threading.Lock()
    abandoned = [False]

    def run():
        try:
            result = fn()
        except BaseException as e:   # re-raised on the caller thread
            with lock:
                if abandoned[0]:
                    _note_late(what, f"{type(e).__name__}: {e}")
                    return
                err.append(e)
            return
        with lock:
            if abandoned[0]:
                _note_late(what, "completed")
                return
            out.append(result)

    t = threading.Thread(target=run, daemon=True, name=f"sync:{what}")
    t.start()
    t.join(timeout)
    with lock:
        # the attempt may finish between the join timeout and this lock —
        # a result that made it into the box in time still counts
        if not out and not err:
            abandoned[0] = True
    if abandoned[0]:
        raise CollectiveError(
            f"{what} timed out after {timeout:g}s (a peer process is "
            "stuck or dead; see machine_list_file ordering for ranks)")
    if err:
        raise err[0]
    return out[0]


def _note_late(what: str, outcome: str) -> None:
    """A previously abandoned collective attempt just finished: log it and
    record the structured event (never silent — a late completion is the
    evidence that ``collective_timeout`` raced a slow peer, exactly what
    the supervisor's hang-vs-timeout composition needs to see)."""
    from ..obs.counters import counters
    counters.inc("collective_late_completions", op=what)
    counters.event("collective_late_completion", op=what, outcome=outcome)
    log.warning("%s attempt completed LATE (%s) after its timeout had "
                "already surfaced; result dropped", what, outcome)


def _retrying(what: str, attempt_fn: Callable[[], Any]) -> Any:
    """Bounded-retry ladder around one collective attempt; every retry is
    counted (obs `collective_retries`) and recorded as a structured
    `collective_retry` event."""
    from ..obs.counters import counters
    last: Optional[BaseException] = None
    for attempt in range(_RETRIES + 1):
        try:
            return attempt_fn()
        except StaleEpochError:
            # the epoch fence is terminal: a stale incarnation cannot
            # become current by retrying — surface it immediately
            raise
        except Exception as e:
            last = e
            if attempt == _RETRIES:
                break
            counters.inc("collective_retries", op=what)
            counters.event("collective_retry", op=what, attempt=attempt + 1,
                           error=str(e))
            log.warning("%s failed (attempt %d/%d): %s — retrying",
                        what, attempt + 1, _RETRIES + 1, e)
            time.sleep(_BACKOFF * (2 ** attempt))
    raise CollectiveError(
        f"{what} failed after {_RETRIES + 1} attempt(s): {last}") from last


def _maybe_inject(what: str) -> None:
    fi = faults_mod.get_faults()
    if fi.enabled and fi.fire("collective_fail"):
        raise faults_mod.InjectedFault(f"collective_fail: injected {what} "
                                       "failure")


def _maybe_corrupt(buf: np.ndarray) -> np.ndarray:
    fi = faults_mod.get_faults()
    if fi.enabled and fi.fire("collective_corrupt"):
        buf = np.array(buf, copy=True)
        flat = buf.reshape(-1)
        if flat.size:
            flat[0] ^= 0xFF      # deterministic single-byte wire corruption
    return buf


def _note(op: str, nbytes: int) -> None:
    from ..obs.counters import counters
    counters.inc("collective_calls", op=op, site="parallel/sync")
    counters.inc("collective_bytes", value=nbytes, op=op,
                 site="parallel/sync")


def allgather_object(obj: Any) -> List[Any]:
    """Gather one picklable host object from every process, in process-index
    order (Network::Allgather of serialized blobs) — with length+CRC
    payload verification, per-attempt timeout, and bounded retry."""

    def attempt() -> List[Any]:
        _maybe_inject("allgather_object")
        _maybe_stale_rejoin("allgather_object")
        if process_count() == 1:
            return [obj]
        from jax.experimental import multihost_utils
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        header = np.asarray([len(payload), zlib.crc32(payload),
                             _group_epoch()], np.int64)

        def gather() -> List[Any]:
            headers = np.asarray(multihost_utils.process_allgather(
                header)).reshape(-1, 3)
            lens = headers[:, 0]
            buf = np.zeros(int(lens.max()), np.uint8)
            buf[:len(payload)] = payload
            gathered = _maybe_corrupt(np.asarray(
                multihost_utils.process_allgather(buf)))
            out = []
            for i in range(len(lens)):
                _check_frame_epoch(int(headers[i, 2]), "allgather_object",
                                   peer=i)
                blob = gathered[i, :int(lens[i])]
                crc = zlib.crc32(np.ascontiguousarray(blob))
                # compare in uint32 space: the gloo CPU transport returns
                # int64 headers sign-truncated to 32 bits, so a crc with
                # the top bit set comes back negative while still carrying
                # the full 32 bits of integrity
                want = int(headers[i, 1]) & 0xFFFFFFFF
                if crc != want:
                    raise CollectiveError(
                        f"allgather_object payload from process {i} failed "
                        f"its CRC check (sent {want:08x}, "
                        f"received {crc:08x}) — corrupt or torn transfer")
                out.append(pickle.loads(blob.tobytes()))
            return out

        return _with_timeout(gather, _TIMEOUT, "allgather_object")

    result = _retrying("allgather_object", attempt)
    if len(result) > 1:
        _note("allgather_object", sum(len(pickle.dumps(o)) for o in [obj]))
    return result


def broadcast_object(obj: Any = None) -> Any:
    """Every process receives process 0's object (rank-0 decision sync).

    A real rank-0 length-then-payload broadcast: non-root processes ship
    nothing — they only learn the payload size from the header phase and
    receive the bytes (plus CRC check) in the second."""

    def attempt() -> Any:
        _maybe_inject("broadcast_object")
        _maybe_stale_rejoin("broadcast_object")
        if process_count() == 1:
            return obj
        import jax
        from jax.experimental import multihost_utils
        is_root = jax.process_index() == 0
        payload = (np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
                   if is_root else np.zeros(0, np.uint8))
        header = np.asarray(
            [len(payload), zlib.crc32(payload) if is_root else 0,
             _group_epoch()], np.int64)

        def bcast() -> Any:
            hdr = np.asarray(multihost_utils.broadcast_one_to_all(header))
            _check_frame_epoch(int(hdr[2]), "broadcast_object", peer=0)
            # uint32-space compare: gloo sign-truncates int64 headers
            n, want = int(hdr[0]), int(hdr[1]) & 0xFFFFFFFF
            buf = payload if is_root else np.zeros(n, np.uint8)
            # broadcast_one_to_all's internal psum promotes u8 to u32;
            # restore the byte view or the CRC runs over 4x the bytes
            got = _maybe_corrupt(np.asarray(
                multihost_utils.broadcast_one_to_all(buf), dtype=np.uint8))
            crc = zlib.crc32(np.ascontiguousarray(got[:n]))
            if crc != want:
                raise CollectiveError(
                    f"broadcast_object payload from process 0 failed its "
                    f"CRC check (sent {want:08x}, received {crc:08x}) on "
                    f"process {jax.process_index()}")
            return pickle.loads(got[:n].tobytes())

        out = _with_timeout(bcast, _TIMEOUT, "broadcast_object")
        _note("broadcast_object", int(header[0]))
        return out

    return _retrying("broadcast_object", attempt)
