"""GSPMD tree growing: NamedSharding over a named (batch, feature) mesh.

The shard_map learners (``parallel/learner.py``) re-created the
reference's hand-rolled network layer in XLA clothing: every psum /
all_gather is still a CALL SITE someone chose.  This module inverts the
contract — the grow program is written once over GLOBAL arrays, inputs
and loop carries are annotated with :class:`jax.sharding.NamedSharding`
over a named 2-D ``(batch, feature)`` mesh, and the XLA SPMD partitioner
inserts (and overlaps) the collectives itself:

* binned data, gradients and the row->leaf partition carry row-sharded
  on ``batch`` (optionally block-sharded over ``feature`` too — the
  "Block-distributed Gradient Boosted Trees" row x column layout);
* the per-leaf histogram pool ``[L, F, B, 3]`` shards on ``feature`` —
  the component that outgrows one chip's HBM first (docs/MEMORY.md), and
  the reason ``mesh_shape=auto`` exists (``parallel/mesh.plan_mesh``);
* the per-split histogram is a plain masked sum over rows; with the
  output constrained to the feature sharding, the partitioner has each
  device reduce only its own output slice and inserts the shard-sized
  cross-``batch`` reduction — the reduce-scatter the reference
  implemented by hand (``data_parallel_tree_learner.cpp:148-163``),
  now owned by the compiler (pinned via the compiled-HLO census,
  ``utils/jaxpr_audit.hlo_collective_census``).

What changes against the windowed serial grower: the ``order``
permutation (and its gather-bucket ``lax.switch``) cannot live under
GSPMD — a data-dependent window slice of a sharded carrier would force
the partitioner to materialize the global array.  The partition is
instead the direct row->leaf map: routing a split is one elementwise
update of ``row_leaf`` (collective-free — every row's bin is local), and
the smaller child's histogram selects on ``row_leaf == child`` over all
local rows.  Per-device split cost is O(rows/shard) instead of the
serial path's O(window) — the trade the reference's data-parallel
learner also makes (each worker scans its whole partition), bought back
by sharding.  Routing decisions, split selection and leaf outputs reuse
the serial grower's exact helpers (``route_goes_left`` / ``best_split``
/ ``pool_rows`` / ``unpack_tree``), so trees are the SAME trees —
byte-identical under order-insensitive (integer) weights, pinned across
mesh shapes in tests/test_gspmd.py.

The HISTOGRAM itself has two formulations under the same program shape
(``gspmd_hist``, resolved in ``boosting._setup_gspmd``):

* ``flat`` — the masked whole-partition scatter-add
  (``subset_histogram_flat``): pure XLA, partitions on any layout, and
  the forced A/B partner;
* ``fused`` — the hybrid: a ``shard_map`` manual-sharding ISLAND inside
  the same jit'd program, in which each device runs the fused Pallas
  gather-histogram (``ops/pallas_hist.hist6_fused``) over its own row
  shard of the packed ``pack_fused_panel`` layout.  Mosaic owns the
  inside of the island (per-shard index compaction + in-kernel row
  DMAs); the SPMD partitioner still owns everything OUTSIDE it — the
  island returns per-device feature-sliced partials and the cross-shard
  reduction into the feature-sharded pool is the partitioner's, with
  the same shard-sized payload the flat path gets (pinned via the HLO
  census: no all-gather of row shards, ever).  One kernel from laptop
  CPU (``hist_interpret=True``) to pod slice.

``parallel/sync.py``'s hardened host-object ladder stays the
control-plane (bin finding, checkpoint barriers, preemption agreement):
GSPMD owns the data plane only.  The shard_map learners remain the
forced A/B partner (``parallel_impl=shardmap``) until on-chip numbers
land.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..data.packing import (PACK_JOINT_BINS, pack_fused_panel,
                            unfold_packed_hist)
from ..grower import (FeatureMeta, GrowerConfig, _depth_gate,
                      expand_bundle_hist, make_expand_maps, pool_rows,
                      route_goes_left, unpack_tree)
from ..obs import trace as obs_trace
from ..obs.counters import counters as obs_counters
from ..ops.histogram import subset_histogram_flat, subset_histogram_fused_local
from ..ops.split import best_split, leaf_output, make_fused_ctx
from .learner import _CHECK_KW, shard_map
from .mesh import BATCH_AXIS, FEATURE_AXIS


def make_gspmd_grower(cfg: GrowerConfig, mesh: Mesh,
                      bundled: bool = False, pack_plan=None,
                      block_shard: bool = False) -> Callable:
    """Build the jitted GSPMD ``grow_tree`` over global arrays.

    Same call signature as ``make_grower``'s product — ``fn(bins,
    [hist_bins,] gw, hw, cw, meta, feat_valid) -> (TreeArrays,
    row_leaf)`` — operating on arrays placed with
    ``NamedSharding(mesh, ...)`` (uncommitted inputs are resharded by the
    first call).  ``row_leaf`` comes back row-sharded on ``batch``.

    The histogram formulation follows ``cfg.hist_method``: ``"fused"``
    builds the shard_map hybrid (module docstring) — the fused Pallas
    kernel is a manual-layout custom call the SPMD partitioner cannot
    split, so it runs INSIDE a manual-sharding island over per-shard
    locals, and only its per-device partial sums re-enter partitioner
    territory.  Any other value runs the flat scatter-add
    (``subset_histogram_flat``; the scan-chunked forms make the
    partitioner all-gather the row shards, and unfusable layouts are
    downgraded loudly by ``boosting._setup_gspmd`` before this builder
    runs — by then the request is always fused or flat).
    """
    L = cfg.num_leaves
    hist_width = (max(PACK_JOINT_BINS, cfg.max_bin) if pack_plan is not None
                  else cfg.max_bin)
    shard_hist = int(mesh.shape[FEATURE_AXIS]) > 1
    f_shards = int(mesh.shape[FEATURE_AXIS])
    use_fused = cfg.hist_method == "fused"

    def cstr(x, spec):
        return lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    def smap(fn, in_specs, out_specs):
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, **{_CHECK_KW: False})

    def grow_impl(bins, hist_src, gw, hw, cw, meta: FeatureMeta,
                  feat_valid):
        n, f = bins.shape
        dtype = gw.dtype
        maps = (make_expand_maps(meta, cfg.max_bin)
                if meta.col is not None else None)
        scfg = cfg.split_config()
        fctx = (make_fused_ctx(meta.num_bin, meta.missing_type,
                               meta.default_bin, cfg.max_bin, scfg)
                if scfg.split_find == "fused" else None)
        num_logical = meta.num_bin.shape[0]
        fh = (pack_plan.num_phys_cols if pack_plan is not None
              else hist_src.shape[1])
        tracer = obs_trace.get_tracer()

        def find(hist, pg, ph, pc, feat_ok):
            obs_counters.inc("split_find_dispatch", impl=cfg.split_find)
            with tracer.span("split_find", traced=True,
                             impl=cfg.split_find), \
                    jax.named_scope("split_find"):
                if maps is not None:
                    hist = expand_bundle_hist(hist, pg, ph, pc, maps)
                return best_split(hist, pg, ph, pc, meta.num_bin,
                                  meta.missing_type, meta.default_bin,
                                  feat_valid & feat_ok, scfg,
                                  is_cat=meta.is_categorical,
                                  with_feat_ok=True, fused_ctx=fctx)

        # ---- fused island: per-shard panel, packed once per grow --------
        # loop-invariant (weights are per-tree constants: the fused kernel
        # selects leaf membership through the row -> leaf partition, not
        # through masked weights), so XLA hoists it out of the while loop.
        # in_specs reshard hist_src's feature axis even when the global
        # carrier is feature-replicated: each device packs only ITS column
        # slice (f-way compute parallelism, and the island's partials stay
        # slice-sized — a local slice, never a collective).
        panel = None
        if use_fused:
            sc_cols = hist_src.shape[1]
            # layout gates live in boosting._setup_gspmd (loudly, before
            # labels are read); by trace time they must all hold
            assert sc_cols % f_shards == 0, (sc_cols, f_shards)
            fcols_loc = sc_cols // f_shards
            words_per = 4 if hist_src.dtype.itemsize == 1 else 2
            panel_fspec = FEATURE_AXIS if f_shards > 1 else None
            # Pin the GLOBAL carriers to their caller placements before the
            # island sees them.  Without the pin the island's
            # feature-sharded in_spec wins the sharding-propagation
            # argument and bins goes feature-sharded program-wide — then
            # routing's dynamic column read inside the while body
            # re-gathers a full row shard EVERY split, exactly the
            # collective the hybrid exists to avoid (the HLO census test
            # pins its absence).  With the pin the reshard is a one-time
            # local slice at the island boundary.
            bins = cstr(bins, P(BATCH_AXIS,
                                FEATURE_AXIS if block_shard else None))
            if pack_plan is None:
                hist_src = bins
            else:
                # the packed histogram matrix is always placed
                # feature-replicated by boosting (P(batch, None))
                hist_src = cstr(hist_src, P(BATCH_AXIS, None))

            def pack_island(bins_loc, g_loc, h_loc, c_loc):
                zrow = jnp.zeros((1, bins_loc.shape[1]), bins_loc.dtype)
                zw = jnp.zeros((1,), g_loc.dtype)
                p, _ = pack_fused_panel(
                    jnp.concatenate([bins_loc, zrow], axis=0),
                    jnp.concatenate([g_loc, zw]),
                    jnp.concatenate([h_loc, zw]),
                    jnp.concatenate([c_loc, zw]))
                return p

            with jax.named_scope("fused_panel"):
                panel = smap(
                    pack_island,
                    in_specs=(P(BATCH_AXIS, panel_fspec), P(BATCH_AXIS),
                              P(BATCH_AXIS), P(BATCH_AXIS)),
                    out_specs=P(BATCH_AXIS, panel_fspec),
                )(hist_src, gw, hw, cw)

        def measure(row_leaf_cur, leaf_id, g_, h_, c_, site):
            """One leaf histogram, both formulations.

            flat: masked whole-partition scatter-add — the sum over the
            row axis IS the collective; with the feature-sharded output
            constraint each device reduces only its own slice and XLA
            inserts the shard-sized cross-batch reduction.

            fused: shard_map island — each device compacts its local
            ``row_leaf == leaf`` rows and runs the fused Pallas
            gather-histogram over its panel slice; the island returns
            [d, C/f, B, 3] per-device partials and the ``sum(axis=0)``
            OUTSIDE the island hands the partitioner the exact same
            shard-sized cross-batch reduction (never an all-gather of row
            shards — pinned by the HLO census)."""
            if use_fused:
                def hist_island(panel_loc, rl_loc, leaf_loc):
                    part = subset_histogram_fused_local(
                        rl_loc, leaf_loc, panel_loc, fcols_loc, words_per,
                        hist_width, row_tile=cfg.row_tile,
                        interpret=cfg.hist_interpret, site=site)
                    return part[None]

                part = smap(
                    hist_island,
                    in_specs=(P(BATCH_AXIS, panel_fspec), P(BATCH_AXIS),
                              P()),
                    out_specs=P(BATCH_AXIS, panel_fspec, None, None),
                )(panel, row_leaf_cur, jnp.asarray(leaf_id, jnp.int32))
                hist = jnp.sum(part, axis=0)
            else:
                hist = subset_histogram_flat(hist_src, g_, h_, c_,
                                             hist_width, site=site)
            if pack_plan is not None:
                hist = unfold_packed_hist(hist, pack_plan, cfg.max_bin)
            return cstr(hist, P(FEATURE_AXIS if shard_hist else None,
                                None, None))

        # ---- root -------------------------------------------------------
        root_g = jnp.sum(gw)
        root_h = jnp.sum(hw)
        root_c = jnp.sum(cw)
        feat_ok_all = jnp.ones((num_logical,), bool)
        row_leaf0 = cstr(jnp.zeros((n,), jnp.int32), P(BATCH_AXIS))
        with tracer.span("histogram", site="root", traced=True), \
                jax.named_scope("histogram"):
            hist_root = measure(row_leaf0, jnp.asarray(0, jnp.int32),
                                gw, hw, cw, site="root")
        res_root, root_feat_ok = find(hist_root, root_g, root_h, root_c,
                                      feat_ok_all)
        res_root = _depth_gate(res_root, jnp.asarray(0), cfg.max_depth)

        store_spec = P(None, FEATURE_AXIS if shard_hist else None,
                       None, None)
        hist_store0 = cstr(jnp.zeros((L, fh, cfg.max_bin, 3), dtype)
                           .at[0].set(hist_root), store_spec)
        feat_ok_store0 = jnp.zeros((L, num_logical), bool).at[0].set(
            root_feat_ok)
        root_f32, root_i32 = pool_rows(res_root, 0)
        sgain0 = jnp.full((L,), -jnp.inf, res_root.gain.dtype).at[0].set(
            res_root.gain)
        sf32_0 = jnp.zeros((L, 8), dtype).at[0].set(root_f32)
        si32_0 = jnp.zeros((L, 3), jnp.int32).at[0].set(root_i32)
        if cfg.has_categorical:
            scat0 = jnp.zeros((L,), bool).at[0].set(res_root.is_cat)
            scatb0 = jnp.zeros((L, cfg.max_bin), bool).at[0].set(
                res_root.cat_bins)
            tcat0 = jnp.zeros((L - 1,), bool)
            tcatb0 = jnp.zeros((L - 1, cfg.max_bin), bool)
        else:
            scat0 = jnp.zeros((0,), bool)
            scatb0 = jnp.zeros((0, 0), bool)
            tcat0 = jnp.zeros((0,), bool)
            tcatb0 = jnp.zeros((0, 0), bool)
        tnf0 = jnp.zeros((L - 1, 3), dtype)
        tni0 = jnp.zeros((L - 1, 5), jnp.int32)
        tlf0 = jnp.zeros((L, 2), dtype).at[0, 1].set(root_c)
        tli0 = jnp.concatenate([jnp.full((L, 1), -1, jnp.int32),
                                jnp.zeros((L, 1), jnp.int32)], axis=1)

        def cond(state):
            step = state[0]
            sgain = state[2]
            return (step < L - 1) & (jnp.max(sgain) > 0.0)

        def body(state):
            (i, row_leaf, sgain, sf32, si32, scat, scatb, hist_store,
             feat_ok, tnf, tni, tlf, tli, tcat, tcatb) = state
            l = jnp.argmax(sgain).astype(jnp.int32)
            new_leaf = i + 1
            node = i
            pair_lr = jnp.stack([l, new_leaf])

            irow = lax.dynamic_index_in_dim(si32, l, axis=0, keepdims=False)
            frow = lax.dynamic_index_in_dim(sf32, l, axis=0, keepdims=False)
            feat, thr = irow[0], irow[1]
            dleft = irow[2].astype(bool)

            # --- routing: ONE elementwise pass over the row partition
            #     (DataPartition::Split without the window machinery —
            #     every row's bin is shard-local, so no collective) -------
            col_idx = feat if meta.col is None else meta.col[feat]
            binf = lax.dynamic_index_in_dim(
                bins, col_idx, axis=1, keepdims=False).astype(jnp.int32)
            cat_args = ((scat[l], scatb[l]) if cfg.has_categorical else ())
            with tracer.span("partition", traced=True), \
                    jax.named_scope("partition"):
                goes_left = route_goes_left(
                    binf, meta, feat, thr, dleft,
                    has_categorical=cfg.has_categorical,
                    is_cat_l=cat_args[0] if cfg.has_categorical else None,
                    cat_row=cat_args[1] if cfg.has_categorical else None,
                    max_bin=cfg.max_bin)
                in_l = row_leaf == l
                row_leaf = cstr(jnp.where(
                    in_l, jnp.where(goes_left, l, new_leaf), row_leaf),
                    P(BATCH_AXIS))

            # --- record the node (same writes as the serial body) --------
            prow = lax.dynamic_index_in_dim(tli, l, axis=0, keepdims=False)
            parent_node = prow[0]
            child_depth = prow[1] + 1
            pn_safe = jnp.where(parent_node >= 0, parent_node, node)
            side = jnp.where(tni[pn_safe, 3] == ~l, 3, 4)
            tni = tni.at[pn_safe, side].set(node, mode="promise_in_bounds")
            tni = tni.at[node].set(
                jnp.stack([feat, thr, irow[2], ~l, ~new_leaf]),
                mode="promise_in_bounds")
            parent_g = frow[0] + frow[3]
            parent_h = frow[1] + frow[4]
            tnf = tnf.at[node].set(
                jnp.stack([sgain[l],
                           leaf_output(parent_g, parent_h,
                                       cfg.lambda_l1, cfg.lambda_l2),
                           tlf[l, 1]]),
                mode="promise_in_bounds")
            tlf = tlf.at[pair_lr].set(
                jnp.stack([jnp.stack([frow[6], frow[2]]),
                           jnp.stack([frow[7], frow[5]])]),
                unique_indices=True, mode="promise_in_bounds")
            tli = tli.at[pair_lr].set(
                jnp.broadcast_to(jnp.stack([node, child_depth]), (2, 2)),
                unique_indices=True, mode="promise_in_bounds")
            if cfg.has_categorical:
                tcat = tcat.at[node].set(cat_args[0],
                                         mode="promise_in_bounds")
                tcatb = tcatb.at[node].set(cat_args[1],
                                           mode="promise_in_bounds")

            # --- smaller-child histogram + parent subtraction ------------
            small_left = frow[2] <= frow[5]
            small_id = jnp.where(small_left, l, new_leaf)
            with tracer.span("histogram", site="split", traced=True), \
                    jax.named_scope("histogram"):
                if use_fused:
                    hist_small = measure(row_leaf, small_id, gw, hw, cw,
                                         site="split")
                else:
                    mask = (row_leaf == small_id).astype(dtype)
                    hist_small = measure(row_leaf, small_id, gw * mask,
                                         hw * mask, cw * mask, site="split")
            hist_parent = lax.dynamic_index_in_dim(hist_store, l, axis=0,
                                                   keepdims=False)
            hist_large = hist_parent - hist_small
            hist2 = jnp.stack([hist_small, hist_large])
            pair_sl = jnp.where(small_left, pair_lr, pair_lr[::-1])
            hist_store = cstr(hist_store.at[pair_sl].set(
                hist2, unique_indices=True, mode="promise_in_bounds"),
                store_spec)

            fok_parent = lax.dynamic_index_in_dim(feat_ok, l, axis=0,
                                                  keepdims=False)
            lr3 = jnp.stack([lax.slice(frow, (0,), (3,)),
                             lax.slice(frow, (3,), (6,))])
            sl3 = jnp.where(small_left, lr3, lr3[::-1])
            res2, fok2 = jax.vmap(find, in_axes=(0, 0, 0, 0, None))(
                hist2, sl3[:, 0], sl3[:, 1], sl3[:, 2], fok_parent)
            res2 = _depth_gate(res2, child_depth, cfg.max_depth)
            feat_ok = feat_ok.at[pair_sl].set(fok2 & fok_parent[None, :],
                                              unique_indices=True)
            rows_f32, rows_i32 = pool_rows(res2, 1)
            sgain = sgain.at[pair_sl].set(
                res2.gain, unique_indices=True, mode="promise_in_bounds")
            sf32 = sf32.at[pair_sl].set(
                rows_f32, unique_indices=True, mode="promise_in_bounds")
            si32 = si32.at[pair_sl].set(
                rows_i32, unique_indices=True, mode="promise_in_bounds")
            if cfg.has_categorical:
                scat = scat.at[pair_sl].set(
                    res2.is_cat, unique_indices=True,
                    mode="promise_in_bounds")
                scatb = scatb.at[pair_sl].set(
                    res2.cat_bins, unique_indices=True,
                    mode="promise_in_bounds")
            return (i + 1, row_leaf, sgain, sf32, si32, scat, scatb,
                    hist_store, feat_ok, tnf, tni, tlf, tli, tcat, tcatb)

        state = (jnp.asarray(0, jnp.int32), row_leaf0, sgain0, sf32_0,
                 si32_0, scat0, scatb0, hist_store0, feat_ok_store0,
                 tnf0, tni0, tlf0, tli0, tcat0, tcatb0)
        state = lax.while_loop(cond, body, state)
        (step, row_leaf, _, _, _, _, _, _, _,
         tnf, tni, tlf, tli, tcat, tcatb) = state
        tree = unpack_tree(step + 1, tni, tnf, tlf, tli, tcat, tcatb, cfg)
        return tree, row_leaf

    if pack_plan is None:
        def grow_tree(bins, gw, hw, cw, meta, feat_valid):
            return grow_impl(bins, bins, gw, hw, cw, meta, feat_valid)
        return jax.jit(grow_tree)

    def grow_tree_packed(bins, hist_bins, gw, hw, cw, meta, feat_valid):
        return grow_impl(bins, hist_bins, gw, hw, cw, meta, feat_valid)
    return jax.jit(grow_tree_packed)
