"""Boosting drivers: GBDT, DART, GOSS, RF.

The reference's ``Boosting`` hierarchy (``src/boosting/``, factory
``boosting.cpp:29-76``) becomes Python classes driving the jitted tree grower:

* :class:`GBDT` — ``gbdt.cpp:67-581``: boost-from-average init tree, gradient
  computation, bagging, per-class tree training, shrinkage, score updates,
  rollback, model (de)serialization in the reference text format;
* :class:`DART` — ``dart.hpp:86-194`` drop/normalize arithmetic;
* :class:`GOSS` — ``goss.hpp:86-137`` gradient-based one-side sampling
  (vectorized: exact top-k threshold + Bernoulli keep of the rest);
* :class:`RF`   — ``rf.hpp:18-213`` bagged random forest with averaged output.

Training scores live on device; the O(N) train-score update uses the grower's
``row_leaf`` partition (the reference's ``ScoreUpdater`` + ``DataPartition``
trick), valid scores use jitted binned traversal.
"""
from __future__ import annotations

import copy
import io
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .data.dataset import TrainingData
from .grower import FeatureMeta, GrowerConfig, StreamedGrower, make_grower
from .metrics import Metric, create_metric, default_metric_for_objective
from .obs import collectives as obs_collectives
from .obs import devprof as obs_devprof
from .obs import flight as obs_flight
from .obs import memory as obs_memory
from .obs import metrics as obs_metrics
from .obs import model_quality as obs_model_quality
from .obs import trace as obs_trace
from .obs.counters import counters as obs_counters
from .ops.histogram import on_tpu
from .objectives import Objective, create_objective, parse_objective_string
from .predictor import (Predictor, predict_binned_leaf, tree_scores_binned,
                        trees_scores_binned)
from .tree import Tree
from .utils import faults as faults_mod
from .utils import log
from .utils.random import make_rng, sample_k
from .utils.timer import PhaseTimers


class NonFiniteError(RuntimeError):
    """A gradient/hessian/leaf value went non-finite and the configured
    ``nonfinite_policy`` could not (or was asked not to) recover."""


class _ValidSet:
    def __init__(self, data: TrainingData, name: str, num_class: int,
                 metrics: List[Metric]):
        self.data = data
        self.name = name
        self.bins = jnp.asarray(data.binned)
        self.metrics = metrics
        n = data.num_data
        self.scores = jnp.zeros((num_class, n), jnp.float32)
        if data.metadata.init_score is not None:
            init = np.asarray(data.metadata.init_score, np.float32)
            self.scores = self.scores + init.reshape(num_class, n)


class GBDT:
    """Gradient Boosting Decision Tree driver (gbdt.cpp)."""

    average_output = False
    sub_model_name = "tree"
    allow_boost_from_average = True
    # DART reads/mutates prior trees every iteration and RF feeds host
    # gradients; both stay on the synchronous path
    pipeline_supported = True
    # nonfinite_policy=rollback discards a poisoned iteration via the
    # rollback arithmetic; DART's drop/normalize bookkeeping cannot be
    # partially unwound, so it escalates to raise instead
    rollback_safe = True

    def __init__(self, config: Config, train_set: Optional[TrainingData] = None,
                 objective: Optional[Objective] = None):
        self.config = config
        self.train_set = train_set
        self.objective = objective
        # tree-materialization pipeline state (see train_one_iter): grown
        # trees wait in _pending as device TreeArrays and drain into _models
        # a few iterations late through ONE batched transfer each
        self._pending: List[dict] = []
        self._pipeline = False
        self._pipeline_depth = 3
        self._stopped_no_split = False
        self._iter_had_split = False
        # non-finite guard bookkeeping (docs/ROBUSTNESS.md): one structured
        # event per tripped iteration; a second trip at the SAME iteration
        # under rollback means the non-finite source is persistent
        self._nf_policy = config.nonfinite_policy
        self._nf_event_iter: Optional[int] = None
        self._nf_rolled_iter: Optional[int] = None
        self._score_stash = None   # (iter, scores, [valid scores]) refs
        # serving caches, both invalidated together whenever the stored
        # trees change other than by appending (rollback, merge, DART
        # normalize, leaf edits): the native C++ predictor and the SoA
        # microbatch engine (lightgbm_tpu.inference / docs/SERVING.md)
        self._native_pred = None
        self._pred_engine = None
        self._pred_engine_ntrees = -1
        # training-set bin distribution for the serving drift monitor
        # (obs/model_quality.py): computed lazily at save when the plane
        # is armed, or parsed back from a loaded model file
        self.feature_distribution = None
        self.models: List[Tree] = []
        self.timers = PhaseTimers()   # TIMETAG analogue (gbdt.cpp:22-64)
        self.iter_ = 0
        self._last_iter_leaves = 0
        self.num_init_iteration = 0
        self.boost_from_average_ = False
        self.best_iteration = -1
        self.eval_history: Dict[str, Dict[str, List[float]]] = {}
        self.valid_sets: List[_ValidSet] = []
        self.train_metrics: List[Metric] = []
        self.num_class = objective.num_tree_per_iteration if objective else 1
        self.label_idx = 0
        self.feature_names: List[str] = (train_set.feature_names if train_set
                                         else [])
        self.max_feature_idx = (train_set.num_total_features - 1 if train_set
                                else 0)
        if train_set is not None:
            self._setup_device(train_set)

    # ------------------------------------------------- pipelined tree pulling
    #
    # ``models`` drains pending device-side trees on every read, so every
    # consumer (save/predict/importance/rollback/bindings) always sees the
    # complete, ordered list; only the training hot loop uses ``_models`` /
    # ``_pending`` directly.

    @property
    def models(self) -> List[Tree]:
        if self._pending:
            self._drain_pending()
        return self._models

    @models.setter
    def models(self, value) -> None:
        if getattr(self, "_pending", None):
            self._drain_pending()
        self._models = list(value)

    def _drain_pending(self, keep_iters: int = 0) -> None:
        """Materialize pending trees (FIFO) until at most ``keep_iters``
        iteration groups remain.  Each materialization is one batched
        ``jax.device_get`` whose transfer was started asynchronously at
        dispatch time, so by the time a record is ``keep_iters`` iterations
        old the bytes are normally already on host."""
        keep = keep_iters * self.num_class
        if self._stopped_no_split:
            keep = 0            # everything still pending must be reverted
        while self._pending and len(self._pending) > keep:
            rec = self._pending.pop(0)
            # the non-finite flags ride the SAME batched device_get the
            # drain already does — no extra host<->device synchronization
            host, nf_ok, gh_ok = jax.device_get(
                (rec["arrays"], rec["nf_ok"], rec["gh_ok"]))
            if not bool(nf_ok):
                self._nonfinite_at_drain(int(rec["iter"]), bool(gh_ok))
            tree = Tree.from_arrays(host, self.train_set.used_features,
                                    self.train_set.bin_mappers,
                                    self._num_bin_host)
            tree.shrink(rec["lr"])
            if self._stopped_no_split:
                # trained past a (lately discovered) no-split iteration:
                # discard, undoing any score contribution it made
                self._revert_tree_scores(rec["k"], tree)
                continue
            self._models.append(tree)
            # split audit (obs/model_quality.py): fold the freshly
            # materialized host arrays — data this drain fetched anyway,
            # so the armed plane adds zero device syncs (pinned)
            obs_model_quality.get_tracker().observe_tree(
                int(rec["iter"]), len(self._models) - 1, tree)
            if tree.num_leaves > 1:
                self._iter_had_split = True
            if rec["k"] == self.num_class - 1:
                if not self._iter_had_split:
                    # the reference stops at the first iteration whose trees
                    # cannot split (gbdt.cpp:541-556); reproduce its exact
                    # final state — drop this iteration's trees and rewind
                    log.warning("Stopped training because there are no more "
                                "leaves that meet the split requirements")
                    for _ in range(self.num_class):
                        self._models.pop()
                    self._stopped_no_split = True
                    self.iter_ = rec["iter"]
                    keep = 0    # later pending trees are all discarded
                self._iter_had_split = False

    def _revert_tree_scores(self, k: int, tree: Tree) -> None:
        """Subtract a discarded tree's contribution (rollback_one_iter's
        arithmetic) from train and valid scores."""
        if tree.num_leaves <= 1:
            return
        tree.shrink(-1.0)
        self.scores = self.scores.at[k].add(self._train_tree_score(tree))
        for vs in self.valid_sets:
            vs.scores = vs.scores.at[k].add(tree_scores_binned(
                vs.bins, tree, self.used_feature_index, self.feat_info,
                self.train_set.bin_mappers))

    # ------------------------------------------------------------------ setup

    def _setup_device(self, train: TrainingData) -> None:
        cfg = self.config
        # host-side for now; _setup_grower owns device placement (multi-
        # process mode shards this globally instead of uploading it whole)
        self.bins = train.binned
        fm = train.feature_meta()
        bundled = "col" in fm
        self.meta = FeatureMeta(
            num_bin=jnp.asarray(fm["num_bin"]),
            missing_type=jnp.asarray(fm["missing_type"]),
            default_bin=jnp.asarray(fm["default_bin"]),
            is_categorical=jnp.asarray(fm["is_categorical"]),
            col=jnp.asarray(fm["col"]) if bundled else None,
            offset=jnp.asarray(fm["offset"]) if bundled else None)
        e = len(fm["num_bin"])
        col = fm["col"] if bundled else np.arange(e, dtype=np.int32)
        off = fm["offset"] if bundled else np.full(e, -1, np.int32)
        self.feat_info = jnp.stack(
            [jnp.asarray(fm["num_bin"]), jnp.asarray(fm["missing_type"]),
             jnp.asarray(fm["default_bin"]), jnp.asarray(col),
             jnp.asarray(off)], axis=1)
        self.used_feature_index = {f: i for i, f in enumerate(train.used_features)}
        self._num_bin_host = np.asarray(fm["num_bin"])
        self.num_data = train.num_data
        n = self.num_data

        self.grower_cfg = GrowerConfig(
            num_leaves=cfg.num_leaves,
            max_depth=cfg.max_depth,
            min_data_in_leaf=cfg.min_data_in_leaf,
            min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
            lambda_l1=cfg.lambda_l1,
            lambda_l2=cfg.lambda_l2,
            min_gain_to_split=cfg.min_gain_to_split,
            max_bin=train.max_num_bin(),
            # the ladder is fused-vs-reference since the gen-1 kernels
            # were retired: on TPU, use_pallas runs the fused in-kernel-
            # gather rung ('auto' and 'on' alike — it is the ONLY Pallas
            # kernel left, and the lowering-proven one); pallas_fused=off
            # / use_pallas=false force the MXU-shaped einsum oracle;
            # off-TPU picks the cpu_hist_method reference
            hist_method=("fused" if cfg.use_pallas and _on_tpu()
                         and cfg.pallas_fused != "off"
                         else "einsum" if _on_tpu()   # MXU-friendly debug
                         else cfg.cpu_hist_method),   # scatter-add on CPU
            row_tile=cfg.pallas_row_tile,
            bucket_min_log2=cfg.pallas_bucket_min_log2,
            gather_words=cfg.gather_words,
            gather_panel=cfg.gather_panel,
            ordered_bins=("off" if cfg.ordered_bins == "auto"
                          else cfg.ordered_bins),
            partition_impl=("scatter" if cfg.partition_impl == "auto"
                            else cfg.partition_impl),
            bucket_scheme=("pow2" if cfg.bucket_scheme == "auto"
                           else cfg.bucket_scheme),
            has_categorical=bool(np.asarray(fm["is_categorical"]).any()),
            has_missing=bool((np.asarray(fm["missing_type"]) != 0).any()),
            max_cat_threshold=cfg.max_cat_threshold,
            max_cat_group=cfg.max_cat_group,
            cat_smooth_ratio=cfg.cat_smooth_ratio,
            min_cat_smooth=cfg.min_cat_smooth,
            max_cat_smooth=cfg.max_cat_smooth,
            split_find=cfg.split_find)
        self._setup_grower(cfg, train)
        # rollback must act BEFORE the next iteration trains on poisoned
        # scores, so it forces synchronous tree materialization; the cheap
        # default (raise) keeps the pipeline and detects at drain time
        self._pipeline = (cfg.pipeline_trees and self.pipeline_supported
                          and not self._multiproc
                          and cfg.nonfinite_policy != "rollback")
        if (cfg.pipeline_trees and self.pipeline_supported
                and not self._multiproc and not self._pipeline):
            log.info("nonfinite_policy=rollback forces synchronous tree "
                     "materialization (pipeline_trees disabled)")

        self.objective.init(train.metadata, n)
        self.num_class = self.objective.num_tree_per_iteration
        self._grad_fn = jax.jit(self.objective.get_gradients)
        self.scores = jnp.zeros((self.num_class, n), jnp.float32)
        self._has_init_score = train.metadata.init_score is not None
        if self._has_init_score:
            init = np.asarray(train.metadata.init_score, np.float32)
            self.scores = self.scores + init.reshape(self.num_class, n)
        self._feat_valid_base = np.ones(len(fm["is_categorical"]), dtype=bool)
        self._bag_weight = jnp.ones((n,), jnp.float32)
        self._bag_cnt = jnp.ones((n,), jnp.float32)
        self._subset_state = None  # (bins[M,F], idx[M], w[M], cnt[M], hist)
        self._bag_rng = make_rng(cfg.bagging_seed)
        self._feat_rng = make_rng(cfg.feature_fraction_seed)

        metric_names = cfg.metric or [default_metric_for_objective(cfg.objective)]
        self.metric_names = metric_names
        self.train_metrics = self._make_metrics(train)

        @jax.jit
        def _update_score(scores_k, leaf_values, row_leaf, lr):
            return scores_k + lr * leaf_values[row_leaf]

        self._update_score = _update_score

        # device-memory observability (obs/memory.py): owner tags for the
        # live-array census (weakly held — never keeps this booster alive)
        # and the pre-compile HBM pre-flight.  Runs BEFORE the first grow
        # call compiles anything, so a shape that cannot fit fails here in
        # milliseconds instead of minutes into a capture window.
        obs_memory.register_residents(self._memory_residents)
        # live metrics source (obs/metrics.py): phase-timer families +
        # iteration gauge for the /metrics scrape (weakly held, like the
        # census providers)
        obs_metrics.register_source(self._metrics_samples)
        self._memory_preflight(cfg, train)

    def _metrics_samples(self) -> list:
        """Live ``/metrics`` samples of this booster: per-phase totals and
        steady-state means (first, compile-inclusive firing excluded — the
        obs/report.py compile⚠ rule applied to the live view) plus the
        iteration gauge.  Pure host-side dict reads; snapshot via ``list``
        so a concurrent scrape never races the training thread's inserts."""
        out = [("train_iterations", {}, float(self.iter_), "gauge")]
        counts = dict(self.timers.counts)
        for name, total in list(self.timers.seconds.items()):
            labels = {"phase": name}
            out.append(("phase_seconds", labels, float(total), "counter"))
            out.append(("phase_iterations", labels,
                        float(counts.get(name, 0)), "counter"))
        for name, mean in self.timers.steady_means().items():
            out.append(("phase_steady_ms", {"phase": name},
                        float(mean) * 1e3, "gauge"))
        return out

    def _memory_residents(self) -> Dict[str, list]:
        """Owner-tagged persistent device arrays for the live census
        (obs/memory.live_census): binned matrix, packed histogram copy,
        scores (+ the rollback stash), bagging vectors, subset gather
        buffers, valid-set arrays, pending pipelined trees."""
        res: Dict[str, list] = {
            # streamed: the binned matrix lives on HOST; its in-flight
            # device blocks are transient and tracked by the stream
            # counters, not the resident census
            "binned": ([] if self._stream_store is not None
                       else [self.bins]),
            "scores": [self.scores],
            "bagging": [self._bag_weight, self._bag_cnt],
        }
        if self._hist_bins is not None:
            res["packed"] = [self._hist_bins]
        if self.objective is not None:
            # labels + the objective's derived per-row device vectors
            # (binary: label sign/weight; ranking: query maps, gains, ...)
            res["objective"] = [v for v in vars(self.objective).values()
                                if hasattr(v, "nbytes")
                                and hasattr(v, "dtype")]
        stash = getattr(self, "_score_stash", None)
        if stash is not None:
            res["scores"] = res["scores"] + [stash[1]] + list(stash[2])
        if self._subset_state is not None:
            res["subset_gather"] = [a for a in self._subset_state
                                    if a is not None]
        if self.valid_sets:
            res["valid"] = [a for vs in self.valid_sets
                            for a in (vs.bins, vs.scores)]
        if self._pending:
            res["pending_trees"] = [a for rec in self._pending
                                    for a in jax.tree.leaves(rec["arrays"])]
        return res

    def _memory_preflight(self, cfg: Config, train: TrainingData) -> None:
        """Predict the training's peak device bytes from the constructed
        shapes and compare against the device capacity / ``hbm_budget``
        (obs/memory.preflight) before the grower compiles."""
        plan = self._pack_plan
        gplan = self._gspmd_plan
        stream = self._stream_store
        ncols = (stream.num_cols if stream is not None
                 else int(np.shape(self.bins)[1]))
        bin_bytes = (stream.dtype.itemsize if stream is not None
                     else self.bins.dtype.itemsize)
        pred = obs_memory.predict_hbm(
            rows=self.num_data,
            features=ncols,
            bins=self.grower_cfg.max_bin,
            leaves=self.grower_cfg.num_leaves,
            num_class=self.num_class,
            bin_bytes=int(bin_bytes),
            stream_chunk_rows=(stream.chunk_rows
                               if stream is not None else 0),
            packed_cols=(plan.num_storage_cols if plan is not None else 0),
            valid_rows=sum(vs.data.num_data for vs in self.valid_sets),
            ordered_bins=self.grower_cfg.ordered_bins == "on",
            # 'auto' resolves ON everywhere since round 8 (grower.py)
            gather_words=self.grower_cfg.gather_words in ("on", "auto"),
            bucket_min_log2=self.grower_cfg.bucket_min_log2,
            # GSPMD: the pre-flight judges the PER-DEVICE peak the planner
            # already sized the mesh for (docs/DISTRIBUTED.md)
            data_shards=(gplan.data if gplan is not None else 1),
            feature_shards=(gplan.feature if gplan is not None else 1),
            block_shard_bins=(gplan.block_shard_bins
                              if gplan is not None else False))
        self.memory_prediction = pred
        obs_memory.preflight(
            pred, hbm_budget=cfg.hbm_budget,
            context=f"{self.num_data} rows x {ncols} cols, "
                    f"{self.grower_cfg.num_leaves} leaves, "
                    f"{self.grower_cfg.max_bin} bins"
                    + (f", streamed in {stream.chunk_rows}-row blocks"
                       if stream is not None else ""))

    def _setup_grower(self, cfg: Config, train: TrainingData) -> None:
        """Select the tree learner (CreateTreeLearner analogue):
        serial on one device; data/feature/voting over the device mesh.

        Multi-process (multi-host) mode: each process holds its OWN row
        partition (the reference's pre-partitioned parallel learning,
        ``docs/Parallel-Learning-Guide.md``); the binned matrix becomes one
        global jax.Array row-sharded across all processes' devices, and
        per-tree gradient vectors are assembled the same way."""
        self._row_pad = 0
        self._feat_pad = 0
        self._multiproc = False
        self._local_bins_cache = None
        self._pack_plan = None
        self._hist_bins = None
        self._gspmd_mesh = None
        self._gspmd_plan = None
        self._stream_store = None   # HostBlockStore when data_stream
        self._streamer = None       # resolved to chunked (data/stream.py)
        self._placement = None      # PlacementPlan the pre-flight walked
        n_devices = len(jax.devices())
        use_dist = cfg.tree_learner != "serial" and (
            cfg.mesh_devices != 1 and n_devices > 1)
        from .parallel.sync import process_count
        if process_count() > 1 and not use_dist:
            log.fatal("num_machines > 1 requires tree_learner=data, voting "
                      "(per-process row partitions) or feature (full data "
                      "on every process) over >1 devices; a serial learner "
                      "would silently train per-partition models")
        # distributed implementation (docs/DISTRIBUTED.md): gspmd writes
        # the grow program over global NamedSharding arrays and the XLA
        # partitioner inserts the collectives; shardmap is the historical
        # explicit-psum choreography, kept as the forced A/B partner.
        # Every downgrade from an explicit request is loud (the rung-
        # honesty discipline: labels must name what runs).
        impl = cfg.parallel_impl
        if impl == "gspmd" and process_count() > 1 \
                and cfg.tree_learner == "feature":
            # feature-parallel multi-host replicates the FULL dataset on
            # every process (the reference contract); the multi-process
            # gspmd placement assembles per-process ROW partitions — the
            # two data contracts are incompatible, so the replication
            # layout keeps the shard_map learner
            log.warning("parallel_impl=gspmd is unavailable for "
                        "multi-process tree_learner=feature (the "
                        "full-data-everywhere replication contract); "
                        "falling back to shard_map")
            obs_counters.event(
                "layout_downgrade", stage="boosting",
                requested="parallel_impl=gspmd", resolved="shardmap",
                reason="multi-process feature-parallel replicates the "
                       "full dataset")
            impl = "shardmap"
        if impl == "gspmd" and cfg.tree_learner == "voting":
            log.warning("parallel_impl=gspmd is unavailable for "
                        "tree_learner=voting (PV-tree vote compression IS "
                        "call-site collective machinery); falling back to "
                        "shard_map")
            obs_counters.event(
                "layout_downgrade", stage="boosting",
                requested="parallel_impl=gspmd", resolved="shardmap",
                reason="voting learner needs explicit vote collectives")
            impl = "shardmap"
        if impl == "auto":
            # gspmd is the default single- AND multi-process: the compiler
            # owns the data plane either way, and the elastic stack
            # (supervisor shrink -> plan_mesh -> elastic_resume) composes
            # with both.  Only the layouts whose data contracts gspmd
            # cannot express keep the shard_map learners.
            impl = ("shardmap" if (cfg.tree_learner == "voting"
                                   or (process_count() > 1
                                       and cfg.tree_learner == "feature"))
                    else "gspmd")
        self._parallel_impl = impl if use_dist else "serial"
        # nibble-pack <=16-bin column pairs for the histogram path
        # (dense_nbits_bin.hpp analogue, data/packing.py).  Multi-process
        # global arrays and the feature-parallel column slicing keep the
        # 1:1 layout (a packed byte would straddle shard ownership).
        if (cfg.enable_bin_packing and process_count() == 1
                and not (use_dist and cfg.tree_learner
                         in ("feature", "data_feature"))):
            from .data.packing import build_pack_plan, pack_columns
            col_bins = (train.layout.col_num_bin
                        if train.layout is not None
                        and train.layout.has_bundles
                        else [train.bin_mappers[i].num_bin
                              for i in train.used_features])
            self._pack_plan = build_pack_plan(col_bins)
            if self._pack_plan is not None:
                if cfg.ordered_bins == "on":
                    log.warning("ordered_bins=on is ignored while nibble "
                                "bin packing is active (the packed storage "
                                "matrix has its own layout); set "
                                "enable_bin_packing=false to use the "
                                "leaf-ordered path")
                    obs_counters.event(
                        "layout_downgrade", stage="boosting",
                        requested="ordered_bins=on", resolved="off",
                        reason="nibble bin packing is active")
                self._hist_bins = pack_columns(np.asarray(train.binned),
                                               self._pack_plan)
                log.info("Bin packing: %d of %d columns nibble-packed "
                         "into %d bytes/row (histogram path)",
                         self._pack_plan.num_packed,
                         self._pack_plan.num_phys_cols,
                         self._pack_plan.num_storage_cols)
        # fused-rung truthfulness: downgrade a fused request the layout
        # cannot serve HERE, so grower_cfg.hist_method (which bench labels
        # and A/B artifacts read) always names the kernel that runs; the
        # grower re-checks the same gate at trace time as a safety net
        if self.grower_cfg.hist_method == "fused":
            from .data.packing import PACK_JOINT_BINS
            from .grower import fused_gate_reason
            plan = self._pack_plan
            hw = (max(PACK_JOINT_BINS, self.grower_cfg.max_bin)
                  if plan is not None else self.grower_cfg.max_bin)
            ncols = (plan.num_storage_cols if plan is not None
                     else train.binned.shape[1])
            reason = fused_gate_reason(
                train.binned.dtype, jnp.float32, hw, ncols,
                self.grower_cfg.ordered_bins == "on" and plan is None)
            if reason is not None:
                log.warning("pallas_fused=on unavailable (%s); using the "
                            "gen-1 pallas kernel", reason)
                obs_counters.event("layout_downgrade", stage="boosting",
                                   requested="fused", resolved="pallas",
                                   reason=reason)
                self.grower_cfg = self.grower_cfg._replace(
                    hist_method="pallas")
        # the bagged-subset optimization (gbdt.cpp:323-382 is_use_subset_)
        # gathers rows into a compact matrix — serial learner only for now
        self._can_subset = not use_dist
        if not use_dist:
            if cfg.tree_learner != "serial":
                log.warning("tree_learner=%s requested but only one device is "
                            "in use (devices=%d, mesh_devices=%d); falling "
                            "back to serial", cfg.tree_learner, n_devices,
                            cfg.mesh_devices)
                obs_counters.event(
                    "layout_downgrade", stage="boosting",
                    requested=f"tree_learner={cfg.tree_learner}",
                    resolved="serial",
                    reason="only one device is in use")
            placement = self._resolve_data_placement(cfg, n_devices)
            self._placement = placement
            if placement is not None and placement.mode == "chunked":
                self._setup_streamed(cfg, train, placement)
                return
            if placement is not None and placement.mode == "sharded":
                # the capacity walk escalated PAST streaming: even the
                # double-buffered block pipeline's footprint exceeds one
                # device, but the mesh the planner sized fits — hand the
                # shape to the gspmd learner instead of OOMing serially
                log.warning("training data exceeds single-device capacity "
                            "even streamed; sharding over the %dx%d mesh "
                            "the placement planner sized",
                            placement.mesh.data, placement.mesh.feature)
                obs_counters.event(
                    "layout_downgrade", stage="boosting",
                    requested="tree_learner=serial", resolved="gspmd",
                    reason="data exceeds one device even as streamed "
                           "blocks")
                self._parallel_impl = "gspmd"
                self._can_subset = False
                self._setup_gspmd(cfg, train, n_devices)
                return
            self.bins = jnp.asarray(self.bins)
            if self._hist_bins is not None:
                self._hist_bins = jnp.asarray(self._hist_bins)
            self.grow = jax.jit(make_grower(self.grower_cfg,
                                            pack_plan=self._pack_plan))
            return
        if self._parallel_impl == "gspmd":
            self._setup_gspmd(cfg, train, n_devices)
            return
        from .parallel.learner import make_distributed_grower
        from .parallel.mesh import (make_2d_mesh, make_mesh, pad_features,
                                    pad_rows)
        axis = "feature" if cfg.tree_learner == "feature" else "data"
        if cfg.tree_learner == "data_feature":
            # near-square factorization of the device count into
            # data x feature shards (the 2-D hybrid learner); clamp to
            # the available devices like make_mesh's 1-D truncation
            nd = min(cfg.mesh_devices or n_devices, n_devices)
            dr = max(d for d in range(1, int(nd ** 0.5) + 1) if nd % d == 0)
            mesh = make_2d_mesh(dr, nd // dr)
            if jax.process_count() > 1:
                log.fatal("tree_learner=data_feature is single-process for "
                          "now; use data/voting/feature across machines")
        else:
            mesh = make_mesh(cfg.mesh_devices or 0, axis)
        shards = int(mesh.devices.size)
        n = self.num_data
        self._multiproc = jax.process_count() > 1
        self._multiproc_replicated = False
        if self._multiproc and cfg.tree_learner == "feature":
            # feature-parallel multi-host: EVERY machine holds the full data
            # (the reference's feature-parallel contract,
            # docs/Parallel-Learning-Guide.md) — arrays are replicated over
            # the global mesh and each device scans its own column slice
            self._multiproc_replicated = True
        elif self._multiproc:
            from jax.experimental import multihost_utils
            from jax.sharding import NamedSharding, PartitionSpec as P
            # every process contributes its local partition; per-device row
            # count must agree globally, so pad to the global max
            local_devs = jax.local_device_count()
            counts = np.asarray(multihost_utils.process_allgather(
                np.asarray([n]))).reshape(-1)
            per_dev = int(-(-int(counts.max()) // local_devs))
            self._row_pad = per_dev * local_devs - n
            self._global_rows = per_dev * shards
            binned = np.asarray(train.binned)
            if self._row_pad:
                binned = np.pad(binned, ((0, self._row_pad), (0, 0)))
            self._row_sharding = NamedSharding(mesh, P(axis))
            self.bins = jax.make_array_from_process_local_data(
                NamedSharding(mesh, P(axis, None)), binned,
                (self._global_rows, binned.shape[1]))
            log.info("Multi-process training: %d processes, %d local rows, "
                     "%d global (padded) rows", jax.process_count(), n,
                     self._global_rows)
        elif cfg.tree_learner in ("data", "voting", "data_feature"):
            # on the 2-D mesh rows shard over the "data" axis only
            self._row_pad = pad_rows(n, int(mesh.shape.get("data", shards)))
            self.bins = (jnp.pad(self.bins, ((0, self._row_pad), (0, 0)))
                         if self._row_pad else jnp.asarray(self.bins))
            if self._hist_bins is not None:
                hb = self._hist_bins
                self._hist_bins = (
                    jnp.pad(hb, ((0, self._row_pad), (0, 0)))
                    if self._row_pad else jnp.asarray(hb))
        if cfg.tree_learner in ("feature", "data_feature"):
            bundled = self.meta.col is not None
            ncols = int(np.shape(self.bins)[1])
            col_pad = pad_features(ncols,
                                   int(mesh.shape.get("feature", shards)))
            # pad PHYSICAL columns; bundled logical meta stays intact
            # (no logical feature maps to a pad column)
            binned = np.asarray(self.bins)
            if col_pad:
                binned = np.pad(binned, ((0, 0), (0, col_pad)))
            if not bundled:
                self._feat_pad = col_pad
                if col_pad:
                    pad1 = lambda a, v: np.pad(np.asarray(a),
                                               (0, self._feat_pad),
                                               constant_values=v)
                    self.meta = FeatureMeta(
                        num_bin=pad1(self.meta.num_bin, 1),
                        missing_type=pad1(self.meta.missing_type, 0),
                        default_bin=pad1(self.meta.default_bin, 0),
                        is_categorical=pad1(self.meta.is_categorical, False))
            if self._multiproc_replicated:
                from jax.sharding import NamedSharding, PartitionSpec as P
                from .parallel.sync import allgather_object
                import zlib
                # the replication CONTRACT must hold: every process feeds the
                # same full matrix (a user migrating from tree_learner=data
                # may still be feeding per-process partitions — reject that
                # loudly instead of training on silently inconsistent data)
                sig = (binned.shape,
                       zlib.crc32(np.ascontiguousarray(binned)))
                sigs = allgather_object(sig)
                if any(s != sig for s in sigs):
                    log.fatal("feature-parallel multi-process training "
                              "requires the FULL identical dataset on every "
                              "process (got differing data signatures %s); "
                              "per-process row partitions need "
                              "tree_learner=data or voting", sigs)
                # identical full data on every process -> one replicated
                # global array; per-row vectors ride the same sharding
                repl = NamedSharding(mesh, P())
                self.bins = jax.make_array_from_process_local_data(
                    repl, binned, binned.shape)
                self._row_sharding = repl
                self._global_rows = n
                log.info("Multi-process feature-parallel: %d processes, "
                         "full data replicated (%d rows)",
                         jax.process_count(), n)
            else:
                self.bins = jnp.asarray(binned)
        if self._multiproc:
            # replicated inputs go in as host arrays (jit replicates them);
            # device-committed single-process arrays would be rejected
            self.meta = FeatureMeta(*[None if f is None else np.asarray(f)
                                      for f in self.meta])
        log.info("Using %s-parallel tree learner over %d devices",
                 cfg.tree_learner, shards)
        self.grow = make_distributed_grower(self.grower_cfg, mesh,
                                            cfg.tree_learner, cfg.top_k,
                                            bundled=self.meta.col is not None,
                                            pack_plan=self._pack_plan)

    def _resolve_data_placement(self, cfg: Config, n_devices: int):
        """Training-data placement pre-flight for the serial learner
        (``parallel/mesh.resolve_placement``): walk resident -> streamed
        -> sharded against the device capacity / ``hbm_budget`` BEFORE
        anything compiles.  Returns the :class:`PlacementPlan` (every
        decision also lands as one ``placement_decision`` obs event), or
        None when the walk does not apply."""
        from .parallel import mesh as mesh_mod
        if cfg.boosting_type in ("dart", "goss"):
            # dart's drop/rescale and goss's top-k subsetting assume the
            # resident row layout; config.py rejects an EXPLICIT chunked
            # pin, and auto never volunteers one — an over-budget shape
            # fails in the preflight with the component breakdown instead
            return None
        capacity = (int(cfg.hbm_budget) if cfg.hbm_budget > 0
                    else obs_memory.device_capacity())
        ncols = int(np.shape(self.bins)[1])
        try:
            return mesh_mod.resolve_placement(
                rows=self.num_data, features=ncols,
                bins=self.grower_cfg.max_bin,
                leaves=self.grower_cfg.num_leaves,
                num_class=self.num_class,
                bin_bytes=int(np.asarray(self.bins).dtype.itemsize),
                packed_cols=(self._pack_plan.num_storage_cols
                             if self._pack_plan is not None else 0),
                valid_rows=sum(vs.data.num_data
                               for vs in self.valid_sets),
                capacity=capacity, data_stream=cfg.data_stream,
                stream_chunk_rows=cfg.stream_chunk_rows,
                n_devices=n_devices, prefer="data", procs=1,
                local_devices=jax.local_device_count())
        except mesh_mod.MeshPlanError:
            # the walk refused before _memory_preflight could run: land
            # the legacy hbm_preflight verdict too (obs/report.py reads
            # that event), then let the richer refusal propagate
            pred = obs_memory.predict_hbm(
                rows=self.num_data, features=ncols,
                bins=self.grower_cfg.max_bin,
                leaves=self.grower_cfg.num_leaves,
                num_class=self.num_class,
                bin_bytes=int(np.asarray(self.bins).dtype.itemsize),
                packed_cols=(self._pack_plan.num_storage_cols
                             if self._pack_plan is not None else 0),
                valid_rows=sum(vs.data.num_data
                               for vs in self.valid_sets))
            try:
                obs_memory.preflight(
                    pred, hbm_budget=cfg.hbm_budget,
                    context=f"{self.num_data} rows x {ncols} cols, "
                            f"placement walk refused")
            except RuntimeError:
                pass
            raise

    def _setup_streamed(self, cfg: Config, train: TrainingData,
                        placement) -> None:
        """``data_stream=chunked``: the quantized binned rows stay
        HOST-side and flow through the device as double-buffered
        static-shape blocks (data/stream.py), grown by the host-driven
        :class:`~.grower.StreamedGrower`.  Trees are byte-identical to
        the resident path under order-insensitive (integer) weights —
        the block accumulation runs in fixed block order."""
        from .data.stream import BlockStreamer
        if self._pack_plan is not None:
            log.warning("nibble bin packing is ignored under "
                        "data_stream=chunked (the packed histogram copy "
                        "is a second resident copy of exactly the matrix "
                        "streaming exists to keep off-device); streaming "
                        "the raw 1:1 bin layout")
            obs_counters.event(
                "layout_downgrade", stage="boosting",
                requested="enable_bin_packing=true", resolved="unpacked",
                reason="streamed blocks keep the raw 1:1 bin layout")
            self._pack_plan = None
            self._hist_bins = None
        if self.grower_cfg.hist_method != "segment":
            log.warning("hist_method=%s is unavailable under "
                        "data_stream=chunked (per-block partial "
                        "histograms run the masked whole-block "
                        "segment-sum); falling back to segment",
                        self.grower_cfg.hist_method)
            obs_counters.event(
                "layout_downgrade", stage="boosting",
                requested=f"hist_method={self.grower_cfg.hist_method}",
                resolved="segment",
                reason="streamed blocks use the masked segment-sum")
            self.grower_cfg = self.grower_cfg._replace(
                hist_method="segment")
        if self.grower_cfg.ordered_bins == "on":
            log.warning("ordered_bins=on is ignored under "
                        "data_stream=chunked (leaf-ordered storage "
                        "assumes the resident row layout); using the "
                        "direct layout")
            obs_counters.event(
                "layout_downgrade", stage="boosting",
                requested="ordered_bins=on", resolved="off",
                reason="streamed blocks keep source row order")
            self.grower_cfg = self.grower_cfg._replace(ordered_bins="off")
        # the bagged-subset gather materializes ANOTHER row matrix on
        # device — bagging under streaming keeps the weight-mask form
        self._can_subset = False
        store = train.to_blocks(placement.chunk_rows)
        self._stream_store = store
        self._streamer = BlockStreamer(store)
        # the grow-call contract passes self.bins positionally; under
        # streaming that slot carries the pipeline, not a device array
        self.bins = self._streamer
        self.grow = StreamedGrower(self.grower_cfg)
        log.info("Using streamed serial tree learner: %d blocks of %d "
                 "rows, double-buffered (%s)", store.num_blocks,
                 store.chunk_rows, placement.reason)

    def _setup_gspmd(self, cfg: Config, train: TrainingData,
                     n_devices: int) -> None:
        """GSPMD learner setup (docs/DISTRIBUTED.md): size the (batch,
        feature) mesh — explicitly (``mesh_shape=DxF``) or through the
        memory-driven planner (``mesh_shape=auto``:
        ``parallel/mesh.plan_mesh`` evaluates the ``predict_hbm`` model
        per candidate shape against the per-device capacity /
        ``hbm_budget``, so a dataset that does not fit one chip's HBM
        trains anyway and an impossible shape fails in milliseconds) —
        place the global arrays, and build the NamedSharding grower.
        XLA owns the data-plane collectives from here;
        ``parallel/sync.py``'s host ladder keeps the control plane."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .grower import fused_gate_reason
        from .parallel import gspmd as gspmd_mod
        from .parallel import mesh as mesh_mod
        # histogram formulation under gspmd (``gspmd_hist``): flat (the
        # masked whole-partition scatter-add — pure XLA, the forced A/B
        # partner) or fused (the shard_map hybrid: the fused Pallas
        # kernel per row shard, partitioner-owned cross-shard reduction).
        # ``auto`` stays flat until the on-chip A/B flips it
        # (capture-backlog discipline, scripts/decide_flips.py).  The
        # serial TPU/CPU ladder baked into grower_cfg.hist_method does
        # not apply here — the partitioner owns the layout.
        gspmd_hist = "flat" if cfg.gspmd_hist == "auto" else cfg.gspmd_hist
        procs = jax.process_count()
        if gspmd_hist == "fused" and procs > 1:
            log.warning("gspmd_hist=fused is single-process for now (the "
                        "hybrid's shard_map island has no multi-host "
                        "numbers); using the flat scatter-add histogram")
            obs_counters.event(
                "layout_downgrade", stage="boosting",
                requested="gspmd_hist=fused", resolved="flat",
                reason="multi-process training")
            gspmd_hist = "flat"
        hist_width = (max(256, self.grower_cfg.max_bin)
                      if self._pack_plan is not None
                      else self.grower_cfg.max_bin)
        sc_cols = (self._pack_plan.num_storage_cols
                   if self._pack_plan is not None
                   else int(np.shape(self.bins)[1]))
        hist_mat = (self._hist_bins if self._pack_plan is not None
                    else self.bins)
        hist_dtype = np.asarray(hist_mat).dtype
        if gspmd_hist == "fused":
            # shape-independent gate (the shape-dependent half runs after
            # the mesh plan below): downgrade loudly BEFORE labels are
            # read, per the rung-honesty discipline
            reason = fused_gate_reason(hist_dtype, jnp.float32, hist_width,
                                       1, False)
            if reason is not None:
                log.warning("gspmd_hist=fused unavailable (%s); using the "
                            "flat scatter-add histogram", reason)
                obs_counters.event(
                    "layout_downgrade", stage="boosting",
                    requested="gspmd_hist=fused", resolved="flat",
                    reason=reason)
                gspmd_hist = "flat"
        nd = min(cfg.mesh_devices or n_devices, n_devices)
        local_devs = jax.local_device_count()
        if procs > 1 and nd != n_devices:
            # a partial mesh cannot hold every process's row partition:
            # some rank's devices would sit outside the mesh and its data
            # would have nowhere to live
            log.warning("mesh_devices=%d ignored across %d processes; the "
                        "gspmd mesh must span all %d devices",
                        cfg.mesh_devices, procs, n_devices)
            obs_counters.event(
                "layout_downgrade", stage="boosting",
                requested=f"mesh_devices={cfg.mesh_devices}",
                resolved=f"mesh_devices={n_devices}",
                reason="multi-process gspmd mesh must span all devices")
            nd = n_devices
        prefer = {"data": "data", "feature": "feature",
                  "data_feature": "square"}.get(cfg.tree_learner, "data")
        explicit = mesh_mod.parse_mesh_shape(cfg.mesh_shape, nd, prefer)
        if explicit is not None and procs > 1:
            refusal = mesh_mod.mesh_shape_fits_processes(
                explicit[0], explicit[1], procs, local_devs)
            if refusal is not None:
                raise mesh_mod.MeshPlanError(
                    f"mesh_shape={cfg.mesh_shape} cannot serve "
                    f"{procs}-process training: {refusal}")
        ncols = int(np.shape(self.bins)[1])
        n = self.num_data
        rows_global = n
        valid_rows = sum(vs.data.num_data for vs in self.valid_sets)
        if procs > 1:
            # the planner (and predict_hbm behind it) must see the GLOBAL
            # shape: every process contributes its own row partition
            from jax.experimental import multihost_utils
            counts = np.asarray(multihost_utils.process_allgather(
                np.asarray([n, valid_rows]))).reshape(-1, 2)
            rows_global = int(counts[:, 0].sum())
            valid_rows = int(counts[:, 1].sum())
            self._proc_row_counts = counts[:, 0].astype(np.int64)
        capacity = (int(cfg.hbm_budget) if cfg.hbm_budget > 0
                    else obs_memory.device_capacity())
        plan_kwargs = dict(
            rows=rows_global, features=ncols,
            bins=self.grower_cfg.max_bin,
            leaves=self.grower_cfg.num_leaves, num_class=self.num_class,
            bin_bytes=int(np.asarray(self.bins).dtype.itemsize),
            packed_cols=(self._pack_plan.num_storage_cols
                         if self._pack_plan is not None else 0),
            valid_rows=valid_rows,
            gspmd_fused=(gspmd_hist == "fused"))
        if explicit is not None:
            d, f = explicit
            from .obs.memory import predict_hbm
            block = str(cfg.shard_axes).strip().lower().replace(" ", "") \
                in ("batch,feature", "feature,batch")
            pred = predict_hbm(data_shards=d, feature_shards=f,
                               block_shard_bins=block, **plan_kwargs)
            plan = mesh_mod.MeshPlan(
                d, f, block, int(pred["peak_bytes"]), capacity,
                dict(sorted({**pred["residents"],
                             **pred["transients"]}.items(),
                            key=lambda kv: -kv[1])[:4]),
                f"explicit mesh_shape={cfg.mesh_shape}")
        else:
            # MeshPlanError propagates: the structured pre-flight error
            # (nothing fits) must surface before anything compiles
            plan = mesh_mod.plan_mesh(nd, capacity=capacity,
                                      prefer=prefer, procs=procs,
                                      local_devices=local_devs,
                                      **plan_kwargs)
        sa = str(cfg.shard_axes).strip().lower().replace(" ", "")
        if sa == "batch":
            plan = plan._replace(block_shard_bins=False)
        elif sa in ("batch,feature", "feature,batch"):
            plan = plan._replace(block_shard_bins=True)
        if gspmd_hist == "fused":
            # shape-dependent half of the fused gate, now that the mesh
            # extents are known: each device's column slice must be exact
            # (shard_map even-split) and fit the kernel's column ceiling
            if sc_cols % plan.feature != 0:
                reason = (f"{sc_cols} histogram columns do not split "
                          f"evenly over {plan.feature} feature shards")
            else:
                reason = fused_gate_reason(hist_dtype, jnp.float32,
                                           hist_width,
                                           sc_cols // plan.feature, False)
            if reason is not None:
                log.warning("gspmd_hist=fused unavailable (%s); using the "
                            "flat scatter-add histogram", reason)
                obs_counters.event(
                    "layout_downgrade", stage="boosting",
                    requested="gspmd_hist=fused", resolved="flat",
                    reason=reason)
                gspmd_hist = "flat"
        # the gspmd builder keys off hist_method: "fused" = hybrid island,
        # anything else = flat (recorded as method=segment by dispatch).
        # Off-TPU the island runs the kernel's interpret mode — same
        # program shape, Pallas emulated — so the hybrid is CPU-testable.
        self.grower_cfg = self.grower_cfg._replace(
            hist_method="fused" if gspmd_hist == "fused" else "segment",
            hist_interpret=(gspmd_hist == "fused" and not _on_tpu()))
        obs_counters.event(
            "mesh_plan", data=plan.data, feature=plan.feature,
            block_shard_bins=plan.block_shard_bins,
            per_device_bytes=plan.per_device_bytes,
            capacity_bytes=plan.capacity, reason=plan.reason)
        obs_counters.gauge("mesh_feature_shards", plan.feature)
        mesh = mesh_mod.make_named_mesh(plan.data, plan.feature)
        bins_spec = P(mesh_mod.BATCH_AXIS,
                      mesh_mod.FEATURE_AXIS if plan.block_shard_bins
                      else None)
        if procs > 1:
            # each process holds its OWN row partition (the reference's
            # pre-partitioned parallel learning): its rows go onto its
            # own batch-axis block of the global NamedSharding array.
            # Per-SHARD row count must agree globally (static shapes), so
            # every partition pads to the global max.
            shards_per_proc = plan.data // procs    # planner guarantees >=1
            per_shard = int(-(-int(self._proc_row_counts.max())
                              // shards_per_proc))
            self._row_pad = per_shard * shards_per_proc - n
            self._global_rows = per_shard * plan.data
            binned = np.asarray(self.bins)
            if self._row_pad:
                binned = np.pad(binned, ((0, self._row_pad), (0, 0)))
            self._multiproc = True
            self._multiproc_replicated = False
            self.bins = jax.make_array_from_process_local_data(
                NamedSharding(mesh, bins_spec), binned,
                (self._global_rows, ncols))
            # replicated grower inputs go in as host arrays (jit
            # replicates them); device-committed single-process arrays
            # would be rejected — the shard_map multiproc precedent
            self.meta = FeatureMeta(*[None if f is None else np.asarray(f)
                                      for f in self.meta])
            log.info("Multi-process GSPMD: %d processes, %d local rows, "
                     "%d global (padded) rows", procs, n,
                     self._global_rows)
        else:
            self._row_pad = mesh_mod.pad_rows(n, plan.data)
            binned = np.asarray(self.bins)
            if self._row_pad:
                binned = np.pad(binned, ((0, self._row_pad), (0, 0)))
            self.bins = jax.device_put(binned,
                                       NamedSharding(mesh, bins_spec))
        if self._hist_bins is not None:
            hb = np.asarray(self._hist_bins)
            if self._row_pad:
                hb = np.pad(hb, ((0, self._row_pad), (0, 0)))
            self._hist_bins = jax.device_put(
                hb, NamedSharding(mesh, P(mesh_mod.BATCH_AXIS, None)))
        self._gspmd_mesh = mesh
        self._gspmd_plan = plan
        self._gspmd_row_sharding = NamedSharding(
            mesh, P(mesh_mod.BATCH_AXIS))
        if self._multiproc:
            self._row_sharding = self._gspmd_row_sharding
        log.info("Using GSPMD %s learner over a %dx%d (batch, feature) "
                 "mesh (%s)", cfg.tree_learner, plan.data, plan.feature,
                 plan.reason)
        self.grow = gspmd_mod.make_gspmd_grower(
            self.grower_cfg, mesh, bundled=self.meta.col is not None,
            pack_plan=self._pack_plan, block_shard=plan.block_shard_bins)

    def grow_hlo_census(self, label: str = "grow") -> Dict[str, Dict[str, int]]:
        """Compiled-HLO collective census of the CURRENT grower
        executable (``obs/collectives.hlo_census``): lowers ``self.grow``
        at the exact training shapes/shardings — with the jit cache and
        the persistent compilation cache this reuses the training's own
        executable — and returns ``{op: {count, bytes, max_bytes}}``.
        This is the honest accounting under GSPMD, where the compiler
        (not a call site) decides which collectives run; bench.py's mesh
        rung and tests/test_gspmd.py's audit both read it."""
        from .obs.collectives import hlo_census
        feat_mask = np.ones(len(self._feat_valid_base), dtype=bool)
        if self._feat_pad:
            feat_mask = np.concatenate(
                [feat_mask, np.zeros(self._feat_pad, dtype=bool)])
        if not self._multiproc:
            feat_mask = jnp.asarray(feat_mask)
        if self._streamer is not None:
            # streamed grower: sum the census over its jit pieces (the
            # zero-added-collectives pin — single-device streaming must
            # not smuggle communication into the program)
            return self.grow.hlo_census(self._streamer, self.meta,
                                        feat_mask, label=label)
        zero = self._dist_row_vec(jnp.zeros((self.num_data,), jnp.float32))
        hist_arg = ((self._hist_bins,)
                    if self._pack_plan is not None else ())
        compiled = self.grow.lower(self.bins, *hist_arg, zero, zero, zero,
                                   self.meta, feat_mask).compile()
        return hlo_census(compiled, label=label)

    def _make_metrics(self, data: TrainingData) -> List[Metric]:
        out = []
        for name in self.metric_names:
            m = create_metric(name, self.config)
            if m is not None:
                m.init(data.metadata, data.num_data)
                out.append(m)
        return out

    def add_valid_set(self, data: TrainingData, name: str) -> None:
        vs = _ValidSet(data, name, self.num_class, self._make_metrics(data))
        # replay existing model onto the new valid set (continued training)
        for i, tree in enumerate(self.models):
            k = i % self.num_class
            vs.scores = vs.scores.at[k].add(
                tree_scores_binned(vs.bins, tree, self.used_feature_index,
                                   self.feat_info,
                                   self.train_set.bin_mappers))
        self.valid_sets.append(vs)

    # --------------------------------------------------------------- training

    def _boost_from_average(self) -> None:
        """gbdt.cpp:407-480: constant init tree from the label average.

        Multi-process: the average is computed from globally summed
        (numerator, denominator) stats before the objective's transform —
        GlobalSyncUpByMean — so every rank starts from the same score."""
        num, den = self.objective.average_stats()
        if self._multiproc:
            from .parallel.sync import allgather_object
            parts = allgather_object((num, den))
            num = sum(p[0] for p in parts)
            den = sum(p[1] for p in parts)
        init = self.objective.init_from_average(num / max(den, 1e-300))
        tree = Tree(1)
        tree.leaf_value[0] = init
        self.models.append(tree)
        self.scores = self.scores + init
        for vs in self.valid_sets:
            vs.scores = vs.scores + init
        self.boost_from_average_ = True
        log.info("Start training from score %f", init)

    def _bagging(self, it: int, grad, hess) -> None:
        """Row bagging (gbdt.cpp:323-382).

        fraction <= 0.5 (the reference's ``is_use_subset_`` regime): exact
        ``fraction * N`` rows sampled without replacement are GATHERED into a
        compact device matrix and the tree grows on that — per-tree cost is
        O(bagged rows), not O(N).  Larger fractions keep the cheaper 0/1
        weight-mask form (Bernoulli, vectorized)."""
        cfg = self.config
        if cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0:
            if it % cfg.bagging_freq == 0:
                n = self.num_data
                if self._can_subset and cfg.bagging_fraction <= 0.5:
                    m = max(1, int(n * cfg.bagging_fraction))
                    idx = sample_k(self._bag_rng, n, m)
                    self._set_subset(idx, np.ones(m, np.float32))
                else:
                    self._subset_state = None
                    mask = (self._bag_rng.random(n)
                            < cfg.bagging_fraction).astype(np.float32)
                    self._bag_weight = jnp.asarray(mask)
                    self._bag_cnt = self._bag_weight
                self._bagging_on = True
        elif getattr(self, "_bagging_on", False):
            # bagging turned off mid-training (reset_parameter callback,
            # ResetBaggingConfig analogue): drop the stale subset/mask so
            # trees see the full data again
            self._bagging_on = False
            self._subset_state = None
            self._bag_weight = jnp.ones((self.num_data,), jnp.float32)
            self._bag_cnt = self._bag_weight

    def _set_subset(self, idx: np.ndarray, w: np.ndarray) -> None:
        """Gather rows ``idx`` (weights ``w``) into the compact subset matrix.

        Padded to a power-of-two bucket so re-bagging recompiles the grower at
        most log2 times; padding rows point at row 0 with weight 0 (they flow
        through the partition but contribute nothing to any histogram,
        count, or output)."""
        m = len(idx)
        m_pad = max(1 << max(int(m - 1).bit_length(), 0), 1024)
        pad = m_pad - m
        idx_p = np.concatenate([idx.astype(np.int32),
                                np.zeros(pad, np.int32)])
        w_p = np.concatenate([w.astype(np.float32), np.zeros(pad, np.float32)])
        idx_d = jnp.asarray(idx_p)
        self._subset_state = (jnp.take(self.bins, idx_d, axis=0),
                              idx_d,
                              jnp.asarray(w_p),
                              jnp.asarray((w_p > 0).astype(np.float32)),
                              (jnp.take(self._hist_bins, idx_d, axis=0)
                               if self._hist_bins is not None else None))
        self._bag_weight = jnp.ones((self.num_data,), jnp.float32)
        self._bag_cnt = self._bag_weight

    def _feature_sample(self) -> np.ndarray:
        frac = self.config.feature_fraction
        mask = self._feat_valid_base.copy()
        if frac < 1.0:
            f = len(mask)
            k = max(1, int(f * frac))
            chosen = self._feat_rng.choice(f, size=k, replace=False)
            sub = np.zeros(f, dtype=bool)
            sub[chosen] = True
            mask &= sub
        return mask

    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> bool:
        """One boosting iteration; returns True if training should stop
        (gbdt.cpp:465-581 TrainOneIter).  Each iteration is one telemetry
        span; the per-phase spans inside come from ``self.timers``."""
        fl = obs_flight.get_flight()
        dp = obs_devprof.get_devprof()
        t0 = time.perf_counter() if fl.enabled else 0.0
        with obs_trace.get_tracer().span("iteration", index=int(self.iter_)), \
                dp.iteration(int(self.iter_)):
            stop = self._train_one_iter_inner(grad, hess)
        # per-iteration device-memory gauge (no-op singleton when memory
        # observability is off; armed it is a host-side read — it rides
        # the fetches the loop already does, adding no syncs of its own)
        obs_memory.get_memory().sample(site="iteration")
        if fl.enabled:
            # flight-recorder progress record: everything here is a
            # host-side registry read — no device fetch, no collective
            dt = time.perf_counter() - t0
            rec: Dict[str, object] = {"seconds": round(dt, 6)}
            if dt > 0:
                rec["trees_per_sec"] = round(self.num_class / dt, 4)
            leaves = self._last_iter_leaves
            if leaves and dt > 0:
                rec["ms_per_leaf"] = round(dt * 1e3 / leaves, 4)
            kernel = obs_counters.observed_kernel()
            if kernel:
                rec["kernel"] = kernel
            peak = obs_memory.get_memory().measured_peak()
            if peak:
                rec["hbm_peak_bytes"] = int(peak)
            coll = obs_collectives.totals()
            if coll["calls"]:
                rec["collective_bytes"] = coll["bytes"]
            # the just-captured devprof window's idle-gap fraction rides
            # the progress record (parsed before this record is built, so
            # the supervisor's straggler verdict can cite it)
            gap = dp.pop_idle_gap() if dp.enabled else None
            if gap is not None:
                rec["idle_gap_fraction"] = gap
            # streamed pipeline: this iteration's blocking transfer waits
            # over its wall clock — the overlap evidence the bench rung
            # and the stream_stall events summarize
            if self._streamer is not None and dt > 0:
                wait = self._streamer.take_wait_ms()
                rec["stream_wait_ms"] = round(wait, 3)
                rec["stream_stall_fraction"] = round(
                    min(1.0, wait / (dt * 1e3)), 4)
            # per-metric eval values (model-quality plane): the engine
            # evaluates AFTER update, so the freshest stashed values are
            # the previous iteration's — stamped as such
            evals = obs_model_quality.get_tracker().eval_fields()
            if evals:
                rec["eval"] = evals
            fl.progress(int(self.iter_), **rec)
        return stop

    def _train_one_iter_inner(self, grad: Optional[np.ndarray] = None,
                              hess: Optional[np.ndarray] = None) -> bool:
        # leaves this iteration actually split (known on the synchronous
        # path only — pipelined trees drain later); the flight recorder's
        # ms/leaf field rides it
        self._last_iter_leaves = 0
        if (self.iter_ == 0 and self.num_init_iteration == 0
                and self.allow_boost_from_average
                and self.objective is not None
                and self.objective.boost_from_average
                and not self._has_init_score
                and self.num_class == 1
                and self.config.boost_from_average
                and not self.boost_from_average_):
            self._boost_from_average()

        # score arrays are immutable jax values, so holding the
        # iteration-start REFERENCES is a zero-copy undo point: rollback of
        # this (or the just-finished) iteration restores them bit-exactly,
        # which arithmetic subtraction cannot do in f32 ((a+b)-b is off by
        # an ulp for ~half of all inputs) — the invariant
        # nonfinite_policy=rollback and tests/test_robustness.py depend on
        if self.rollback_safe:
            self._score_stash = (self.iter_, self.scores,
                                 [vs.scores for vs in self.valid_sets])

        # pipelined mode never blocks in the loop: every phase is an async
        # dispatch and freshly grown trees drain to host a few iterations
        # late (one batched transfer each).  Synchronous mode blocks each
        # phase on its outputs so async dispatch does not misattribute
        # device time to the next phase.
        # custom gradients stay synchronous: the caller computed them from
        # the CURRENT prediction state, so a lately-discovered no-split
        # rewind must never invalidate iterations their fobj already saw
        pipeline = self._pipeline and grad is None and hess is None
        if not pipeline and self._pending:
            self._drain_pending()           # never interleave modes
            if self._stopped_no_split:
                self._stopped_no_split = False
                return True
        with self.timers.phase("boosting"):
            if grad is None or hess is None:
                g, h = self._grad_fn(self.scores)
            else:
                g = jnp.asarray(grad, jnp.float32).reshape(self.num_class, -1)
                h = jnp.asarray(hess, jnp.float32).reshape(self.num_class, -1)
            fi = faults_mod.get_faults()
            if fi.enabled:
                if fi.fire("nan_grad", int(self.iter_)):
                    g = g.at[0, 0].set(jnp.nan)
                if fi.fire("inf_hess", int(self.iter_)):
                    h = h.at[0, 0].set(jnp.inf)
            # device-side finiteness flag, fetched later alongside values
            # the loop already pulls (num_leaves / the drain batch) — the
            # guard adds no host<->device synchronization of its own
            gh_ok = jnp.isfinite(g).all() & jnp.isfinite(h).all()
            if self._nf_policy == "clamp":
                g = jnp.where(jnp.isfinite(g), g, 0.0)
                h = jnp.where(jnp.isfinite(h), h, 1.0)
            if not pipeline:
                jax.block_until_ready((g, h))
        with self.timers.phase("bagging"):
            g, h, cnt = self._sample(self.iter_, g, h)
            if not pipeline:
                jax.block_until_ready((g, h, cnt))

        lr = self._shrinkage_rate()
        any_split = False
        for k in range(self.num_class):
            # re-sampled PER TREE like the reference's BeforeTrain
            # (serial_tree_learner.cpp:234-260), not once per iteration
            feat_mask = np.asarray(self._feature_sample())
            if self._feat_pad:
                feat_mask = np.concatenate(
                    [feat_mask, np.zeros(self._feat_pad, dtype=bool)])
            if not self._multiproc:   # multiproc: host arrays auto-replicate
                feat_mask = jnp.asarray(feat_mask)
            with self.timers.phase("tree"):
                if self._subset_state is not None:
                    # compact bagged matrix: tree cost is O(bagged rows)
                    sbins, sidx, sw, scnt, shist = self._subset_state
                    hist_arg = (shist,) if self._pack_plan is not None else ()
                    arrays, row_leaf = self.grow(sbins, *hist_arg,
                                                 g[k][sidx] * sw,
                                                 h[k][sidx] * sw, scnt,
                                                 self.meta, feat_mask)
                else:
                    hist_arg = ((self._hist_bins,)
                                if self._pack_plan is not None else ())
                    arrays, row_leaf = self.grow(
                        self.bins, *hist_arg,
                        self._dist_row_vec(g[k] * self._bag_weight),
                        self._dist_row_vec(h[k] * self._bag_weight),
                        self._dist_row_vec(cnt), self.meta, feat_mask)
                    row_leaf = self._local_rows(row_leaf)
                nf_ok = gh_ok & jnp.isfinite(arrays.leaf_value).all()
                if pipeline:
                    # start the host copy NOW; the batched device_get a few
                    # iterations later finds the bytes already landed
                    jax.tree.map(
                        lambda a: getattr(a, "copy_to_host_async",
                                          lambda: None)(), arrays)
                else:
                    if self._multiproc:
                        # tree arrays are replicated — pull to host once so
                        # the local scoring/predict paths see process-local
                        # data
                        arrays = jax.tree.map(np.asarray, arrays)
                        num_leaves = int(arrays.num_leaves)
                        nf_ok_h = bool(np.asarray(nf_ok))
                        gh_ok_h = bool(np.asarray(gh_ok))
                    else:
                        # ONE fetch for the split count AND the guard flags
                        # (the sync the loop was already paying)
                        num_leaves, nf_ok_h, gh_ok_h = jax.device_get(
                            (arrays.num_leaves, nf_ok, gh_ok))
                        num_leaves = int(num_leaves)
                    if not bool(nf_ok_h) \
                            and self._handle_nonfinite(k, bool(gh_ok_h)):
                        return False    # iteration rolled back; retry next
                    self._last_iter_leaves += max(0, num_leaves - 1)
                    tree = Tree.from_arrays(
                        arrays, self.train_set.used_features,
                        self.train_set.bin_mappers, self._num_bin_host)
                    tree.shrink(lr)
                    self._models.append(tree)
                    # split audit over the arrays this sync path already
                    # fetched — zero added device traffic (pinned)
                    obs_model_quality.get_tracker().observe_tree(
                        int(self.iter_), len(self._models) - 1, tree)
            # pipelined: the split/no-split outcome is unknown on host, but
            # a no-split tree's leaf_value is all zeros so the score update
            # is a provable no-op — dispatch it unconditionally
            if pipeline or num_leaves > 1:
                any_split = True
                with self.timers.phase("score"):
                    if self._subset_state is not None:
                        # out-of-bag rows need scores too (UpdateScoreOutOfBag,
                        # gbdt.cpp:452-463): route ALL rows through the fresh
                        # device-side tree — no host round-trip
                        row_leaf = predict_binned_leaf(
                            self.bins, arrays.split_feature,
                            arrays.threshold_bin, arrays.default_left,
                            arrays.left_child, arrays.right_child,
                            self.feat_info, arrays.is_cat, arrays.cat_bins)
                    self.scores = self.scores.at[k].set(self._update_score(
                        self.scores[k], arrays.leaf_value, row_leaf,
                        jnp.asarray(lr, jnp.float32)))
                    # valid sets are scored from the DEVICE-side TreeArrays —
                    # no host tree conversion or per-tree jit re-entry in the
                    # hot loop (weak-spot fix: tree_scores_binned stays for
                    # replay/rollback/DART paths only)
                    for vs in self.valid_sets:
                        vleaf = predict_binned_leaf(
                            vs.bins, arrays.split_feature,
                            arrays.threshold_bin, arrays.default_left,
                            arrays.left_child, arrays.right_child,
                            self.feat_info, arrays.is_cat, arrays.cat_bins)
                        vs.scores = vs.scores.at[k].set(self._update_score(
                            vs.scores[k], arrays.leaf_value, vleaf,
                            jnp.asarray(lr, jnp.float32)))
                    if not pipeline:
                        jax.block_until_ready(self.scores)
            if pipeline:
                self._pending.append(
                    {"iter": self.iter_, "k": k, "arrays": arrays, "lr": lr,
                     "nf_ok": nf_ok, "gh_ok": gh_ok})
        self._after_iter()
        self.iter_ += 1
        if pipeline:
            with self.timers.phase("tree"):
                self._drain_pending(keep_iters=self._pipeline_depth)
            if self._stopped_no_split:
                # one-shot, like the sync path: a later call retries (a
                # reset_parameter / rollback may have re-enabled splitting)
                self._stopped_no_split = False
                return True
            return False
        if not any_split:
            log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            # remove the useless trees of this iteration
            for _ in range(self.num_class):
                self._models.pop()
            self.iter_ -= 1
            return True
        return False

    def _sample(self, it, g, h):
        """Row sampling hook: bagging for GBDT, overridden by GOSS/RF."""
        self._bagging(it, g, h)
        return g, h, self._bag_cnt

    # ---- local-rows <-> global-mesh-rows adapters (multi-process) ----------

    def _dist_row_vec(self, x) -> jnp.ndarray:
        """Local per-row vector [n_local] -> the grower's row input: padded
        in-process, or assembled into a global row-sharded jax.Array when
        each process holds its own partition (device-to-device: the local
        slices are placed on their local devices, never via host)."""
        if not self._multiproc:
            x = jnp.pad(x, (0, self._row_pad)) if self._row_pad else x
            if self._gspmd_mesh is not None:
                # commit to the mesh's batch sharding so the grower's
                # input shardings stay stable across iterations (no
                # reshard-driven recompiles)
                return jax.device_put(x, self._gspmd_row_sharding)
            return x
        xl = jnp.pad(jnp.asarray(x, jnp.float32), (0, self._row_pad)) \
            if self._row_pad else jnp.asarray(x, jnp.float32)
        imap = self._row_sharding.addressable_devices_indices_map(
            (self._global_rows,))
        # works for both shardings: row-sharded slices are rebased to this
        # process's block; replicated slices are the full range on every
        # device (start 0) — either way, device-to-device placement only
        start0 = min(s[0].start or 0 for s in imap.values())
        shards = [jax.device_put(
            xl[(s[0].start or 0) - start0:
               (s[0].stop if s[0].stop is not None else self._global_rows)
               - start0], d)
                  for d, s in imap.items()]
        return jax.make_array_from_single_device_arrays(
            (self._global_rows,), self._row_sharding, shards)

    def _local_rows(self, row_leaf) -> jnp.ndarray:
        """The grower's row-sharded output -> this process's local rows."""
        if not self._multiproc:
            if self._gspmd_mesh is not None:
                # fully addressable single-process global array: read it
                # out once per tree (the multiproc path's precedent) so
                # the score update consumes an unsharded map
                return jnp.asarray(np.asarray(row_leaf)[:self.num_data])
            return row_leaf[:self.num_data] if self._row_pad else row_leaf
        if self._multiproc_replicated:   # fully addressable: read directly
            return jnp.asarray(np.asarray(row_leaf)[:self.num_data])
        parts = sorted(row_leaf.addressable_shards,
                       key=lambda s: s.index[0].start or 0)
        # a (batch, feature) mesh replicates the row map along feature:
        # keep one shard per row window, not one per device
        seen = set()
        uniq = []
        for p in parts:
            st = p.index[0].start or 0
            if st not in seen:
                seen.add(st)
                uniq.append(p)
        local = np.concatenate([np.asarray(p.data) for p in uniq])
        return jnp.asarray(local[:self.num_data])

    def _shrinkage_rate(self) -> float:
        return self.config.learning_rate

    def _after_iter(self) -> None:
        pass

    def _train_tree_score(self, tree: Tree) -> jnp.ndarray:
        """Per-row contribution of a tree on this process's train bins."""
        if self._multiproc or self._stream_store is not None:
            # global sharded bins are unusable in a local jit; streamed
            # bins are a host pipeline.  Either way the (rare: rollback /
            # revert) whole-matrix traversal uploads a cached copy.
            if self._local_bins_cache is None:   # cached: DART/rollback reuse
                self._local_bins_cache = jnp.asarray(self.train_set.binned)
            return tree_scores_binned(self._local_bins_cache, tree,
                                      self.used_feature_index, self.feat_info,
                                      self.train_set.bin_mappers)
        s = tree_scores_binned(self.bins, tree, self.used_feature_index,
                               self.feat_info, self.train_set.bin_mappers)
        return s[:self.num_data] if self._row_pad else s

    def _pop_tree_and_revert(self, k: int) -> None:
        """Pop the last stored tree (class ``k``) and subtract its score
        contributions from train and valid scores — the unit step of
        ``rollback_one_iter``, also reused by the non-finite guard's
        partial same-iteration unwind."""
        tree = self.models.pop()
        if tree.num_leaves > 1:
            tree.shrink(-1.0)
            self.scores = self.scores.at[k].add(self._train_tree_score(tree))
            for vs in self.valid_sets:
                vs.scores = vs.scores.at[k].add(tree_scores_binned(
                    vs.bins, tree, self.used_feature_index, self.feat_info,
                    self.train_set.bin_mappers))

    def _stash_usable(self, expect_iter: int) -> bool:
        stash = getattr(self, "_score_stash", None)
        return (self.rollback_safe and stash is not None
                and stash[0] == expect_iter
                and len(stash[2]) == len(self.valid_sets))

    def _restore_score_stash(self) -> None:
        _, self.scores, vscores = self._score_stash
        for vs, s in zip(self.valid_sets, vscores):
            vs.scores = s
        self._score_stash = None

    def rollback_one_iter(self) -> None:
        """gbdt.cpp:583-600.

        Rolling back the most recent iteration restores train/valid scores
        from the iteration-start stash — bit-exact.  Older rollbacks (the
        stash only covers one step) fall back to the reference's
        subtract-the-contribution arithmetic, exact up to f32 rounding."""
        if self.iter_ <= 0:
            return
        self._drop_serving_caches()  # model length alone can't detect this
        if self._stash_usable(self.iter_ - 1):
            for _ in range(self.num_class):
                self.models.pop()
            self._restore_score_stash()
        else:
            self._score_stash = None
            for k in reversed(range(self.num_class)):
                self._pop_tree_and_revert(k)
        self.iter_ -= 1

    # ----------------------------------------------------- non-finite guard

    def _nf_event(self, it: int, stage: str, detected: str) -> None:
        """One structured obs event per tripped iteration (the multiclass
        loop and the per-tree drain records must not multiply it)."""
        if self._nf_event_iter == it:
            return
        self._nf_event_iter = it
        obs_counters.inc("nonfinite_trips", policy=self._nf_policy)
        obs_counters.event("nonfinite", stage=stage, iteration=it,
                           policy=self._nf_policy, detected=detected)
        log.warning("Non-finite %s detected at iteration %d "
                    "(nonfinite_policy=%s)", stage, it, self._nf_policy)

    def _handle_nonfinite(self, k: int, gh_ok: bool) -> bool:
        """Synchronous-path guard trip for class ``k`` of this iteration
        (BEFORE the tree is stored or any score update ran).  Returns True
        when the iteration was rolled back and must be retried."""
        it = int(self.iter_)
        stage = "leaf_value" if gh_ok else "grad/hess"
        self._nf_event(it, stage, detected="iteration")
        if self._nf_policy == "clamp":
            # grad/hess were sanitized on device; a non-finite LEAF with
            # finite inputs means the tree math itself diverged — no safe
            # clamp exists for that
            if gh_ok:
                raise NonFiniteError(
                    f"non-finite leaf values at iteration {it} (tree {k}) "
                    "with finite gradients; clamping cannot recover")
            return False
        if self._nf_policy == "rollback" and self.rollback_safe:
            if self._nf_rolled_iter == it:
                raise NonFiniteError(
                    f"non-finite {stage} persisted at iteration {it} after "
                    "rollback — the source is not transient; fix the "
                    "objective/data or use nonfinite_policy=clamp")
            self._nf_rolled_iter = it
            self._drop_serving_caches()
            # unwind this iteration's already-stored earlier classes:
            # restore the iteration-start score references (bit-exact) and
            # drop their trees; arithmetic revert is the fallback
            if self._stash_usable(it):
                for _ in range(k):
                    self.models.pop()
                self._restore_score_stash()
            else:
                for kk in reversed(range(k)):
                    self._pop_tree_and_revert(kk)
            log.warning("Rolled back iteration %d (%d earlier class "
                        "tree(s) unwound); retrying", it, k)
            return True
        hint = ("rollback is unavailable for this boosting type; use "
                "nonfinite_policy=clamp"
                if self._nf_policy == "rollback" else
                "set nonfinite_policy=rollback or clamp to recover")
        raise NonFiniteError(
            f"non-finite {stage} detected at iteration {it} (tree {k}); "
            f"{hint}, or fix the objective/data producing it")

    def _nonfinite_at_drain(self, it: int, gh_ok: bool) -> None:
        """Pipelined-path guard trip, detected at the (late) drain of
        iteration ``it``'s trees.  Under clamp the device values were
        already sanitized — this is visibility only; otherwise raise."""
        stage = "leaf_value" if gh_ok else "grad/hess"
        self._nf_event(it, stage, detected="drain")
        if self._nf_policy != "clamp":
            raise NonFiniteError(
                f"non-finite {stage} detected at iteration {it} (pipelined "
                "tree drain); set nonfinite_policy=rollback for prompt "
                "per-iteration recovery or clamp to sanitize")

    # ------------------------------------------------------------ checkpoint

    def data_fingerprint(self) -> int:
        """Identity of THIS process's dataset partition (shape + dtype + a
        strided sample of the binned matrix).  Rides every checkpoint — and
        the multi-process manifest — so a resume over different data (a
        re-partitioned shard, changed binning) is a structured error
        instead of silent divergence."""
        from . import checkpoint as checkpoint_mod
        ts = self.train_set
        return checkpoint_mod.data_fingerprint(
            None if ts is None else ts.binned,
            0 if ts is None else ts.num_data)

    def checkpoint_state(self) -> dict:
        """Bit-exact resumable training state (lightgbm_tpu.checkpoint):
        everything ``train_one_iter`` reads that is not derivable from the
        config + dataset — device score matrices, RNG streams, the live
        bagging subset/mask, and iteration bookkeeping."""
        self._drain_pending()
        st = {
            "data_fingerprint": self.data_fingerprint(),
            "kind": self.sub_model_name,
            "models": list(self._models),
            "iter_": self.iter_,
            "num_init_iteration": self.num_init_iteration,
            "boost_from_average_": self.boost_from_average_,
            "best_iteration": self.best_iteration,
            "scores": np.asarray(self.scores),
            "valid_scores": [np.asarray(vs.scores) for vs in self.valid_sets],
            "bag_rng": self._bag_rng.bit_generator.state,
            "feat_rng": self._feat_rng.bit_generator.state,
            "bagging_on": getattr(self, "_bagging_on", False),
            "bag_weight": np.asarray(self._bag_weight),
            "bag_cnt": np.asarray(self._bag_cnt),
            "subset": (None if self._subset_state is None else
                       {"idx": np.asarray(self._subset_state[1]),
                        "w": np.asarray(self._subset_state[2])}),
            "learning_rate": self.config.learning_rate,
        }
        return st

    def load_checkpoint_state(self, st: dict) -> None:
        """Inverse of :meth:`checkpoint_state`; requires a booster built
        on the same dataset/params (the checkpoint carries training state,
        not the binned data — the fingerprint check enforces exactly
        that)."""
        fp = st.get("data_fingerprint")
        if fp is not None and int(fp) != self.data_fingerprint():
            from .checkpoint import CheckpointError
            raise CheckpointError(
                "checkpoint dataset-partition fingerprint does not match "
                "the training data this booster holds — resuming would "
                "silently diverge (did the row shard or binning change?)")
        self._pending = []
        self._models = list(st["models"])
        self.iter_ = int(st["iter_"])
        self.num_init_iteration = int(st["num_init_iteration"])
        self.boost_from_average_ = bool(st["boost_from_average_"])
        self.best_iteration = st["best_iteration"]
        self.scores = jnp.asarray(st["scores"])
        for vs, s in zip(self.valid_sets, st["valid_scores"]):
            vs.scores = jnp.asarray(s)
        self._bag_rng = make_rng(0)
        self._bag_rng.bit_generator.state = st["bag_rng"]
        self._feat_rng = make_rng(0)
        self._feat_rng.bit_generator.state = st["feat_rng"]
        self._bagging_on = bool(st["bagging_on"])
        self._bag_weight = jnp.asarray(st["bag_weight"])
        self._bag_cnt = jnp.asarray(st["bag_cnt"])
        if st["subset"] is not None and self._stream_store is not None:
            log.fatal("checkpoint carries a bagged-subset gather state but "
                      "this booster streams its binned data "
                      "(data_stream=chunked keeps no device row matrix to "
                      "gather from); resume with data_stream=resident")
        if st["subset"] is not None:
            idx_d = jnp.asarray(st["subset"]["idx"])
            w_p = np.asarray(st["subset"]["w"])
            self._subset_state = (
                jnp.take(self.bins, idx_d, axis=0), idx_d, jnp.asarray(w_p),
                jnp.asarray((w_p > 0).astype(np.float32)),
                (jnp.take(self._hist_bins, idx_d, axis=0)
                 if self._hist_bins is not None else None))
        else:
            self._subset_state = None
        self.config.learning_rate = float(st["learning_rate"])
        self._stopped_no_split = False
        self._iter_had_split = False
        self._score_stash = None
        self._drop_serving_caches()

    # ------------------------------------------------------------------- eval

    def eval_train(self) -> List[Tuple[str, str, float, bool]]:
        return self._eval("training", self.train_metrics,
                          np.asarray(self.scores, np.float64))

    def eval_valid(self) -> List[Tuple[str, str, float, bool]]:
        out = []
        for vs in self.valid_sets:
            out.extend(self._eval(vs.name, vs.metrics,
                                  np.asarray(vs.scores, np.float64)))
        return out

    def _eval(self, name, metrics, scores) -> List[Tuple[str, str, float, bool]]:
        with self.timers.phase("metric"):
            return self._eval_inner(name, metrics, scores)

    def _eval_inner(self, name, metrics, scores) -> List[Tuple[str, str, float, bool]]:
        results = []
        mq = obs_model_quality.get_tracker()
        for m in metrics:
            vals = m.eval(scores, self.objective)
            for mn, v in zip(m.names(), vals):
                results.append((name, mn, float(v), m.is_higher_better))
                # stash for the NEXT progress record (the engine loop
                # evaluates after update, so the flight stream carries
                # each iteration's evals one record late)
                mq.note_eval(name, mn, float(v))
        return results

    # ---------------------------------------------------------------- predict

    def _drop_serving_caches(self) -> None:
        """Invalidate every derived serving artifact.  Appending trees is
        detected by length (the cheap common case during training); any
        other mutation of the stored trees must call this."""
        self._native_pred = None
        self._pred_engine = None
        self._pred_engine_ntrees = -1

    def predict_engine(self, prewarm: bool = False, buckets=None,
                       build: bool = True, backend: str = "auto",
                       traversal: str = None):
        """The cached SoA serving engine for the current model
        (lightgbm_tpu.inference.PredictEngine; docs/SERVING.md).  Built at
        most once per model state: the flatten + threshold tables are
        reused across every subsequent predict/serving call, and appended
        trees (continued training) rebuild automatically.  ``build=False``
        only returns an engine that is already fresh."""
        fresh = (self._pred_engine is not None
                 and self._pred_engine_ntrees == len(self.models))
        if not fresh:
            if not build:
                return None
            from .inference import PredictEngine
            kw = {} if buckets is None else {"buckets": buckets}
            if traversal is None:
                traversal = getattr(self.config, "serving_traversal", "auto")
            self._pred_engine = PredictEngine(
                self.models, self.num_class, prewarm=prewarm,
                backend=backend, model_str=self.save_model_to_string(),
                traversal=traversal, **kw)
            self._pred_engine_ntrees = len(self.models)
        elif prewarm and not self._pred_engine._warmed:
            self._pred_engine.prewarm()
        return self._pred_engine

    def predictor(self, num_iteration: int = -1, raw_score: bool = False,
                  pred_early_stop: bool = False,
                  pred_early_stop_freq: Optional[int] = None,
                  pred_early_stop_margin: Optional[float] = None) -> Predictor:
        return Predictor(self.models, self.num_class, self.objective,
                         engine=self.predict_engine(build=False),
                         average_output=self.average_output,
                         num_iteration=(num_iteration + (1 if (
                             self.boost_from_average_ and num_iteration > 0)
                             else 0)) if num_iteration > 0 else -1,
                         early_stop=pred_early_stop,
                         early_stop_freq=(
                             pred_early_stop_freq if pred_early_stop_freq
                             is not None else self.config.pred_early_stop_freq),
                         early_stop_margin=(
                             pred_early_stop_margin if pred_early_stop_margin
                             is not None
                             else self.config.pred_early_stop_margin))

    def predict(self, X, num_iteration: int = -1, raw_score: bool = False,
                pred_leaf: bool = False, pred_contrib: bool = False,
                pred_early_stop: bool = False,
                pred_early_stop_freq: Optional[int] = None,
                pred_early_stop_margin: Optional[float] = None):
        if pred_contrib:
            # TreeSHAP path attribution — routed around the native
            # short-circuit (the C++ predictor is margin-only here)
            p = self.predictor(num_iteration)
            return p.predict_contrib(X, num_features=self.max_feature_idx + 1)
        if not pred_leaf and not pred_early_stop:
            out = self._native_predict(X, num_iteration, raw_score)
            if out is not None:
                return out
        p = self.predictor(num_iteration, raw_score, pred_early_stop,
                           pred_early_stop_freq, pred_early_stop_margin)
        if pred_leaf:
            return p.predict_leaf_index(X)
        return p.predict(X, raw_score=raw_score)

    def _native_predict(self, X, num_iteration: int, raw_score: bool):
        """OpenMP serving path (predictor.hpp analogue) for batch predict —
        the numpy per-tree walk stays as the fallback/oracle.  Returns None
        when the native library is unavailable or the objective's output
        transform is not implemented natively."""
        from . import native
        obj = self.objective.name if self.objective is not None else ""
        native_transforms = ("regression", "regression_l1", "huber", "fair",
                             "poisson", "binary", "multiclass",
                             "multiclassova", "xentropy", "xentlambda",
                             "lambdarank", "")
        if not native.available() or (not raw_score
                                      and obj not in native_transforms):
            return None
        try:
            if (getattr(self, "_native_pred", None) is None
                    or self._native_pred_ntrees != len(self.models)):
                self._native_pred = native.NativePredictor(
                    model_str=self.save_model_to_string())
                self._native_pred_ntrees = len(self.models)
            ni = num_iteration
            if ni is not None and ni > 0 and self.boost_from_average_:
                ni += 1     # the init tree counts as one stored iteration
            out = self._native_pred.predict(
                np.atleast_2d(np.asarray(X, np.float64)),
                num_iteration=ni if ni and ni > 0 else -1,
                raw_score=raw_score)
            return out
        except Exception as e:     # fall back to the python walk
            log.debug("native predict unavailable (%s); using python path", e)
            return None

    def current_iteration(self) -> int:
        return self.iter_ + self.num_init_iteration

    def merge_from(self, other: "GBDT") -> None:
        """GBDT::MergeFrom (gbdt.h:47-66): the other model's trees are
        PREPENDED (they become init iterations) and this model's follow.
        num_init_iteration grows by the merged count so current_iteration
        keeps matching total trees / num_class — the observable the
        reference gets by deriving iteration counts from models_.size().
        Like the reference, training scores are not recomputed — merge is
        a model-combination operation for predict/save."""
        merged = [copy.deepcopy(t) for t in other.models]
        self.num_init_iteration += len(merged) // max(self.num_class, 1)
        self.models = merged + self.models
        self._drop_serving_caches()

    # ------------------------------------------------------------- model file

    def feature_importance(self, importance_type: str = "split",
                           num_iteration: int = -1) -> np.ndarray:
        """Split/gain importance (gbdt.cpp FeatureImportance), vectorized:
        one concatenation over the kept trees' split arrays + one masked
        bincount instead of the historical trees x splits Python loop
        (reference-parity pinned in tests/test_metrics.py)."""
        n_feat = self.max_feature_idx + 1
        trees = self.models
        if num_iteration > 0:
            cut = (num_iteration + (1 if self.boost_from_average_ else 0)) \
                * self.num_class
            trees = trees[:cut]
        split_trees = [t for t in trees if t.num_leaves > 1]
        if not split_trees:
            return np.zeros(n_feat, dtype=np.float64)
        feats = np.concatenate([t.split_feature[:t.num_leaves - 1]
                                for t in split_trees])
        gains = np.concatenate([t.split_gain[:t.num_leaves - 1]
                                for t in split_trees])
        mask = gains > 0
        weights = gains[mask] if importance_type == "gain" else None
        return np.bincount(feats[mask], weights=weights,
                           minlength=n_feat).astype(np.float64)

    def save_model_to_string(self, num_iteration: int = -1) -> str:
        """gbdt.cpp:948-997 SaveModelToString — reference text format."""
        buf = io.StringIO()
        buf.write(self.sub_model_name + "\n")
        buf.write(f"num_class={self.num_class}\n")
        buf.write(f"num_tree_per_iteration={self.num_class}\n")
        buf.write(f"label_index={self.label_idx}\n")
        buf.write(f"max_feature_idx={self.max_feature_idx}\n")
        if self.objective is not None:
            buf.write(f"objective={self.objective.to_string()}\n")
        if self.boost_from_average_:
            buf.write("boost_from_average\n")
        if self.average_output:
            buf.write("average_output\n")
        buf.write("feature_names=" + " ".join(self.feature_names) + "\n")
        infos = [m.feature_info_str() for m in self.train_set.bin_mappers] \
            if self.train_set else []
        buf.write("feature_infos=" + " ".join(infos) + "\n")
        buf.write("\n")
        num_used = len(self.models)
        if num_iteration > 0:
            ni = num_iteration + (1 if self.boost_from_average_ else 0)
            num_used = min(ni * self.num_class, num_used)
        for i in range(num_used):
            buf.write(self.models[i].to_string(i))
            buf.write("\n")
        buf.write("\nfeature importances:\n")
        # importances over the KEPT trees only (gbdt.cpp:989
        # FeatureImportance(num_used_model)); saved_feature_importance_type
        # = 1 writes total gain at full precision — the reference's int
        # truncation only applies to split counts, which ARE integers
        gain_mode = self.config.saved_feature_importance_type == 1
        imp = self.feature_importance(
            importance_type="gain" if gain_mode else "split",
            num_iteration=num_iteration)
        order = np.argsort(-imp, kind="mergesort")
        for f in order:
            if imp[f] > 0:
                val = repr(float(imp[f])) if gain_mode else int(imp[f])
                buf.write(f"{self.feature_names[f]}={val}\n")
        dist = self._training_distribution()
        if dist:
            buf.write("\n")
            buf.write(obs_model_quality.format_distribution(dist))
        return buf.getvalue()

    def _training_distribution(self):
        """Training-set bin distribution for the serving drift monitor —
        computed once (host bincounts over the already-binned matrix)
        when the model-quality plane is armed, then cached; loaded
        models carry the parsed section instead."""
        if self.feature_distribution is not None:
            return self.feature_distribution
        if not obs_model_quality.get_tracker().enabled:
            return None
        try:
            self.feature_distribution = \
                obs_model_quality.training_bin_distribution(self.train_set)
        except Exception as e:      # never fail a model save over telemetry
            log.debug("training distribution unavailable (%s)", e)
            self.feature_distribution = {}
        return self.feature_distribution

    def save_model(self, filename: str, num_iteration: int = -1) -> None:
        with open(filename, "w") as f:
            f.write(self.save_model_to_string(num_iteration))

    @staticmethod
    def load_from_string(model_str: str, config: Optional[Config] = None) -> "GBDT":
        """gbdt.cpp:1010+ LoadModelFromString."""
        config = config or Config()
        lines = model_str.splitlines()
        booster = GBDT(config)
        header: Dict[str, str] = {}
        i = 0
        if lines and lines[0].strip() in ("tree", "dart", "goss", "rf"):
            booster.sub_model_name = lines[0].strip()
            i = 1
        while i < len(lines):
            line = lines[i].strip()
            if line.startswith("Tree="):
                break
            if line == "boost_from_average":
                booster.boost_from_average_ = True
            elif line == "average_output":
                booster.average_output = True
            elif "=" in line:
                k, v = line.split("=", 1)
                header[k] = v
            i += 1
        booster.num_class = int(header.get("num_tree_per_iteration",
                                           header.get("num_class", "1")))
        booster.label_idx = int(header.get("label_index", "0"))
        booster.max_feature_idx = int(header.get("max_feature_idx", "0"))
        booster.feature_names = header.get("feature_names", "").split()
        if "objective" in header:
            cfg = config.copy()
            booster.objective = parse_objective_string(header["objective"], cfg)
        # parse tree blocks
        blocks: List[str] = []
        cur: List[str] = []
        for line in lines[i:]:
            s = line.strip()
            if s.startswith("Tree="):
                if cur:
                    blocks.append("\n".join(cur))
                cur = []
            elif s.startswith("feature importances"):
                break
            elif s:
                cur.append(s)
        if cur:
            blocks.append("\n".join(cur))
        for b in blocks:
            booster.models.append(Tree.from_string(b))
        booster.num_init_iteration = len(booster.models) // max(booster.num_class, 1)
        booster.iter_ = 0
        # optional trailing sections (the tree-block loop above stops at
        # "feature importances"): the training bin distribution feeds the
        # serving drift monitor
        dist = obs_model_quality.parse_distribution(lines)
        if dist:
            booster.feature_distribution = dist
        return booster


class DART(GBDT):
    """dart.hpp — Dropouts meet MART.

    Model files still start with "tree" like every reference boosting type
    (no SubModelName override exists in the reference; a DART model file IS
    just its trees, already normalized)."""

    pipeline_supported = False   # reads/shrinks prior trees every iteration
    rollback_safe = False        # drop/normalize bookkeeping cannot be
                                 # partially unwound mid-iteration

    def __init__(self, config, train_set=None, objective=None):
        super().__init__(config, train_set, objective)
        self._drop_rng = make_rng(config.drop_seed)
        self.tree_weight: List[float] = []
        self.sum_weight = 0.0
        self._drop_index: List[int] = []
        self._shrinkage = config.learning_rate

    def _trees_scores(self, trees, bins) -> jnp.ndarray:
        """Batched [T, N] contributions (one vmapped call for all dropped
        trees — the drop/normalize walk is per-iteration hot path)."""
        if bins is self.bins and self._multiproc:
            if self._local_bins_cache is None:
                self._local_bins_cache = jnp.asarray(self.train_set.binned)
            bins = self._local_bins_cache
        out = trees_scores_binned(bins, trees, self.used_feature_index,
                                  self.feat_info, self.train_set.bin_mappers)
        if bins is self.bins and self._row_pad and not self._multiproc:
            out = out[:, :self.num_data]
        return out

    def _select_drop(self) -> None:
        cfg = self.config
        self._drop_index = []
        if self._drop_rng.random() >= cfg.skip_drop:
            drop_rate = cfg.drop_rate
            n_iter = self.iter_
            if cfg.uniform_drop:
                if cfg.max_drop > 0 and n_iter > 0:
                    drop_rate = min(drop_rate, cfg.max_drop / n_iter)
                self._drop_index = [i for i in range(n_iter)
                                    if self._drop_rng.random() < drop_rate]
            else:
                if self.sum_weight > 0:
                    inv_avg = len(self.tree_weight) / self.sum_weight
                    if cfg.max_drop > 0:
                        drop_rate = min(drop_rate,
                                        cfg.max_drop * inv_avg / self.sum_weight)
                    self._drop_index = [
                        i for i in range(n_iter)
                        if self._drop_rng.random()
                        < drop_rate * self.tree_weight[i] * inv_avg]
        k = len(self._drop_index)
        if not cfg.xgboost_dart_mode:
            self._shrinkage = cfg.learning_rate / (1.0 + k)
        else:
            self._shrinkage = (cfg.learning_rate if k == 0
                               else cfg.learning_rate / (cfg.learning_rate + k))

    def _model_index(self, it: int, k: int) -> int:
        off = 1 if self.boost_from_average_ else 0
        return off + it * self.num_class + k

    def train_one_iter(self, grad=None, hess=None) -> bool:
        # drop trees BEFORE computing gradients (dart.hpp DroppingTrees);
        # dropped contributions (at original weight w) are cached so the
        # Shrinkage(-1)/Shrinkage(1/(k+1))/Shrinkage(-k) dance of the reference
        # reduces to: train -= w ; later train += F*w, valid -= (1-F)*w, with
        # F = k/(k+1) (or k/(lr+k) in xgboost mode).
        if (self.iter_ == 0 and self.objective is not None
                and self.allow_boost_from_average
                and self.objective.boost_from_average and not self._has_init_score
                and self.num_class == 1 and self.config.boost_from_average
                and not self.boost_from_average_):
            self._boost_from_average()
        self._select_drop()
        self._drop_train_contrib = {}
        pairs = [(i, k) for i in self._drop_index
                 for k in range(self.num_class)]
        if pairs:
            contribs = self._trees_scores(
                [self.models[self._model_index(i, k)] for i, k in pairs],
                self.bins)
            for t, (i, k) in enumerate(pairs):
                self._drop_train_contrib[(i, k)] = contribs[t]
                self.scores = self.scores.at[k].add(-contribs[t])
        finished = super().train_one_iter(grad, hess)
        if not finished:
            self.tree_weight.append(self._shrinkage)
            self.sum_weight += self._shrinkage
            self._normalize()
        else:
            for (i, k), contrib in self._drop_train_contrib.items():
                self.scores = self.scores.at[k].add(contrib)
        return finished

    def _shrinkage_rate(self) -> float:
        return self._shrinkage

    def checkpoint_state(self) -> dict:
        st = super().checkpoint_state()
        st["dart"] = {"drop_rng": self._drop_rng.bit_generator.state,
                      "tree_weight": list(self.tree_weight),
                      "sum_weight": self.sum_weight,
                      "shrinkage": self._shrinkage}
        return st

    def load_checkpoint_state(self, st: dict) -> None:
        super().load_checkpoint_state(st)
        d = st.get("dart") or {}
        if "drop_rng" in d:
            self._drop_rng = make_rng(0)
            self._drop_rng.bit_generator.state = d["drop_rng"]
        self.tree_weight = list(d.get("tree_weight", []))
        self.sum_weight = float(d.get("sum_weight", 0.0))
        self._shrinkage = float(d.get("shrinkage", self.config.learning_rate))

    def _normalize(self) -> None:
        """dart.hpp:141-180 (see train_one_iter comment for the algebra)."""
        cfg = self.config
        k = float(len(self._drop_index))
        if k == 0:
            return
        factor = (k / (k + 1.0) if not cfg.xgboost_dart_mode
                  else k / (k + cfg.learning_rate))
        pairs = [(i, c) for i in self._drop_index
                 for c in range(self.num_class)]
        dropped = [self.models[self._model_index(i, c)] for i, c in pairs]
        self._drop_serving_caches()  # in-place shrink stales both caches
        # one batched traversal per valid set for ALL dropped trees
        valid_contribs = [self._trees_scores(dropped, vs.bins)
                          for vs in self.valid_sets]
        for t, (i, c) in enumerate(pairs):
            dropped[t].shrink(factor)
            self.scores = self.scores.at[c].add(
                self._drop_train_contrib[(i, c)] * factor)
            for vs, contrib in zip(self.valid_sets, valid_contribs):
                vs.scores = vs.scores.at[c].add(contrib[t] * (factor - 1.0))
        for i in self._drop_index:
            if not cfg.uniform_drop and i < len(self.tree_weight):
                denom = (k + 1.0 if not cfg.xgboost_dart_mode
                         else k + cfg.learning_rate)
                self.sum_weight -= self.tree_weight[i] / denom
                self.tree_weight[i] *= factor


class GOSS(GBDT):
    """goss.hpp — Gradient-based One-Side Sampling.

    Stays pipeline-eligible: ``_sample`` pulls the gradient magnitudes to
    host each post-warmup iteration (the top-k threshold is a host
    decision, like the reference's), but that sync never forces TREE
    materialization — the per-tree batched-transfer saving applies in
    full."""

    def _sample(self, it, g, h):
        cfg = self.config
        n = self.num_data
        if it < int(1.0 / max(cfg.learning_rate, 1e-10)):
            ones = jnp.ones((n,), jnp.float32)
            self._bag_weight = ones
            self._subset_state = None
            return g, h, ones
        s = np.asarray(jnp.sum(jnp.abs(g * h), axis=0))
        top_k = max(1, int(n * cfg.top_rate))
        other_k = max(1, int(n * cfg.other_rate))
        thr = np.partition(s, n - top_k)[n - top_k]
        is_top = s >= thr
        n_top = int(is_top.sum())
        rest = n - n_top
        keep_prob = min(1.0, other_k / max(rest, 1))
        keep_other = (~is_top) & (self._bag_rng.random(n) < keep_prob)
        multiply = (n - top_k) / other_k
        if self._can_subset and cfg.top_rate + cfg.other_rate <= 0.5:
            # goss.hpp:120-130 subset regime: gather kept rows, grow compact
            idx = np.flatnonzero(is_top | keep_other)
            w = np.where(is_top[idx], 1.0, multiply).astype(np.float32)
            self._set_subset(idx.astype(np.int32), w)
            return g, h, self._bag_cnt
        self._subset_state = None
        w = np.where(is_top, 1.0, np.where(keep_other, multiply, 0.0)) \
            .astype(np.float32)
        cnt = (w > 0).astype(np.float32)
        self._bag_weight = jnp.asarray(w)
        return g, h, jnp.asarray(cnt)


class RF(GBDT):
    """rf.hpp — bagged random forest: no shrinkage, averaged output,
    gradients always computed from the zero score, no boost-from-average."""
    average_output = True
    allow_boost_from_average = False
    pipeline_supported = False   # feeds host-side gradients every iteration

    def __init__(self, config, train_set=None, objective=None):
        super().__init__(config, train_set, objective)
        if train_set is not None:
            zero = jnp.zeros_like(self.scores)
            self._g0, self._h0 = self._grad_fn(zero)

    def train_one_iter(self, grad=None, hess=None) -> bool:
        if grad is None or hess is None:
            grad, hess = self._g0, self._h0
            return super().train_one_iter(np.asarray(grad), np.asarray(hess))
        return super().train_one_iter(grad, hess)

    def _shrinkage_rate(self) -> float:
        return 1.0

    def _eval(self, name, metrics, scores):
        it = max(self.iter_, 1)
        return super()._eval(name, metrics, scores / it)


def create_boosting(config: Config, train_set: Optional[TrainingData] = None,
                    objective: Optional[Objective] = None) -> GBDT:
    """Factory (boosting.cpp:29-76)."""
    t = config.boosting_type
    if t in ("gbdt", "gbrt"):
        cls = GBDT
    elif t == "dart":
        cls = DART
    elif t == "goss":
        cls = GOSS
    elif t in ("rf", "random_forest"):
        cls = RF
    else:
        log.fatal("Unknown boosting type %s", t)
    return cls(config, train_set, objective)


def _on_tpu() -> bool:
    try:
        return on_tpu()
    except Exception:
        return False
