"""Process-wide counter/event registry.

The structured side of the telemetry subsystem: cheap named counters with
optional tags, bounded structured events, and gauges.  The load-bearing
users:

* **histogram-kernel dispatch identity** — every dispatch site records
  ``hist_dispatch`` tagged ``method=fused|einsum|segment``, so a
  ``BENCH_*.json`` can prove which kernel a rung *actually* traced
  instead of trusting its label (:func:`observed_kernel`, consumed by
  ``bench.py`` / ``scripts/decide_flips.py``);
* **layout-downgrade events** — the warn-once fallback paths (fused
  gate, ``gspmd_hist=fused`` mesh gating, gather_words/panel gating)
  also record a ``layout_downgrade`` event with the machine-readable
  reason;
* **collective accounting** — ``obs/collectives.py`` feeds
  ``collective_calls`` / ``collective_bytes`` tagged by op + site;
* **checkpoint lifecycle events** — the resume paths
  (:mod:`lightgbm_tpu.checkpoint`) record ``checkpoint_skipped``
  (iteration + reason for every torn/demoted snapshot the scan rejected),
  ``checkpoint_resume`` (iteration + ``kind=single|group``), and
  ``preempt_checkpoint`` (clean preemption exits) — so a resumed run's
  telemetry explains exactly which snapshot it continued from and why;
* **supervisor lifecycle events** — the self-healing supervisor
  (:mod:`lightgbm_tpu.supervisor`) records every liveness decision:
  ``rank_dead`` (exit code + last heartbeat), ``rank_hang`` (heartbeat
  age vs the effective hang timeout), ``group_restart`` (attempt, resume
  iteration, backoff), ``restart_budget_exhausted``, ``crash_report``
  (a rank left one behind), and ``stale_sweep`` (startup hygiene
  removals) — an unattended recovery is never an unexplained one.

Counts recorded from inside jit tracing are TRACE-time counts (once per
compiled call site), which is exactly the "per call site" identity the
honesty checks need — a recompile shows up as a fresh increment.
"""
from __future__ import annotations

import collections
import threading
from typing import Any, Dict, List, Optional


def _tag_key(tags: Dict[str, Any]) -> str:
    if not tags:
        return ""
    return ",".join(f"{k}={tags[k]}" for k in sorted(tags))


class CounterRegistry:
    """Thread-safe registry: counters[name][tag_key] -> number."""

    MAX_EVENTS = 512     # ring buffer: telemetry must never grow host
    #                      memory without bound — a long training with
    #                      telemetry on keeps the newest MAX_EVENTS events
    #                      and counts the overflow (``events_dropped``)
    #                      instead of leaking

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Dict[str, float]] = {}
        self._gauges: Dict[str, float] = {}
        self._events: collections.deque = collections.deque(
            maxlen=self.MAX_EVENTS)
        self._events_dropped = 0
        self._sinks: List[Any] = []

    # ------------------------------------------------------------- writers

    def inc(self, name: str, value: float = 1, **tags) -> None:
        key = _tag_key(tags)
        with self._lock:
            bucket = self._counters.setdefault(name, {})
            bucket[key] = bucket.get(key, 0) + value

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def event(self, name: str, **fields) -> None:
        """Record a structured event (layout downgrade, recompile, ...).
        Storage is a bounded ring: at capacity the OLDEST event is evicted
        and ``events_dropped`` counts the loss (surfaced in snapshots and
        the report) so truncation is visible, never silent."""
        from .trace import process_index   # lazy: avoid import cycles
        ev = {"event": name, "proc": process_index(), **fields}
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._events_dropped += 1
            self._events.append(ev)
            sinks = tuple(self._sinks)
        for sink in sinks:       # outside the lock: a sink may take its own
            try:
                sink(ev)
            except Exception:
                pass             # a telemetry sink must never break emitters

    def add_sink(self, fn) -> None:
        """Subscribe a callable to every future structured event (the
        flight recorder streams the ring to disk as it fills)."""
        with self._lock:
            if fn not in self._sinks:
                self._sinks.append(fn)

    def remove_sink(self, fn) -> None:
        with self._lock:
            if fn in self._sinks:
                self._sinks.remove(fn)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._events.clear()
            self._events_dropped = 0

    # ------------------------------------------------------------- readers

    def get(self, name: str) -> Dict[str, float]:
        with self._lock:
            return dict(self._counters.get(name, {}))

    def total(self, name: str) -> float:
        with self._lock:
            return sum(self._counters.get(name, {}).values())

    def events(self, name: Optional[str] = None) -> List[dict]:
        with self._lock:
            evs = list(self._events)
        return evs if name is None else [e for e in evs
                                         if e.get("event") == name]

    def events_tail(self, n: int) -> List[dict]:
        """The newest ``n`` events across all names — what a crash report
        flushes (checkpoint.write_crash_report): the last things this
        process observed before dying."""
        with self._lock:
            evs = list(self._events)
        return evs[-max(0, int(n)):]

    def events_dropped(self) -> int:
        with self._lock:
            return self._events_dropped

    def snapshot(self) -> Dict[str, Any]:
        from .trace import process_index
        with self._lock:
            return {"counters": {n: dict(b)
                                 for n, b in self._counters.items()},
                    "gauges": dict(self._gauges),
                    "events": list(self._events),
                    "events_dropped": self._events_dropped,
                    "process_index": process_index()}

    # --------------------------------------------- derived: kernel identity

    def observed_kernel(self) -> Optional[str]:
        """The histogram-kernel identity this process actually traced: the
        dominant ``method=`` tag of ``hist_dispatch`` (trace-time call-site
        counts).  None when no histogram was dispatched yet."""
        per_method: Dict[str, float] = {}
        for key, v in self.get("hist_dispatch").items():
            tags = dict(kv.split("=", 1) for kv in key.split(",") if "=" in kv)
            m = tags.get("method")
            if m:
                per_method[m] = per_method.get(m, 0) + v
        if not per_method:
            return None
        return max(per_method, key=per_method.get)


counters = CounterRegistry()
