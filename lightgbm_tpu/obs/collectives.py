"""Collective-traffic accounting helpers.

Three complementary mechanisms (collective SHAPES are backend-independent
— the mesh is the unit of sharding, not the wire — so byte counts
measured at trace/compile time hold for any same-shard-count slice):

* :func:`intercept` — monkeypatch ``lax.psum``/``pmax``/``pmin``/
  ``all_gather`` for a block and collect one record per traced collective
  with the caller site and the per-split/per-tree classification.  This is
  the machinery ``scripts/comm_audit.py`` originally grew privately
  (``_record``/``_nbytes``); it now lives here so the audit script and any
  ad-hoc analysis share one implementation.
* :func:`note_collective` — explicit accounting call the distributed
  strategies (``parallel/learner.py``) make next to each collective they
  issue; feeds the ``collective_calls`` / ``collective_bytes`` counters of
  :mod:`lightgbm_tpu.obs.counters` without any monkeypatching, so every
  distributed training run carries its collective budget in telemetry.
* :func:`hlo_census` — the GSPMD-era accounting
  (``parallel/gspmd.py``, docs/DISTRIBUTED.md): with ``NamedSharding``
  the COMPILER inserts the collectives, so call-site counters undercount
  by construction — the census reads them back out of the compiled
  executable (``utils/jaxpr_audit.hlo_collective_census``) and records
  them as ``hlo_collective_*`` counters + one ``hlo_collectives`` event,
  keeping bench telemetry honest when no call site ever ran.
"""
from __future__ import annotations

import contextlib
import os
import traceback
from typing import Any, Dict, List, Optional

INTERCEPTED_OPS = ("psum", "pmax", "pmin", "all_gather")


def tree_nbytes(tree: Any) -> int:
    """Total payload bytes of a pytree of arrays / tracers / shape structs."""
    import jax
    import numpy as np
    total = 0
    for x in jax.tree_util.tree_leaves(tree):
        if hasattr(x, "dtype"):
            size = getattr(x, "size", None)
            if size is None:
                size = int(np.prod(getattr(x, "shape", ())))
            total += int(size) * x.dtype.itemsize
    return total


def classify_site(stack=None):
    """(site, per_split) for the innermost lightgbm_tpu frame.

    ``per_split`` matches a stack frame literally named ``body`` inside
    grower.py — the grow loop is one ``lax.while_loop`` whose body is
    traced exactly once, so collectives issued from it are the PER-SPLIT
    set and everything else is per-tree setup (the same separation the
    reference draws for its per-split ReduceScatter).  comm_audit fails
    loudly if this classifier ever stops matching."""
    if stack is None:
        stack = traceback.extract_stack()
    obs_dir = os.sep + "obs" + os.sep
    site = next((f"{os.path.basename(f.filename)}:{f.lineno}"
                 for f in reversed(stack)
                 if "lightgbm_tpu" in f.filename
                 and obs_dir not in f.filename), "?")
    per_split = any(f.name == "body" and "grower.py" in f.filename
                    for f in stack)
    return site, per_split


def note_collective(op: str, value: Any, axis: Any, site: str) -> None:
    """Count one traced collective into the process counters (cheap: runs
    once per compiled call site, never in the device hot loop)."""
    from .counters import counters
    nb = tree_nbytes(value)
    counters.inc("collective_calls", op=op, site=site)
    counters.inc("collective_bytes", value=nb, op=op, site=site)


def hlo_census(compiled_or_text, label: str = "grow") -> Dict[str, Dict[str, int]]:
    """Compiled-HLO collective census, recorded into the counter registry.

    Returns ``{op: {"count", "bytes", "max_bytes"}}`` (see
    ``utils/jaxpr_audit.hlo_collective_census``) and records each op as
    ``hlo_collective_calls`` / ``hlo_collective_bytes`` counters tagged
    ``op=<op>,label=<label>`` plus one structured ``hlo_collectives``
    event, so reports and bench JSONs carry the compiler-inserted
    communication next to the call-site counters."""
    from ..utils.jaxpr_audit import hlo_collective_census
    from .counters import counters
    census = hlo_collective_census(compiled_or_text)
    for op, rec in census.items():
        counters.inc("hlo_collective_calls", value=rec["count"], op=op,
                     label=label)
        counters.inc("hlo_collective_bytes", value=rec["bytes"], op=op,
                     label=label)
    counters.event(
        "hlo_collectives", label=label,
        **{op.replace("-", "_"): f"{rec['count']}x/{rec['bytes']}B"
           for op, rec in census.items()})
    return census


def totals() -> Dict[str, int]:
    """Aggregate collective traffic this process has accounted so far:
    call-site counters (``note_collective``) plus the compiled-HLO census
    (``hlo_census``) in one ``{"calls", "bytes"}`` pair — what the flight
    recorder stamps into every progress record so a stream shows
    communication growth over time."""
    from .counters import counters
    return {"calls": int(counters.total("collective_calls")
                         + counters.total("hlo_collective_calls")),
            "bytes": int(counters.total("collective_bytes")
                         + counters.total("hlo_collective_bytes"))}


@contextlib.contextmanager
def intercept(records: Optional[List[Dict[str, Any]]] = None,
              count: bool = False):
    """Intercept jax collectives for the duration of the block.

    Yields the record list; each traced collective appends
    ``{"op", "bytes", "axis", "site", "per_split"}`` (byte-compatible with
    the fields ``scripts/comm_audit.py`` always emitted).  ``count=True``
    additionally feeds the interception into the counter registry."""
    from jax import lax
    out: List[Dict[str, Any]] = [] if records is None else records
    orig = {}

    def wrap(name):
        fn = getattr(lax, name)
        orig[name] = fn

        def inner(x, axis_name, **kw):
            site, per_split = classify_site()
            out.append({"op": name, "bytes": tree_nbytes(x),
                        "axis": str(axis_name), "site": site,
                        "per_split": per_split})
            if count:
                note_collective(name, x, axis_name, site)
            return fn(x, axis_name, **kw)
        return inner

    for name in INTERCEPTED_OPS:
        setattr(lax, name, wrap(name))
    try:
        yield out
    finally:
        for name, fn in orig.items():
            setattr(lax, name, fn)
