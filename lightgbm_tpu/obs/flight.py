"""Per-rank flight recorder: a bounded, rotated, rank-tagged JSONL event
stream a run appends to WHILE it runs.

The counter ring (:mod:`lightgbm_tpu.obs.counters`) keeps the newest
``MAX_EVENTS`` structured events in memory and only ever leaves the
process in a crash-report tail or a trace file written at exit — a healthy
multi-hour run is a black box.  Armed with the ``obs_stream_path`` param,
every rank streams instead:

* **progress records** — boosting appends one iteration-stamped record per
  ``train_one_iter`` (iteration, seconds, trees/s, ms/leaf when the
  synchronous path knows the leaf count, observed histogram-kernel
  identity, HBM peak, cumulative collective bytes incl. the HLO census);
* **structured events as they happen** — the recorder registers itself as
  a counter-registry *sink*, so every ``layout_downgrade`` /
  ``checkpoint_skipped`` / ``nonfinite`` / ... event lands in the stream
  the moment it is recorded, not only in a post-mortem ring tail;
* **memory inflections** — the armed memory monitor records an
  ``hbm_peak`` line whenever the peak grows past its last mark by >10 %.

The stream is append-only JSONL (the torn-tail-tolerant format the trace
reader already parses), rotated at :data:`MAX_BYTES` with one retained
generation — a recorder can run for days without growing the disk.  Writes
are unsynced host-side file appends, exactly the heartbeat discipline:
zero collectives, zero device syncs (pinned with the rest of the armed
telemetry plane in ``tests/test_metrics.py``).

The supervisor tails every rank's stream (``stream_path(base, rank)``)
and compares per-rank progress *rates*: a rank whose rate falls
``straggler_factor`` behind the group median raises a structured
``rank_straggler`` event — liveness upgraded from "alive" (heartbeats) to
"healthy".  Disarmed, the active recorder is the shared
:data:`NULL_FLIGHT` no-op singleton (the ``obs/trace.py`` discipline).
"""
from __future__ import annotations

import json
import os
import statistics
import threading
import time
from typing import Any, Dict, List, Optional

from .trace import process_index

MAX_BYTES = 4 << 20        # rotate past this; one .1 generation retained


def stream_path(base: str, rank: int) -> str:
    """The per-rank stream file for an ``obs_stream_path`` base (the
    ``<output_model>.heartbeat.rank_R`` naming convention)."""
    return f"{base}.rank_{rank}"


class NullFlightRecorder:
    """Disarmed recorder: every operation is a constant no-op, shared
    process-wide so the instrumented hot paths never allocate."""
    enabled = False
    path: Optional[str] = None

    def record(self, kind: str, **fields) -> None:
        pass

    def progress(self, iteration: int, **fields) -> None:
        pass

    def close(self) -> None:
        pass


NULL_FLIGHT = NullFlightRecorder()


class FlightRecorder:
    """Armed recorder bound to one stream file."""
    enabled = True

    def __init__(self, path: str, rank: Optional[int] = None,
                 max_bytes: int = MAX_BYTES):
        self.path = str(path)
        self.rank = int(rank) if rank is not None else process_index()
        self.max_bytes = max(4096, int(max_bytes))
        self._lock = threading.Lock()
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        self._fh = open(self.path, "a")
        self._size = self._fh.tell()

    def record(self, kind: str, **fields) -> None:
        """Append one event line.  Unsynced (liveness, not durability —
        the heartbeat rule); a full disk must never kill training."""
        from ..checkpoint import group_epoch
        rec = {"t": round(time.time(), 3), "rank": self.rank,
               "event": str(kind), "epoch": group_epoch()}
        rec.update(fields)
        line = json.dumps(rec, default=str) + "\n"
        with self._lock:
            try:
                if self._size + len(line) > self.max_bytes:
                    self._rotate()
                self._fh.write(line)
                self._fh.flush()
                self._size += len(line)
            except (OSError, ValueError):
                pass             # a dead stream is a stale one, not a crash

    def progress(self, iteration: int, **fields) -> None:
        self.record("progress", iteration=int(iteration), **fields)

    def _rotate(self) -> None:
        self._fh.close()
        os.replace(self.path, self.path + ".1")
        self._fh = open(self.path, "a")
        self._size = 0

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.close()
            except (OSError, ValueError):
                pass

    # counter-registry sink: every structured event streams as it happens
    def _absorb_event(self, ev: Dict[str, Any]) -> None:
        fields = {k: v for k, v in ev.items() if k != "event"}
        self.record(ev.get("event", "?"), **fields)


_active: Any = NULL_FLIGHT


def get_flight():
    """The process-wide active recorder (NULL_FLIGHT when disarmed)."""
    return _active


def start(path: str, rank: Optional[int] = None,
          max_bytes: int = MAX_BYTES) -> FlightRecorder:
    """Arm a recorder on ``path`` and subscribe it to the counter-registry
    event stream."""
    global _active
    from .counters import counters
    stop()
    _active = FlightRecorder(path, rank=rank, max_bytes=max_bytes)
    counters.add_sink(_active._absorb_event)
    return _active


def stop() -> Optional[str]:
    """Disarm; returns the stream path that was active, or None."""
    global _active
    fl, _active = _active, NULL_FLIGHT
    if not fl.enabled:
        return None
    from .counters import counters
    counters.remove_sink(fl._absorb_event)
    fl.close()
    return fl.path


# ------------------------------------------------------------------ readers


def read_stream(path: str, include_rotated: bool = True) -> List[dict]:
    """Every parseable record of a stream, rotated generation first.
    Torn-tail tolerant: a killed writer leaves a readable prefix and the
    final partial line is skipped, never raised on."""
    out: List[dict] = []
    paths = ([path + ".1"] if include_rotated else []) + [path]
    for p in paths:
        try:
            with open(p) as f:
                text = f.read()
        except OSError:
            continue
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


def tail_records(path: str, max_bytes: int = 65536) -> List[dict]:
    """The records in the last ``max_bytes`` of a stream (the supervisor's
    cheap repeated read; the first line of the window may be partial and
    is dropped along with any torn tail)."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            if size > max_bytes:
                f.seek(size - max_bytes)
            chunk = f.read().decode("utf-8", errors="replace")
    except OSError:
        return []
    lines = chunk.splitlines()
    if size > max_bytes and lines:
        lines = lines[1:]              # partial first line of the window
    out = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return out


# ------------------------------------------------------- straggler verdicts


def progress_rate(records: List[dict]) -> Optional[float]:
    """Iterations per second across the ``progress`` records of one rank's
    stream window, or None when fewer than two usable records exist."""
    prog = [r for r in records
            if r.get("event") == "progress"
            and isinstance(r.get("iteration"), (int, float))
            and isinstance(r.get("t"), (int, float))]
    if len(prog) < 2:
        return None
    di = float(prog[-1]["iteration"]) - float(prog[0]["iteration"])
    dt = float(prog[-1]["t"]) - float(prog[0]["t"])
    if di <= 0 or dt <= 0:
        return None
    return di / dt


def recent_idle_gap(records: List[dict]) -> Optional[float]:
    """Median ``idle_gap_fraction`` across the ``progress`` records of one
    rank's stream window (present when the devprof plane was armed), or
    None — a straggler verdict that can say "the gap is host-side idle,
    not device work" is worth far more than a bare rate ratio."""
    gaps = [float(r["idle_gap_fraction"]) for r in records
            if r.get("event") == "progress"
            and isinstance(r.get("idle_gap_fraction"), (int, float))]
    if not gaps:
        return None
    return round(statistics.median(gaps), 4)


def detect_stragglers(rates: Dict[int, Optional[float]],
                      factor: float) -> List[Dict[str, Any]]:
    """Ranks whose progress rate falls ``factor`` behind the group median
    (``rate * factor < median``).  Needs at least two ranks with measured
    rates; a rank with no rate yet is unknown, not a straggler (the
    heartbeat layer owns "silent")."""
    valid = {r: float(v) for r, v in rates.items() if v}
    if len(valid) < 2:
        return []
    med = statistics.median(valid.values())
    out = []
    for rank, rate in sorted(valid.items()):
        if rate * float(factor) < med:
            out.append({"rank": rank, "rate": round(rate, 4),
                        "median_rate": round(med, 4),
                        "behind": round(med / rate, 2)})
    return out
