"""lightgbm_tpu.obs — structured telemetry: spans, counters, collectives.

Three pillars (see docs/OBSERVABILITY.md):

* :mod:`.trace` — nested-span tracer; no-op when disabled, Chrome-trace
  JSON/JSONL + ``jax.profiler.TraceAnnotation`` mirroring when enabled;
* :mod:`.counters` — process-wide counters/events (histogram-kernel
  dispatch identity, layout downgrades, collective bytes);
* :mod:`.report` — ``python -m lightgbm_tpu.obs <trace>`` renders the
  per-phase / per-kernel markdown tables.

Enable from training via ``engine.train(params={"trace_path": ...})`` or
``telemetry=true``; from the bench via ``BENCH_TRACE=<path>``.
"""
from . import trace
from .counters import counters
from .trace import get_tracer

__all__ = ["trace", "counters", "get_tracer"]
