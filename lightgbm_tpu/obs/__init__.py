"""lightgbm_tpu.obs — structured telemetry: spans, counters, collectives,
device memory.

Four pillars (see docs/OBSERVABILITY.md):

* :mod:`.trace` — nested-span tracer; no-op when disabled, Chrome-trace
  JSON/JSONL + ``jax.profiler.TraceAnnotation`` mirroring when enabled;
* :mod:`.counters` — process-wide counters/events (histogram-kernel
  dispatch identity, layout downgrades, collective bytes);
* :mod:`.memory` — device-memory observability: live HBM accounting
  (``memory_stats`` / tagged live-array census), compiled-executable
  ``memory_analysis`` capture, the ``predict_hbm`` fit-predictor and the
  pre-compile ``hbm_budget`` pre-flight;
* :mod:`.report` — ``python -m lightgbm_tpu.obs <trace>...`` renders the
  per-phase / per-kernel / memory markdown tables (multiple trace files
  merge rank-tagged).

Enable from training via ``engine.train(params={"trace_path": ...})`` or
``telemetry=true``; from the bench via ``BENCH_TRACE=<path>``.
"""
from . import memory, trace
from .counters import counters
from .trace import get_tracer

__all__ = ["memory", "trace", "counters", "get_tracer"]
