"""lightgbm_tpu.obs — structured telemetry: spans, counters, collectives,
device memory.

Four pillars (see docs/OBSERVABILITY.md):

* :mod:`.trace` — nested-span tracer; no-op when disabled, Chrome-trace
  JSON/JSONL + ``jax.profiler.TraceAnnotation`` mirroring when enabled;
* :mod:`.counters` — process-wide counters/events (histogram-kernel
  dispatch identity, layout downgrades, collective bytes);
* :mod:`.memory` — device-memory observability: live HBM accounting
  (``memory_stats`` / tagged live-array census), compiled-executable
  ``memory_analysis`` capture, the ``predict_hbm`` fit-predictor and the
  pre-compile ``hbm_budget`` pre-flight;
* :mod:`.report` — ``python -m lightgbm_tpu.obs <trace>...`` renders the
  per-phase / per-kernel / memory markdown tables (multiple trace files
  merge rank-tagged);
* :mod:`.metrics` — the LIVE plane: a Prometheus text view of the whole
  registry (counters/gauges, phase steady-state means, memory peaks,
  serving latency histograms), served from ``GET /metrics`` on the
  serving HTTP front and a standalone ``metrics_port`` exporter thread;
* :mod:`.flight` — per-rank flight recorder: a bounded rotated JSONL
  stream of iteration progress + structured events as they happen
  (``obs_stream_path``), tailed by the supervisor for straggler verdicts.

Enable from training via ``engine.train(params={"trace_path": ...})`` or
``telemetry=true``; from the bench via ``BENCH_TRACE=<path>``; the live
plane via ``metrics_port`` / ``obs_stream_path``.
"""
from . import flight, memory, metrics, trace
from .counters import counters
from .trace import get_tracer

__all__ = ["flight", "memory", "metrics", "trace", "counters",
           "get_tracer"]
