"""Model-quality observability plane (the fifth pillar).

Four planes (spans, counters/events, memory, flight/devprof) answer "why
is my run slow / out of memory / unhealthy"; this one answers "why is my
model wrong":

* **split audit** — every materialized tree's already-fetched arrays are
  folded host-side into per-split ``split_audit`` flight records plus
  per-feature cumulative gain / split-count accumulators, exported as
  ``lgbm_tpu_feature_gain_total{feature=}`` /
  ``lgbm_tpu_feature_split_total{feature=}``.  Pure reads of host data the
  trainer fetched anyway — zero added device syncs or collectives (pinned
  in tests/test_metrics.py).
* **TreeSHAP attribution** — the exact Lundberg/Lee path-attribution
  recursion, vectorized over rows (the recursion *structure* — node visit
  order, path features, cover fractions, duplicate-feature unwinds — is
  row-independent; only the hot-child indicators and path weights are
  per-row, so one pass per tree carries ``[path, N]`` arrays instead of
  recursing per row).  ``predict(pred_contrib=True)`` rides it with
  decisions taken from the serving engine's device-binned rows; the
  per-row recursive oracle stays as the parity twin.
* **serving drift** — per-feature PSI between the training-set bin
  distribution (stored in the model file) and the bin histogram of what
  the serving engine actually traverses, exported as
  ``lgbm_tpu_feature_drift{feature=}`` gauges + ``feature_drift``
  structured events past ``drift_threshold``.

Armed via the ``model_quality`` param (``auto`` follows ``telemetry``).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..tree import (K_CATEGORICAL_MASK, K_DEFAULT_LEFT_MASK, MISSING_NAN,
                    MISSING_ZERO, ZERO_RANGE)
from . import flight as obs_flight
from . import metrics as obs_metrics
from .counters import counters


def _feature_name(names: Optional[Sequence[str]], idx: int) -> str:
    if names is not None and 0 <= idx < len(names):
        return str(names[idx])
    return f"Column_{idx}"


# ------------------------------------------------------------ split audit


class NullModelQuality:
    """Disarmed tracker (the shared no-op singleton discipline)."""
    enabled = False

    def observe_tree(self, iteration: int, tree_index: int, tree) -> None:
        pass

    def note_eval(self, dataset: str, metric: str, value: float) -> None:
        pass

    def eval_fields(self) -> Dict[str, float]:
        return {}

    def metrics_samples(self) -> list:
        return []

    def summary(self, top_k: int = 10) -> Dict[str, Any]:
        return {}


NULL_MODEL_QUALITY = NullModelQuality()


class ModelQualityTracker:
    """Training-side split auditor: folds each materialized tree's host
    arrays into per-feature gain/split-count accumulators, streams
    per-split ``split_audit`` records into the flight recorder, and
    stashes the freshest eval values for the next ``progress`` record.

    Everything here reads host arrays the trainer already fetched to
    build the :class:`~lightgbm_tpu.tree.Tree` — the hot path gains no
    device sync and no collective (pinned)."""

    enabled = True

    def __init__(self, feature_names: Optional[Sequence[str]] = None):
        self.feature_names = list(feature_names) if feature_names else None
        self._gain: Dict[int, float] = {}
        self._splits: Dict[int, int] = {}
        # gain-decay curve: per-iteration total split gain (a flat-lining
        # curve is the convergence diagnostic the report renders)
        self._iter_gain: Dict[int, float] = {}
        self._evals: Dict[str, float] = {}
        self.trees_seen = 0
        obs_metrics.register_source(self.metrics_samples)

    # -- per-tree fold ----------------------------------------------------

    def observe_tree(self, iteration: int, tree_index: int, tree) -> None:
        n = tree.num_leaves - 1
        self.trees_seen += 1
        if n <= 0:
            return
        feats = np.asarray(tree.split_feature[:n], np.int64)
        gains = np.asarray(tree.split_gain[:n], np.float64)
        for f in np.unique(feats):
            sel = feats == f
            self._gain[int(f)] = self._gain.get(int(f), 0.0) \
                + float(gains[sel].sum())
            self._splits[int(f)] = self._splits.get(int(f), 0) \
                + int(sel.sum())
        self._iter_gain[int(iteration)] = \
            self._iter_gain.get(int(iteration), 0.0) + float(gains.sum())
        fl = obs_flight.get_flight()
        if not fl.enabled:
            return
        lc = tree.left_child[:n]
        rc = tree.right_child[:n]
        child_count = np.where(
            lc < 0, tree.leaf_count[~np.minimum(lc, -1)],
            tree.internal_count[np.maximum(lc, 0)])
        rchild_count = np.where(
            rc < 0, tree.leaf_count[~np.minimum(rc, -1)],
            tree.internal_count[np.maximum(rc, 0)])
        for i in range(n):
            fl.record(
                "split_audit", iteration=int(iteration), tree=int(tree_index),
                node=i, feature=_feature_name(self.feature_names,
                                              int(feats[i])),
                bin_threshold=int(tree.threshold_bin[i]),
                threshold=float(tree.threshold[i]), gain=float(gains[i]),
                left_count=int(child_count[i]),
                right_count=int(rchild_count[i]),
                default_left=bool(tree.decision_type[i]
                                  & K_DEFAULT_LEFT_MASK),
                categorical=bool(tree.decision_type[i] & K_CATEGORICAL_MASK))

    # -- eval stash (ride the NEXT progress record) -----------------------

    def note_eval(self, dataset: str, metric: str, value: float) -> None:
        self._evals[f"{dataset}:{metric}"] = float(value)

    def eval_fields(self) -> Dict[str, float]:
        """Freshest per-metric eval values, for the progress record."""
        return dict(self._evals)

    # -- exports ----------------------------------------------------------

    def metrics_samples(self) -> list:
        out = []
        for f, g in sorted(self._gain.items()):
            name = _feature_name(self.feature_names, f)
            out.append(("feature_gain", {"feature": name}, g, "counter"))
            out.append(("feature_split", {"feature": name},
                        self._splits.get(f, 0), "counter"))
        return out

    def summary(self, top_k: int = 10) -> Dict[str, Any]:
        order = sorted(self._gain, key=lambda f: -self._gain[f])
        return {
            "trees_seen": self.trees_seen,
            "top_features": [
                {"feature": _feature_name(self.feature_names, f),
                 "gain": self._gain[f], "splits": self._splits.get(f, 0)}
                for f in order[:top_k]],
            "gain_curve": [[it, self._iter_gain[it]]
                           for it in sorted(self._iter_gain)],
        }


_active: Any = NULL_MODEL_QUALITY


def get_tracker():
    """The process-wide active tracker (no-op singleton when disarmed)."""
    return _active


def start(feature_names: Optional[Sequence[str]] = None) -> ModelQualityTracker:
    global _active
    _active = ModelQualityTracker(feature_names)
    return _active


def stop():
    """Disarm; returns the retired tracker (its metrics source weakref
    drops out of the registry with it)."""
    global _active
    t, _active = _active, NULL_MODEL_QUALITY
    return t


def resolve_armed(model_quality: str, telemetry_on: bool) -> bool:
    """The ``model_quality`` param ladder: ``auto`` follows telemetry."""
    if model_quality == "on":
        return True
    if model_quality == "off":
        return False
    return telemetry_on


# ------------------------------------------------------------- TreeSHAP
#
# The exact TreeSHAP recursion (Lundberg et al., the reference's
# tree.cpp:TreeSHAP), vectorized over rows.  A path element carries
# (feature, zero_fraction, one_fraction, pweight); feature identities,
# zero fractions (cover ratios) and the unwind positions depend only on
# the tree, so they stay scalars — one_fraction/pweight become [N]
# vectors and every branch on ``one_fraction != 0`` becomes a masked
# ``np.where`` with guarded denominators.


def _decide_host(tree, X: np.ndarray) -> np.ndarray:
    """go-left per (internal node, row) from RAW features — the same
    NumericalDecisionInner / CategoricalDecision semantics as
    ``Tree.predict`` (tree.h:231-313), evaluated for every node."""
    n = tree.num_leaves - 1
    N = X.shape[0]
    go = np.zeros((n, N), bool)
    for i in range(n):
        fv = X[:, tree.split_feature[i]]
        dt = int(tree.decision_type[i])
        mt = (dt >> 2) & 3
        if dt & K_CATEGORICAL_MASK:
            go[i] = [tree._cat_decision(float(v), i) for v in fv]
            continue
        nan_mask = np.isnan(fv)
        v = np.where(nan_mask & (mt != MISSING_NAN), 0.0, fv)
        is_missing = ((mt == MISSING_ZERO) & (np.abs(v) <= ZERO_RANGE)) | \
                     ((mt == MISSING_NAN) & nan_mask)
        go[i] = np.where(is_missing, bool(dt & K_DEFAULT_LEFT_MASK),
                         v <= tree.threshold[i])
    return go


def expected_value(tree) -> float:
    """``Tree::ExpectedValue``: the training-cover-weighted mean output —
    the bias term TreeSHAP assigns to the last contribution column."""
    if tree.num_leaves <= 1:
        return float(tree.leaf_value[0]) if len(tree.leaf_value) else 0.0
    total = float(tree.internal_count[0])
    if total <= 0:
        return 0.0
    return float(np.dot(tree.leaf_count[:tree.num_leaves].astype(np.float64),
                        tree.leaf_value[:tree.num_leaves]) / total)


def _node_count(tree, child: int) -> float:
    return float(tree.leaf_count[~child] if child < 0
                 else tree.internal_count[child])


def tree_contribs(tree, go: np.ndarray, num_features: int,
                  phi: Optional[np.ndarray] = None) -> np.ndarray:
    """SHAP contributions of one tree for all rows at once.

    ``go`` is the [num_internal, N] go-left decision matrix (from
    :func:`_decide_host` or the serving engine's device-binned rows —
    both route identically); returns/accumulates ``phi`` [N,
    num_features + 1] with the expected value in the last column."""
    N = go.shape[1] if tree.num_leaves > 1 else \
        (phi.shape[0] if phi is not None else 0)
    if phi is None:
        phi = np.zeros((N, num_features + 1), np.float64)
    phi[:, num_features] += expected_value(tree)
    if tree.num_leaves <= 1:
        return phi
    n_rows = go.shape[1]

    # path state, one slot per unique feature on the path (+ the leading
    # sentinel): feature / zero_fraction are row-independent per slot
    def recurse(node: int, depth: int, pfeat: List[int], pzero: List[float],
                pone: List[np.ndarray], ppw: List[np.ndarray],
                parent_zero: float, parent_one: np.ndarray,
                parent_feat: int) -> None:
        # ExtendPath
        pfeat = pfeat + [parent_feat]
        pzero = pzero + [parent_zero]
        pone = pone + [parent_one]
        ppw = ppw + [np.ones(n_rows) if depth == 0 else np.zeros(n_rows)]
        for i in range(depth - 1, -1, -1):
            ppw[i + 1] = ppw[i + 1] + parent_one * ppw[i] \
                * ((i + 1) / (depth + 1))
            ppw[i] = parent_zero * ppw[i] * ((depth - i) / (depth + 1))
        if node < 0:                                    # leaf
            leaf_v = float(tree.leaf_value[~node])
            for i in range(1, depth + 1):
                w = _unwound_sum(pzero, pone, ppw, depth, i)
                phi[:, pfeat[i]] += w * (pone[i] - pzero[i]) * leaf_v
            return
        lc = int(tree.left_child[node])
        rc = int(tree.right_child[node])
        node_cnt = float(tree.internal_count[node])
        feat = int(tree.split_feature[node])
        left_zero = _node_count(tree, lc) / node_cnt
        right_zero = _node_count(tree, rc) / node_cnt
        inc_zero, inc_one = 1.0, np.ones(n_rows)
        # a feature already on the path: undo its previous extension and
        # fold its fractions into the incoming ones
        for pi in range(1, depth + 1):
            if pfeat[pi] == feat:
                inc_zero, inc_one = pzero[pi], pone[pi]
                pfeat, pzero, pone, ppw, depth = _unwind(
                    pfeat, pzero, pone, ppw, depth, pi)
                break
        go_l = go[node]
        # hot/cold is per-row: each child's incoming one_fraction keeps
        # the rows routed to it and zeroes the rest
        recurse(lc, depth + 1, pfeat, pzero, pone, ppw,
                left_zero * inc_zero, np.where(go_l, inc_one, 0.0), feat)
        recurse(rc, depth + 1, pfeat, pzero, pone, ppw,
                right_zero * inc_zero, np.where(go_l, 0.0, inc_one), feat)

    recurse(0, 0, [], [], [], [], 1.0, np.ones(n_rows), -1)
    return phi


def _unwound_sum(pzero, pone, ppw, depth: int, pi: int) -> np.ndarray:
    """UnwoundPathSum, rows at once: total permutation weight of the
    subsets along the path with element ``pi`` removed."""
    one = pone[pi]
    zero = pzero[pi]
    nonzero = one != 0
    next_one = np.array(ppw[depth], copy=True)
    total = np.zeros_like(next_one)
    for i in range(depth - 1, -1, -1):
        with np.errstate(divide="ignore", invalid="ignore"):
            tmp = np.where(nonzero,
                           next_one * ((depth + 1) / ((i + 1) * np.where(
                               nonzero, one, 1.0))), 0.0)
            alt = (ppw[i] * ((depth + 1) / (depth - i))
                   / (zero if zero != 0 else 1.0)) \
                if zero != 0 else np.zeros(1)
        total = total + np.where(nonzero, tmp, alt)
        next_one = np.where(nonzero,
                            ppw[i] - tmp * zero * ((depth - i) / (depth + 1)),
                            next_one)
    return total


def _unwind(pfeat, pzero, pone, ppw, depth: int, pi: int):
    """UnwindPath, rows at once: remove path element ``pi``, restoring
    the pweights to the state before it was extended in."""
    one = pone[pi]
    zero = pzero[pi]
    nonzero = one != 0
    ppw = [np.array(w, copy=True) for w in ppw]
    next_one = np.array(ppw[depth], copy=True)
    for i in range(depth - 1, -1, -1):
        with np.errstate(divide="ignore", invalid="ignore"):
            new_if = next_one * ((depth + 1) / ((i + 1) * np.where(
                nonzero, one, 1.0)))
            new_else = ppw[i] * ((depth + 1) / (depth - i)) \
                / (zero if zero != 0 else 1.0) if zero != 0 \
                else np.zeros(1)
        tmp = np.array(ppw[i], copy=True)
        ppw[i] = np.where(nonzero, new_if, new_else)
        next_one = np.where(nonzero,
                            tmp - ppw[i] * zero * ((depth - i) / (depth + 1)),
                            next_one)
    # shift feature/zero/one down over the removed slot; the RESTORED
    # pweights stay in place and the LAST slot drops (tree_shap.h
    # unwind_path shifts everything except pweight)
    pfeat = pfeat[:pi] + pfeat[pi + 1:]
    pzero = pzero[:pi] + pzero[pi + 1:]
    pone = pone[:pi] + pone[pi + 1:]
    ppw = ppw[:depth]
    return pfeat, pzero, pone, ppw, depth - 1


def contribs_from_raw(tree, X: np.ndarray, num_features: int,
                      phi: Optional[np.ndarray] = None) -> np.ndarray:
    """Vectorized TreeSHAP of one tree over raw host features."""
    go = _decide_host(tree, np.asarray(X, np.float64)) \
        if tree.num_leaves > 1 else np.zeros((0, len(X)), bool)
    if phi is None:
        phi = np.zeros((len(X), num_features + 1), np.float64)
    return tree_contribs(tree, go, num_features, phi)


# -- the per-row recursive oracle (parity twin) ---------------------------


def contribs_oracle(tree, x: np.ndarray, num_features: int) -> np.ndarray:
    """Independent single-row TreeSHAP: the literal reference recursion
    with scalar path elements (tree.cpp:TreeSHAP).  Kept as the parity
    twin the vectorized path is pinned against."""
    phi = np.zeros(num_features + 1, np.float64)
    phi[num_features] += expected_value(tree)
    if tree.num_leaves <= 1:
        return phi
    x = np.asarray(x, np.float64)

    def decision(node: int) -> bool:
        fv = float(x[tree.split_feature[node]])
        dt = int(tree.decision_type[node])
        mt = (dt >> 2) & 3
        if dt & K_CATEGORICAL_MASK:
            return bool(tree._cat_decision(fv, node))
        is_nan = np.isnan(fv)
        if is_nan and mt != MISSING_NAN:
            fv = 0.0
        missing = ((mt == MISSING_ZERO) and abs(fv) <= ZERO_RANGE) or \
                  (mt == MISSING_NAN and is_nan)
        if missing:
            return bool(dt & K_DEFAULT_LEFT_MASK)
        return fv <= tree.threshold[node]

    def extend(path, zero, one, feat):
        path = [dict(p) for p in path]
        d = len(path)
        path.append({"f": feat, "z": zero, "o": one,
                     "w": 1.0 if d == 0 else 0.0})
        for i in range(d - 1, -1, -1):
            path[i + 1]["w"] += one * path[i]["w"] * (i + 1) / (d + 1)
            path[i]["w"] = zero * path[i]["w"] * (d - i) / (d + 1)
        return path

    def unwound_sum(path, pi):
        d = len(path) - 1
        one, zero = path[pi]["o"], path[pi]["z"]
        next_one = path[d]["w"]
        total = 0.0
        for i in range(d - 1, -1, -1):
            if one != 0:
                tmp = next_one * (d + 1) / ((i + 1) * one)
                total += tmp
                next_one = path[i]["w"] - tmp * zero * (d - i) / (d + 1)
            elif zero != 0:
                total += path[i]["w"] * (d + 1) / (zero * (d - i))
        return total

    def unwind(path, pi):
        d = len(path) - 1
        one, zero = path[pi]["o"], path[pi]["z"]
        path = [dict(p) for p in path]
        next_one = path[d]["w"]
        for i in range(d - 1, -1, -1):
            if one != 0:
                tmp = path[i]["w"]
                path[i]["w"] = next_one * (d + 1) / ((i + 1) * one)
                next_one = tmp - path[i]["w"] * zero * (d - i) / (d + 1)
            elif zero != 0:
                path[i]["w"] = path[i]["w"] * (d + 1) / (zero * (d - i))
        # shift feature/fractions down over the removed slot; pweights
        # stay in place and the LAST slot drops (tree_shap.h unwind_path)
        for i in range(pi, d):
            path[i]["f"] = path[i + 1]["f"]
            path[i]["z"] = path[i + 1]["z"]
            path[i]["o"] = path[i + 1]["o"]
        return path[:d]

    def rec(node, path, zero, one, feat):
        path = extend(path, zero, one, feat)
        if node < 0:
            for i in range(1, len(path)):
                w = unwound_sum(path, i)
                phi[path[i]["f"]] += w * (path[i]["o"] - path[i]["z"]) \
                    * float(tree.leaf_value[~node])
            return
        lc, rc = int(tree.left_child[node]), int(tree.right_child[node])
        hot, cold = (lc, rc) if decision(node) else (rc, lc)
        node_cnt = float(tree.internal_count[node])
        hot_zero = _node_count(tree, hot) / node_cnt
        cold_zero = _node_count(tree, cold) / node_cnt
        inc_zero, inc_one = 1.0, 1.0
        sf = int(tree.split_feature[node])
        for pi in range(1, len(path)):
            if path[pi]["f"] == sf:
                inc_zero, inc_one = path[pi]["z"], path[pi]["o"]
                path = unwind(path, pi)
                break
        rec(hot, path, hot_zero * inc_zero, inc_one, sf)
        rec(cold, path, cold_zero * inc_zero, 0.0, sf)

    rec(0, [], 1.0, 1.0, -1)
    return phi


# -------------------------------------------------------- serving drift


def training_bin_distribution(train_set) -> Dict[int, List[Tuple[float, int]]]:
    """Per-original-feature ``(representative value, count)`` histogram of
    the TRAINING data's bins — the reference distribution the serving
    drift monitor projects into its own threshold-rank space.

    NaN bins project at 0.0 (the serving binner maps NaN rows through
    ``where(nan, 0, x)``), so a drift-free replay of the training data
    lands rank-for-rank on this distribution.  Bundled (EFB) layouts and
    categorical features are skipped — drift is a numerical-distribution
    alarm."""
    out: Dict[int, List[Tuple[float, int]]] = {}
    if train_set is None or train_set.binned is None:
        return out
    layout = getattr(train_set, "layout", None)
    if layout is not None and getattr(layout, "has_bundles", False):
        return out
    binned = train_set.binned
    for j, f in enumerate(train_set.used_features):
        m = train_set.bin_mappers[f]
        if getattr(m, "bin_2_categorical", None):
            continue
        cnt = np.bincount(np.asarray(binned[:, j], np.int64),
                          minlength=m.num_bin)
        pairs: List[Tuple[float, int]] = []
        nan_bin = m.num_bin - 1 if m.missing_type == MISSING_NAN else -1
        for b in range(m.num_bin):
            if cnt[b] == 0:
                continue
            v = 0.0 if b == nan_bin else float(m.bin_to_value(b))
            pairs.append((v, int(cnt[b])))
        if pairs:
            out[int(f)] = pairs
    return out


def format_distribution(dist: Dict[int, List[Tuple[float, int]]]) -> str:
    """Model-file ``feature_distribution:`` section body."""
    lines = ["feature_distribution:"]
    for f in sorted(dist):
        body = " ".join(f"{v:.17g}:{c}" for v, c in dist[f])
        lines.append(f"{f}={body}")
    return "\n".join(lines) + "\n"


def parse_distribution(lines: Sequence[str]) -> Dict[int, List[Tuple[float, int]]]:
    """Inverse of :func:`format_distribution` over raw model-file lines."""
    out: Dict[int, List[Tuple[float, int]]] = {}
    it = iter(lines)
    for line in it:
        if line.strip() == "feature_distribution:":
            break
    else:
        return out
    for line in it:
        s = line.strip()
        if not s or "=" not in s:
            break
        f, body = s.split("=", 1)
        try:
            pairs = [(float(p.split(":")[0]), int(p.split(":")[1]))
                     for p in body.split()]
        except (ValueError, IndexError):
            continue
        out[int(f)] = pairs
    return out


def psi(p_counts: np.ndarray, q_counts: np.ndarray,
        eps: float = 1e-6) -> float:
    """Population stability index between two count histograms."""
    ps = p_counts.sum()
    qs = q_counts.sum()
    if ps <= 0 or qs <= 0:
        return 0.0
    p = np.maximum(p_counts / ps, eps)
    q = np.maximum(q_counts / qs, eps)
    return float(np.sum((p - q) * np.log(p / q)))


class DriftMonitor:
    """Serving-side train-vs-serve distribution watchdog.

    Attached to a :class:`~lightgbm_tpu.inference.PredictEngine`; every
    microbatch's binned rows fold into per-feature threshold-rank
    histograms (one scatter-add over data the engine binned anyway).
    Every ``window_rows`` served rows the per-feature PSI against the
    stored training distribution is recomputed; features past
    ``threshold`` fire one ``feature_drift`` structured event per window
    and every feature exports a ``feature_drift`` gauge."""

    def __init__(self, bundle, distribution: Dict[int, List[Tuple[float, int]]],
                 feature_names: Optional[Sequence[str]] = None,
                 threshold: float = 0.2, window_rows: int = 4096):
        self.threshold = float(threshold)
        self.window_rows = max(int(window_rows), 1)
        self.feature_names = list(feature_names) if feature_names else None
        nb1 = bundle.num_bins + 1
        self.cols = np.asarray(bundle.cols, np.int64)
        # training distribution projected into THIS bundle's rank space:
        # rank = searchsorted(thr64, value) — the same left-side rank the
        # serving binner assigns the raw value
        self.ref = np.zeros((len(self.cols), nb1), np.float64)
        self.active = np.zeros(len(self.cols), bool)
        for i, f in enumerate(self.cols):
            pairs = distribution.get(int(f))
            u = bundle.thr64[i]
            if not pairs or not len(u):
                continue
            vals = np.asarray([v for v, _ in pairs], np.float64)
            cnts = np.asarray([c for _, c in pairs], np.float64)
            ranks = np.searchsorted(u, vals, side="left")
            np.add.at(self.ref[i], ranks, cnts)
            self.active[i] = True
        self.obs = np.zeros_like(self.ref)
        self.rows_in_window = 0
        self.rows_total = 0
        self.windows = 0
        self.last_psi = np.zeros(len(self.cols), np.float64)
        self.events_fired = 0

    @property
    def enabled(self) -> bool:
        return bool(self.active.any())

    def _name(self, col: int) -> str:
        return _feature_name(self.feature_names, int(self.cols[col]))

    def add_counts(self, counts: np.ndarray, rows: int) -> None:
        """Fold one microbatch's per-feature rank histogram [Fc, NB+1]."""
        if not self.enabled or rows <= 0:
            return
        c = np.asarray(counts, np.float64)
        self.obs[:, :c.shape[1]] += c
        self.rows_in_window += int(rows)
        self.rows_total += int(rows)
        if self.rows_in_window >= self.window_rows:
            self._evaluate()

    def add_bins(self, bins: np.ndarray) -> None:
        """Host-binned twin: fold raw rank rows [n, Fc]."""
        if not self.enabled or not len(bins):
            return
        nb1 = self.ref.shape[1]
        counts = np.stack([np.bincount(bins[:, i], minlength=nb1)[:nb1]
                           for i in range(bins.shape[1])]) \
            if bins.shape[1] else np.zeros((0, nb1))
        self.add_counts(counts, len(bins))

    def _evaluate(self) -> None:
        self.windows += 1
        for i in range(len(self.cols)):
            if not self.active[i]:
                continue
            self.last_psi[i] = psi(self.ref[i], self.obs[i])
            if self.threshold > 0 and self.last_psi[i] > self.threshold:
                self.events_fired += 1
                counters.event(
                    "feature_drift", feature=self._name(i),
                    psi=round(self.last_psi[i], 6),
                    threshold=self.threshold,
                    window_rows=self.rows_in_window, window=self.windows)
        self.obs[:] = 0
        self.rows_in_window = 0

    def samples(self) -> list:
        """Live metrics-source rows (ModelServer folds these into its
        registered source)."""
        out = []
        for i in range(len(self.cols)):
            if self.active[i]:
                out.append(("feature_drift", {"feature": self._name(i)},
                            float(self.last_psi[i]), "gauge"))
        out.append(("drift_windows", {}, float(self.windows), "counter"))
        return out

    def stats(self) -> Dict[str, Any]:
        """The ``GET /stats`` drift block."""
        return {
            "rows_seen": self.rows_total,
            "windows": self.windows,
            "window_rows": self.window_rows,
            "threshold": self.threshold,
            "events_fired": self.events_fired,
            "psi": {self._name(i): round(float(self.last_psi[i]), 6)
                    for i in range(len(self.cols)) if self.active[i]},
        }
