"""Render a telemetry trace into per-phase / per-kernel markdown tables.

``python -m lightgbm_tpu.obs <trace>`` is the CLI wrapper.  Accepts every
format ``obs/trace.py`` writes: a Chrome-trace object
(``{"traceEvents": [...]}``), a bare JSON array, or JSONL (one event per
line — a killed process leaves a readable prefix, so partial files parse
too).  The trace is self-contained: the final ``telemetry.summary`` event
carries the counter-registry snapshot (kernel dispatch identity, layout
downgrades, collective bytes) alongside the span timeline.
"""
from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional


def load_events(path: str) -> List[dict]:
    with open(path) as f:
        text = f.read()
    text = text.strip()
    if not text:
        return []
    if path.endswith(".jsonl") or "\n" in text and not text.startswith(("[", "{")):
        events = []
        for line in text.splitlines():
            line = line.strip().rstrip(",")
            if not line or line in ("[", "]"):
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue                  # tolerate a torn tail line
        return events
    obj = json.loads(text)
    if isinstance(obj, dict):
        return list(obj.get("traceEvents", []))
    return list(obj)


def summary_payload(events: List[dict], kind: str) -> Optional[dict]:
    """Last embedded ``telemetry.summary`` payload of the given kind."""
    out = None
    for ev in events:
        if ev.get("name") == "telemetry.summary":
            args = ev.get("args", {})
            if args.get("kind") == kind:
                out = args.get("payload")
    return out


def phase_table(events: List[dict],
                traced: Optional[bool] = None) -> List[Dict[str, Any]]:
    """Aggregate complete ("X") spans by name: count/total/mean/max (ms).

    ``traced`` filters on the span's ``traced`` arg: True keeps only
    TRACE-TIME spans (emitted from inside jit — they fire once per
    compilation and their durations include tracing/compile work), False
    keeps only host wall-clock spans, None keeps everything (the --json
    CLI view).  Host rows additionally carry ``first_ms`` (the
    chronologically first firing) and ``steady_mean_ms`` (mean of the
    rest): a first firing that dwarfs the steady state is the compile —
    totals that mix the two mislead (observed: a ``score`` phase showing
    11.2 s total of which 10.8 s was the first, compile-inclusive
    firing)."""
    agg: Dict[str, List[tuple]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        is_traced = bool(ev.get("args", {}).get("traced"))
        if traced is not None and is_traced != traced:
            continue
        agg.setdefault(ev["name"], []).append(
            (float(ev.get("ts", 0)), float(ev.get("dur", 0)) / 1e3))
    rows = []
    for name, spans in agg.items():
        spans.sort()
        durs = [d for _, d in spans]
        row = {"span": name, "count": len(durs),
               "total_ms": sum(durs),
               "mean_ms": sum(durs) / len(durs),
               "max_ms": max(durs)}
        if traced is False:
            rest = durs[1:]
            row["first_ms"] = durs[0]
            row["steady_mean_ms"] = (sum(rest) / len(rest)) if rest \
                else durs[0]
            row["compile_skewed"] = bool(
                rest and durs[0] > 3 * row["steady_mean_ms"])
        rows.append(row)
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def _split_tags(key: str) -> Dict[str, str]:
    return dict(kv.split("=", 1) for kv in key.split(",") if "=" in kv)


def kernel_table(counters: Dict[str, Dict[str, float]]) -> List[Dict[str, Any]]:
    rows = []
    for name in ("hist_dispatch", "pallas_impl"):
        for key, v in sorted(counters.get(name, {}).items()):
            tags = _split_tags(key)
            rows.append({"counter": name,
                         "kernel": tags.get("method", tags.get("impl", "?")),
                         "site": tags.get("site", "-"),
                         "traced_calls": int(v)})
    return rows


def observed_kernel(counters: Dict[str, Dict[str, float]]) -> Optional[str]:
    per: Dict[str, float] = {}
    for key, v in counters.get("hist_dispatch", {}).items():
        m = _split_tags(key).get("method")
        if m:
            per[m] = per.get(m, 0) + v
    return max(per, key=per.get) if per else None


def _md_table(headers: List[str], rows: List[List[Any]]) -> List[str]:
    out = ["| " + " | ".join(headers) + " |",
           "|" + "|".join("---" for _ in headers) + "|"]
    for r in rows:
        out.append("| " + " | ".join(str(c) for c in r) + " |")
    return out


def render(path: str) -> str:
    events = load_events(path)
    snap = summary_payload(events, "counters") or {}
    counters = snap.get("counters", {})
    lines = [f"# lightgbm_tpu telemetry report — `{path}`", ""]
    obs = observed_kernel(counters)
    if obs is not None:
        lines += [f"**Observed histogram kernel identity:** `{obs}`", ""]
    lines += ["## Per-phase spans", "",
              "Host wall-clock spans (Chrome-trace `X` events).  A span "
              "whose FIRST firing dwarfs its steady state (marked "
              "`compile⚠`) included jit compilation — judge throughput "
              "by `steady mean`, not `total`.", ""]
    prows = phase_table(events, traced=False)
    if prows:
        lines += _md_table(
            ["span", "count", "total ms", "first ms", "steady mean ms",
             "max ms", ""],
            [[r["span"], r["count"], f"{r['total_ms']:.3f}",
              f"{r['first_ms']:.3f}", f"{r['steady_mean_ms']:.3f}",
              f"{r['max_ms']:.3f}",
              "compile⚠" if r["compile_skewed"] else ""] for r in prows])
    else:
        lines.append("(no spans recorded)")
    trows = phase_table(events, traced=True)
    if trows:
        lines += ["", "## Trace-time spans (compile-inclusive)", "",
                  "Spans emitted from INSIDE jitted code fire once per "
                  "compilation — durations measure tracing/compile work, "
                  "never steady-state execution (the on-device twin is "
                  "the `jax.named_scope` XProf attribution).", ""]
        lines += _md_table(
            ["span", "count", "total ms", "mean ms", "max ms"],
            [[r["span"], r["count"], f"{r['total_ms']:.3f}",
              f"{r['mean_ms']:.3f}", f"{r['max_ms']:.3f}"] for r in trows])
    lines += ["", "## Per-kernel dispatch identity", ""]
    krows = kernel_table(counters)
    if krows:
        lines += _md_table(
            ["counter", "kernel", "site", "traced calls"],
            [[r["counter"], r["kernel"], r["site"], r["traced_calls"]]
             for r in krows])
    else:
        lines.append("(no kernel dispatches recorded)")
    coll = counters.get("collective_bytes", {})
    if coll:
        lines += ["", "## Collectives (trace-time payloads)", ""]
        lines += _md_table(
            ["op", "site", "bytes"],
            [[_split_tags(k).get("op", "?"), _split_tags(k).get("site", "-"),
              int(v)] for k, v in sorted(coll.items())])
    events_list = snap.get("events", [])
    if events_list:
        lines += ["", "## Structured events", ""]
        for e in events_list[-32:]:
            kind = e.get("event", "?")
            rest = {k: v for k, v in e.items() if k != "event"}
            lines.append(f"- `{kind}` {json.dumps(rest)}")
    gauges = snap.get("gauges", {})
    if gauges:
        lines += ["", "## Gauges", ""]
        for k, v in sorted(gauges.items()):
            lines.append(f"- `{k}` = {v}")
    lines.append("")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    if not argv:
        sys.stderr.write(
            "usage: python -m lightgbm_tpu.obs [--json] <trace.json[l]>\n")
        return 2
    path = argv[0]
    try:
        if as_json:
            events = load_events(path)
            print(json.dumps({
                "phases": phase_table(events),
                "summary": summary_payload(events, "counters") or {}},
                indent=1))
        else:
            print(render(path))
    except BrokenPipeError:      # `... | head` closing the pipe is fine
        try:
            sys.stdout.close()
        except Exception:
            pass
    return 0
