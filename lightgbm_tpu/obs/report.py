"""Render a telemetry trace into per-phase / per-kernel markdown tables.

``python -m lightgbm_tpu.obs <trace>...`` is the CLI wrapper.  Accepts
every format ``obs/trace.py`` writes: a Chrome-trace object
(``{"traceEvents": [...]}``), a bare JSON array, or JSONL (one event per
line — a killed process leaves a readable prefix, so partial files parse
too).  The trace is self-contained: the final ``telemetry.summary`` event
carries the counter-registry snapshot (kernel dispatch identity, layout
downgrades, collective bytes, memory gauges) alongside the span timeline.

Multiple trace files — one per process of a multi-host training — merge
into ONE report: every span is rank-tagged (``[r<k>] span``, from the
``proc`` stamp each event carries, falling back to file order) and the
per-file counter summaries render side by side, so cross-rank skew is
visible in a single phase table instead of needing N terminals.
"""
from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional

# --json output schema: 2 added the schema stamp itself plus the per-file
# serving_stats / hlo_collectives entries (the multi-rank merge parity of
# the markdown report); 3 added the per-file device_profile entry (the
# obs/devprof.py attribution block embedded as a telemetry.summary event);
# 4 added the per-file model_quality entry (obs/model_quality.py tracker
# summary: per-feature cumulative gain, gain-decay curve)
REPORT_SCHEMA_VERSION = 4


def load_events(path: str) -> List[dict]:
    with open(path) as f:
        text = f.read()
    text = text.strip()
    if not text:
        return []
    if path.endswith(".jsonl") or "\n" in text and not text.startswith(("[", "{")):
        events = []
        for line in text.splitlines():
            line = line.strip().rstrip(",")
            if not line or line in ("[", "]"):
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue                  # tolerate a torn tail line
        return events
    obj = json.loads(text)
    if isinstance(obj, dict):
        return list(obj.get("traceEvents", []))
    return list(obj)


def load_events_ranked(paths: List[str]) -> List[tuple]:
    """Load several trace files as ``[(path, rank, events), ...]``.

    The rank is the ``proc`` stamp the events carry (multi-host traces);
    when the stamps do not distinguish the files (e.g. two single-host
    runs, both proc 0), file order does."""
    loaded = []
    for i, p in enumerate(paths):
        events = load_events(p)
        procs = {e["proc"] for e in events if "proc" in e}
        loaded.append([p, procs.pop() if len(procs) == 1 else i, events])
    if len({r for _, r, _ in loaded}) < len(loaded):
        for i, entry in enumerate(loaded):
            entry[1] = i
    return [tuple(entry) for entry in loaded]


def summary_payload(events: List[dict], kind: str) -> Optional[dict]:
    """Last embedded ``telemetry.summary`` payload of the given kind."""
    out = None
    for ev in events:
        if ev.get("name") == "telemetry.summary":
            args = ev.get("args", {})
            if args.get("kind") == kind:
                out = args.get("payload")
    return out


def phase_table(events: List[dict],
                traced: Optional[bool] = None) -> List[Dict[str, Any]]:
    """Aggregate complete ("X") spans by name: count/total/mean/max (ms).

    ``traced`` filters on the span's ``traced`` arg: True keeps only
    TRACE-TIME spans (emitted from inside jit — they fire once per
    compilation and their durations include tracing/compile work), False
    keeps only host wall-clock spans, None keeps everything (the --json
    CLI view).  Host rows additionally carry ``first_ms`` (the
    chronologically first firing) and ``steady_mean_ms`` (mean of the
    rest): a first firing that dwarfs the steady state is the compile —
    totals that mix the two mislead (observed: a ``score`` phase showing
    11.2 s total of which 10.8 s was the first, compile-inclusive
    firing)."""
    agg: Dict[str, List[tuple]] = {}
    peak: Dict[str, int] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args", {})
        is_traced = bool(args.get("traced"))
        if traced is not None and is_traced != traced:
            continue
        agg.setdefault(ev["name"], []).append(
            (float(ev.get("ts", 0)), float(ev.get("dur", 0)) / 1e3))
        if "peak_bytes" in args:    # memory monitor phase annotation
            peak[ev["name"]] = max(peak.get(ev["name"], 0),
                                   int(args["peak_bytes"]))
    rows = []
    for name, spans in agg.items():
        spans.sort()
        durs = [d for _, d in spans]
        row = {"span": name, "count": len(durs),
               "total_ms": sum(durs),
               "mean_ms": sum(durs) / len(durs),
               "max_ms": max(durs)}
        if name in peak:
            row["peak_bytes"] = peak[name]
        if traced is False:
            rest = durs[1:]
            row["first_ms"] = durs[0]
            row["steady_mean_ms"] = (sum(rest) / len(rest)) if rest \
                else durs[0]
            row["compile_skewed"] = bool(
                rest and durs[0] > 3 * row["steady_mean_ms"])
        rows.append(row)
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def _split_tags(key: str) -> Dict[str, str]:
    return dict(kv.split("=", 1) for kv in key.split(",") if "=" in kv)


def kernel_table(counters: Dict[str, Dict[str, float]]) -> List[Dict[str, Any]]:
    rows = []
    for name in ("hist_dispatch",):
        for key, v in sorted(counters.get(name, {}).items()):
            tags = _split_tags(key)
            rows.append({"counter": name,
                         "kernel": tags.get("method", tags.get("impl", "?")),
                         "site": tags.get("site", "-"),
                         "traced_calls": int(v)})
    return rows


def observed_kernel(counters: Dict[str, Dict[str, float]]) -> Optional[str]:
    per: Dict[str, float] = {}
    for key, v in counters.get("hist_dispatch", {}).items():
        m = _split_tags(key).get("method")
        if m:
            per[m] = per.get(m, 0) + v
    return max(per, key=per.get) if per else None


def _md_table(headers: List[str], rows: List[List[Any]]) -> List[str]:
    out = ["| " + " | ".join(headers) + " |",
           "|" + "|".join("---" for _ in headers) + "|"]
    for r in rows:
        out.append("| " + " | ".join(str(c) for c in r) + " |")
    return out


def _memory_lines(snap: dict) -> List[str]:
    """The report's Memory section: predicted/measured gauges, the
    pre-flight verdict, executable memory-analysis events, top residents."""
    gauges = snap.get("gauges", {})
    events = snap.get("events", [])
    mem_gauges = {k: v for k, v in gauges.items()
                  if k.startswith(("memory_", "hbm_")) or (
                      k.startswith("exec_") and k.endswith("_bytes"))}
    preflight = [e for e in events if e.get("event") == "hbm_preflight"]
    summaries = [e for e in events if e.get("event") == "memory_summary"]
    execs = [e for e in events if e.get("event") == "exec_memory"]
    if not (mem_gauges or preflight or summaries or execs):
        return []
    lines = ["", "## Memory", ""]
    for k in sorted(mem_gauges):
        lines.append(f"- `{k}` = {mem_gauges[k] / 1e6:.2f} MB")
    for e in preflight[-1:]:
        lines.append(f"- pre-flight: `{e.get('verdict')}` "
                     f"(predicted {e.get('predicted_peak_bytes', 0) / 1e9:.3f}"
                     f" GB, capacity {e.get('capacity_bytes')}, "
                     f"hbm_budget {e.get('hbm_budget')})")
    for e in summaries[-1:]:
        lines.append(f"- measured peak ({e.get('source')}): "
                     f"{e.get('measured_peak_bytes', 0) / 1e6:.2f} MB; "
                     f"top residents: {e.get('top_residents')}")
    for e in execs:
        lines.append(f"- executable `{e.get('label')}`: "
                     f"temp {e.get('temp_bytes', 0) / 1e6:.2f} MB, "
                     f"peak {e.get('peak_bytes', 0) / 1e6:.2f} MB")
    return lines


def _serving_lines(events: List[dict],
                   counters: Dict[str, Dict[str, float]],
                   gauges: Dict[str, Any],
                   rank: Optional[int] = None) -> List[str]:
    """The report's Serving section: predict-executable dispatch identity
    (batch bucket + executable tag), the ``predict_jit_entries`` recompile
    gauge, and the server's per-bucket latency histograms/percentiles
    (the ``serving stats`` summary the ModelServer flushes at stop).
    ``rank`` titles the per-rank section of a multi-trace merge."""
    dispatch = counters.get("predict_dispatch", {})
    stats = summary_payload(events, "serving stats")
    jit_gauge = {k: v for k, v in gauges.items()
                 if k.endswith("predict_jit_entries")}
    if not (dispatch or stats):
        return []
    title = "## Serving / predict" + \
        (f" — rank {rank}" if rank is not None else "")
    lines = ["", title, ""]
    for k, v in sorted(jit_gauge.items()):
        lines.append(f"- `{k}` = {int(v)} compiled microbatch signature(s)")
    if dispatch:
        lines += ["", "Microbatch dispatches by (bucket, input path, "
                      "executable identity) — a warmed ladder must only "
                      "ever reuse these signatures:", ""]
        rows = []
        for key, v in sorted(dispatch.items(),
                             key=lambda kv: int(_split_tags(kv[0])
                                               .get("bucket", 0))):
            t = _split_tags(key)
            rows.append([t.get("bucket", "?"), t.get("path", "?"),
                         t.get("exec", "?"), int(v)])
        lines += _md_table(["bucket", "path", "executable", "dispatches"],
                           rows)
    if stats:
        lines += ["", f"Server totals: {stats.get('requests', 0)} requests "
                      f"/ {stats.get('rows', 0)} rows in "
                      f"{stats.get('batches', 0)} coalesced batches, "
                      f"{stats.get('qps', 0)} req/s, "
                      f"{stats.get('rows_per_s', 0)} rows/s, "
                      f"{stats.get('swaps', 0)} hot swap(s).", ""]
        rows = []
        hist_keys: List[str] = []
        for b, s in sorted(stats.get("buckets", {}).items(),
                           key=lambda kv: int(kv[0])):
            if not hist_keys:
                hist_keys = list(s.get("hist", {}))
            rows.append([b, s.get("count"), s.get("p50_ms"),
                         s.get("p99_ms"), s.get("max_ms")]
                        + [s.get("hist", {}).get(h, 0) for h in hist_keys])
        if rows:
            lines += _md_table(["bucket", "requests", "p50 ms", "p99 ms",
                                "max ms"] + hist_keys, rows)
    return lines


def _devprof_lines(events: List[dict],
                   rank: Optional[int] = None) -> List[str]:
    """The report's Device time section: the ``device_profile`` summary
    the devprof plane embeds (per-phase device ms, top ops, per-iteration
    host/device overlap) — the on-device answer the host span tables
    cannot give."""
    dp = summary_payload(events, "device_profile")
    if not dp:
        return []
    title = "## Device time (devprof attribution)" + \
        (f" — rank {rank}" if rank is not None else "")
    frac = dp.get("attributed_fraction")
    lines = ["", title, "",
             f"Captured {dp.get('captured_iterations', 0)} steady-state "
             f"iteration window(s) (first firing/compile excluded); "
             f"{dp.get('total_op_ms', 0):.1f} ms of device op time, "
             + (f"{frac:.1%} attributed to named phases."
                if isinstance(frac, (int, float))
                else "nothing attributable recorded."), ""]
    phases = dp.get("phase_device_ms", {})
    total = dp.get("total_op_ms") or 0
    if phases:
        lines += _md_table(
            ["phase", "device ms", "share"],
            [[p, f"{ms:.3f}", f"{ms / total:.1%}" if total else "-"]
             for p, ms in phases.items()])
    top = dp.get("top_ops", [])
    if top:
        lines += ["", "Top ops by device time:", ""]
        lines += _md_table(
            ["op", "phase", "ms", "count"],
            [[o.get("op"), o.get("phase"), f"{o.get('ms', 0):.3f}",
              o.get("count")] for o in top])
    iters = dp.get("iterations", [])
    if iters:
        lines += ["", "Per-iteration host↔device accounting (idle gap = "
                      "host window not covered by device work):", ""]
        lines += _md_table(
            ["iteration", "host ms", "device busy ms", "overlap",
             "idle gap"],
            [[it.get("iteration"), f"{it.get('host_ms', 0):.3f}",
              f"{it.get('device_busy_ms', 0):.3f}",
              f"{it.get('overlap_fraction', 0):.1%}",
              f"{it.get('idle_gap_fraction', 0):.1%}"] for it in iters])
    if dp.get("capture_failed"):
        lines += ["", "(capture failed mid-run — the table covers the "
                      "windows that completed)"]
    return lines


def _model_quality_lines(events: List[dict],
                         rank: Optional[int] = None) -> List[str]:
    """The report's Model quality section: the ``model_quality`` summary
    the tracker embeds at teardown — per-feature cumulative split gain
    (the what-did-the-model-learn answer) and the gain-decay curve (is
    more boosting still buying anything)."""
    mq = summary_payload(events, "model_quality")
    if not mq:
        return []
    title = "## Model quality" + \
        (f" — rank {rank}" if rank is not None else "")
    lines = ["", title, "",
             f"{mq.get('trees_seen', 0)} tree(s) audited.  Top features "
             "by cumulative split gain:", ""]
    top = mq.get("top_features", [])
    if top:
        total = sum(float(t.get("gain", 0)) for t in top) or 1.0
        lines += _md_table(
            ["feature", "gain", "share of top-K", "splits"],
            [[t.get("feature"), f"{float(t.get('gain', 0)):.4g}",
              f"{float(t.get('gain', 0)) / total:.1%}",
              t.get("splits")] for t in top])
    else:
        lines.append("(no splits audited)")
    curve = mq.get("gain_curve", [])
    if len(curve) >= 2:
        # decay verdict: last-quartile gain vs first-quartile gain — a
        # ratio near zero says late iterations stopped learning
        gains = [float(g) for _, g in curve]
        q = max(len(gains) // 4, 1)
        head, tail = sum(gains[:q]) / q, sum(gains[-q:]) / q
        lines += ["", f"Gain decay over {len(curve)} iteration(s): "
                      f"first-quartile mean {head:.4g} → last-quartile "
                      f"mean {tail:.4g}"
                      + (f" ({tail / head:.1%} retained)." if head > 0
                         else ".")]
    return lines


def render(path) -> str:
    paths = [path] if isinstance(path, str) else list(path)
    ranked = load_events_ranked(paths)
    multi = len(ranked) > 1
    if multi:
        # rank-tag every SPAN so the merged tables stay attributable; the
        # embedded telemetry.summary payloads keep their names (they are
        # read per-file below, never from the merged stream)
        events = [dict(ev, name=f"[r{rank}] {ev['name']}")
                  if ev.get("ph") == "X" else ev
                  for _, rank, evs in ranked for ev in evs]
        snap = {}
        counters = {}
        for _, rank, evs in ranked:
            rsnap = summary_payload(evs, "counters") or {}
            for name, buckets in rsnap.get("counters", {}).items():
                merged = counters.setdefault(name, {})
                for key, v in buckets.items():
                    merged[f"proc={rank}," + key if key
                           else f"proc={rank}"] = v
            for e in rsnap.get("events", []):
                snap.setdefault("events", []).append(e)
            for k, v in rsnap.get("gauges", {}).items():
                snap.setdefault("gauges", {})[f"[r{rank}] {k}"] = v
            snap["events_dropped"] = (snap.get("events_dropped", 0)
                                      + rsnap.get("events_dropped", 0))
    else:
        events = ranked[0][2]
        snap = summary_payload(events, "counters") or {}
        counters = snap.get("counters", {})
    title = ", ".join(f"`{p}` (rank {r})" for p, r, _ in ranked) if multi \
        else f"`{paths[0]}`"
    lines = [f"# lightgbm_tpu telemetry report — {title}", ""]
    if multi:
        for p, rank, evs in ranked:
            rsnap = summary_payload(evs, "counters") or {}
            obs = observed_kernel(rsnap.get("counters", {}))
            if obs is not None:
                lines.append(f"**rank {rank}** (`{p}`) observed histogram "
                             f"kernel identity: `{obs}`")
        if lines[-1] != "":
            lines.append("")
    obs = observed_kernel(counters)
    if obs is not None:
        lines += [f"**Observed histogram kernel identity:** `{obs}`", ""]
    lines += ["## Per-phase spans", "",
              "Host wall-clock spans (Chrome-trace `X` events).  A span "
              "whose FIRST firing dwarfs its steady state (marked "
              "`compile⚠`) included jit compilation — judge throughput "
              "by `steady mean`, not `total`.", ""]
    prows = phase_table(events, traced=False)
    if prows:
        with_peak = any("peak_bytes" in r for r in prows)
        headers = ["span", "count", "total ms", "first ms",
                   "steady mean ms", "max ms"]
        headers += (["peak MB", ""] if with_peak else [""])
        lines += _md_table(
            headers,
            [[r["span"], r["count"], f"{r['total_ms']:.3f}",
              f"{r['first_ms']:.3f}", f"{r['steady_mean_ms']:.3f}",
              f"{r['max_ms']:.3f}"]
             + ([f"{r['peak_bytes'] / 1e6:.1f}" if "peak_bytes" in r
                 else "-"] if with_peak else [])
             + ["compile⚠" if r["compile_skewed"] else ""] for r in prows])
    else:
        lines.append("(no spans recorded)")
    trows = phase_table(events, traced=True)
    if trows:
        lines += ["", "## Trace-time spans (compile-inclusive)", "",
                  "Spans emitted from INSIDE jitted code fire once per "
                  "compilation — durations measure tracing/compile work, "
                  "never steady-state execution (the on-device twin is "
                  "the `jax.named_scope` XProf attribution).", ""]
        lines += _md_table(
            ["span", "count", "total ms", "mean ms", "max ms"],
            [[r["span"], r["count"], f"{r['total_ms']:.3f}",
              f"{r['mean_ms']:.3f}", f"{r['max_ms']:.3f}"] for r in trows])
    lines += ["", "## Per-kernel dispatch identity", ""]
    krows = kernel_table(counters)
    if krows:
        lines += _md_table(
            ["counter", "kernel", "site", "traced calls"],
            [[r["counter"], r["kernel"], r["site"], r["traced_calls"]]
             for r in krows])
    else:
        lines.append("(no kernel dispatches recorded)")
    coll = counters.get("collective_bytes", {})
    if coll:
        lines += ["", "## Collectives (trace-time payloads)", ""]
        lines += _md_table(
            ["op", "site", "bytes"],
            [[_split_tags(k).get("op", "?"), _split_tags(k).get("site", "-"),
              int(v)] for k, v in sorted(coll.items())])
    hlo_calls = counters.get("hlo_collective_calls", {})
    if hlo_calls:
        # compiler-inserted collectives (GSPMD): call-site counters can't
        # see these — the census reads the compiled executable
        # (obs/collectives.hlo_census, docs/DISTRIBUTED.md).  In a
        # multi-trace merge the counter keys carry the proc tag, so the
        # table keeps every rank's census attributable
        hlo_bytes = counters.get("hlo_collective_bytes", {})
        with_proc = any("proc=" in k for k in hlo_calls)
        lines += ["", "## Compiled-HLO collective census "
                  "(compiler-inserted)", ""]
        lines += _md_table(
            (["rank"] if with_proc else []) + ["op", "executable", "ops",
                                               "bytes"],
            [([_split_tags(k).get("proc", "-")] if with_proc else [])
             + [_split_tags(k).get("op", "?"),
                _split_tags(k).get("label", "-"), int(v),
                int(hlo_bytes.get(k, 0))]
             for k, v in sorted(hlo_calls.items())])
    if multi:
        # per-rank serving sections: the stats payload is per-file (one
        # serving process per trace), so it must never merge/overwrite —
        # PR 5 left this section single-trace only
        for p, rank, evs in ranked:
            rsnap = summary_payload(evs, "counters") or {}
            lines += _serving_lines(evs, rsnap.get("counters", {}),
                                    rsnap.get("gauges", {}), rank=rank)
    else:
        lines += _serving_lines(events, counters, snap.get("gauges", {}))
    if multi:
        for p, rank, evs in ranked:
            lines += _devprof_lines(evs, rank=rank)
            lines += _model_quality_lines(evs, rank=rank)
    else:
        lines += _devprof_lines(events)
        lines += _model_quality_lines(events)
    lines += _memory_lines(snap)
    events_list = snap.get("events", [])
    if events_list:
        lines += ["", "## Structured events", ""]
        dropped = snap.get("events_dropped", 0)
        if dropped:
            lines += [f"(ring buffer overflowed: {dropped} oldest events "
                      "dropped)", ""]
        for e in events_list[-32:]:
            kind = e.get("event", "?")
            rest = {k: v for k, v in e.items() if k != "event"}
            lines.append(f"- `{kind}` {json.dumps(rest)}")
    gauges = snap.get("gauges", {})
    if gauges:
        lines += ["", "## Gauges", ""]
        for k, v in sorted(gauges.items()):
            lines.append(f"- `{k}` = {v}")
    lines.append("")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    if not argv:
        sys.stderr.write(
            "usage: python -m lightgbm_tpu.obs [--json] "
            "<trace.json[l]> [<trace2> ...]\n")
        return 2
    try:
        if as_json:
            # machine-readable: one entry per file (rank-tagged) so
            # tpu_capture_phase2.sh / decide_flips.py consume reports
            # without re-parsing markdown
            files = []
            for p, rank, events in load_events_ranked(argv):
                summary = summary_payload(events, "counters") or {}
                files.append({
                    "path": p, "rank": rank,
                    "phases": phase_table(events),
                    "observed_kernel": observed_kernel(
                        summary.get("counters", {})),
                    "memory": {
                        k: v for k, v in summary.get("gauges", {}).items()
                        if k.startswith(("memory_", "hbm_", "exec_"))},
                    # per-rank serving + census entries (the merged-report
                    # parity): one serving process per trace file
                    "serving_stats": summary_payload(events,
                                                     "serving stats"),
                    "device_profile": summary_payload(events,
                                                      "device_profile"),
                    "model_quality": summary_payload(events,
                                                     "model_quality"),
                    "hlo_collectives": summary.get("counters", {}).get(
                        "hlo_collective_calls", {}),
                    "events_dropped": summary.get("events_dropped", 0),
                    "summary": summary})
            doc = files[0] if len(files) == 1 else {"files": files}
            doc["schema_version"] = REPORT_SCHEMA_VERSION
            print(json.dumps(doc, indent=1))
        else:
            print(render(argv))
    except BrokenPipeError:      # `... | head` closing the pipe is fine
        try:
            sys.stdout.close()
        except Exception:
            pass
    return 0
