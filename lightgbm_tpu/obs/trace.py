"""Nested-span tracer: Chrome-trace JSON/JSONL out, XProf-correlated.

The reference only ever had ``#ifdef TIMETAG`` chrono counters
(``serial_tree_learner.cpp:10-37``); here the evidence is produced by the
library itself.  One process-wide active tracer (module functions
:func:`start` / :func:`stop` / :func:`get_tracer`):

* **disabled** (the default) it is a :class:`NullTracer` whose ``span()``
  returns ONE shared no-op context manager — the hot-loop cost of an
  instrumented phase is a dict lookup and two no-op calls, no allocation
  (pinned by ``tests/test_obs.py::test_disabled_tracer_is_allocation_free``);
* **enabled** it records wall-clock spans as Chrome trace events
  (``ph: "X"``, microsecond ``ts``/``dur``) and mirrors every span into
  ``jax.profiler.TraceAnnotation`` so host spans line up with XProf
  captures taken via ``profile_dir`` on-chip.

Output format follows the Chrome Trace Event spec: a ``*.jsonl`` path gets
one event object per line (append-friendly, crash-tolerant — a killed
child still leaves a readable prefix); any other path gets the standard
``{"traceEvents": [...], "otherData": {...}}`` object.  Counter/summary
payloads (the :mod:`lightgbm_tpu.obs.counters` snapshot, phase-timer
totals) are embedded as instant events named ``telemetry.summary`` so one
file carries the whole story; ``obs/report.py`` renders it.

Spans emitted from inside jitted code (the grower) fire at TRACE time —
once per compilation, not per execution; their on-device counterpart is
the ``jax.named_scope`` annotation baked into the lowered HLO, which XProf
attributes per kernel launch.  ``obs/report.py`` labels them accordingly.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

# resolved lazily; False once probing failed (jax absent / too old)
_TraceAnnotation: Any = None

# process index resolved once (multi-host traces from different ranks must
# stay distinguishable after they are merged into one report)
_PROC: Any = None


def process_index() -> int:
    global _PROC
    if _PROC is None:
        try:
            import jax
            _PROC = int(jax.process_index())
        except Exception:
            _PROC = 0
    return _PROC


def _jax_annotation(name: str):
    global _TraceAnnotation
    if _TraceAnnotation is None:
        try:
            from jax.profiler import TraceAnnotation as ta
            _TraceAnnotation = ta
        except Exception:  # pragma: no cover - jax is a hard dep here
            _TraceAnnotation = False
    return _TraceAnnotation(name) if _TraceAnnotation else None


class _NullSpan:
    """Shared no-op context manager (the disabled fast path)."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class NullTracer:
    """Disabled tracer: every operation is a no-op; ``span()`` hands back
    the one shared :data:`NULL_SPAN` so the instrumented hot loops never
    allocate when telemetry is off."""
    enabled = False
    path: Optional[str] = None

    def span(self, name: str, **args):
        return NULL_SPAN

    def instant(self, name: str, **args) -> None:
        pass

    def summary(self, name: str, payload: Dict[str, Any]) -> None:
        pass

    def events(self) -> List[dict]:
        return []


NULL_TRACER = NullTracer()


class _Span:
    __slots__ = ("_tr", "_name", "_args", "_ts", "_jax")

    def __init__(self, tracer: "Tracer", name: str, args: dict):
        self._tr = tracer
        self._name = name
        self._args = args
        self._ts = 0.0
        self._jax = None

    def __enter__(self):
        ann = _jax_annotation(self._name)
        if ann is not None:
            ann.__enter__()
            self._jax = ann
        self._ts = self._tr._now_us()
        return self

    def __exit__(self, *exc):
        dur = self._tr._now_us() - self._ts
        if self._jax is not None:
            self._jax.__exit__(*exc)
        ev = {"name": self._name, "ph": "X", "ts": round(self._ts, 3),
              "dur": round(dur, 3), "pid": self._tr.pid,
              "proc": self._tr.proc, "tid": threading.get_ident()}
        if self._args:
            ev["args"] = self._args
        self._tr._append(ev)
        return False


class Tracer:
    """Recording tracer.  Thread-safe; timestamps are microseconds since
    construction (``perf_counter`` based, like the phase timers)."""
    enabled = True

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.pid = os.getpid()
        self.proc = process_index()
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._events: List[dict] = []

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _append(self, ev: dict) -> None:
        with self._lock:
            self._events.append(ev)

    def span(self, name: str, **args) -> _Span:
        """Context manager recording one complete ("X") event; nesting is
        expressed through ts/dur containment, exactly how Chrome/Perfetto
        rebuild the flame graph."""
        return _Span(self, name, args)

    def instant(self, name: str, **args) -> None:
        ev = {"name": name, "ph": "i", "s": "p", "ts": round(self._now_us(), 3),
              "pid": self.pid, "proc": self.proc,
              "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._append(ev)

    def summary(self, name: str, payload: Dict[str, Any]) -> None:
        """Attach a structured summary payload (phase-timer totals, counter
        snapshots) as a ``telemetry.summary`` instant event."""
        self.instant("telemetry.summary", kind=name, **{"payload": payload})

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def write(self, path: Optional[str] = None) -> Optional[str]:
        """Serialize to ``path`` (default: the constructor path).  Embeds a
        final summary event carrying the current counter-registry snapshot
        so the trace file is self-contained."""
        path = path or self.path
        from .counters import counters  # lazy: avoid import cycles
        from . import metrics as obs_metrics
        # the live-scrape view rides along so obs_diff can compare two
        # traces at the metrics level without a /metrics endpoint
        self.summary("metrics", obs_metrics.snapshot())
        self.summary("counters", counters.snapshot())
        if not path:
            return None
        events = self.events()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            if path.endswith(".jsonl"):
                for ev in events:
                    f.write(json.dumps(ev) + "\n")
            else:
                json.dump({"traceEvents": events,
                           "otherData": {"producer": "lightgbm_tpu.obs"}}, f)
        return path


_active: Any = NULL_TRACER


def get_tracer():
    """The process-wide active tracer (NullTracer when telemetry is off)."""
    return _active


def start(path: Optional[str] = None) -> Tracer:
    """Install a recording tracer as the process-wide active one."""
    global _active
    _active = Tracer(path)
    return _active


def stop() -> Optional[str]:
    """Write the active trace (if it has a path) and disable tracing.
    Returns the written path, or None."""
    global _active
    tr, _active = _active, NULL_TRACER
    if isinstance(tr, Tracer):
        return tr.write()
    return None


@contextlib.contextmanager
def tracing(path: Optional[str] = None):
    """``with tracing("t.json"):`` — enable for a block, write on exit."""
    tr = start(path)
    try:
        yield tr
    finally:
        stop()
