"""Device-time attribution: programmatic profiler capture + phase accounting.

Every host-side span the obs stack records (``obs/trace.py``,
``utils/timer.py``) measures wall-clock around an *async dispatch* — on an
accelerator it cannot say where device time actually goes.  This module
closes that gap without XProf-in-a-browser: it arms ``jax.profiler``
capture windows over steady-state boosting iterations, parses the emitted
trace-event artifacts on the host, and attributes device op time to the
``jax.named_scope`` phase twins the kernels already carry (``histogram``
root/split, ``split_find``, ``partition``, ``fused_panel``, the serving
``traverse``) — falling back to the host ``TraceAnnotation`` phase
windows (``boosting``/``bagging``/``tree``/``score``/...) that
``obs/trace.py`` mirrors into every capture.

Capture discipline follows the PhaseTimers convention: the FIRST firing
seen is the compile and is never captured; the next ``profile_iters``
steady-state iterations each get their own start/stop window, parsed
immediately so the per-iteration idle-gap fraction is known before the
flight-recorder progress record for that iteration is written.

Disarmed (the default) the plane is :data:`NULL_DEVPROF` — one shared
no-op whose ``iteration()`` returns the shared :data:`NULL_WINDOW`; the
hot-loop cost is an attribute read and two no-op calls, no allocation
(pinned by ``tests/test_devprof.py``).  Armed, the capture overhead is
explicit and bounded: ``profile_iters`` windows, then the profiler is
never touched again.

The parsing layer (:func:`load_trace_events`, :func:`op_events`,
:func:`phase_windows`, :func:`attribute`) is pure — tier-1 tests feed it
synthetic trace-event fixtures, no TPU required.  ``scripts/
bench_history.py`` reuses the same loader for longitudinal artifacts.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import re
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils import log
from .counters import counters

SCHEMA_VERSION = 1

# device-side named_scope twins baked into the lowered HLO; XProf-style
# artifacts carry them in op names / tf_op metadata ("scope attribution")
SCOPE_PHASES = ("histogram", "split_find", "partition", "fused_panel",
                "traverse")
# host-side TraceAnnotation windows obs/trace.py mirrors into captures
# ("window attribution" — the CPU/sync fallback when scope names are
# fused away or the backend does not label ops)
HOST_PHASES = ("histogram", "split_find", "partition", "fused_panel",
               "boosting", "bagging", "tree", "score", "metric",
               "predict_bin", "predict_traverse", "predict_margin",
               "serving_batch")

_SCOPE_RE = re.compile(
    r"(?:^|[/ .])(" + "|".join(SCOPE_PHASES) + r")(?:[/ .\d]|$)")

TOP_K = 10


# --------------------------------------------------------------- parsing


def load_trace_events(path: str) -> List[dict]:
    """Trace events from a Chrome-trace artifact: ``.json`` / ``.json.gz``
    holding ``{"traceEvents": [...]}`` or a bare list, or ``.jsonl`` with
    one event per line (torn tails tolerated, like obs/report.py)."""
    opener = gzip.open if path.endswith(".gz") else open
    if path.endswith(".jsonl"):
        events = []
        with opener(path, "rt") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    break  # torn tail from a killed writer
        return events
    with opener(path, "rt") as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        return list(doc.get("traceEvents", []))
    return list(doc) if isinstance(doc, list) else []


def find_capture_files(log_dir: str) -> List[str]:
    """The Chrome-trace artifacts of a ``jax.profiler`` capture directory
    (``plugins/profile/<run>/<host>.trace.json.gz``), newest run last."""
    pats = (os.path.join(log_dir, "plugins", "profile", "*", "*.trace.json*"),
            os.path.join(log_dir, "*.trace.json*"))
    out: List[str] = []
    for pat in pats:
        out.extend(sorted(glob.glob(pat), key=os.path.getmtime))
    return out


def _is_device_pid(ev: dict, device_pids: set) -> bool:
    return ev.get("pid") in device_pids


def _device_pids(events: List[dict]) -> set:
    """Process ids the profiler labels as device streams (TPU/GPU planes:
    ``process_name`` metadata like "/device:TPU:0 ...")."""
    pids = set()
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            name = str((ev.get("args") or {}).get("name", ""))
            if "/device:" in name.lower() or "xla ops" in name.lower():
                pids.add(ev.get("pid"))
    return pids


def op_events(events: List[dict]) -> List[dict]:
    """Complete ("X") events that represent device/XLA op executions:
    events on a device-labelled pid, or host-backend events tagged with an
    ``hlo_op`` arg (the XLA:CPU form).  Python-tracer frames (``$``-prefixed
    names) and untagged host activity are excluded."""
    device_pids = _device_pids(events)
    out = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = str(ev.get("name", ""))
        if name.startswith("$"):
            continue
        args = ev.get("args") or {}
        if _is_device_pid(ev, device_pids) or "hlo_op" in args:
            out.append(ev)
    return out


def phase_windows(events: List[dict]) -> List[Tuple[float, float, str]]:
    """Host phase windows ``(ts, end, phase)`` from the TraceAnnotation
    mirror of obs tracer spans, sorted by start time."""
    wins = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = str(ev.get("name", ""))
        if name in HOST_PHASES:
            ts = float(ev.get("ts", 0.0))
            dur = float(ev.get("dur", 0.0))
            wins.append((ts, ts + dur, name))
    wins.sort()
    return wins


def _scope_phase(ev: dict) -> Optional[str]:
    """Phase from the named_scope token in the op name or its metadata
    (TPU/GPU traces carry the scope path in ``tf_op``/``long_name``)."""
    hay = [str(ev.get("name", ""))]
    for v in (ev.get("args") or {}).values():
        if isinstance(v, str):
            hay.append(v)
    for h in hay:
        m = _SCOPE_RE.search(h)
        if m:
            return m.group(1)
    return None


def _window_phase(ev: dict,
                  wins: List[Tuple[float, float, str]]) -> Optional[str]:
    """Fallback attribution: the innermost host window containing the op's
    midpoint; else the window with maximal time overlap; else the last
    window dispatched before the op began (async dispatch ordering)."""
    ts = float(ev.get("ts", 0.0))
    end = ts + float(ev.get("dur", 0.0))
    mid = (ts + end) / 2.0
    containing = [w for w in wins if w[0] <= mid <= w[1]]
    if containing:
        return min(containing, key=lambda w: w[1] - w[0])[2]
    best, best_ov = None, 0.0
    for w in wins:
        ov = min(end, w[1]) - max(ts, w[0])
        if ov > best_ov:
            best, best_ov = w[2], ov
    if best:
        return best
    before = [w for w in wins if w[0] <= ts]
    return before[-1][2] if before else None


def _busy_us(ops: List[dict], t0: Optional[float] = None,
             t1: Optional[float] = None) -> float:
    """Union length (µs) of the op intervals, optionally clipped to
    [t0, t1] — device busy time without double-counting overlap."""
    spans = []
    for ev in ops:
        a = float(ev.get("ts", 0.0))
        b = a + float(ev.get("dur", 0.0))
        if t0 is not None:
            a = max(a, t0)
        if t1 is not None:
            b = min(b, t1)
        if b > a:
            spans.append((a, b))
    spans.sort()
    busy, cur_a, cur_b = 0.0, None, None
    for a, b in spans:
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                busy += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        busy += cur_b - cur_a
    return busy


def attribute(events: List[dict], top_k: int = TOP_K,
              ops: Optional[List[dict]] = None) -> Dict[str, Any]:
    """Attribute device op time to named phases.

    Scope-token attribution first (named_scope twins in op names/metadata),
    host-window fallback second.  Returns the per-phase device-ms table,
    the top-K op list, totals, and the attributed fraction.

    ``ops`` bypasses :func:`op_events` with already-classified op events —
    required when ``events`` no longer carries the ``process_name``
    metadata that identifies device pids (the armed profiler's retained
    state)."""
    if ops is None:
        ops = op_events(events)
    wins = phase_windows(events)
    phase_us: Dict[str, float] = {}
    per_op: Dict[Tuple[str, str], Dict[str, float]] = {}
    attributed = 0.0
    total = 0.0
    for ev in ops:
        dur = float(ev.get("dur", 0.0))
        total += dur
        phase = _scope_phase(ev) or _window_phase(ev, wins)
        if phase:
            phase_us[phase] = phase_us.get(phase, 0.0) + dur
            attributed += dur
        key = (str(ev.get("name", "")), phase or "(unattributed)")
        agg = per_op.setdefault(key, {"us": 0.0, "count": 0})
        agg["us"] += dur
        agg["count"] += 1
    top = sorted(per_op.items(), key=lambda kv: -kv[1]["us"])[:top_k]
    return {
        "phase_device_ms": {p: round(us / 1e3, 4)
                            for p, us in sorted(phase_us.items(),
                                                key=lambda kv: -kv[1])},
        "top_ops": [{"op": name, "phase": phase,
                     "ms": round(agg["us"] / 1e3, 4),
                     "count": int(agg["count"])}
                    for (name, phase), agg in top],
        "op_count": len(ops),
        "total_op_ms": round(total / 1e3, 4),
        "attributed_ms": round(attributed / 1e3, 4),
        "attributed_fraction": round(attributed / total, 4) if total else None,
        "device_busy_ms": round(_busy_us(ops) / 1e3, 4),
    }


# ------------------------------------------------------------- profiler


class _NullWindow:
    """Shared no-op iteration context (the disarmed fast path)."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_WINDOW = _NullWindow()


class NullDeviceProfiler:
    """Disarmed plane: every operation a no-op, ``iteration()`` hands back
    the one shared :data:`NULL_WINDOW` — zero allocation in the loop."""
    enabled = False

    def iteration(self, index: int = 0):
        return NULL_WINDOW

    def pop_idle_gap(self) -> Optional[float]:
        return None

    def summary(self) -> Optional[Dict[str, Any]]:
        return None


NULL_DEVPROF = NullDeviceProfiler()


class _IterWindow:
    __slots__ = ("_dp", "_index")

    def __init__(self, dp: "DeviceProfiler", index: int):
        self._dp = dp
        self._index = index

    def __enter__(self):
        self._dp._enter(self._index)
        return self

    def __exit__(self, *exc):
        self._dp._exit(self._index)
        return False


class DeviceProfiler:
    """Armed plane: one ``jax.profiler`` start/stop window per captured
    steady-state iteration, parsed immediately on stop."""
    enabled = True

    def __init__(self, log_dir: Optional[str] = None, profile_iters: int = 2,
                 keep_artifacts: bool = False, top_k: int = TOP_K):
        self._own_dir = log_dir is None
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="lgbm_devprof_")
        self.profile_iters = max(1, int(profile_iters))
        self.keep_artifacts = keep_artifacts
        self.top_k = top_k
        self._seen = 0            # firings observed (first = compile, skipped)
        self._capturing = False
        self._failed = False
        self._t_start = 0.0
        self._cur_dir = ""
        self._last_gap: Optional[float] = None
        self.iterations: List[Dict[str, Any]] = []
        # classified per-window, kept separately: device-pid ops are only
        # identifiable while the process_name metadata is at hand, so
        # summary() must never re-run op_events() over retained state
        self._ops: List[dict] = []          # op events, all windows
        self._host_events: List[dict] = []  # host phase-window events

    # ----------------------------------------------------- window control

    def iteration(self, index: int = 0) -> _IterWindow:
        return _IterWindow(self, index)

    def _enter(self, index: int) -> None:
        self._seen += 1
        if (self._seen <= 1 or self._failed
                or len(self.iterations) >= self.profile_iters):
            return  # compile firing / already done / profiler unusable
        self._cur_dir = os.path.join(self.log_dir, "iter_%05d" % index)
        try:
            import jax
            jax.profiler.start_trace(self._cur_dir)
        except Exception as exc:  # profiler busy (profile_dir) or absent
            self._failed = True
            log.warning("devprof: start_trace failed, device-time "
                        "attribution disabled for this run: %s", exc)
            return
        self._capturing = True
        self._t_start = time.perf_counter()

    def _exit(self, index: int) -> None:
        if not self._capturing:
            return
        self._capturing = False
        host_s = time.perf_counter() - self._t_start
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as exc:
            self._failed = True
            log.warning("devprof: stop_trace failed: %s", exc)
            return
        events: List[dict] = []
        for path in find_capture_files(self._cur_dir):
            try:
                events.extend(load_trace_events(path))
            except Exception as exc:
                log.warning("devprof: unreadable artifact %s: %s", path, exc)
        ops = op_events(events)
        busy_us = _busy_us(ops)
        # host_ms spans start_trace-return to stop_trace-call, so any
        # profiler-induced host overhead inside the window counts as idle
        # gap — on short iterations idle_gap_fraction is biased high
        host_us = host_s * 1e6
        overlap = min(1.0, busy_us / host_us) if host_us > 0 else 0.0
        gap = round(max(0.0, 1.0 - overlap), 4)
        self._last_gap = gap
        self._ops.extend(ops)
        self._host_events.extend(
            ev for ev in events
            if ev.get("ph") == "X" and str(ev.get("name")) in HOST_PHASES)
        self.iterations.append({
            "iteration": int(index),
            "host_ms": round(host_s * 1e3, 4),
            "device_busy_ms": round(busy_us / 1e3, 4),
            "overlap_fraction": round(overlap, 4),
            "idle_gap_fraction": gap,
        })
        counters.event("devprof_capture", iteration=int(index),
                       ops=len(ops), device_busy_ms=round(busy_us / 1e3, 3),
                       idle_gap_fraction=gap)
        from . import metrics as obs_metrics
        obs_metrics.note_capture()
        if not self.keep_artifacts:
            shutil.rmtree(self._cur_dir, ignore_errors=True)

    # ---------------------------------------------------------- reporting

    def pop_idle_gap(self) -> Optional[float]:
        """The just-captured iteration's idle-gap fraction, once (the
        flight-recorder progress record consumes it)."""
        gap, self._last_gap = self._last_gap, None
        return gap

    def summary(self) -> Optional[Dict[str, Any]]:
        """The schema-versioned ``device_profile`` block: attribution over
        every captured window, plus the per-iteration accounting."""
        block: Dict[str, Any] = {
            "schema_version": SCHEMA_VERSION,
            "source": "jax.profiler",
            "profile_iters": self.profile_iters,
            "captured_iterations": len(self.iterations),
            "iterations": list(self.iterations),
        }
        if self._failed:
            block["capture_failed"] = True
        block.update(attribute(self._host_events, top_k=self.top_k,
                               ops=self._ops))
        return block

    def finalize(self) -> Optional[Dict[str, Any]]:
        if self._capturing:  # training aborted mid-window
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._capturing = False
        out = self.summary()
        if self._own_dir and not self.keep_artifacts:
            shutil.rmtree(self.log_dir, ignore_errors=True)
        return out


# ------------------------------------------------- process-wide singleton

_active: Any = NULL_DEVPROF
_last_summary: Optional[Dict[str, Any]] = None


def get_devprof():
    """The process-wide device profiler (NULL_DEVPROF when disarmed)."""
    return _active


def start(log_dir: Optional[str] = None, profile_iters: int = 2,
          keep_artifacts: bool = False) -> DeviceProfiler:
    """Arm the device-time attribution plane process-wide."""
    global _active
    if isinstance(_active, DeviceProfiler):
        stop()
    _active = DeviceProfiler(log_dir=log_dir, profile_iters=profile_iters,
                             keep_artifacts=keep_artifacts)
    return _active


def stop() -> Optional[Dict[str, Any]]:
    """Disarm; returns (and stashes) the final ``device_profile`` block."""
    global _active, _last_summary
    dp, _active = _active, NULL_DEVPROF
    if isinstance(dp, DeviceProfiler):
        _last_summary = dp.finalize()
        return _last_summary
    return None


def last_summary() -> Optional[Dict[str, Any]]:
    """The most recent finalized ``device_profile`` block (bench embeds
    it after ``stop()``)."""
    return _last_summary
