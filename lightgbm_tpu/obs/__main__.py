"""CLI: ``python -m lightgbm_tpu.obs <trace.json[l]>``."""
import sys

from .report import main

sys.exit(main())
