"""Live metrics plane: a process-wide Prometheus view of the telemetry
registry, scrapeable WHILE training/serving runs.

Every earlier obs leg (PR 2 counters/spans, PR 5 memory, PR 10 HLO census)
is post-hoc: written at ``train()`` exit, read from files.  This module
derives one *live* metrics view from the same sources — the
:mod:`lightgbm_tpu.obs.counters` counters/gauges, the
``utils/timer.PhaseTimers`` steady-state means, memory peaks, and the
serving per-bucket latency stats — rendered in the Prometheus text
exposition format (``text/plain; version=0.0.4``), and serves it two ways:

* ``GET /metrics`` on the serving HTTP front
  (:mod:`lightgbm_tpu.serving`);
* a standalone exporter thread armed by the ``metrics_port`` param in
  ``engine.train`` (bound at ``metrics_port + rank`` so multi-rank groups
  never collide) and in the supervisor (which also exposes its restart
  budget and per-rank heartbeat ages).

Everything a scrape reads is host-side state — counter dicts, wall-clock
totals, reservoir summaries.  Rendering never touches a device, issues a
collective, or blocks the training loop (the PR 6-style zero-added-
collectives pin extends over an armed exporter; ``tests/test_metrics.py``).
Disarmed, the active exporter is the shared :data:`NULL_EXPORTER`
singleton (the ``obs/trace.py`` discipline): arming is the only thing
that allocates.

Components register live sample *sources* (:func:`register_source`, weakly
referenced like ``obs/memory.register_residents``): the boosting driver
contributes phase-timer families, a ``ModelServer`` its per-bucket latency
histograms, the supervisor its restart/heartbeat gauges.  A source
returns ``[(name, labels, value, type), ...]``; names are prefixed
``lgbm_tpu_`` and sanitized at render time.
"""
from __future__ import annotations

import json
import re
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from .counters import counters

PREFIX = "lgbm_tpu_"
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
# stamped into snapshot() blocks (bench JSONs, obs_diff artifacts) so a
# consumer can tell when the sample vocabulary changed shape
SCHEMA_VERSION = 1

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_OK = re.compile(r"[^a-zA-Z0-9_]")


def sanitize_name(name: str) -> str:
    name = _NAME_OK.sub("_", str(name))
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def _label_value(v: Any) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _format_labels(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_LABEL_OK.sub("_", str(k))}="{_label_value(v)}"'
        for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt(v: Any) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def _split_tags(key: str) -> Dict[str, str]:
    return dict(kv.split("=", 1) for kv in key.split(",") if "=" in kv)


# ------------------------------------------------------------------ sources

# weakly referenced zero-arg callables returning
# [(name, labels, value, type), ...]; dead components drop out on render
_sources: List[Any] = []


def register_source(fn: Callable[[], list]) -> None:
    """Register a live sample source (bound methods via ``WeakMethod`` so
    a source never keeps its component alive)."""
    try:
        ref = weakref.WeakMethod(fn)
    except TypeError:
        ref = weakref.ref(fn)
    _sources.append(ref)


def _collect_sources() -> List[Tuple[str, Dict[str, Any], float, str]]:
    out: List[Tuple[str, Dict[str, Any], float, str]] = []
    live = []
    for ref in _sources:
        fn = ref()
        if fn is None:
            continue
        live.append(ref)
        try:
            out.extend(fn())
        except Exception:
            # a scrape must never fail because one component is mid-
            # teardown; the remaining families still render
            continue
    _sources[:] = live
    return out


# ------------------------------------------------------------ capture age

# wall-clock of the newest on-chip evidence (a devprof capture window or
# an explicitly noted profile/capture artifact); None = never this process
_last_capture_ts: Optional[float] = None


def note_capture(ts: Optional[float] = None) -> None:
    """Record that fresh device-profile evidence was just captured
    (called by :mod:`lightgbm_tpu.obs.devprof` per completed window)."""
    global _last_capture_ts
    _last_capture_ts = time.time() if ts is None else float(ts)


def last_capture_age() -> float:
    """Seconds since the newest capture, or -1 when none happened — the
    ROADMAP capture-backlog early warning: a scrape answers "is the
    on-chip evidence stale?" without reading artifacts."""
    if _last_capture_ts is None:
        return -1.0
    # whole-second resolution: staleness is a minutes/hours question, and
    # back-to-back scrapes (snapshot vs a live GET) must agree sample-wise
    return float(int(max(0.0, time.time() - _last_capture_ts)))


# ---------------------------------------------------------------- rendering


def _families() -> Dict[str, Tuple[str, Dict[str, float]]]:
    """The full metrics view as ``{metric: (type, {label_str: value})}``.

    Counter families (registry counters + source counters) sum across
    duplicate series (two boosters contributing the same phase counter);
    gauge duplicates resolve last-wins.
    """
    fams: Dict[str, Tuple[str, Dict[str, float]]] = {}

    def add(name: str, labels: Dict[str, Any], value: float,
            mtype: str) -> None:
        metric = PREFIX + sanitize_name(name)
        if mtype == "counter" and not metric.endswith("_total"):
            metric += "_total"
        mtype0, series = fams.setdefault(metric, (mtype, {}))
        key = _format_labels(labels)
        if mtype0 == "counter" and key in series:
            series[key] += float(value)
        else:
            series[key] = float(value)

    snap = counters.snapshot()
    for name, buckets in snap["counters"].items():
        for key, v in buckets.items():
            add(name, _split_tags(key), v, "counter")
    for name, v in snap["gauges"].items():
        add(name, {}, v, "gauge")
    add("events_dropped", {}, snap["events_dropped"], "counter")
    add("process_index", {}, snap["process_index"], "gauge")
    add("last_capture_age_seconds", {}, last_capture_age(), "gauge")
    for name, labels, value, mtype in _collect_sources():
        add(name, dict(labels or {}), value, mtype)
    return fams


def render_prometheus() -> str:
    """The whole metrics view in Prometheus text exposition format."""
    lines: List[str] = []
    for metric, (mtype, series) in sorted(_families().items()):
        lines.append(f"# TYPE {metric} "
                     f"{'counter' if mtype == 'counter' else 'gauge'}")
        for key, v in sorted(series.items()):
            lines.append(f"{metric}{key} {_fmt(v)}")
    return "\n".join(lines) + "\n"


def snapshot() -> Dict[str, Any]:
    """Machine-readable twin of :func:`render_prometheus`: a flat
    ``{"<metric>{labels}": value}`` sample map plus the schema version —
    what ``bench.py`` embeds as the ``metrics_snapshot`` block and
    ``scripts/obs_diff.py`` compares."""
    samples: Dict[str, float] = {}
    for metric, (_, series) in _families().items():
        for key, v in series.items():
            samples[metric + key] = v
    return {"schema_version": SCHEMA_VERSION, "samples": samples}


def parse_prometheus(text: str) -> Dict[str, float]:
    """Inverse of :func:`render_prometheus` (sample-name fidelity only):
    ``{"metric{labels}": value}``.  Comment/blank lines are skipped;
    malformed lines are tolerated (a torn scrape is still comparable)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            key, val = line.rsplit(" ", 1)
            out[key] = float(val)
        except ValueError:
            continue
    return out


# ----------------------------------------------------------------- exporter


class NullExporter:
    """Disarmed exporter (the shared no-op singleton)."""
    enabled = False
    port: Optional[int] = None

    def stop(self) -> None:
        pass


NULL_EXPORTER = NullExporter()


class MetricsExporter:
    """Standalone scrape endpoint: one daemon thread serving
    ``GET /metrics`` (Prometheus text) and ``GET /healthz`` (JSON).
    ``port`` is the actually bound port (pass 0 for an ephemeral one —
    the *param* value 0 means "off" and never reaches here)."""
    enabled = True

    def __init__(self, port: int, host: str = ""):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        from ..utils import log

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):       # noqa: N802 - stdlib API name
                if self.path.startswith("/metrics"):
                    body = render_prometheus().encode()
                    ctype = CONTENT_TYPE
                    code = 200
                    counters.inc("metrics_scrapes")
                elif self.path.startswith("/healthz"):
                    body = json.dumps({"ok": True}).encode()
                    ctype = "application/json"
                    code = 200
                else:
                    body = b"unknown path; try /metrics\n"
                    ctype = "text/plain"
                    code = 404
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                log.debug("metrics exporter: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="lgbm-metrics-exporter",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


_active: Any = NULL_EXPORTER


def get_exporter():
    """The process-wide active exporter (NULL_EXPORTER when disarmed)."""
    return _active


def start_exporter(port: int):
    """Arm the process-wide exporter on ``port`` (0 = ephemeral).  A bind
    failure disarms loudly instead of killing the training/serving
    process: live telemetry is an observer, never a dependency."""
    global _active
    from ..utils import log
    stop_exporter()
    try:
        _active = MetricsExporter(port)
    except OSError as e:
        log.warning("metrics exporter: cannot bind port %s (%s); live "
                    "scraping disabled for this process", port, e)
        _active = NULL_EXPORTER
        return _active
    log.info("metrics exporter: GET /metrics on port %d", _active.port)
    return _active


def stop_exporter() -> None:
    """Disarm and release the port (idempotent)."""
    global _active
    exp, _active = _active, NULL_EXPORTER
    exp.stop()
