"""Device-memory observability: live HBM accounting, compiled-executable
memory analysis, and the measured fit-predictor.

On TPU the hard wall is HBM, not FLOPs — ``docs/MEMORY.md``'s Epsilon-like
``hist_store`` alone is 1.56 GB — and before this module that table was
hand-computed: nothing ever measured actual device bytes, so a wrong
estimate surfaced as an opaque on-chip OOM during a scarce capture window.
Three legs, each independently usable:

* **live accounting** — :class:`MemoryMonitor`, armed through
  :func:`start`/:func:`stop` with the established no-op-singleton
  discipline (``obs/trace.py``, ``utils/faults.py``): when disarmed the
  active monitor is the shared :data:`NULL_MEMORY` whose every method is a
  constant no-op, so the instrumented hot paths (per-iteration sample,
  per-phase span annotation) cost one attribute read.  Armed, each sample
  reads ``device.memory_stats()`` where the backend provides it (TPU) and
  falls back to a ``jax.live_arrays()`` census elsewhere (CPU) — both are
  host-side reads, so sampling adds ZERO host<->device synchronizations
  (the rule PR 3's non-finite guards established).  Census bytes are
  attributed to owner tags (binned matrix, scores, bagging, ...) through
  resident providers the boosting driver registers
  (:func:`register_residents`).

* **static analysis** — :func:`executable_memory` wraps
  ``compiled.memory_analysis()`` (argument/output/temp/alias bytes of a
  jitted executable) into a plain dict, records the numbers as obs
  gauges + one ``exec_memory`` event, and is what
  ``scripts/profile_grow_steps.py`` and the ``tests/test_grow_jaxpr.py``
  byte-budget ratchet consume: a copy-insertion regression now fails a
  CPU test instead of an on-chip capture window.

* **fit prediction** — :func:`predict_hbm` codifies the
  ``docs/MEMORY.md`` analytic model (regenerated from this function by
  ``scripts/gen_memory_doc.py``); :func:`preflight` compares the
  predicted peak against the device capacity (or an explicit
  ``hbm_budget`` param) BEFORE the grower compiles, turning on-chip OOMs
  into actionable pre-flight diagnostics.  Predicted-vs-measured
  agreement is validated on CPU in tier-1 (``tests/test_memory.py``)
  within the documented tolerance (see :data:`RESIDENT_TOLERANCE`).
"""
from __future__ import annotations

import json
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence

from .counters import counters

# Documented predicted-vs-measured tolerance for the RESIDENT bytes model
# on the CPU live-array census (tests/test_memory.py, bench.py memory
# block): the census counts real allocator bytes while the model counts
# ideal array payloads, so padding/rounding plus small untracked arrays
# (tree SoA, feature meta, pipeline pending records) make the ratio drift
# from 1.  The acceptance band is measured/predicted in
# [1 - RESIDENT_TOLERANCE, 1 + RESIDENT_TOLERANCE].
RESIDENT_TOLERANCE = 0.35


# --------------------------------------------------------------- live stats


def device_memory_stats(device=None) -> Optional[Dict[str, int]]:
    """Normalized ``device.memory_stats()`` or None when the backend does
    not expose allocator stats (CPU).  Keys (when present):
    ``bytes_in_use``, ``peak_bytes_in_use``, ``bytes_limit``."""
    try:
        import jax
        dev = device if device is not None else jax.devices()[0]
        stats = dev.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    out = {}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_free_block_bytes", "num_allocs"):
        if key in stats:
            out[key] = int(stats[key])
    return out or None


# Owner-tag providers: each is a (weakly referenced) zero-arg callable
# returning {tag: [jax arrays]}.  The boosting driver registers its bound
# method here at setup; dead boosters drop out automatically.
_providers: List[Any] = []


def register_residents(provider: Callable[[], Dict[str, list]]) -> None:
    """Register an owner-tag provider for the live-array census.  Bound
    methods are held through ``weakref.WeakMethod`` so a provider never
    keeps its booster alive."""
    try:
        ref = weakref.WeakMethod(provider)
    except TypeError:
        ref = weakref.ref(provider)
    _providers.append(ref)


def live_census() -> Dict[str, Any]:
    """One pass over ``jax.live_arrays()``: total bytes plus a per-owner-tag
    breakdown.  Arrays no registered provider claims land in ``untagged``
    (jit-internal temporaries never appear here at all — XLA workspace is
    not a jax array; on TPU it is covered by ``memory_stats`` instead)."""
    import jax
    tag_of: Dict[int, str] = {}
    live_refs = []
    for ref in _providers:
        fn = ref()
        if fn is None:
            continue
        live_refs.append(ref)
        try:
            owned = fn()
        except Exception:
            continue
        for tag, arrays in owned.items():
            for a in arrays:
                if a is not None:
                    tag_of[id(a)] = tag
    _providers[:] = live_refs
    by_tag: Dict[str, int] = {}
    total = 0
    for a in jax.live_arrays():
        try:
            nbytes = int(a.nbytes)
        except Exception:
            continue
        total += nbytes
        tag = tag_of.get(id(a), "untagged")
        by_tag[tag] = by_tag.get(tag, 0) + nbytes
    return {"total_bytes": total, "by_tag": by_tag}


class NullMemoryMonitor:
    """Disarmed monitor: every operation is a constant no-op, shared
    process-wide (the tracer/faults singleton discipline) so the hot-loop
    sample/annotate sites never allocate when memory observability is
    off."""
    enabled = False
    source = None

    def sample(self, site: str = "") -> Optional[int]:
        return None

    def annotate(self, span) -> None:
        pass

    def measured_peak(self) -> int:
        return 0

    def baseline(self) -> int:
        return 0

    def top_residents(self, k: int = 6) -> List[Dict[str, Any]]:
        return []

    def summary(self) -> Dict[str, Any]:
        return {}


NULL_MEMORY = NullMemoryMonitor()


class MemoryMonitor:
    """Armed monitor.  ``source`` names the evidence backing the numbers:
    ``memory_stats`` (TPU allocator truth, includes XLA workspace) or
    ``live_census`` (CPU fallback: persistent jax arrays only)."""
    enabled = True

    def __init__(self):
        self._peak = 0
        self._flight_mark = 0
        self._last_census: Optional[Dict[str, Any]] = None
        stats = device_memory_stats()
        self.source = "memory_stats" if stats else "live_census"
        self._baseline = (stats["bytes_in_use"] if stats
                          and "bytes_in_use" in stats
                          else live_census()["total_bytes"])
        counters.gauge("memory_baseline_bytes", self._baseline)

    def sample(self, site: str = "") -> Optional[int]:
        """Record the current device occupancy; returns the sampled bytes.
        Host-side reads only — never synchronizes the device."""
        stats = device_memory_stats() if self.source == "memory_stats" \
            else None
        if stats:
            in_use = stats.get("bytes_in_use", 0)
            peak = stats.get("peak_bytes_in_use", in_use)
        else:
            self._last_census = live_census()
            in_use = peak = self._last_census["total_bytes"]
        self._peak = max(self._peak, peak)
        counters.gauge("memory_bytes_in_use", in_use)
        counters.gauge("memory_peak_bytes", self._peak)
        if self._peak > self._flight_mark * 1.1:
            # flight-recorder inflection: the peak grew >10% past its last
            # streamed mark — a live stream shows WHEN memory jumped, not
            # just the final number (no-op singleton when disarmed)
            self._flight_mark = self._peak
            from .flight import get_flight
            get_flight().record("hbm_peak", peak_bytes=int(self._peak),
                                site=site, source=self.source)
        return in_use

    def annotate(self, span) -> None:
        """Attach the sampled bytes to a recording tracer span (the
        ``PhaseTimers`` hook).  A ``NULL_SPAN`` has no ``_args`` and is
        skipped, so the disabled-tracer fast path stays allocation-free."""
        args = getattr(span, "_args", None)
        if args is None:
            return
        b = self.sample(site="phase")
        if b is not None:
            args["peak_bytes"] = int(self._peak)

    def measured_peak(self) -> int:
        return self._peak

    def baseline(self) -> int:
        return self._baseline

    def top_residents(self, k: int = 6) -> List[Dict[str, Any]]:
        """Largest owner tags of the most recent census (taken on demand
        when the monitor rides ``memory_stats`` — the tag breakdown is a
        census-only view either way)."""
        census = self._last_census or live_census()
        tags = sorted(census["by_tag"].items(), key=lambda kv: -kv[1])
        return [{"tag": t, "bytes": b} for t, b in tags[:k]]

    def summary(self) -> Dict[str, Any]:
        return {"source": self.source,
                "baseline_bytes": self._baseline,
                "measured_peak_bytes": self._peak,
                "top_residents": self.top_residents()}


_active: Any = NULL_MEMORY


def get_memory():
    """The process-wide active monitor (NULL_MEMORY when disarmed)."""
    return _active


def start() -> MemoryMonitor:
    """Arm a recording monitor as the process-wide active one."""
    global _active
    _active = MemoryMonitor()
    return _active


def stop() -> Dict[str, Any]:
    """Disarm; flushes the final summary into the counter registry (one
    ``memory_summary`` event + gauges) so a trace written afterwards is
    self-contained, and returns it."""
    global _active
    mon, _active = _active, NULL_MEMORY
    if not mon.enabled:
        return {}
    mon.sample(site="final")
    summ = mon.summary()
    counters.gauge("memory_measured_peak_bytes", summ["measured_peak_bytes"])
    counters.event("memory_summary", **{
        k: v for k, v in summ.items() if k != "top_residents"},
        top_residents=[f"{r['tag']}={r['bytes']}"
                       for r in summ["top_residents"]])
    return summ


# ---------------------------------------------------------- static analysis


def executable_memory(compiled, label: str = "") -> Optional[Dict[str, int]]:
    """``compiled.memory_analysis()`` as a plain dict (bytes):
    ``argument/output/temp/alias/generated_code`` plus ``peak_bytes``
    (argument + output + temp — the executable's device footprint while it
    runs).  With ``label`` the numbers also land as obs gauges
    (``exec_<label>_{temp,peak}_bytes``) and one ``exec_memory`` event.
    Returns None when the backend reports nothing."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
    }
    out["peak_bytes"] = (out["argument_bytes"] + out["output_bytes"]
                         + out["temp_bytes"])
    if label:
        counters.gauge(f"exec_{label}_temp_bytes", out["temp_bytes"])
        counters.gauge(f"exec_{label}_peak_bytes", out["peak_bytes"])
        counters.event("exec_memory", label=label, **out)
    return out


def analyze_jitted(fn, *args, label: str = "") -> Optional[Dict[str, int]]:
    """AOT lower+compile ``fn`` at ``args`` (arrays or ShapeDtypeStructs)
    and return :func:`executable_memory` of the result.  This compiles —
    use it from profilers/tests, never from a training hot path (the
    persistent compilation cache makes repeats cheap)."""
    import jax
    compiled = jax.jit(fn).lower(*args).compile()
    return executable_memory(compiled, label=label)


# ------------------------------------------------------------ fit predictor


def _pow2_at_least(n: int, floor: int = 1) -> int:
    p = max(int(floor), 1)
    while p < n:
        p *= 2
    return p


def predict_hbm(rows: int, features: int, bins: int = 255, leaves: int = 31,
                num_class: int = 1, bin_bytes: Optional[int] = None,
                packed_cols: int = 0, valid_rows: int = 0,
                ordered_bins: bool = False, gather_words: bool = False,
                bucket_min_log2: int = 6, serving_trees: int = 0,
                serving_nodes: int = 0, serving_cols: int = 0,
                serving_bins: int = 0,
                serving_buckets: Sequence[int] = (),
                data_shards: int = 1, feature_shards: int = 1,
                block_shard_bins: bool = False,
                gspmd_fused: bool = False,
                stream_chunk_rows: int = 0) -> Dict[str, Any]:
    """Analytic device-memory model of one training (the codified
    ``docs/MEMORY.md`` audit; that doc's table is generated from this
    function by ``scripts/gen_memory_doc.py``).

    ``features`` counts PHYSICAL binned columns (post-EFB).  Components
    split into **residents** — persistent jax arrays the boosting driver
    holds between iterations, what the CPU live-array census sees — and
    **transients** — XLA workspace of the jitted grower (gather staging,
    ``order``, ``hist_store``), visible only to ``memory_stats`` on TPU.
    ``peak_bytes`` = residents + transients; ``resident_bytes`` is the
    number the census-based CPU validation compares against (tolerance
    :data:`RESIDENT_TOLERANCE`).

    ``data_shards``/``feature_shards`` turn the model PER-DEVICE for a
    GSPMD ``(batch, feature)`` mesh (docs/DISTRIBUTED.md): row-linear
    terms divide by ``data_shards``, the histogram pool by
    ``feature_shards``, and the binned matrix additionally by
    ``feature_shards`` when ``block_shard_bins`` (``shard_axes``
    block-shards the data itself).  This is what makes the function the
    sharding PLANNER's cost model (``parallel/mesh.plan_mesh``): the
    planner evaluates it per candidate mesh shape and picks one whose
    per-device peak fits the chip.  Defaults (1, 1) reproduce the
    single-device model unchanged.

    ``stream_chunk_rows`` > 0 models the STREAMED single-device mode
    (``data_stream=chunked``; data/stream.py): the binned matrix stays
    host-side, so its resident term vanishes and is replaced by the
    double-buffered pair of static-shape row blocks, the per-block
    row->leaf routing vectors, and the carried histogram pool — the terms
    that make HBM a function of the CHUNK size instead of the row count.
    """
    rows = int(rows)
    features = int(features)
    d = max(int(data_shards), 1)
    fs = max(int(feature_shards), 1)
    rows_d = -(-rows // d)                  # rows per data shard (ceil)
    if bin_bytes is None:
        bin_bytes = 1 if bins < 256 else 2
    maxbuf = _pow2_at_least(rows_d, 1 << bucket_min_log2)
    residents = {
        # the binned matrix [N, C] (+ the nibble-packed histogram copy):
        # row-sharded over ``batch``; over ``feature`` too when the
        # planner block-shards it
        "binned": rows_d * -(-features // (fs if block_shard_bins else 1))
        * bin_bytes,
        "packed": rows_d * int(packed_cols),
        # train scores live twice per class: the current array + the
        # iteration-start rollback stash (boosting.train_one_iter)
        "scores": 2 * num_class * rows_d * 4,
        # per-iteration gradient/hessian pair, alive through the tree phase
        "grad_hess": 2 * num_class * rows_d * 4,
        # the objective's label + ~2 derived per-row device vectors
        # (binary's sign/weight; a rough but measured-against constant)
        "objective": 3 * rows_d * 4,
        # bagging weight + count vectors
        "bagging": 2 * rows_d * 4,
        # each valid set: binned matrix + per-class scores
        "valid": -(-int(valid_rows) // d) * (features * bin_bytes
                                             + num_class * 4),
    }
    words_bytes = (-(-features * bin_bytes // 4) + 3) * 4  # [W+3] u32 panel
    row_bytes = features * bin_bytes + 12                  # bins + g,h,c
    # the per-leaf histogram pool [L, F, B, 3] f32 — sharded over the
    # ``feature`` mesh axis (the planner's main lever: this is the
    # component that outgrows a chip first at Epsilon-wide shapes)
    pool_bytes = leaves * -(-features // fs) * bins * 3 * 4
    if d > 1 or fs > 1:
        # GSPMD grower layout (parallel/gspmd.py): no gather buckets, no
        # sentinel staging, no ``order`` permutation — the partition is
        # the row_leaf map and the per-split histogram is one flat
        # masked scatter-add whose workspace (segment indices i32 + the
        # broadcast (g, h, c) value rows) covers this device's row shard
        # x its histogram columns (all columns when the binned matrix is
        # replicated along ``feature``, its own slice when block-sharded)
        if gspmd_fused:
            # hybrid grower (gspmd_hist=fused): each device packs its
            # (row shard x feature slice) of the binned matrix into the
            # gather-word panel once per grow and runs the fused Mosaic
            # kernel per split — the scatter workspace is replaced by
            # the resident-sized panel plus the compacted order vector
            # (with its aligned over-fetch tail)
            sc = int(packed_cols) or features
            cols_d = -(-sc // fs)
            per = 4 if bin_bytes == 1 else 2
            words = -(-(-(-cols_d // 8) * 8) // per) + 3
            width = -(-words // 128) * 128
            transients = {
                "fused_panel": (rows_d + 1) * width * 4,
                "fused_order": (rows_d + 2048) * 4,
                # row_leaf carry + routing column + child mask
                "row_leaf": 3 * rows_d * 4,
                "hist_store": pool_bytes,
            }
        else:
            fcols = -(-features // (fs if block_shard_bins else 1))
            transients = {
                "hist_scatter": rows_d * fcols * 16,
                # row_leaf carry + routing column + child mask
                "row_leaf": 3 * rows_d * 4,
                "hist_store": pool_bytes,
            }
    elif stream_chunk_rows and int(stream_chunk_rows) > 0:
        # streamed out-of-core mode (data_stream=chunked; data/stream.py
        # + grower.StreamedGrower): the binned matrix never becomes
        # device-resident — the device holds the DOUBLE-BUFFERED pair of
        # static-shape row blocks, the per-block row->leaf routing
        # vectors (alive across the whole tree), and the carried
        # histogram pool; the per-split workspace is the masked
        # scatter-add over ONE block (segment indices i32 + broadcast
        # (g, h, c) value rows), so it scales with the chunk, not N
        chunk = min(int(stream_chunk_rows), rows_d)
        residents["binned"] = 0
        residents["stream_blocks"] = 2 * chunk * features * bin_bytes
        residents["stream_row_leaf"] = rows_d * 4
        residents["hist_pool"] = pool_bytes
        transients = {
            "stream_hist_scatter": chunk * features * 16,
        }
    else:
        transients = {
            # sentinel-padded copy of the histogram inputs (hbins_pad +
            # the three weight vectors; the word/panel layout on TPU)
            "staging": (rows_d + 1) * (words_bytes if gather_words
                                       else row_bytes),
            # order [N + maxbuf] i32 + the final row->leaf map [N] i32
            "order_partition": (rows_d + maxbuf) * 4 + rows_d * 4,
            "hist_store": pool_bytes,
            # the pow2 gather buffer for the largest bucket
            "gather_buffer": maxbuf * (words_bytes if gather_words
                                       else row_bytes),
            # leaf-ordered copies ride the carry when ordered_bins=on
            "ordered_copies": ((rows_d + maxbuf) * row_bytes
                               if ordered_bins else 0),
        }
    if serving_trees > 0:
        # the serving engine's term (docs/SERVING.md): resident SoA node
        # arrays [Tp, P] (feat/thr/left/right i32 + miss/cat_ref i32 +
        # default_left/is_cat bool = 26 B/node) + the per-column bin
        # threshold tables; transient per-bucket microbatch buffers (raw
        # f32 input + bins/cats i32 + nan/zero masks + per-tree
        # node/leaf/output state), summed over the ladder — pessimistic
        # by design, a pre-flight bound, since at most one bucket is in
        # flight per engine at a time
        residents["serving_model"] = (serving_trees * serving_nodes * 26
                                      + serving_cols * serving_bins * 4)
        transients["serving_batches"] = sum(
            b * (serving_cols * 14 + serving_trees * 12)
            for b in serving_buckets)
    resident_bytes = sum(residents.values())
    transient_bytes = sum(transients.values())
    return {
        "inputs": {"rows": rows, "features": features, "bins": bins,
                   "leaves": leaves, "num_class": num_class,
                   "bin_bytes": bin_bytes, "packed_cols": int(packed_cols),
                   "valid_rows": int(valid_rows),
                   "ordered_bins": bool(ordered_bins),
                   "gather_words": bool(gather_words),
                   "data_shards": d, "feature_shards": fs,
                   "block_shard_bins": bool(block_shard_bins),
                   "gspmd_fused": bool(gspmd_fused),
                   "stream_chunk_rows": int(stream_chunk_rows)},
        "residents": residents,
        "transients": transients,
        "resident_bytes": resident_bytes,
        "transient_bytes": transient_bytes,
        "peak_bytes": resident_bytes + transient_bytes,
    }


def device_capacity(device=None) -> Optional[int]:
    """Total device memory in bytes when the backend reports it (TPU
    ``bytes_limit``), else None (CPU host memory is not the budgeted
    resource)."""
    stats = device_memory_stats(device)
    return stats.get("bytes_limit") if stats else None


def preflight(pred: Dict[str, Any], hbm_budget: float = 0.0,
              context: str = "") -> Dict[str, Any]:
    """Compare a :func:`predict_hbm` prediction against the device budget
    BEFORE anything compiles.

    ``hbm_budget`` > 0 is a hard budget in bytes: exceeding it raises
    (``log.fatal``) with the component breakdown — the whole point is to
    fail in seconds on host instead of minutes into a capture window.
    With no explicit budget the check is advisory: when the backend
    reports a capacity (TPU) and the predicted peak exceeds it, a warning
    names the dominant components.  Every outcome lands as one
    ``hbm_preflight`` obs event + a ``hbm_predicted_peak_bytes`` gauge."""
    from ..utils import log
    peak = int(pred["peak_bytes"])
    capacity = device_capacity()
    budget = int(hbm_budget) if hbm_budget and hbm_budget > 0 else None
    limit = budget if budget is not None else capacity
    top = sorted({**pred["residents"], **pred["transients"]}.items(),
                 key=lambda kv: -kv[1])[:3]
    detail = ", ".join(f"{k}={v / 1e9:.2f} GB" for k, v in top)
    counters.gauge("hbm_predicted_peak_bytes", peak)
    verdict = "ok"
    if limit is not None and peak > limit:
        verdict = "over_budget" if budget is not None else "over_capacity"
    counters.event("hbm_preflight", predicted_peak_bytes=peak,
                   capacity_bytes=capacity, hbm_budget=budget,
                   verdict=verdict, context=context)
    if verdict == "over_budget":
        log.fatal("predicted peak HBM %.2f GB exceeds hbm_budget %.2f GB "
                  "(%s; top components: %s) — shrink the shape "
                  "(max_bin/num_leaves/rows) or raise hbm_budget",
                  peak / 1e9, limit / 1e9, context or "pre-flight", detail)
    if verdict == "over_capacity":
        log.warning("predicted peak HBM %.2f GB exceeds device capacity "
                    "%.2f GB (%s; top components: %s) — an on-chip OOM is "
                    "likely; set hbm_budget to fail fast",
                    peak / 1e9, limit / 1e9, context or "pre-flight",
                    detail)
    return {"predicted_peak_bytes": peak, "capacity_bytes": capacity,
            "hbm_budget": budget, "verdict": verdict}


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m lightgbm_tpu.obs.memory`` — one JSON snapshot of every
    device's ``memory_stats`` plus the live-array census; the capture
    playbook collects one per bench rung."""
    import jax
    snap = {"devices": [{"id": int(d.id), "platform": d.platform,
                         "memory_stats": device_memory_stats(d)}
                        for d in jax.devices()],
            "live_census": live_census()}
    print(json.dumps(snap, indent=1))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
