"""Prediction paths.

* ``predict_binned_leaf`` — jitted vectorized tree traversal over *binned*
  features, the analogue of ``Tree::AddPredictionToScore`` /
  ``NumericalDecisionInner`` (``tree.h:257-313``).  Used every iteration to
  update validation scores on device and by DART's drop/normalize score
  arithmetic.
* ``Predictor`` — host-side batch prediction over raw feature matrices
  (``src/application/predictor.hpp:24-195`` analogue): raw score, transformed
  output, leaf indices, with optional margin-based early stopping
  (``src/boosting/prediction_early_stop.cpp:13-70``).
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .tree import Tree
from .utils import log

MISSING_NONE, MISSING_ZERO, MISSING_NAN = 0, 1, 2


@jax.jit
def predict_binned_leaf(bins: jnp.ndarray,          # [N, F] int
                        split_feature: jnp.ndarray,  # [P] i32 (inner index, padded)
                        threshold_bin: jnp.ndarray,  # [P] i32
                        default_left: jnp.ndarray,   # [P] bool
                        left_child: jnp.ndarray,     # [P] i32
                        right_child: jnp.ndarray,    # [P] i32
                        feat_info: jnp.ndarray,      # [E, 5]: num_bin, missing, default_bin, col, offset
                        is_cat: jnp.ndarray,         # [P] bool
                        cat_mask: jnp.ndarray        # [P, W] bool (W=1 if no cat)
                        ) -> jnp.ndarray:
    """Return leaf index [N] for each row (Numerical/CategoricalDecisionInner
    semantics, tree.h:257-313).

    Node arrays are padded to a bucketed length P so jit compiles once per
    size bucket, not per tree.  Padding nodes must have child pointers < 0.
    """
    n = bins.shape[0]
    num_nodes = split_feature.shape[0]
    node = jnp.zeros((n,), jnp.int32)

    def cond(state):
        node, _ = state
        return jnp.any(node >= 0)

    def body(state):
        node, leaf = state
        nd = jnp.clip(node, 0, num_nodes - 1)
        f = split_feature[nd]
        col = feat_info[f, 3]
        b = jnp.take_along_axis(bins, col[:, None], axis=1)[:, 0].astype(jnp.int32)
        nb = feat_info[f, 0]
        mt = feat_info[f, 1]
        db = feat_info[f, 2]
        # EFB decode: physical slot -> logical bin (data/bundling.py layout)
        off = feat_info[f, 4]
        local = b - off
        in_range = (local >= 0) & (local < nb - 1)
        sub = jnp.where(in_range, local + (local >= db).astype(jnp.int32), db)
        b = jnp.where(off < 0, b, sub)
        is_missing = (((mt == MISSING_NAN) & (b == nb - 1))
                      | ((mt == MISSING_ZERO) & (b == db)))
        go_left = jnp.where(is_missing, default_left[nd], b <= threshold_bin[nd])
        cat_left = cat_mask[nd, jnp.clip(b, 0, cat_mask.shape[1] - 1)]
        go_left = jnp.where(is_cat[nd], cat_left, go_left)
        nxt = jnp.where(go_left, left_child[nd], right_child[nd])
        active = node >= 0
        new_node = jnp.where(active, nxt, node)
        new_leaf = jnp.where(active & (nxt < 0), ~nxt, leaf)
        # encode finished rows with node = -1 (any negative stops traversal)
        return new_node, new_leaf

    node, leaf = lax.while_loop(cond, body, (node, jnp.zeros((n,), jnp.int32)))
    return leaf


def tree_scores_binned(bins: jnp.ndarray, tree: Tree, used_feature_index,
                       feat_info: jnp.ndarray,
                       bin_mappers=None) -> jnp.ndarray:
    """Per-row output of one host tree evaluated on binned data [N].

    ``bin_mappers`` (per original feature) is required only for trees with
    categorical nodes, to translate value bitsets into bin masks.  Thin
    wrapper over the batched :func:`trees_scores_binned` (one packing
    implementation to maintain)."""
    return trees_scores_binned(bins, [tree], used_feature_index, feat_info,
                               bin_mappers)[0]


def trees_scores_binned(bins: jnp.ndarray, trees: List[Tree],
                        used_feature_index, feat_info: jnp.ndarray,
                        bin_mappers=None) -> jnp.ndarray:
    """Per-row outputs of SEVERAL host trees on binned data -> [T, N].

    All trees are padded to one shared pow2 node bucket and traversed by a
    single vmapped jit call — DART's drop/normalize walks many trees per
    iteration, and one batched call replaces T separate jit re-entries."""
    n = bins.shape[0]
    if not trees:
        return jnp.zeros((0, n), jnp.float32)
    num_t = len(trees)
    max_nn = max(max(t.num_leaves - 1, 1) for t in trees)
    p = 1
    while p < max_nn:
        p *= 2
    # BOTH axes pow2-bucketed so jit signatures stay bounded (DART drops a
    # random tree count each iteration — padding trees are 0-valued stumps)
    tp = 1
    while tp < num_t:
        tp *= 2
    width = int(np.asarray(feat_info[:, 0]).max())
    any_cat = any(t.num_cat > 0 for t in trees)
    sf = np.zeros((tp, p), np.int32)
    thr = np.zeros((tp, p), np.int32)
    dl = np.zeros((tp, p), bool)
    lc = np.full((tp, p), -1, np.int32)
    rc = np.full((tp, p), -1, np.int32)
    ic = np.zeros((tp, p), bool)
    cm = np.zeros((tp, p, width if any_cat else 1), bool)
    lv = np.zeros((tp, p + 1), np.float32)
    for ti, tree in enumerate(trees):
        nn = tree.num_leaves - 1
        lv[ti, :tree.num_leaves] = tree.leaf_value[:tree.num_leaves]
        if nn <= 0:
            continue
        if not getattr(tree, "_binned_ok", False):
            if bin_mappers is None:
                log.fatal("bin_mappers required to predict a deserialized "
                          "tree on binned data")
            tree.ensure_binned(bin_mappers)
        sf[ti, :nn] = [used_feature_index[f]
                       for f in tree.split_feature[:nn]]
        thr[ti, :nn] = tree.threshold_bin[:nn]
        dl[ti, :nn] = (tree.decision_type[:nn] & 2) > 0
        lc[ti, :nn] = tree.left_child[:nn]
        rc[ti, :nn] = tree.right_child[:nn]
        cat = (tree.decision_type[:nn] & 1) > 0
        ic[ti, :nn] = cat
        if tree.num_cat > 0 and cat.any():
            if bin_mappers is None:
                log.fatal("bin_mappers required to predict a categorical "
                          "tree on binned data")
            for i in np.nonzero(cat)[0]:
                cm[ti, i] = tree.cat_bin_mask(
                    int(i), bin_mappers[tree.split_feature[i]], width)
    leaf = jax.vmap(predict_binned_leaf,
                    in_axes=(None, 0, 0, 0, 0, 0, None, 0, 0))(
        bins, jnp.asarray(sf), jnp.asarray(thr), jnp.asarray(dl),
        jnp.asarray(lc), jnp.asarray(rc), feat_info, jnp.asarray(ic),
        jnp.asarray(cm))                                   # [Tp, N]
    return jnp.take_along_axis(jnp.asarray(lv), leaf, axis=1)[:num_t]


class Predictor:
    """Host batch predictor over a trained model (list of Trees).

    ``engine`` (a :class:`lightgbm_tpu.inference.PredictEngine`) attaches
    the cached serving artifact — device-resident SoA node arrays + bin
    threshold tables flattened once at model load — and ``predict_raw`` /
    ``predict_leaf_index`` reuse it instead of re-walking the Python tree
    list per call.  Outputs are bit-identical to the per-tree host loop
    (:meth:`predict_raw_trees`, kept as the oracle and the early-stop
    path); see docs/SERVING.md."""

    def __init__(self, trees: List[Tree], num_tree_per_iteration: int,
                 objective=None, average_output: bool = False,
                 num_iteration: int = -1,
                 early_stop: bool = False, early_stop_freq: int = 10,
                 early_stop_margin: float = 10.0, engine=None):
        self.trees = trees
        self.k = max(num_tree_per_iteration, 1)
        self.objective = objective
        self.average_output = average_output
        total_iters = len(trees) // self.k
        if num_iteration is not None and num_iteration > 0:
            self.num_iteration = min(num_iteration, total_iters)
        else:
            self.num_iteration = total_iters
        self.early_stop = early_stop
        self.early_stop_freq = max(early_stop_freq, 1)
        self.early_stop_margin = early_stop_margin
        self.engine = engine

    def attach_engine(self, prewarm: bool = False) -> "Predictor":
        """Build (or reuse) the SoA serving engine for this tree list."""
        if self.engine is None:
            from .inference import PredictEngine
            self.engine = PredictEngine(self.trees, self.k, prewarm=prewarm)
        return self

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        """Raw margin scores [K, N]; routed through the attached serving
        engine when one exists (bit-identical, pinned).  Early-stopped
        requests slice the cached SoA bundle too: ONE batched device
        traversal yields every (tree, row) leaf, and the margin
        accumulation below replays the reference early-stop loop exactly
        (same f64 leaf tables, same per-iteration adds over the same
        active rows), so the output is bit-identical to
        :meth:`predict_raw_trees` while the per-tree host traversal loop
        never runs."""
        if self.engine is None:
            return self.predict_raw_trees(X)
        if not self.early_stop:
            return self.engine.raw_scores(X,
                                          num_trees=self.num_iteration * self.k)
        leaves = self.engine.leaves(X)                     # [T, N]
        lv = self.engine.bundle.leaf_value                 # [Tp, P+1] f64
        n = leaves.shape[1]
        out = np.zeros((self.k, n), dtype=np.float64)
        active = np.ones(n, dtype=bool)
        for it in range(self.num_iteration):
            if not active.any():
                break
            idx = np.nonzero(active)[0]
            for k in range(self.k):
                t = it * self.k + k
                out[k, idx] += lv[t][leaves[t, idx]]
            if (it + 1) % self.early_stop_freq == 0:
                margin = self._margin(out[:, idx])
                active[idx[margin >= self.early_stop_margin]] = False
        return out

    def predict_raw_trees(self, X: np.ndarray) -> np.ndarray:
        """The per-tree host traversal loop — the bit-exactness oracle the
        engine path is pinned against, and the only implementation of
        margin-based early stopping."""
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        n = X.shape[0]
        out = np.zeros((self.k, n), dtype=np.float64)
        if not self.early_stop:
            for it in range(self.num_iteration):
                for k in range(self.k):
                    t = self.trees[it * self.k + k]
                    out[k] += t.predict(X)
        else:
            active = np.ones(n, dtype=bool)
            for it in range(self.num_iteration):
                if not active.any():
                    break
                idx = np.nonzero(active)[0]
                for k in range(self.k):
                    t = self.trees[it * self.k + k]
                    out[k, idx] += t.predict(X[idx])
                if (it + 1) % self.early_stop_freq == 0:
                    margin = self._margin(out[:, idx])
                    active[idx[margin >= self.early_stop_margin]] = False
        return out

    def _margin(self, scores: np.ndarray) -> np.ndarray:
        """binary: |s|; multiclass: top1 - top2 (prediction_early_stop.cpp)."""
        if scores.shape[0] == 1:
            return np.abs(scores[0])
        srt = np.sort(scores, axis=0)
        return srt[-1] - srt[-2]

    def predict(self, X: np.ndarray, raw_score: bool = False) -> np.ndarray:
        return self._transform(self.predict_raw(X), raw_score)

    def _transform(self, out: np.ndarray,
                   raw_score: bool = False) -> np.ndarray:
        """Margin [K, N] -> user-facing output (also the serving loop's
        per-request post-processing, so coalesced raw and transformed
        requests share one traversal)."""
        if not raw_score:
            # GBDT::Predict (gbdt_prediction.cpp:29-38): average_output
            # (RF) divides by the iteration count and does NOT apply the
            # objective transform; otherwise ConvertOutput
            if self.average_output:
                if self.num_iteration > 0:
                    out = out / self.num_iteration
            elif self.objective is not None:
                out = np.asarray(self.objective.convert_output(out),
                                 dtype=np.float64)
        if out.shape[0] == 1:
            return out[0]
        return out.T  # [N, K] like the reference python package

    def predict_contrib(self, X: np.ndarray,
                        num_features: Optional[int] = None) -> np.ndarray:
        """TreeSHAP feature contributions ``[N, K * (num_features + 1)]``
        (``pred_contrib=True``; gbdt.cpp PredictContrib semantics): per
        class, per-feature SHAP values plus the expected value in the
        last column, summing to the raw margin to float roundoff.

        With an attached engine the per-node decisions come from ONE
        device binning pass over the bucket ladder (the serving rank
        space — identical routing to the serving traversal); without one
        they are replayed from raw features host-side.  Both ride the
        vectorized row-parallel TreeSHAP recursion in
        :mod:`lightgbm_tpu.obs.model_quality`; the per-row recursive
        oracle (``contribs_oracle``) is the pinned parity twin."""
        from .obs import model_quality as mq
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        n = X.shape[0]
        if num_features is None:
            num_features = X.shape[1]
        total = self.num_iteration * self.k
        phi = np.zeros((n, self.k, num_features + 1), np.float64)
        binned = self.engine.binned_arrays(X) if self.engine is not None \
            else None
        for t in range(total):
            tree = self.trees[t]
            nn = tree.num_leaves - 1
            if binned is not None and nn > 0:
                go = self.engine.bundle.go_matrix(t, nn, *binned)
                mq.tree_contribs(tree, go, num_features, phi[:, t % self.k])
            else:
                mq.contribs_from_raw(tree, X, num_features,
                                     phi[:, t % self.k])
        if self.average_output and self.num_iteration > 0:
            phi /= self.num_iteration
        return phi.reshape(n, self.k * (num_features + 1))

    def predict_leaf_index(self, X: np.ndarray) -> np.ndarray:
        total = self.num_iteration * self.k
        if self.engine is not None:
            # leaf indices are integers: engine routing is identical by
            # construction, so this is the same output without T host walks
            return np.ascontiguousarray(
                self.engine.leaves(X)[:total].T.astype(np.int32))
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        n = X.shape[0]
        out = np.zeros((n, total), dtype=np.int32)
        for i in range(total):
            out[:, i] = self.trees[i].predict_leaf_index(X)
        return out
