"""Prediction paths.

* ``predict_binned_leaf`` — jitted vectorized tree traversal over *binned*
  features, the analogue of ``Tree::AddPredictionToScore`` /
  ``NumericalDecisionInner`` (``tree.h:257-313``).  Used every iteration to
  update validation scores on device and by DART's drop/normalize score
  arithmetic.
* ``Predictor`` — host-side batch prediction over raw feature matrices
  (``src/application/predictor.hpp:24-195`` analogue): raw score, transformed
  output, leaf indices, with optional margin-based early stopping
  (``src/boosting/prediction_early_stop.cpp:13-70``).
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .tree import Tree
from .utils import log

MISSING_NONE, MISSING_ZERO, MISSING_NAN = 0, 1, 2


@jax.jit
def predict_binned_leaf(bins: jnp.ndarray,          # [N, F] int
                        split_feature: jnp.ndarray,  # [P] i32 (inner index, padded)
                        threshold_bin: jnp.ndarray,  # [P] i32
                        default_left: jnp.ndarray,   # [P] bool
                        left_child: jnp.ndarray,     # [P] i32
                        right_child: jnp.ndarray,    # [P] i32
                        feat_info: jnp.ndarray,      # [E, 5]: num_bin, missing, default_bin, col, offset
                        is_cat: jnp.ndarray,         # [P] bool
                        cat_mask: jnp.ndarray        # [P, W] bool (W=1 if no cat)
                        ) -> jnp.ndarray:
    """Return leaf index [N] for each row (Numerical/CategoricalDecisionInner
    semantics, tree.h:257-313).

    Node arrays are padded to a bucketed length P so jit compiles once per
    size bucket, not per tree.  Padding nodes must have child pointers < 0.
    """
    n = bins.shape[0]
    num_nodes = split_feature.shape[0]
    node = jnp.zeros((n,), jnp.int32)

    def cond(state):
        node, _ = state
        return jnp.any(node >= 0)

    def body(state):
        node, leaf = state
        nd = jnp.clip(node, 0, num_nodes - 1)
        f = split_feature[nd]
        col = feat_info[f, 3]
        b = jnp.take_along_axis(bins, col[:, None], axis=1)[:, 0].astype(jnp.int32)
        nb = feat_info[f, 0]
        mt = feat_info[f, 1]
        db = feat_info[f, 2]
        # EFB decode: physical slot -> logical bin (data/bundling.py layout)
        off = feat_info[f, 4]
        local = b - off
        in_range = (local >= 0) & (local < nb - 1)
        sub = jnp.where(in_range, local + (local >= db).astype(jnp.int32), db)
        b = jnp.where(off < 0, b, sub)
        is_missing = (((mt == MISSING_NAN) & (b == nb - 1))
                      | ((mt == MISSING_ZERO) & (b == db)))
        go_left = jnp.where(is_missing, default_left[nd], b <= threshold_bin[nd])
        cat_left = cat_mask[nd, jnp.clip(b, 0, cat_mask.shape[1] - 1)]
        go_left = jnp.where(is_cat[nd], cat_left, go_left)
        nxt = jnp.where(go_left, left_child[nd], right_child[nd])
        active = node >= 0
        new_node = jnp.where(active, nxt, node)
        new_leaf = jnp.where(active & (nxt < 0), ~nxt, leaf)
        # encode finished rows with node = -1 (any negative stops traversal)
        return new_node, new_leaf

    node, leaf = lax.while_loop(cond, body, (node, jnp.zeros((n,), jnp.int32)))
    return leaf


def tree_scores_binned(bins: jnp.ndarray, tree: Tree, used_feature_index,
                       feat_info: jnp.ndarray,
                       bin_mappers=None) -> jnp.ndarray:
    """Per-row output of one host tree evaluated on binned data [N].

    ``bin_mappers`` (per original feature) is required only for trees with
    categorical nodes, to translate value bitsets into bin masks.
    """
    n = bins.shape[0]
    nn = tree.num_leaves - 1
    if nn <= 0:
        val = tree.leaf_value[0] if len(tree.leaf_value) else 0.0
        return jnp.full((n,), float(val), jnp.float32)
    if not getattr(tree, "_binned_ok", False):
        if bin_mappers is None:
            log.fatal("bin_mappers required to predict a deserialized tree "
                      "on binned data")
        tree.ensure_binned(bin_mappers)
    # pad node arrays to a power-of-two bucket: bounded set of jit signatures
    p = 1
    while p < nn:
        p *= 2
    def pad(a, fill=0):
        return np.concatenate([np.asarray(a[:nn]),
                               np.full(p - nn, fill, dtype=np.asarray(a).dtype)])
    inner = np.asarray([used_feature_index[f] for f in tree.split_feature[:nn]],
                       dtype=np.int32)
    is_cat = (tree.decision_type[:nn] & 1) > 0
    if tree.num_cat > 0 and is_cat.any():
        if bin_mappers is None:
            log.fatal("bin_mappers required to predict a categorical tree "
                      "on binned data")
        width = int(np.asarray(feat_info[:, 0]).max())
        cat_mask = np.zeros((p, width), dtype=bool)
        for i in np.nonzero(is_cat)[0]:
            cat_mask[i] = tree.cat_bin_mask(
                int(i), bin_mappers[tree.split_feature[i]], width)
    else:
        cat_mask = np.zeros((p, 1), dtype=bool)
    leaf = predict_binned_leaf(
        bins,
        jnp.asarray(pad(inner)),
        jnp.asarray(pad(tree.threshold_bin)),
        jnp.asarray(pad((tree.decision_type[:nn] & 2) > 0, False)),
        jnp.asarray(pad(tree.left_child, -1)),
        jnp.asarray(pad(tree.right_child, -1)),
        feat_info,
        jnp.asarray(pad(is_cat, False)),
        jnp.asarray(cat_mask))
    return jnp.asarray(tree.leaf_value, jnp.float32)[leaf]


class Predictor:
    """Host batch predictor over a trained model (list of Trees)."""

    def __init__(self, trees: List[Tree], num_tree_per_iteration: int,
                 objective=None, average_output: bool = False,
                 num_iteration: int = -1,
                 early_stop: bool = False, early_stop_freq: int = 10,
                 early_stop_margin: float = 10.0):
        self.trees = trees
        self.k = max(num_tree_per_iteration, 1)
        self.objective = objective
        self.average_output = average_output
        total_iters = len(trees) // self.k
        if num_iteration is not None and num_iteration > 0:
            self.num_iteration = min(num_iteration, total_iters)
        else:
            self.num_iteration = total_iters
        self.early_stop = early_stop
        self.early_stop_freq = max(early_stop_freq, 1)
        self.early_stop_margin = early_stop_margin

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        """Raw margin scores [K, N]."""
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        n = X.shape[0]
        out = np.zeros((self.k, n), dtype=np.float64)
        if not self.early_stop:
            for it in range(self.num_iteration):
                for k in range(self.k):
                    t = self.trees[it * self.k + k]
                    out[k] += t.predict(X)
        else:
            active = np.ones(n, dtype=bool)
            for it in range(self.num_iteration):
                if not active.any():
                    break
                idx = np.nonzero(active)[0]
                for k in range(self.k):
                    t = self.trees[it * self.k + k]
                    out[k, idx] += t.predict(X[idx])
                if (it + 1) % self.early_stop_freq == 0:
                    margin = self._margin(out[:, idx])
                    active[idx[margin >= self.early_stop_margin]] = False
        if self.average_output and self.num_iteration > 0:
            out /= self.num_iteration
        return out

    def _margin(self, scores: np.ndarray) -> np.ndarray:
        """binary: |s|; multiclass: top1 - top2 (prediction_early_stop.cpp)."""
        if scores.shape[0] == 1:
            return np.abs(scores[0])
        srt = np.sort(scores, axis=0)
        return srt[-1] - srt[-2]

    def predict(self, X: np.ndarray, raw_score: bool = False) -> np.ndarray:
        out = self.predict_raw(X)
        if not raw_score and self.objective is not None:
            out = np.asarray(self.objective.convert_output(out), dtype=np.float64)
        if out.shape[0] == 1:
            return out[0]
        return out.T  # [N, K] like the reference python package

    def predict_leaf_index(self, X: np.ndarray) -> np.ndarray:
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        n = X.shape[0]
        total = self.num_iteration * self.k
        out = np.zeros((n, total), dtype=np.int32)
        for i in range(total):
            out[:, i] = self.trees[i].predict_leaf_index(X)
        return out
