"""Training callbacks — mirrors python-package/lightgbm/callback.py:48-204."""
from __future__ import annotations

import collections
from typing import Callable, Dict, List

from .utils import log


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


# callback env mirrors the reference CallbackEnv namedtuple
CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            result = "\t".join
            parts = []
            for item in env.evaluation_result_list:
                if len(item) == 4:
                    name, metric, value, _ = item
                    parts.append(f"{name}'s {metric}: {value:g}")
                else:
                    name, metric, value, _, stdv = item
                    parts.append(f"{name}'s {metric}: {value:g} + {stdv:g}")
            log.info("[%d]\t%s", env.iteration + 1, result(parts))
    _callback.order = 10
    return _callback


def record_evaluation(eval_result: Dict) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")
    eval_result.clear()

    def _callback(env: CallbackEnv) -> None:
        for item in env.evaluation_result_list:
            name, metric, value = item[0], item[1], item[2]
            eval_result.setdefault(name, collections.OrderedDict())
            eval_result[name].setdefault(metric, [])
            eval_result[name][metric].append(value)
    _callback.order = 20
    return _callback


def reset_parameter(**kwargs) -> Callable:
    """Reset parameters (e.g. learning_rate) per iteration: value may be a
    list (per-iteration) or a callable iteration -> value."""

    def _callback(env: CallbackEnv) -> None:
        new_params = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(f"Length of list {key} has to equal "
                                     "num_boost_round")
                new_params[key] = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_params[key] = value(env.iteration - env.begin_iteration)
            else:
                raise ValueError("Only list and callable values are supported "
                                 "as a parameter")
        if new_params:
            env.model.reset_parameter(new_params)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, verbose: bool = True) -> Callable:
    """Stop when no metric improved for ``stopping_rounds`` iterations.

    A NaN metric value never compares as an improvement (every comparison
    against NaN is False), so a metric that goes NaN simply stops the
    improvement clock: the best score/iteration stay at the last *finite*
    best and training early-stops once the patience runs out — it never
    records NaN as a best or crashes (pinned by
    ``tests/test_robustness.py``).

    The returned callback exposes ``checkpoint_state()`` /
    ``restore_state(state)`` so snapshot checkpoints
    (:mod:`lightgbm_tpu.checkpoint`) can carry the best-score bookkeeping
    across a crash-resume without divergence.
    """
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List = []
    cmp_op: List[Callable] = []
    pending_restore: List[Dict] = []

    def _init(env: CallbackEnv) -> None:
        if not env.evaluation_result_list:
            raise ValueError("For early stopping, at least one dataset and "
                             "eval metric is required for evaluation")
        if verbose:
            log.info("Train until valid scores didn't improve in %d rounds.",
                     stopping_rounds)
        for item in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            if item[3]:  # higher is better
                best_score.append(float("-inf"))
                cmp_op.append(lambda x, y: x > y)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda x, y: x < y)

    def _callback(env: CallbackEnv) -> None:
        if not cmp_op:
            _init(env)
        if pending_restore:
            st = pending_restore.pop()
            best_score[:] = st["best_score"]
            best_iter[:] = st["best_iter"]
            best_score_list[:] = st["best_score_list"]
        for i, item in enumerate(env.evaluation_result_list):
            score = item[2]
            # NaN fails both cmp directions: never an improvement
            if cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            elif env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    log.info("Early stopping, best iteration is: [%d]",
                             best_iter[i] + 1)
                raise EarlyStopException(best_iter[i], best_score_list[i])

    def _checkpoint_state() -> Dict:
        return {"best_score": list(best_score),
                "best_iter": list(best_iter),
                "best_score_list": list(best_score_list)}

    def _restore_state(state: Dict) -> None:
        # applied lazily on the next call, AFTER _init sized the lists
        pending_restore[:] = [dict(state)]

    _callback.order = 30
    _callback.checkpoint_state = _checkpoint_state
    _callback.restore_state = _restore_state
    return _callback
