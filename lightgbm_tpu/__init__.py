"""lightgbm_tpu — a TPU-native gradient boosting framework.

A from-scratch re-design of the LightGBM feature set (reference:
hdyen/LightGBM v2.0.5) for TPU hardware: histogram construction and
leaf-wise tree growth run as jitted XLA/Pallas programs, distribution uses
``jax.sharding`` meshes with XLA collectives over ICI/DCN, and the host data
layer (binning, parsing, model IO) mirrors the reference's semantics so
models and APIs interoperate.
"""
from .basic import Booster, Dataset
from .boosting import NonFiniteError
from .callback import (EarlyStopException, early_stopping, print_evaluation,
                       record_evaluation, reset_parameter)
from .config import Config
from .engine import cv, train
from .plotting import (create_tree_digraph, plot_contrib_summary,
                       plot_importance, plot_metric, plot_tree)

__version__ = "0.1.0"

__all__ = [
    "Booster", "Dataset", "Config", "train", "cv",
    "early_stopping", "print_evaluation", "record_evaluation",
    "reset_parameter", "EarlyStopException", "NonFiniteError",
    "plot_importance", "plot_metric", "plot_tree", "create_tree_digraph",
    "plot_contrib_summary",
]

try:  # sklearn API is optional at import time
    from .sklearn import (LGBMClassifier, LGBMModel, LGBMRanker,  # noqa: F401
                          LGBMRegressor)
    __all__ += ["LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker"]
except ImportError:  # pragma: no cover
    pass
