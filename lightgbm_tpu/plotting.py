"""Plotting utilities (feature importance / metric curves / tree graphs).

API mirrors the reference python package ``plotting.py:22-428``
(``plot_importance``, ``plot_metric``, ``plot_tree``, ``create_tree_digraph``)
but is written against this framework's Booster / dump_model structures.
matplotlib and graphviz are optional — a clear error is raised when missing.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple, Union

import numpy as np


def _check_not_tuple_of_2_elements(obj, obj_name: str) -> None:
    if not isinstance(obj, tuple) or len(obj) != 2:
        raise TypeError(f"{obj_name} must be a tuple of 2 elements.")


def _get_booster(booster):
    # accept Booster or sklearn estimator (as the reference plotting does)
    from .basic import Booster
    if hasattr(booster, "booster_"):          # sklearn estimator
        booster = booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be Booster or LGBMModel instance")


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim: Optional[Tuple[float, float]] = None,
                    ylim: Optional[Tuple[float, float]] = None,
                    title: Optional[str] = "Feature importance",
                    xlabel: Optional[str] = "Feature importance",
                    ylabel: Optional[str] = "Features",
                    importance_type: str = "split",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, grid: bool = True,
                    precision: Optional[int] = 3,
                    **kwargs):
    """Horizontal bar chart of feature importance (plotting.py:22-120).

    ``importance_type="gain"`` values are float64 cumulative gains (the
    vectorized ``GBDT.feature_importance``) — they annotate with
    ``precision`` decimals instead of the split-count integer form."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot importance.")

    booster = _get_booster(booster)
    importance = np.asarray(booster.feature_importance(importance_type))
    feature_name = booster.feature_name()
    if not len(importance):
        raise ValueError("Booster's feature_importance is empty.")

    tuples = sorted(zip(feature_name, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    labels, values = zip(*tuples) if tuples else ((), ())

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y,
                _float2str(x, precision) if importance_type == "gain"
                else str(int(x)), va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, max(values) * 1.1 if values else 1)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        ylim = (-1, len(values))
    ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_contrib_summary(booster, data, ax=None, height: float = 0.2,
                         max_num_features: Optional[int] = None,
                         title: Optional[str] = "Feature contributions",
                         xlabel: Optional[str] = "mean |SHAP contribution|",
                         ylabel: Optional[str] = "Features",
                         precision: Optional[int] = 3, figsize=None,
                         grid: bool = True, **kwargs):
    """Horizontal bar chart of mean absolute SHAP contributions over
    ``data`` (the ``plot_split_value_histogram``-style summary view of
    ``predict(pred_contrib=True)``): per-feature mean |phi|, classes
    aggregated, the expected-value column dropped."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot "
                          "contributions.")

    booster = _get_booster(booster)
    contribs = np.asarray(booster.predict(data, pred_contrib=True))
    feature_name = booster.feature_name()
    n_feat = len(feature_name)
    # [n, K*(F+1)] class-major -> mean |phi| per feature across rows and
    # classes; the last column of every class block is the expected value
    per_class = contribs.reshape(contribs.shape[0], -1, n_feat + 1)
    mean_abs = np.abs(per_class[:, :, :n_feat]).mean(axis=(0, 1))

    tuples = sorted(zip(feature_name, mean_abs), key=lambda x: x[1])
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    labels, values = zip(*tuples) if tuples else ((), ())

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x, y, _float2str(x, precision), va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    ax.set_xlim((0, max(values) * 1.1 if values else 1))
    ax.set_ylim((-1, len(values)))
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster_or_record: Union[Dict, object],
                metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None,
                ax=None, xlim=None, ylim=None,
                title: Optional[str] = "Metric during training",
                xlabel: Optional[str] = "Iterations",
                ylabel: Optional[str] = "auto", figsize=None,
                grid: bool = True):
    """Plot metric curves recorded by ``record_evaluation``
    (plotting.py:123-222)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot metric.")

    if isinstance(booster_or_record, dict):
        eval_results = booster_or_record
    else:
        raise TypeError("booster_or_record must be a dict recorded by "
                        "record_evaluation (pass eval_result dict)")
    if not eval_results:
        raise ValueError("eval results cannot be empty.")

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)

    names = list(eval_results.keys())
    if dataset_names is None:
        dataset_names = names
    msg = "valid dataset names: " + ", ".join(names)

    num_iters = 0
    for name in dataset_names:
        if name not in eval_results:
            raise ValueError(f"dataset {name!r} not found; {msg}")
        metrics = eval_results[name]
        if metric is None:
            if len(metrics) > 1:
                raise ValueError("more than one metric available, "
                                 "please specify metric in params")
            metric = list(metrics.keys())[0]
        if metric not in metrics:
            raise ValueError(f"metric {metric!r} not recorded for {name!r}")
        results = metrics[metric]
        num_iters = max(num_iters, len(results))
        ax.plot(range(len(results)), results, label=name)

    ax.legend(loc="best")
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, num_iters)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
        ax.set_ylim(ylim)
    if ylabel == "auto":
        ylabel = metric
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def _float2str(value, precision: Optional[int] = 3) -> str:
    return (f"{value:.{precision}f}" if precision is not None
            else str(value))


def create_tree_digraph(booster, tree_index: int = 0,
                        show_info: Optional[List[str]] = None,
                        precision: Optional[int] = 3,
                        name: Optional[str] = None,
                        comment: Optional[str] = None,
                        format: Optional[str] = None,  # noqa: A002
                        engine: Optional[str] = None,
                        encoding: Optional[str] = None,
                        graph_attr=None, node_attr=None, edge_attr=None,
                        body=None, strict: bool = False):
    """Build a graphviz.Digraph of one tree from dump_model JSON
    (plotting.py:225-340)."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("You must install graphviz to plot tree.")

    booster = _get_booster(booster)
    model = booster.dump_model()
    tree_infos = model["tree_info"]
    if tree_index >= len(tree_infos):
        raise IndexError("tree_index is out of range.")
    tree_info = tree_infos[tree_index]
    show_info = show_info or []
    feature_names = model.get("feature_names")

    graph = Digraph(name=name, comment=comment, format=format, engine=engine,
                    encoding=encoding, graph_attr=graph_attr,
                    node_attr=node_attr, edge_attr=edge_attr, body=body,
                    strict=strict)

    def add(node, parent=None, decision=None):
        if "split_index" in node:
            nid = f"split{node['split_index']}"
            feat = node["split_feature"]
            if feature_names is not None and 0 <= feat < len(feature_names):
                feat = feature_names[feat]
            label = f"split_feature_name: {feat}"
            is_cat = node.get("decision_type") == "categorical"
            if is_cat:
                left_edge, right_edge = "in set", "not in set"
            else:
                left_edge, right_edge = node.get("decision_type", "<="), ">"
                label += f"\\nthreshold: {_float2str(node['threshold'], precision)}"
            for info in ("split_gain", "internal_value", "internal_count"):
                if info in show_info and info in node:
                    label += f"\\n{info}: {_float2str(node[info], precision)}"
            graph.node(nid, label=label)
            add(node["left_child"], nid, left_edge)
            add(node["right_child"], nid, right_edge)
        else:
            nid = f"leaf{node['leaf_index']}"
            label = f"leaf_index: {node['leaf_index']}"
            label += f"\\nleaf_value: {_float2str(node['leaf_value'], precision)}"
            if "leaf_count" in show_info and "leaf_count" in node:
                label += f"\\nleaf_count: {node['leaf_count']}"
            graph.node(nid, label=label)
        if parent is not None:
            graph.edge(parent, nid, decision)

    add(tree_info["tree_structure"])
    return graph


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None,
              show_info: Optional[List[str]] = None,
              precision: Optional[int] = 3, **kwargs):
    """Render one tree into a matplotlib axis (plotting.py:343-428)."""
    try:
        import matplotlib.image as mpimg
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot tree.")
    from io import BytesIO

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)
    graph = create_tree_digraph(booster, tree_index=tree_index,
                                show_info=show_info, precision=precision,
                                **kwargs)
    s = BytesIO(graph.pipe(format="png"))
    img = mpimg.imread(s)
    ax.imshow(img)
    ax.axis("off")
    return ax
