"""Self-healing training: the supervisor process.

PR 6 made multi-process training *resumable* — coordinated shard-set
checkpoints with a rank-0 manifest commit point, a resume barrier, and
SIGTERM preemption safety.  But recovery was still a human: a crashed
rank, a wedged collective, or an OOM-killed worker left the group dead
until someone reran the job with ``snapshot_resume=true``.  This module
closes that loop::

    python -m lightgbm_tpu.supervisor config=train.conf num_machines=2 \
        heartbeat_interval=1 hang_timeout=120 restart_limit=3

The supervisor spawns the rank processes and watches two liveness
signals, cheapest first:

* **exit codes** — a rank that dies (crash, OOM kill, ``SimulatedCrash``
  from the fault matrix) is seen at the next poll: ``rank_dead``;
* **heartbeat files** — each rank stamps iteration + wall-time into
  ``<output_model>.heartbeat.rank_R`` at every iteration boundary
  (``heartbeat_interval`` param; pure host-side writes, zero added
  collectives).  A live process whose stamp is older than the effective
  hang timeout is wedged: ``rank_hang``.

``hang_timeout`` **composes with** ``collective_timeout``: the effective
timeout is raised to exceed the collective ladder's worst case
(``collective_timeout * (collective_retries + 1)`` plus slack), so a rank
stuck in a *host-object* collective surfaces in-band first — as a named
``CollectiveError`` that kills the rank and leaves a crash report — and
the heartbeat path only has to catch what nothing in-band can: a stuck
device collective, a livelocked host loop, a rank wedged before init.

On either signal the supervisor runs one **restart cycle**:

1. **teardown** — SIGTERM to every live rank first (the PR 6
   ``preempt_signal`` path: a *healthy* group member writes a coordinated
   checkpoint and exits cleanly — best-effort, since a dead peer fails
   the commit barrier after ``collective_timeout``), then SIGKILL to
   whatever is left after ``term_grace`` seconds;
2. **triage** — per-rank crash reports (``<output_model>.crash.rank_R``,
   written by the rank itself on abnormal exit: exception, all-thread
   stacks, obs event-ring tail) are surfaced as ``crash_report`` events;
3. **budget** — restarts are bounded by ``restart_limit`` with
   exponential ``restart_backoff``; the budget **resets after forward
   progress** (a restart that finds a newer committed checkpoint than the
   last one proves the job advances between failures — a crash loop at a
   fixed iteration does not);
4. **relaunch** — stale atomic-write tmp files are swept
   (:func:`lightgbm_tpu.checkpoint.sweep_stale_tmp`), and the group is
   respawned with the same command line; workers run with
   ``snapshot_resume=true`` so they agree on the newest everywhere-valid
   set through the PR 6 resume barrier.  The final model is byte-identical
   to an uninterrupted run (pinned by ``tests/test_supervisor.py``).

Every decision is a structured obs event — ``rank_dead`` / ``rank_hang`` /
``group_restart`` / ``restart_budget_exhausted`` / ``crash_report`` /
``stale_sweep`` — an unattended recovery is never an unexplained one.

The live telemetry plane (docs/OBSERVABILITY.md "Live telemetry") extends
liveness to *health*: with ``obs_stream_path`` set the supervisor tails
every rank's flight-recorder stream and compares per-rank progress rates
— a rank ``straggler_factor`` behind the group median raises a structured
``rank_straggler`` event (a verdict, never a restart); with
``metrics_port`` set the supervisor serves its restart budget, last
restart time, and per-rank heartbeat ages as Prometheus gauges, so one
scrape answers "is this group healthy".
"""
from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import checkpoint as checkpoint_mod
from .obs import flight as flight_mod
from .obs import metrics as metrics_mod
from .obs.counters import counters
from .utils import log

DEFAULT_HANG_TIMEOUT = 300.0
# the restart counter each incarnation sees: lets a test harness (or a
# canary deployment) arm behavior on the FIRST incarnation only
ATTEMPT_ENV = "LGBM_TPU_SUPERVISOR_ATTEMPT"


def effective_hang_timeout(hang_timeout: float, heartbeat_interval: float,
                           collective_timeout: Optional[float],
                           collective_retries: int = 0) -> float:
    """The hang timeout actually enforced: the configured one, raised to
    clear the collective ladder's worst case so an in-band
    ``CollectiveError`` gets its chance to surface first (the rank then
    dies with an exit code + crash report — far better evidence than
    "heartbeat went quiet")."""
    t = float(hang_timeout) if hang_timeout and hang_timeout > 0 \
        else DEFAULT_HANG_TIMEOUT
    if collective_timeout and collective_timeout > 0:
        floor = (float(collective_timeout) * (int(collective_retries) + 1)
                 + float(heartbeat_interval) + 1.0)
        if t < floor:
            log.warning("hang_timeout %gs raised to %gs so the collective "
                        "ladder (timeout %gs x %d attempt(s)) can surface "
                        "an in-band CollectiveError first", t, floor,
                        collective_timeout, collective_retries + 1)
            t = floor
    return t


class _Rank:
    __slots__ = ("rank", "proc", "spawned_at")

    def __init__(self, rank: int, proc: subprocess.Popen, spawned_at: float):
        self.rank = rank
        self.proc = proc
        self.spawned_at = spawned_at


class Supervisor:
    """Spawn, watch, and heal one training group.

    ``argv`` is the worker command line, identical for every rank and
    every relaunch; rank identity travels as the ``LGBM_TPU_RANK``
    environment variable (the mesh bring-up convention) and the restart
    count as ``LGBM_TPU_SUPERVISOR_ATTEMPT``.  ``prelaunch`` runs before
    every (re)launch — e.g. :func:`parallel.mesh.refresh_local_ports` for
    single-host groups whose dead coordinator port may linger in
    TIME_WAIT."""

    def __init__(self, argv: Sequence[str], output_model: str,
                 world: int = 1, *,
                 heartbeat_interval: float = 1.0,
                 hang_timeout: float = 0.0,
                 restart_limit: int = 3,
                 restart_backoff: float = 1.0,
                 collective_timeout: Optional[float] = None,
                 collective_retries: int = 0,
                 term_grace: Optional[float] = None,
                 startup_grace: Optional[float] = None,
                 poll_interval: float = 0.1,
                 env: Optional[Dict[str, str]] = None,
                 prelaunch: Optional[Callable[["Supervisor"], None]] = None,
                 obs_stream: str = "",
                 straggler_factor: float = 4.0,
                 straggler_interval: float = 1.0,
                 metrics_port: int = 0,
                 elastic_resume: bool = False,
                 elastic_min_ranks: int = 1,
                 world_shrink_after: int = 2,
                 machine_list_file: str = "",
                 hbm_budget: int = 0):
        self.argv = list(argv)
        self.output_model = str(output_model)
        self.world = max(1, int(world))
        self.heartbeat_interval = float(heartbeat_interval)
        self.hang_timeout = effective_hang_timeout(
            hang_timeout, heartbeat_interval, collective_timeout,
            collective_retries)
        # before the FIRST heartbeat of an incarnation lands the rank is
        # "starting", not "beating" — runtime imports + device init +
        # grower compiles happen there, so the no-file-yet verdict uses a
        # separate (more generous) deadline than the stale-file one
        self.startup_grace = float(startup_grace) \
            if startup_grace is not None else max(self.hang_timeout, 60.0)
        self.restart_limit = max(0, int(restart_limit))
        self.restart_backoff = max(0.0, float(restart_backoff))
        self.term_grace = float(term_grace) if term_grace is not None \
            else (float(collective_timeout or 10.0) + 5.0)
        self.poll_interval = float(poll_interval)
        self.env = dict(env or {})
        self.prelaunch = prelaunch
        self.attempt = 0              # total relaunches so far
        self._ranks: List[_Rank] = []
        self._progress_mark: Optional[int] = None
        # live telemetry plane (docs/OBSERVABILITY.md "Live telemetry"):
        # the flight streams every rank appends under obs_stream are
        # tailed for straggler verdicts (rate vs group median), and the
        # supervisor's own restart state is exposed as scrape gauges
        self.obs_stream = str(obs_stream or "")
        self.straggler_factor = max(1.001, float(straggler_factor))
        self.straggler_interval = max(0.1, float(straggler_interval))
        self.metrics_port = int(metrics_port or 0)
        self._restarts_since_progress = 0
        self._last_restart_unix = 0.0
        self._last_straggler_check = 0.0
        self._stragglers_flagged: set = set()
        # elastic groups (docs/ROBUSTNESS.md "Elastic groups"): a rank
        # whose every relaunch dies BEFORE its first heartbeat is a lost
        # host — after world_shrink_after consecutive startup failures the
        # group relaunches one rank smaller through the elastic-resume
        # path (never below elastic_min_ranks)
        self.elastic_resume = bool(elastic_resume)
        self.elastic_min_ranks = max(1, int(elastic_min_ranks))
        self.world_shrink_after = max(1, int(world_shrink_after))
        self.machine_list_file = str(machine_list_file or "")
        self.hbm_budget = int(hbm_budget or 0)
        self._startup_failures: Dict[int, int] = {}
        self._evicted_total = 0
        metrics_mod.register_source(self._metrics_samples)

    def _metrics_samples(self) -> list:
        """Live ``/metrics`` view of group health: one scrape answers "is
        this group healthy" — the remaining restart budget, the last
        restart time, and every rank's heartbeat age (read fresh from the
        heartbeat files at scrape time; -1 = never stamped)."""
        out = [
            ("restart_budget_remaining", {},
             float(max(0, self.restart_limit
                       - self._restarts_since_progress)), "gauge"),
            ("last_restart_unix", {}, float(self._last_restart_unix),
             "gauge"),
            ("supervisor_restarts", {}, float(self.attempt), "counter"),
            ("supervisor_world", {}, float(self.world), "gauge"),
            # the elastic shrink signal: supervisor_world is the CONFIGURED
            # world of the incarnation being scraped; world_size tracks the
            # same value but is the documented, stable name a dashboard
            # alerts on — a drop in one scrape IS a shrink
            ("world_size", {}, float(self.world), "gauge"),
            # evictions are permanent (a shrink never un-happens), so the
            # counter + the world_size drop tell the whole story in one
            # scrape; per-rank gauges below only cover LIVE ranks — an
            # evicted rank's heartbeat gauge is dropped, not left to age
            ("rank_evicted_total", {}, float(self._evicted_total),
             "counter"),
        ]
        for r in range(self.world):
            hb = checkpoint_mod.read_heartbeat(
                checkpoint_mod.heartbeat_path(self.output_model, r))
            out.append(("rank_heartbeat_age_seconds", {"rank": str(r)},
                        float(hb[1]) if hb else -1.0, "gauge"))
            if hb:
                out.append(("rank_iteration", {"rank": str(r)},
                            float(hb[0]), "gauge"))
        return out

    # ------------------------------------------------------------- lifecycle

    def run(self) -> int:
        """Supervise until the group completes (returns 0) or the restart
        budget is exhausted (returns 1)."""
        d = os.path.dirname(os.path.abspath(self.output_model))
        os.makedirs(d, exist_ok=True)
        # startup hygiene: leftovers of PREVIOUS jobs under this prefix —
        # dead-pid atomic-write tmps, orphan crash reports, stale
        # heartbeats — are swept before the first spawn
        checkpoint_mod.sweep_stale_tmp(self.output_model,
                                       crash_reports=True, heartbeats=True)
        self._progress_mark = checkpoint_mod.latest_committed_iteration(
            self.output_model)
        exporter_armed = False
        if self.metrics_port > 0:
            metrics_mod.start_exporter(self.metrics_port)
            exporter_armed = True
        try:
            return self._run_loop()
        finally:
            if exporter_armed:
                metrics_mod.stop_exporter()

    def _run_loop(self) -> int:
        self._restarts_since_progress = 0
        self._launch()
        while True:
            time.sleep(self.poll_interval)
            verdict = self._check()
            if verdict is None:
                continue
            if verdict == "done":
                log.info("Supervisor: all %d rank(s) completed cleanly "
                         "(%d restart(s) along the way)", self.world,
                         self.attempt)
                return 0
            reason, rank, detail = verdict
            self._teardown()
            self._collect_crash_reports()
            # startup-failure bookkeeping for the elastic shrink trigger:
            # _launch sweeps heartbeats per incarnation, so no stamp for
            # the failed rank means it died BEFORE its first iteration
            # boundary — the repeatable shape of a lost host.  A rank that
            # got as far as beating resets its counter.
            hb = checkpoint_mod.read_heartbeat(
                checkpoint_mod.heartbeat_path(self.output_model, rank))
            if hb is None:
                self._startup_failures[rank] = \
                    self._startup_failures.get(rank, 0) + 1
            else:
                self._startup_failures.pop(rank, None)
            if (self.elastic_resume
                    and self._startup_failures.get(rank, 0)
                    >= self.world_shrink_after
                    and self.world - 1 >= self.elastic_min_ranks):
                rc = self._shrink(rank, reason, detail)
                if rc is not None:
                    return rc
                continue
            it = checkpoint_mod.latest_committed_iteration(self.output_model)
            if it is not None and (self._progress_mark is None
                                   or it > self._progress_mark):
                # forward progress since the last restart: the job is
                # advancing between failures — refill the budget
                self._progress_mark = it
                self._restarts_since_progress = 0
            self._restarts_since_progress += 1
            restarts_since_progress = self._restarts_since_progress
            if restarts_since_progress > self.restart_limit:
                counters.event("restart_budget_exhausted",
                               limit=self.restart_limit,
                               attempts=self.attempt + 1,
                               reason=reason, rank=rank,
                               resume_iteration=it)
                log.warning("Supervisor: restart budget exhausted (%d "
                          "restart(s) without forward progress, last "
                          "failure: %s on rank %d); giving up — the last "
                          "committed checkpoint is iteration %s",
                          self.restart_limit, reason, rank, it)
                return 1
            delay = self.restart_backoff * (2 ** (restarts_since_progress - 1))
            self.attempt += 1
            self._last_restart_unix = time.time()
            counters.gauge("restart_budget_remaining",
                           max(0, self.restart_limit
                               - restarts_since_progress))
            counters.gauge("last_restart_unix", self._last_restart_unix)
            counters.event("group_restart", attempt=self.attempt,
                           restarts_since_progress=restarts_since_progress,
                           resume_iteration=it, backoff=delay,
                           reason=reason, rank=rank, detail=detail)
            log.warning("Supervisor: %s (rank %d, %s) — restarting the "
                        "group from committed iteration %s in %.2gs "
                        "(restart %d/%d since last progress)", reason, rank,
                        detail, it, delay, restarts_since_progress,
                        self.restart_limit)
            if delay > 0:
                time.sleep(delay)
            self._launch()

    def _shrink(self, rank: int, reason: str, detail: str) -> Optional[int]:
        """Degraded-world relaunch: evict ``rank`` (its host is not coming
        back), pre-flight the mesh plan for the smaller device set, and
        relaunch the group at ``world - 1`` through the elastic-resume
        path.  Returns None on success (supervision continues) or the
        process exit code when the shrunk world cannot be planned."""
        counters.event("rank_evicted", rank=rank, reason=reason,
                       detail=detail, world=self.world,
                       startup_failures=self._startup_failures.get(rank, 0))
        log.warning("Supervisor: rank %d failed at startup %d time(s) in a "
                    "row (%s, %s) — declaring its host lost and shrinking "
                    "the group", rank, self._startup_failures.get(rank, 0),
                    reason, detail)
        new_world = self.world - 1
        # the PR 10 pre-flight, re-run for the SHRUNK device set: the
        # smaller group re-shards or fails here, before any compile.
        # capacity is only enforceable when the operator gave a budget —
        # plan_mesh with capacity=None picks a layout but cannot refuse.
        it = checkpoint_mod.latest_committed_iteration(self.output_model)
        manifest = None
        if it is not None:
            try:
                manifest = checkpoint_mod.load_manifest(self.output_model,
                                                        it)
            except checkpoint_mod.CheckpointError:
                manifest = None
        if manifest and manifest.get("num_data_global"):
            from .parallel.mesh import MeshPlanError, plan_mesh
            try:
                plan_mesh(new_world, int(manifest["num_data_global"]),
                          max(1, int(manifest.get("num_features", 1) or 1)),
                          bins=max(1, int(manifest.get("max_bin", 255)
                                          or 255)),
                          leaves=max(2, int(manifest.get("num_leaves", 31)
                                            or 31)),
                          num_class=max(1, int(manifest.get("num_class", 1)
                                               or 1)),
                          capacity=(self.hbm_budget
                                    if self.hbm_budget > 0 else None))
            except MeshPlanError as e:
                counters.event("mesh_plan_failed", world=new_world,
                               evicted_rank=rank, error=str(e))
                log.warning("Supervisor: cannot shrink to %d rank(s) — "
                            "mesh pre-flight refused the layout: %s",
                            new_world, e)
                return 1
        # drop the evicted rank's machine-list entry so the smaller
        # group's rendezvous never waits on the dead host
        if self.machine_list_file \
                and os.path.exists(self.machine_list_file):
            from .parallel import mesh
            machines = mesh.parse_machine_list(self.machine_list_file)
            if rank < len(machines):
                del machines[rank]
                mesh.write_machine_list(self.machine_list_file, machines)
        old_world = self.world
        self.world = new_world
        self.attempt += 1
        self._startup_failures = {}
        self._restarts_since_progress = 0
        self._last_restart_unix = time.time()
        self._evicted_total += 1
        # metrics hygiene: the per-rank gauges iterate range(self.world),
        # so the top index drops out of /metrics by renumbering alone —
        # but the dead incarnation's top-index FILES (heartbeat, crash
        # report, flight stream) must go too, or the next scrape-side
        # consumer (or a later world GROWTH) reads a ghost
        for r in range(new_world, old_world):
            victims = [checkpoint_mod.heartbeat_path(self.output_model, r),
                       checkpoint_mod.crash_report_path(self.output_model,
                                                        r)]
            if self.obs_stream:
                victims.append(flight_mod.stream_path(self.obs_stream, r))
            for path in victims:
                try:
                    os.unlink(path)
                except OSError:
                    pass
        counters.gauge("world_size", self.world)
        counters.gauge("rank_evicted_total", self._evicted_total)
        counters.event("world_resize", world=self.world, evicted_rank=rank,
                       attempt=self.attempt, resume_iteration=it)
        log.warning("Supervisor: relaunching at world=%d (attempt %d) via "
                    "elastic resume from committed iteration %s",
                    self.world, self.attempt, it)
        self._launch()
        return None

    def _launch(self) -> None:
        # a fresh incarnation must not inherit the previous one's liveness
        # artifacts: dead-pid tmps and old heartbeat stamps are swept,
        # along with heartbeat/crash/flight files stamped with DEAD
        # incarnation epochs (crash reports of the incarnation that just
        # failed stay until read by _collect_crash_reports)
        checkpoint_mod.sweep_stale_tmp(
            self.output_model, heartbeats=True,
            current_epoch=self.attempt,
            flight_base=self.obs_stream or "")
        # the startup-barrier fence: stamp the group's current incarnation
        # BEFORE spawning, so any straggler from a dead incarnation that
        # reaches jax.distributed bring-up sees a newer stamped epoch and
        # refuses the rendezvous (StaleEpochError) instead of wedging it
        checkpoint_mod.write_group_epoch_file(self.output_model,
                                              self.attempt)
        if self.prelaunch is not None:
            self.prelaunch(self)
        self._ranks = []
        for r in range(self.world):
            env = dict(os.environ)
            env.update(self.env)
            env["LGBM_TPU_RANK"] = str(r)
            env[ATTEMPT_ENV] = str(self.attempt)
            # the incarnation epoch fence (parallel/sync.py) + the elastic
            # world override (engine.train): children of THIS incarnation
            # are distinguishable from any stale survivor's artifacts
            env[checkpoint_mod.GROUP_EPOCH_ENV] = str(self.attempt)
            env["LGBM_TPU_WORLD"] = str(self.world)
            logf = open(f"{self.output_model}.rank_{r}.log", "ab")
            try:
                proc = subprocess.Popen(self.argv, env=env, stdout=logf,
                                        stderr=subprocess.STDOUT)
            finally:
                logf.close()      # the child holds its own fd now
            self._ranks.append(_Rank(r, proc, time.time()))
        log.info("Supervisor: launched %d rank(s) (attempt %d): %s",
                 self.world, self.attempt, " ".join(self.argv))

    # ------------------------------------------------------------- liveness

    def _check(self):
        """One poll: ``None`` (healthy), ``"done"`` (all ranks exited 0),
        or ``(reason, rank, detail)`` for the first failure seen."""
        all_done = True
        for rk in self._ranks:
            rc = rk.proc.poll()
            if rc is None:
                all_done = False
            elif rc != 0:
                hb = checkpoint_mod.read_heartbeat(
                    checkpoint_mod.heartbeat_path(self.output_model,
                                                  rk.rank))
                counters.event("rank_dead", rank=rk.rank, exit_code=rc,
                               last_heartbeat_iteration=(
                                   hb[0] if hb else None))
                return ("rank_dead", rk.rank, f"exit code {rc}")
        if all_done:
            return "done"
        now = time.time()
        for rk in self._ranks:
            if rk.proc.poll() is not None:      # exited 0: stops beating
                continue
            hb = checkpoint_mod.read_heartbeat(
                checkpoint_mod.heartbeat_path(self.output_model, rk.rank))
            age = hb[1] if hb is not None else now - rk.spawned_at
            deadline = self.hang_timeout if hb is not None \
                else self.startup_grace
            if age > deadline:
                counters.event("rank_hang", rank=rk.rank,
                               heartbeat_age=round(age, 3),
                               hang_timeout=deadline,
                               phase="beating" if hb else "starting",
                               iteration=(hb[0] if hb else None))
                return ("rank_hang", rk.rank,
                        f"heartbeat {age:.1f}s old (timeout {deadline:g}s"
                        + ("" if hb else ", never stamped") + ")")
        self._straggler_check(now)
        return None

    def _straggler_check(self, now: float) -> None:
        """Health beyond liveness: tail every rank's flight stream
        (``obs_stream_path``) and compare per-rank progress RATES.  A rank
        a ``straggler_factor`` behind the group median raises one
        structured ``rank_straggler`` event per incarnation — a verdict,
        not a restart trigger: a slow rank is making progress, and
        restarting it would destroy exactly the evidence an operator
        needs.  Host-side file reads, throttled to
        ``straggler_interval``."""
        if not self.obs_stream \
                or now - self._last_straggler_check < self.straggler_interval:
            return
        self._last_straggler_check = now
        rates = {}
        tails = {}
        for r in range(self.world):
            recs = flight_mod.tail_records(
                flight_mod.stream_path(self.obs_stream, r))
            tails[r] = recs
            rates[r] = flight_mod.progress_rate(recs)
        for s in flight_mod.detect_stragglers(rates, self.straggler_factor):
            key = (s["rank"], self.attempt)
            if key in self._stragglers_flagged:
                continue
            self._stragglers_flagged.add(key)
            extra = {}
            # devprof-armed ranks stamp the per-iteration idle-gap into
            # their progress records; citing it distinguishes a
            # host-stalled straggler from a device-bound one
            gap = flight_mod.recent_idle_gap(tails.get(s["rank"], []))
            if gap is not None:
                extra["idle_gap_fraction"] = gap
            counters.event("rank_straggler", rank=s["rank"],
                           rate=s["rate"], median_rate=s["median_rate"],
                           behind=s["behind"],
                           factor=self.straggler_factor,
                           attempt=self.attempt, **extra)
            counters.gauge(f"rank_straggler_behind_r{s['rank']}",
                           s["behind"])
            log.warning("Supervisor: rank %d is a straggler — %.3g it/s "
                        "vs group median %.3g (%.3gx behind, threshold "
                        "%gx); group is alive but not healthy",
                        s["rank"], s["rate"], s["median_rate"],
                        s["behind"], self.straggler_factor)

    # ------------------------------------------------------------- teardown

    def _teardown(self) -> None:
        """Escalating group stop: SIGTERM first (the ``preempt_signal``
        path — a healthy rank checkpoints and exits cleanly), SIGKILL for
        whatever is still alive after ``term_grace`` seconds."""
        live = [rk for rk in self._ranks if rk.proc.poll() is None]
        for rk in live:
            try:
                rk.proc.terminate()
            except OSError:      # pragma: no cover - exited under our feet
                pass
        deadline = time.time() + self.term_grace
        for rk in live:
            try:
                rk.proc.wait(timeout=max(0.0, deadline - time.time()))
            except subprocess.TimeoutExpired:
                log.warning("Supervisor: rank %d still alive %gs after "
                            "SIGTERM; escalating to SIGKILL", rk.rank,
                            self.term_grace)
                try:
                    rk.proc.kill()
                except OSError:  # pragma: no cover - exited under our feet
                    pass
                rk.proc.wait()

    def _collect_crash_reports(self) -> None:
        for r in range(self.world):
            path = checkpoint_mod.crash_report_path(self.output_model, r)
            if not os.path.exists(path):
                continue
            counters.event("crash_report", rank=r, path=path,
                           bytes=os.path.getsize(path))
            log.warning("Supervisor: rank %d left a crash report: %s",
                        r, path)


# ------------------------------------------------------------------ CLI

def main(argv: Optional[List[str]] = None) -> int:
    """``python -m lightgbm_tpu.supervisor <cli args>``: supervise the
    equivalent ``python -m lightgbm_tpu.cli`` training.  The worker
    command is the SAME argument list plus ``snapshot_resume=true`` (so
    every incarnation resumes from the newest everywhere-valid set — a
    first launch with no snapshots trains from scratch) and the effective
    ``heartbeat_interval``."""
    argv = list(sys.argv[1:] if argv is None else argv)
    from .cli import parse_cli
    from .config import config_from_params
    params = parse_cli(argv)
    cfg = config_from_params(params)
    log.set_verbosity(cfg.verbose)
    heartbeat = cfg.heartbeat_interval if cfg.heartbeat_interval > 0 else 1.0
    worker_argv = ([sys.executable, "-m", "lightgbm_tpu.cli"] + argv +
                   [f"heartbeat_interval={heartbeat}",
                    "snapshot_resume=true"])
    if cfg.metrics_port > 0:
        # the supervisor's own exporter binds metrics_port; workers get
        # metrics_port + 1 and each rank adds its process index on top
        # (engine.train), so one group scrapes at P, P+1, P+2, ...
        worker_argv.append(f"metrics_port={cfg.metrics_port + 1}")
    prelaunch = None
    if cfg.num_machines > 1 and cfg.machine_list_file:
        from .parallel import mesh

        def prelaunch(sup, _path=cfg.machine_list_file):
            # single-host groups: the dead coordinator's port can linger
            # in TIME_WAIT; refresh loopback entries per incarnation
            # (non-local entries are left untouched)
            mesh.refresh_local_ports(_path)
    sup = Supervisor(
        worker_argv, cfg.output_model, cfg.num_machines,
        heartbeat_interval=heartbeat, hang_timeout=cfg.hang_timeout,
        restart_limit=cfg.restart_limit,
        restart_backoff=cfg.restart_backoff,
        collective_timeout=cfg.collective_timeout,
        collective_retries=cfg.collective_retries, prelaunch=prelaunch,
        obs_stream=cfg.obs_stream_path,
        straggler_factor=cfg.straggler_factor,
        metrics_port=cfg.metrics_port,
        elastic_resume=cfg.elastic_resume,
        elastic_min_ranks=cfg.elastic_min_ranks,
        world_shrink_after=cfg.world_shrink_after,
        machine_list_file=cfg.machine_list_file,
        hbm_budget=cfg.hbm_budget)
    rc = sup.run()
    for name in ("rank_dead", "rank_hang", "group_restart",
                 "restart_budget_exhausted", "rank_straggler",
                 "rank_evicted", "world_resize", "mesh_plan_failed"):
        for e in counters.events(name):
            log.info("supervisor event: %s", e)
    return rc


if __name__ == "__main__":
    sys.exit(main())
