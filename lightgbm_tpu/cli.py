"""Command-line application — the reference CLI's analogue.

``lightgbm-tpu config=train.conf [key=value ...]`` mirrors
``src/application/application.cpp`` + ``src/main.cpp``: k=v args merged over a
config file (CLI wins), task dispatch train / predict / convert_model, data
loaded from text files with ``.weight``/``.query`` side files, models in the
reference text format.
"""
from __future__ import annotations

import sys
from typing import Dict, List

import numpy as np

from .basic import Booster, Dataset
from .config import (Config, canonicalize_params, config_from_params,
                     parse_config_file)
from .engine import train as train_fn
from .utils import log


def parse_cli(argv: List[str]) -> Dict[str, str]:
    params: Dict[str, str] = {}
    for arg in argv:
        if "=" not in arg:
            log.warning("Unknown CLI argument %s (expected key=value)", arg)
            continue
        k, v = arg.split("=", 1)
        params[k.strip()] = v.strip()
    if "config" in params or "config_file" in params:
        path = params.pop("config", None) or params.pop("config_file")
        file_params = parse_config_file(path)
        for k, v in file_params.items():
            params.setdefault(k, v)  # CLI args win (application.cpp:48-104)
    return params


def run_train(cfg: Config, params: Dict[str, str]) -> None:
    if not cfg.data:
        log.fatal("No training data specified (data=...)")
    dtrain = Dataset(cfg.data, params=params)
    valid_sets, valid_names = [], []
    for i, vpath in enumerate(cfg.valid_data):
        valid_sets.append(dtrain.create_valid(vpath))
        valid_names.append(f"valid_{i + 1}")
    if cfg.is_training_metric:
        valid_sets = [dtrain] + valid_sets
        valid_names = ["training"] + valid_names
    booster = train_fn(dict(params), dtrain,
                       num_boost_round=cfg.num_iterations,
                       valid_sets=valid_sets, valid_names=valid_names,
                       early_stopping_rounds=cfg.early_stopping_round or None,
                       verbose_eval=cfg.output_freq if cfg.verbose >= 1 else False,
                       # snapshot_resume=true: a preempted/killed run is
                       # re-launched with the SAME command line and picks up
                       # from the latest valid checkpoint (docs/ROBUSTNESS.md)
                       resume=cfg.snapshot_resume or None)
    booster.save_model(cfg.output_model)
    log.info("Finished training; model saved to %s", cfg.output_model)


def run_predict(cfg: Config, params: Dict[str, str]) -> None:
    if not cfg.data:
        log.fatal("No prediction data specified (data=...)")
    if not cfg.input_model:
        log.fatal("No model specified (input_model=...)")
    # serving path: native C++ predictor (predictor.hpp analogue) unless a
    # feature it doesn't cover (early stop) is requested
    from . import native
    if native.available() and not cfg.pred_early_stop:
        from .data.parser import load_text_file
        X, _, _ = load_text_file(cfg.data, has_header=cfg.has_header,
                                 label_idx=0)
        pred = native.NativePredictor(model_file=cfg.input_model)
        if cfg.is_predict_leaf_index:
            preds = pred.predict_leaf(X, cfg.num_iteration_predict)
        else:
            preds = pred.predict(X, cfg.num_iteration_predict,
                                 cfg.is_predict_raw_score)
        out = np.asarray(preds).reshape(np.asarray(X).shape[0], -1)
        np.savetxt(cfg.output_result, out, delimiter="\t", fmt="%.18g")
        log.info("Finished prediction (native); results saved to %s",
                 cfg.output_result)
        return
    booster = Booster(model_file=cfg.input_model, params=params)
    preds = booster.predict(cfg.data,
                            num_iteration=cfg.num_iteration_predict,
                            raw_score=cfg.is_predict_raw_score,
                            pred_leaf=cfg.is_predict_leaf_index,
                            pred_early_stop=cfg.pred_early_stop)
    out = np.atleast_2d(np.asarray(preds))
    if out.shape[0] == 1:
        out = out.T
    np.savetxt(cfg.output_result, out, delimiter="\t", fmt="%.18g")
    log.info("Finished prediction; results saved to %s", cfg.output_result)


def run_convert_model(cfg: Config, params: Dict[str, str]) -> None:
    """convert_model task: emit the model as portable, dependency-free C++
    if-else code (gbdt.cpp ModelToIfElse analogue) with the EXACT
    NumericalDecision/CategoricalDecision semantics of tree.h:231-313 —
    all three missing modes, default-left routing, categorical bitsets,
    multiclass tree interleaving.  The generated translation unit exports

        extern "C" void PredictRawAll(const double* fval, double* out);
        double PredictRaw(const double* fval);      // num_class == 1 only

    and is the compiled-model oracle for the conversion-consistency test
    (the reference's tests/cpp_test discipline)."""
    booster = Booster(model_file=cfg.input_model, params=params)
    trees = booster.inner.models
    k = max(booster.inner.num_class, 1)
    lines = ["#include <cmath>", "",
             "// categorical split bitsets (tree.h cat_threshold)"]
    for ti, t in enumerate(trees):
        for node in range(t.num_leaves - 1):
            if t.is_categorical(node):
                bits = ", ".join(f"{int(b)}u" for b in t.cat_bitset(node))
                lines.append(f"static const unsigned int kCat_{ti}_{node}"
                             f"[] = {{{bits}}};")
    lines += [
        "",
        "// CategoricalDecision (tree.h:268-283)",
        "static bool InBitset(const unsigned int* bits, int n, double fval,",
        "                     bool nan_is_missing) {",
        "  if (std::isnan(fval)) {",
        "    if (nan_is_missing) return false;",
        "    fval = 0.0;",
        "  }",
        "  const int v = static_cast<int>(fval);",
        "  if (v < 0) return false;",
        "  const int i1 = v / 32, i2 = v % 32;",
        "  return i1 < n && ((bits[i1] >> i2) & 1u);",
        "}",
        "",
        'extern "C" void PredictRawAll(const double* fval, double* out) {',
        f"  for (int c = 0; c < {k}; ++c) out[c] = 0.0;",
    ]
    for ti, t in enumerate(trees):
        cls = ti % k
        lines.append(f"  // tree {ti} (class {cls})")
        if t.num_leaves <= 1:
            lines.append(f"  out[{cls}] += {t.leaf_value[0]:.17g};")
            continue
        # explicit stack, not recursion — leaf-wise trees can be deeper
        # than the Python recursion limit
        stack = [("node", 0, 1)]
        while stack:
            kind, item, indent = stack.pop()
            if kind == "text":
                lines.append(item)
                continue
            node = item
            pad = "  " * indent
            if node < 0:
                leaf = ~node
                lines.append(f"{pad}out[{cls}] += "
                             f"{t.leaf_value[leaf]:.17g};")
                continue
            f = int(t.split_feature[node])
            if t.is_categorical(node):
                nbits = len(t.cat_bitset(node))
                nan_missing = "true" if t.missing_type(node) == 2 else "false"
                cond = (f"InBitset(kCat_{ti}_{node}, {nbits}, fval[{f}], "
                        f"{nan_missing})")
            else:
                # NumericalDecision (tree.h:231-266): NaN maps to 0.0
                # unless missing_type is NaN; zero-range/NaN missing
                # routes by default_left; otherwise v <= threshold
                thr = float(t.threshold[node])
                mt = t.missing_type(node)
                dl = "true" if t.default_left(node) else "false"
                v = f"(std::isnan(fval[{f}]) ? 0.0 : fval[{f}])"
                if mt == 2:       # NaN is the missing value
                    cond = (f"(std::isnan(fval[{f}]) ? {dl} : "
                            f"(fval[{f}] <= {thr:.17g}))")
                elif mt == 1:     # zero range is the missing value
                    cond = (f"(std::fabs({v}) <= 1e-20 ? {dl} : "
                            f"({v} <= {thr:.17g}))")
                else:             # no missing handling; NaN folds to 0.0
                    cond = f"{v} <= {thr:.17g}"
            lines.append(f"{pad}if ({cond}) {{")
            stack.append(("text", f"{pad}}}", 0))
            stack.append(("node", int(t.right_child[node]), indent + 1))
            stack.append(("text", f"{pad}}} else {{", 0))
            stack.append(("node", int(t.left_child[node]), indent + 1))
    lines.append("}")
    if k == 1:
        lines += ["",
                  'extern "C" double PredictRaw(const double* fval) {',
                  "  double out = 0.0;",
                  "  PredictRawAll(fval, &out);",
                  "  return out;",
                  "}"]
    with open(cfg.convert_model, "w") as f:
        f.write("\n".join(lines) + "\n")
    log.info("Model converted to %s", cfg.convert_model)


def run_dump_model(cfg: Config, params: Dict[str, str]) -> None:
    """dump_model task: write the model as JSON (the C API's
    LGBM_BoosterDumpModel / Python dump_model surface, exposed through
    the CLI so file-transport bindings — the R package — can reach it).
    Output path comes from ``convert_model`` (shared with the C++
    converter task); when not given explicitly it defaults to
    ``<input_model>.json`` rather than the converter's .cpp name."""
    import json
    if not cfg.input_model:
        log.fatal("No model specified (input_model=...)")
    # explicit convert_model= (under any alias) wins even if it equals
    # the converter default; otherwise default to <input_model>.json
    given = "convert_model" in canonicalize_params(params)
    out_path = cfg.convert_model if given else cfg.input_model + ".json"
    booster = Booster(model_file=cfg.input_model, params=params)
    with open(out_path, "w") as f:
        json.dump(booster.dump_model(), f)
    log.info("Model dumped to %s", out_path)


def main(argv: List[str] = None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    params = parse_cli(argv)
    cfg = config_from_params(params)
    log.set_verbosity(cfg.verbose)
    if cfg.num_machines > 1:
        # bring the network layer up before any device work, exactly like
        # the reference CLI (application.cpp:190-224)
        from .parallel.mesh import init_distributed_from_config
        init_distributed_from_config(cfg)
    task = params.get("task", "train")
    if task == "train":
        run_train(cfg, params)
    elif task in ("predict", "prediction", "test"):
        run_predict(cfg, params)
    elif task == "convert_model":
        run_convert_model(cfg, params)
    elif task == "dump_model":
        run_dump_model(cfg, params)
    else:
        log.fatal("Unknown task %s", task)
    return 0


if __name__ == "__main__":
    sys.exit(main())
