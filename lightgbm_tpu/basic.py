"""Public ``Dataset`` / ``Booster`` API.

Mirrors ``python-package/lightgbm/basic.py`` (Dataset :548-1210,
Booster :1213-1854) but binds directly to the in-process TPU engine instead of
ctypes into a C library: lazy construction, reference-aligned validation
datasets, pandas passthrough, model save/load, training loop primitives.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from . import data as data_mod
from .boosting import GBDT, create_boosting
from .config import Config, canonicalize_params, config_from_params
from .data.dataset import TrainingData, construct
from .data.parser import load_text_file, read_header_names
from .objectives import create_objective
from .utils import log


def _to_matrix(data) -> np.ndarray:
    if hasattr(data, "values"):         # pandas DataFrame / Series
        data = data.values
    arr = np.asarray(data)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    return arr


def _data_from_pandas(data, pandas_categorical):
    """Convert a DataFrame's ``category`` columns to their integer codes
    (reference basic.py:225-263 _data_from_pandas).  On the train dataset
    ``pandas_categorical`` is None and the category levels are recorded;
    on valid/predict data the recorded levels re-align the codes so the
    same string maps to the same code everywhere.

    Returns (float_matrix, cat_col_names, pandas_categorical)."""
    cat_cols = [c for c in data.columns
                if str(data[c].dtype) == "category"]
    if pandas_categorical is None:
        pandas_categorical = [list(data[c].cat.categories) for c in cat_cols]
    else:
        if len(cat_cols) != len(pandas_categorical):
            raise ValueError("train and valid dataset categorical_feature "
                             "do not match.")
        data = data.copy()
        for col, cats in zip(cat_cols, pandas_categorical):
            if list(data[col].cat.categories) != list(cats):
                data[col] = data[col].cat.set_categories(cats)
    if cat_cols:
        data = data.copy()
        for c in cat_cols:
            # code -1 means NaN or a level outside the train categories —
            # route it through the missing-value path, not as a phantom
            # category (reference _data_from_pandas replace({-1: nan}))
            codes = data[c].cat.codes.to_numpy().astype(np.float64)
            codes[codes == -1] = np.nan
            data[c] = codes
    return (np.asarray(data.values, dtype=np.float64), cat_cols,
            pandas_categorical)


def _load_pandas_categorical(model_str: str):
    """Last-line ``pandas_categorical:<json>`` of a model file
    (reference basic.py:277-289)."""
    import json
    last = model_str.rstrip().rsplit("\n", 1)[-1]
    if last.startswith("pandas_categorical:"):
        return json.loads(last[len("pandas_categorical:"):])
    return None


class Dataset:
    """Lazily-constructed training dataset (basic.py:548+ semantics)."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List] = "auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = False, silent: bool = False):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params or {})
        self.free_raw_data = free_raw_data
        self._constructed: Optional[TrainingData] = None
        self.raw: Optional[np.ndarray] = None
        self.pandas_categorical: Optional[List[List]] = None

    # -- lazy construction --------------------------------------------------

    def _distributed_row_selection(self, cfg: Config,
                                   n_rows: int) -> Optional[np.ndarray]:
        """Row→machine assignment when several processes train
        data/voting-parallel from the SAME data file without
        pre-partitioning (dataset_loader.cpp LoadTextDataToMemory:563-607):
        a shared-seed random draw per row — per QUERY when query data
        exists — keeps exactly the rows assigned to this rank, so the
        union over ranks is a disjoint cover of the file.  Caller
        established the dist-rows predicate and the distributed runtime."""
        import jax
        if jax.process_count() <= 1:
            return None
        from .utils.random import make_rng
        nm = jax.process_count()
        rank = jax.process_index()
        rng = make_rng(cfg.data_random_seed)
        if self.group is not None:
            counts = np.asarray(self.group, dtype=np.int64)
            assign = rng.integers(0, nm, size=len(counts))
            row_q = np.repeat(np.arange(len(counts)), counts)
            sel = np.flatnonzero(assign[row_q] == rank)
            self.group = counts[assign == rank]
        else:
            assign = rng.integers(0, nm, size=n_rows)
            sel = np.flatnonzero(assign == rank)
        log.info("Distributed loading: rank %d keeps %d of %d rows",
                 rank, len(sel), n_rows)
        return sel

    def construct(self, config: Optional[Config] = None) -> "Dataset":
        cfg = config or config_from_params(self.params)
        # shared-file row distribution applies to the TRAIN file only —
        # validation data (reference set) stays whole on every rank, like
        # the reference's LoadFromFileAlignWithOtherDataset
        dist_intent = (cfg.num_machines > 1 and not cfg.is_pre_partition
                       and cfg.tree_learner in ("data", "voting")
                       and self.reference is None)
        dist_rows = dist_intent and isinstance(self.data, (str, os.PathLike))
        if self._constructed is not None:
            if (dist_intent and getattr(self, "_loaded_from_file", False)
                    and not getattr(self, "_dist_sharded", False)):
                # constructed earlier without the distribution params
                # (e.g. num_data() before train()): training data-parallel
                # on full per-rank replicas would double-count every row —
                # rebuild from the file with the real config
                if not isinstance(self.data, (str, os.PathLike)):
                    log.fatal(
                        "Dataset was constructed without distributed row "
                        "partitioning and the raw file reference was "
                        "freed; pass the num_machines/tree_learner params "
                        "to the Dataset or construct it inside train()")
                log.warning("Reconstructing dataset with distributed row "
                            "partitioning (it was first constructed "
                            "without the parallel params)")
                self._constructed = None
                if getattr(self, "_label_from_file", False):
                    self.label = None   # reload file labels at full length;
                                        # a user-supplied label is kept and
                                        # sharded by [sel] like weight
            else:
                return self
        if dist_rows:
            # bring the distributed runtime up BEFORE any jax backend
            # touch, so an early construct() (num_data, save_binary, ...)
            # shards exactly like the one inside train() — idempotent
            from .parallel.mesh import init_distributed_from_config
            init_distributed_from_config(cfg)
            if cfg.use_two_round_loading:
                log.warning("use_two_round_loading falls back to in-memory "
                            "loading when rows are distributed across "
                            "machines (set pre_partition=true to stream "
                            "per-machine files)")
        elif isinstance(self.data, (str, os.PathLike)) \
                and self.reference is None:
            # CheckCanLoadFromBin (dataset_loader.cpp:980-1018): prefer an
            # existing "<data>.bin" cache; accept the data file itself
            # being a binary cache
            path = str(self.data)
            for candidate in (path + ".bin", path):
                if self._is_binary_cache(candidate):
                    log.info("Loading dataset from binary cache %s",
                             candidate)
                    self._constructed = \
                        self._load_binary_training_data(candidate)
                    # user-supplied fields override the cached metadata
                    # (reference binary load + set_field flow)
                    if self.label is not None:
                        self.set_label(self.label)
                    else:
                        self.label = self._constructed.metadata.label
                    if self.weight is not None:
                        self.set_weight(self.weight)
                    if self.group is not None:
                        self.set_group(self.group)
                    if self.init_score is not None:
                        self.set_init_score(self.init_score)
                    self._loaded_from_file = True
                    self._dist_sharded = False
                    return self
        if (isinstance(self.data, (str, os.PathLike))
                and cfg.use_two_round_loading and self.reference is None
                and not dist_rows):
            # two-round streamed loading (dataset_loader.cpp:181-207): the
            # raw float matrix never materializes — sample pass, then a
            # chunked bin-as-you-read pass into the final uint8/16 matrix
            path = str(self.data)
            meta_probe = data_mod.Metadata(0)
            meta_probe.load_side_files(path)
            names = (list(self.feature_name)
                     if isinstance(self.feature_name, (list, tuple))
                     else (read_header_names(path, 0) if cfg.has_header
                           else None))
            cat_idx: List[int] = []
            if isinstance(self.categorical_feature, (list, tuple)):
                for c in self.categorical_feature:
                    if isinstance(c, str) and names and c in names:
                        cat_idx.append(names.index(c))
                    elif not isinstance(c, str):
                        cat_idx.append(int(c))
            self._constructed = data_mod.construct_streamed(
                path, cfg,
                label=(None if self.label is None
                       else np.asarray(self.label, np.float32).ravel()),
                weight=meta_probe.weight if self.weight is None
                else np.asarray(self.weight),
                group=(np.diff(meta_probe.query_boundaries)
                       if self.group is None
                       and meta_probe.query_boundaries is not None
                       else self.group),
                init_score=meta_probe.init_score if self.init_score is None
                else np.asarray(self.init_score),
                feature_names=names, categorical_features=cat_idx)
            self.label = self._constructed.metadata.label
            self.raw = None
            self._loaded_from_file = True
            self._dist_sharded = False
            if cfg.is_save_binary_file:
                self._save_binary_cache()
            if self.free_raw_data:
                self.data = None
            return self
        if isinstance(self.data, data_mod.CsrMatrix):
            # sparse C-ABI ingest: two-round chunked binning — the full
            # dense float64 matrix never materializes (data/sparse.py)
            names = (list(self.feature_name)
                     if isinstance(self.feature_name, (list, tuple))
                     else None)
            cat_idx: List[int] = []
            if isinstance(self.categorical_feature, (list, tuple)):
                for c in self.categorical_feature:
                    if isinstance(c, str) and names and c in names:
                        cat_idx.append(names.index(c))
                    elif not isinstance(c, str):
                        cat_idx.append(int(c))
            ref = self.reference.construct(config)._constructed \
                if self.reference is not None else None
            self._constructed = data_mod.construct_csr(
                self.data, cfg,
                label=(None if self.label is None
                       else np.asarray(self.label, np.float32).ravel()),
                weight=(None if self.weight is None
                        else np.asarray(self.weight)),
                group=None if self.group is None else np.asarray(self.group),
                init_score=(None if self.init_score is None
                            else np.asarray(self.init_score)),
                feature_names=names, categorical_features=cat_idx,
                reference=ref)
            self.raw = None
            self._loaded_from_file = False
            self._dist_sharded = False
            if self.free_raw_data:
                self.data = None
            return self
        pd_cat_cols: List = []   # pandas category-dtype columns, by name
        if isinstance(self.data, (str, os.PathLike)):
            path = str(self.data)
            feats, labels, names = load_text_file(
                path, has_header=cfg.has_header, label_idx=0)
            if self.label is None:
                self.label = labels
                self._label_from_file = True
            mat = feats
            if names and self.feature_name == "auto":
                self.feature_name = names
            # side files: .weight / .query / .init
            meta_probe = data_mod.Metadata(len(labels))
            meta_probe.load_side_files(path)
            if self.weight is None and meta_probe.weight is not None:
                self.weight = meta_probe.weight
            if self.group is None and meta_probe.query_boundaries is not None:
                self.group = np.diff(meta_probe.query_boundaries)
            if self.init_score is None and meta_probe.init_score is not None:
                self.init_score = meta_probe.init_score
            sel = self._distributed_row_selection(cfg, len(mat)) \
                if dist_rows else None
            self._loaded_from_file = True
            self._dist_sharded = sel is not None
            self._want_binary_save = (cfg.is_save_binary_file
                                      and sel is None)
            if sel is not None:   # this rank's shard of the shared file
                n_full = len(mat)
                mat = mat[sel]
                if self.label is not None:
                    self.label = np.asarray(self.label)[sel]
                if self.weight is not None:
                    self.weight = np.asarray(self.weight)[sel]
                if self.init_score is not None:
                    init = np.asarray(self.init_score)
                    k = max(int(getattr(cfg, "num_class", 1) or 1), 1)
                    if k > 1 and init.size == k * n_full:
                        # flattened [num_class, N] layout: select the
                        # shard's rows within every class block
                        init = init.reshape(k, n_full)[:, sel].ravel()
                    else:
                        init = init[sel]
                    self.init_score = init
                # self.group was already partitioned by query unit
        elif hasattr(self.data, "columns") and hasattr(self.data, "dtypes"):
            # pandas: category-dtype columns become their codes, with the
            # train dataset's category levels re-aligning valid data
            # (reference _data_from_pandas)
            ref_pc = (self.reference.pandas_categorical
                      if self.reference is not None
                      else self.pandas_categorical)
            mat, pd_cat_cols, self.pandas_categorical = \
                _data_from_pandas(self.data, ref_pc)
        else:
            mat = _to_matrix(self.data)

        cat_idx: List[int] = []
        names: Optional[List[str]] = None
        if isinstance(self.feature_name, (list, tuple)):
            names = list(self.feature_name)
        if hasattr(self.data, "columns"):   # pandas
            cols = [str(c) for c in self.data.columns]
            if names is None:
                names = cols
            explicit = (list(self.categorical_feature)
                        if self.categorical_feature not in ("auto", None)
                        else [])
            # category-dtype columns are categorical features regardless
            # of the explicit list (reference basic.py:241-247)
            for c in explicit + [str(c) for c in pd_cat_cols]:
                idx = cols.index(c) if isinstance(c, str) else int(c)
                if idx not in cat_idx:
                    cat_idx.append(idx)
        elif isinstance(self.categorical_feature, (list, tuple)):
            for c in self.categorical_feature:
                if isinstance(c, str) and names and c in names:
                    cat_idx.append(names.index(c))
                elif not isinstance(c, str):
                    cat_idx.append(int(c))

        ref = self.reference.construct(config)._constructed \
            if self.reference is not None else None
        label = np.asarray(self.label, dtype=np.float32).ravel() \
            if self.label is not None else None
        self._constructed = construct(
            mat, cfg, label=label,
            weight=None if self.weight is None else np.asarray(self.weight),
            group=None if self.group is None else np.asarray(self.group),
            init_score=None if self.init_score is None
            else np.asarray(self.init_score),
            feature_names=names, categorical_features=cat_idx, reference=ref)
        self.raw = mat if not self.free_raw_data else None
        if getattr(self, "_want_binary_save", False):
            self._want_binary_save = False
            self._save_binary_cache()
        if self.free_raw_data:
            self.data = None
        return self

    def _save_binary_cache(self) -> None:
        """is_save_binary_file: write the "<data>.bin" cache next to the
        text file (dataset_loader.cpp SaveBinaryFile flow)."""
        bin_path = str(self.data) + ".bin"
        self.save_binary(bin_path)
        log.info("Saved binary dataset cache to %s", bin_path)

    @property
    def constructed(self) -> TrainingData:
        if self._constructed is None:
            self.construct()
        return self._constructed

    # -- reference-like helpers --------------------------------------------

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score,
                       params=params or self.params)

    def set_label(self, label) -> "Dataset":
        self.label = label
        if self._constructed is not None:
            self._constructed.metadata.set_label(np.asarray(label))
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = weight
        if self._constructed is not None:
            self._constructed.metadata.set_weight(
                None if weight is None else np.asarray(weight))
        return self

    def set_group(self, group) -> "Dataset":
        self.group = group
        if self._constructed is not None:
            self._constructed.metadata.set_query(
                None if group is None else np.asarray(group))
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = init_score
        if self._constructed is not None:
            self._constructed.metadata.set_init_score(
                None if init_score is None else np.asarray(init_score))
        return self

    def get_label(self):
        return (np.asarray(self.constructed.metadata.label)
                if self.constructed.metadata.label is not None else None)

    def get_weight(self):
        return self.constructed.metadata.weight

    def get_group(self):
        qb = self.constructed.metadata.query_boundaries
        return None if qb is None else np.diff(qb)

    def get_init_score(self):
        return self.constructed.metadata.init_score

    def set_field(self, field_name: str, data) -> "Dataset":
        """Generic metadata setter (reference Dataset.set_field)."""
        setters = {"label": self.set_label, "weight": self.set_weight,
                   "group": self.set_group, "query": self.set_group,
                   "init_score": self.set_init_score}
        if field_name not in setters:
            raise ValueError(f"Unknown field {field_name!r}")
        return setters[field_name](data)

    def get_field(self, field_name: str):
        getters = {"label": self.get_label, "weight": self.get_weight,
                   "group": self.get_group, "query": self.get_group,
                   "init_score": self.get_init_score}
        if field_name not in getters:
            raise ValueError(f"Unknown field {field_name!r}")
        return getters[field_name]()

    def set_feature_name(self, feature_name) -> "Dataset":
        if feature_name == "auto":     # reference sentinel: keep as-is
            return self
        self.feature_name = list(feature_name)
        if self._constructed is not None:
            self._constructed.feature_names = list(feature_name)
        return self

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        if self._constructed is not None and \
                categorical_feature != self.categorical_feature:
            log.warning("categorical_feature change after construction "
                        "requires reconstructing the Dataset")
            self._constructed = None
        self.categorical_feature = categorical_feature
        return self

    def set_reference(self, reference: "Dataset") -> "Dataset":
        if self._constructed is not None and reference is not self.reference:
            self._constructed = None   # rebin against the new reference
        self.reference = reference
        return self

    def get_ref_chain(self, ref_limit: int = 100):
        """Set of datasets reachable through reference links."""
        chain, cur = [], self
        while cur is not None and len(chain) < ref_limit:
            chain.append(cur)
            cur = cur.reference
        return set(chain)

    def ensure_raw(self) -> Optional[np.ndarray]:
        """Raw feature matrix for the consumers that need one (cv, subset,
        continued training).  When the dataset was constructed without
        materializing it — binary-cache load or streamed loading — the
        matrix is recovered by re-parsing the original text file, provided
        that file still exists, is not itself a cache, and agrees with the
        constructed row count (guards against stale caches)."""
        if self.raw is not None:
            return self.raw
        if isinstance(self.data, data_mod.CsrMatrix):
            # chunk-assembled full densify — only the consumers that
            # genuinely need the whole matrix pay for it
            self.raw = np.asarray(self.data)
            return self.raw
        if isinstance(self.data, (str, os.PathLike)) \
                and not self._is_binary_cache(str(self.data)):
            cfg = config_from_params(self.params)
            try:
                feats, _, _ = load_text_file(str(self.data),
                                             has_header=cfg.has_header)
            except Exception as e:
                log.warning("Could not recover raw data from %s: %s",
                            self.data, e)
                return None
            if self._constructed is not None \
                    and len(feats) != self._constructed.num_data:
                log.warning("Raw file %s has %d rows but the constructed "
                            "dataset has %d — refusing the mismatch",
                            self.data, len(feats),
                            self._constructed.num_data)
                return None
            self.raw = feats
            return self.raw
        return None

    def subset(self, used_indices, params=None) -> "Dataset":
        """Row-subset Dataset sharing this dataset's bin mappers
        (reference Dataset.subset; requires raw data retained in memory)."""
        self.construct()
        raw = self.ensure_raw()
        if raw is None:
            log.fatal("Cannot subset: raw data not in memory (construct "
                      "with free_raw_data=False from an in-memory matrix)")
        idx = np.asarray(used_indices, dtype=np.int64)
        label = self.get_label()
        w = self.get_weight()
        init = self.get_init_score()
        group = self.get_group()
        sub_group = None
        if group is not None:
            # per-row query ids -> counts of SELECTED rows per query, empty
            # queries dropped (row subset of grouped data keeps group
            # structure like the reference's index-based subset)
            qid = np.repeat(np.arange(len(group)), group.astype(np.int64))
            counts = np.bincount(qid[idx], minlength=len(group))
            sub_group = counts[counts > 0]
        return Dataset(raw[idx],
                       label=None if label is None else label[idx],
                       weight=None if w is None else np.asarray(w)[idx],
                       group=sub_group,
                       init_score=None if init is None
                       else np.asarray(init)[idx],
                       reference=self,
                       params=dict(params or self.params))

    def num_data(self) -> int:
        return self.constructed.num_data

    def num_feature(self) -> int:
        return self.constructed.num_total_features

    # token identifying our binary dataset cache files — the analogue of
    # Dataset::binary_file_token checked by CheckCanLoadFromBin.  The
    # payload is npz + JSON, loaded with allow_pickle=False: a cache file
    # is DATA, never executable (unlike pickle).
    BINARY_TOKEN = b"lightgbm_tpu.dataset.v2\n"

    def save_binary(self, filename: str, compress: bool = True) -> "Dataset":
        """Binary dataset cache (Dataset::SaveBinaryFile analogue).

        ``compress=False`` skips zlib (the reference's binary file is also
        raw) — random bin indices barely compress and the deflate pass
        dominates save time on large matrices."""
        import io
        import json
        c = self.constructed
        mappers = [{
            "num_bin": int(m.num_bin), "bin_type": int(m.bin_type),
            "missing_type": int(m.missing_type),
            "is_trivial": bool(m.is_trivial),
            "bin_upper_bound": (None if m.bin_upper_bound is None
                                else [float(x) for x in m.bin_upper_bound]),
            "categorical_2_bin": (None if m.categorical_2_bin is None
                                  else {str(k): int(v) for k, v
                                        in m.categorical_2_bin.items()}),
            "bin_2_categorical": (None if m.bin_2_categorical is None
                                  else [int(x) for x in m.bin_2_categorical]),
            "min_val": float(m.min_val), "max_val": float(m.max_val),
            "default_bin": int(m.default_bin),
        } for m in c.bin_mappers]
        meta = {
            "mappers": mappers,
            "feature_names": list(c.feature_names or []),
            "num_total_features": int(c.num_total_features),
            "used_features": [int(x) for x in c.used_features],
            "bundles": (None if c.layout is None
                        else [[int(j) for j in b] for b in c.layout.bundles]),
        }
        arrays = {"binned": np.asarray(c.binned),
                  "meta_json": np.frombuffer(
                      json.dumps(meta).encode(), dtype=np.uint8).copy()}
        for key, val in (("label", c.metadata.label),
                         ("weight", c.metadata.weight),
                         ("query_boundaries", c.metadata.query_boundaries),
                         ("init_score", c.metadata.init_score)):
            if val is not None:
                arrays[key] = np.asarray(val)
        buf = io.BytesIO()
        (np.savez_compressed if compress else np.savez)(buf, **arrays)
        with open(filename, "wb") as f:
            f.write(Dataset.BINARY_TOKEN)
            f.write(buf.getvalue())
        return self

    @staticmethod
    def _is_binary_cache(filename: str) -> bool:
        try:
            with open(filename, "rb") as f:
                return f.read(len(Dataset.BINARY_TOKEN)) == \
                    Dataset.BINARY_TOKEN
        except OSError:
            return False

    @staticmethod
    def _load_binary_training_data(filename: str) -> TrainingData:
        import io
        import json
        from .data.binning import BinMapper
        from .data.bundling import BundleLayout
        with open(filename, "rb") as f:
            head = f.read(len(Dataset.BINARY_TOKEN))
            if head != Dataset.BINARY_TOKEN:
                raise ValueError(f"{filename} is not a lightgbm_tpu binary "
                                 "dataset cache")
            npz = np.load(io.BytesIO(f.read()), allow_pickle=False)
        meta = json.loads(bytes(npz["meta_json"]).decode())
        td = TrainingData()
        td.binned = npz["binned"]
        td.used_features = list(meta["used_features"])
        td.feature_names = meta["feature_names"]
        td.num_total_features = meta["num_total_features"]
        td.num_data = len(td.binned)
        td.bin_mappers = []
        for d in meta["mappers"]:
            m = BinMapper()
            m.num_bin = d["num_bin"]
            m.bin_type = d["bin_type"]
            m.missing_type = d["missing_type"]
            m.is_trivial = d["is_trivial"]
            m.bin_upper_bound = (None if d["bin_upper_bound"] is None else
                                 np.asarray(d["bin_upper_bound"], np.float64))
            m.categorical_2_bin = (None if d["categorical_2_bin"] is None
                                   else {int(k): v for k, v
                                         in d["categorical_2_bin"].items()})
            m.bin_2_categorical = d["bin_2_categorical"]
            m.min_val = d["min_val"]
            m.max_val = d["max_val"]
            m.default_bin = d["default_bin"]
            td.bin_mappers.append(m)
        if meta.get("bundles") is not None:
            td.layout = BundleLayout(meta["bundles"], td.bin_mappers,
                                     td.used_features)
        td.metadata = data_mod.Metadata(td.num_data)
        td.metadata.set_label(npz["label"] if "label" in npz else None)
        td.metadata.set_weight(npz["weight"] if "weight" in npz else None)
        td.metadata.query_boundaries = (npz["query_boundaries"]
                                        if "query_boundaries" in npz else None)
        td.metadata.set_init_score(npz["init_score"]
                                   if "init_score" in npz else None)
        return td

    @staticmethod
    def load_binary(filename: str) -> "Dataset":
        ds = Dataset(None)
        ds._constructed = Dataset._load_binary_training_data(filename)
        return ds


class Booster:
    """Training/prediction handle (basic.py:1213+ semantics)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None, silent: bool = False):
        self.params = dict(params or {})
        self.best_iteration = -1
        self.best_score: Dict = {}
        self._train_dataset = train_set
        self.pandas_categorical: Optional[List[List]] = None
        if train_set is not None:
            cfg = config_from_params(self.params)
            log.set_verbosity(cfg.verbose)
            train_set.construct(cfg)
            self.pandas_categorical = train_set.pandas_categorical
            objective = create_objective(cfg)
            self.inner: GBDT = create_boosting(cfg, train_set.constructed,
                                               objective)
        elif model_file is not None:
            with open(model_file) as f:
                content = f.read()
            self.inner = GBDT.load_from_string(
                content, config_from_params(self.params))
            self.pandas_categorical = _load_pandas_categorical(content)
        elif model_str is not None:
            self.inner = GBDT.load_from_string(
                model_str, config_from_params(self.params))
            self.pandas_categorical = _load_pandas_categorical(model_str)
        else:
            raise ValueError("Booster needs train_set, model_file or model_str")

    # -- training loop primitives ------------------------------------------

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        data.construct(self.inner.config)
        self.inner.add_valid_set(data.constructed, name)
        self._valid_datasets = getattr(self, "_valid_datasets", [])
        self._valid_datasets.append(data)
        return self

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration; custom objective fobj(preds, train_data) ->
        (grad, hess) like the reference."""
        if fobj is None:
            return self.inner.train_one_iter()
        scores = np.asarray(self.inner.scores, np.float64)
        preds = scores.reshape(-1) if scores.shape[0] > 1 else scores[0]
        grad, hess = fobj(preds, self._train_dataset)
        return self.inner.train_one_iter(np.asarray(grad), np.asarray(hess))

    def rollback_one_iter(self) -> "Booster":
        self.inner.rollback_one_iter()
        return self

    def current_iteration(self) -> int:
        return self.inner.current_iteration()

    def attr(self, key: str):
        """Free-form model attribute (reference Booster.attr)."""
        return getattr(self, "_attr", {}).get(key)

    def set_attr(self, **kwargs) -> "Booster":
        store = getattr(self, "_attr", {})
        for k, v in kwargs.items():
            if v is None:
                store.pop(k, None)
            else:
                store[k] = str(v)
        self._attr = store
        return self

    def set_train_data_name(self, name: str) -> "Booster":
        self._train_data_name = name
        return self

    def free_dataset(self) -> "Booster":
        """Release the training/validation data (binned matrices, scores,
        bag subsets) — predict/save/dump still work; further training and
        eval do not (reference Booster.free_dataset contract)."""
        self._train_dataset = None
        self._valid_datasets = []
        inner = self.inner
        inner.train_set = None
        inner.valid_sets = []
        inner.bins = None
        inner.scores = None
        inner._subset_state = None
        inner._local_bins_cache = None
        inner._stream_store = None
        inner._streamer = None
        return self

    def get_leaf_output(self, tree_id: int, leaf_id: int) -> float:
        """Raw leaf output; tree_id indexes the stored model list directly,
        INCLUDING the boost-from-average init tree when present — the
        reference pushes that init tree into models_ too
        (gbdt.cpp:467-483), so the numbering matches."""
        return float(self.inner.models[tree_id].leaf_value[leaf_id])

    def set_leaf_output(self, tree_id: int, leaf_id: int,
                        value: float) -> "Booster":
        """LGBM_BoosterSetLeafValue analogue: overwrite one leaf's raw
        output (same tree numbering as get_leaf_output)."""
        self.inner.models[tree_id].leaf_value[leaf_id] = float(value)
        self.inner._drop_serving_caches()   # serving caches now stale
        return self

    def merge(self, other: "Booster") -> "Booster":
        """LGBM_BoosterMerge: prepend other's trees to this model
        (reference GBDT::MergeFrom ordering)."""
        self.inner.merge_from(other.inner)
        return self

    def eval(self, data: Dataset, name: str, feval=None):
        """Evaluate the current model on an arbitrary dataset
        (reference Booster.eval)."""
        datasets = getattr(self, "_valid_datasets", [])
        for i, vs in enumerate(self.inner.valid_sets):
            if i < len(datasets) and datasets[i] is data:
                break
        else:
            self.add_valid(data, name)   # not attached: score from scratch
            vs = self.inner.valid_sets[-1]
        res = [(name, m, v, h) for (_, m, v, h)
               in self.inner._eval(vs.name, vs.metrics,
                                   np.asarray(vs.scores, np.float64))]
        return self._add_feval(res, name, feval, vs.scores, data)

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        canon = canonicalize_params(params)
        for k, v in canon.items():
            setattr(self.inner.config, k, type(getattr(self.inner.config, k))(v)
                    if not isinstance(getattr(self.inner.config, k), list) else v)
        self.params.update(canon)   # keep the param record in sync (reference
        return self                 # Booster.reset_parameter does the same)

    # -- evaluation ---------------------------------------------------------

    def eval_train(self, feval=None):
        res = self.inner.eval_train()
        return self._add_feval(res, "training", feval,
                               self.inner.scores, self._train_dataset)

    def eval_valid(self, feval=None):
        res = self.inner.eval_valid()
        if feval is not None:
            datasets = getattr(self, "_valid_datasets", [])
            for i, vs in enumerate(self.inner.valid_sets):
                ds = datasets[i] if i < len(datasets) else None
                res = self._add_feval(res, vs.name, feval, vs.scores, ds)
        return res

    def _add_feval(self, res, name, feval, scores, dataset):
        if feval is not None:
            scores = np.asarray(scores, np.float64)
            preds = scores.reshape(-1) if scores.shape[0] > 1 else scores[0]
            out = feval(preds, dataset)
            if isinstance(out, tuple):
                out = [out]
            for metric, value, is_higher_better in out:
                res = list(res) + [(name, metric, value, is_higher_better)]
        return res

    # -- prediction / io ----------------------------------------------------

    def predict(self, data, num_iteration: int = -1, raw_score: bool = False,
                pred_leaf: bool = False, pred_contrib: bool = False,
                pred_early_stop: bool = False,
                pred_parameter: Optional[Dict[str, Any]] = None, **kwargs):
        if isinstance(data, (str, os.PathLike)):
            feats, _, _ = load_text_file(str(data),
                                         has_header=self.inner.config.has_header)
            data = feats
        elif hasattr(data, "columns") and hasattr(data, "dtypes"):
            data = _data_from_pandas(data, self.pandas_categorical)[0]
        else:
            data = _to_matrix(data)
        if num_iteration is None or num_iteration <= 0:
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        # reference basic.py predict accepts per-call prediction params
        # (pred_parameter dict); merge with the keyword forms
        pp = canonicalize_params(pred_parameter or {})
        pred_early_stop = bool(pp.get("pred_early_stop", pred_early_stop))
        pred_leaf = bool(pp.get("is_predict_leaf_index", pred_leaf))
        pred_contrib = bool(pp.get("is_predict_contrib", pred_contrib))
        raw_score = bool(pp.get("is_predict_raw_score", raw_score))
        es_freq = pp.get("pred_early_stop_freq")
        es_margin = pp.get("pred_early_stop_margin")
        return self.inner.predict(
            data, num_iteration=num_iteration, raw_score=raw_score,
            pred_leaf=pred_leaf, pred_contrib=pred_contrib,
            pred_early_stop=pred_early_stop,
            pred_early_stop_freq=None if es_freq is None else int(es_freq),
            pred_early_stop_margin=(None if es_margin is None
                                    else float(es_margin)))

    def predict_engine(self, prewarm: bool = True, buckets=None):
        """Build (or return the cached) SoA serving engine for this model
        — the flatten + device threshold tables + pre-warmed microbatch
        executables of docs/SERVING.md.  Called once at model
        load/finalize by the serving loop; subsequent ``predict`` calls
        reuse it through the cached :class:`Predictor` engine."""
        return self.inner.predict_engine(prewarm=prewarm, buckets=buckets)

    def save_model(self, filename: str, num_iteration: int = -1) -> "Booster":
        if num_iteration is None or num_iteration <= 0:
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        self.inner.save_model(filename, num_iteration)
        if self.pandas_categorical:
            # trailing mapping line, ignored by model parsers (reference
            # _save_pandas_categorical)
            import json
            with open(filename, "a") as f:
                f.write("\npandas_categorical:"
                        + json.dumps(self.pandas_categorical) + "\n")
        return self

    def model_to_string(self, num_iteration: int = -1) -> str:
        s = self.inner.save_model_to_string(num_iteration)
        if self.pandas_categorical:
            import json
            s += ("\npandas_categorical:"
                  + json.dumps(self.pandas_categorical) + "\n")
        return s

    def dump_model(self, num_iteration: int = -1) -> Dict:
        """JSON model dump (gbdt.cpp DumpModel)."""
        inner = self.inner
        trees = inner.models
        if num_iteration > 0:
            cut = (num_iteration + (1 if inner.boost_from_average_ else 0)) \
                * inner.num_class
            trees = trees[:cut]
        return {
            "name": "tree",
            "version": "v2",
            "num_class": inner.num_class,
            "num_tree_per_iteration": inner.num_class,
            "label_index": inner.label_idx,
            "max_feature_idx": inner.max_feature_idx,
            "objective": inner.objective.to_string() if inner.objective else "",
            "average_output": inner.average_output,
            "feature_names": inner.feature_names,
            "tree_info": [t.to_json(i) for i, t in enumerate(trees)],
        }

    def feature_importance(self, importance_type: str = "split",
                           iteration: int = -1) -> np.ndarray:
        return self.inner.feature_importance(importance_type, iteration)

    def feature_name(self) -> List[str]:
        return list(self.inner.feature_names)

    def num_trees(self) -> int:
        return len(self.inner.models)

    def num_feature(self) -> int:
        return self.inner.max_feature_idx + 1

    # pickle support: serialize via model string
    def __getstate__(self):
        state = {"params": self.params,
                 "best_iteration": self.best_iteration,
                 "best_score": self.best_score,
                 "model_str": self.inner.save_model_to_string(-1)}
        return state

    def __setstate__(self, state):
        self.params = state["params"]
        self.best_iteration = state["best_iteration"]
        self.best_score = state["best_score"]
        self._train_dataset = None
        self.inner = GBDT.load_from_string(
            state["model_str"], config_from_params(self.params))
