"""Evaluation metrics (host-side numpy).

Re-creates the reference metric factory (``src/metric/metric.cpp:11-47``) and
formulas (``regression_metric.hpp``, ``binary_metric.hpp``,
``multiclass_metric.hpp``, ``rank_metric.hpp``, ``map_metric.hpp``,
``xentropy_metric.hpp``, ``dcg_calculator.cpp``).  Metrics consume raw scores
plus the objective's ``convert_output`` exactly like the reference
(``Metric::Eval(score, objective_function)``).

Metrics are cheap relative to training, so they run on host numpy in f64 —
which also matches the reference's double accumulators.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from .config import Config
from .data.metadata import Metadata
from .objectives import Objective, default_label_gain
from .utils import log

K_EPSILON = 1e-15


class Metric:
    name = "base"
    is_higher_better = False  # factor -1 in reference means "minimize"

    def __init__(self, config: Config):
        self.config = config
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.metadata: Optional[Metadata] = None
        self.sum_weights = 0.0

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.metadata = metadata
        self.label = np.asarray(metadata.label, dtype=np.float64)
        self.weight = (np.asarray(metadata.weight, dtype=np.float64)
                       if metadata.weight is not None else None)
        self.sum_weights = (float(self.weight.sum()) if self.weight is not None
                            else float(num_data))

    def names(self) -> List[str]:
        return [self.name]

    def eval(self, score: np.ndarray, objective: Optional[Objective]) -> List[float]:
        raise NotImplementedError

    def _avg(self, loss: np.ndarray) -> float:
        if self.weight is not None:
            return float((loss * self.weight).sum() / self.sum_weights)
        return float(loss.mean())


class _PointwiseRegressionMetric(Metric):
    """CRTP pattern of regression_metric.hpp:16-110."""

    def point_loss(self, label, score):
        raise NotImplementedError

    def average(self, v: float) -> float:
        return v

    def eval(self, score, objective):
        s = np.asarray(score[0], dtype=np.float64)
        if objective is not None and getattr(objective, "name", "") not in (
                "regression", "regression_l1", "huber", "fair", "poisson"):
            s = np.asarray(objective.convert_output(s), dtype=np.float64)
        return [self.average(self._avg(self.point_loss(self.label, s)))]


class L2Metric(_PointwiseRegressionMetric):
    name = "l2"

    def point_loss(self, label, score):
        return (score - label) ** 2


class RMSEMetric(_PointwiseRegressionMetric):
    name = "rmse"

    def point_loss(self, label, score):
        return (score - label) ** 2

    def average(self, v):
        return float(np.sqrt(v))


class L1Metric(_PointwiseRegressionMetric):
    name = "l1"

    def point_loss(self, label, score):
        return np.abs(score - label)


class HuberMetric(_PointwiseRegressionMetric):
    name = "huber"

    def point_loss(self, label, score):
        d = self.config.huber_delta
        diff = score - label
        return np.where(np.abs(diff) <= d, 0.5 * diff * diff,
                        d * (np.abs(diff) - 0.5 * d))


class FairMetric(_PointwiseRegressionMetric):
    name = "fair"

    def point_loss(self, label, score):
        c = self.config.fair_c
        x = np.abs(score - label)
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_PointwiseRegressionMetric):
    name = "poisson"

    def point_loss(self, label, score):
        eps = 1e-10
        s = np.where(score < eps, eps, score)
        return s - label * np.log(s)


class BinaryLoglossMetric(Metric):
    name = "binary_logloss"

    def eval(self, score, objective):
        prob = np.asarray(objective.convert_output(score[0])
                          if objective is not None else score[0], dtype=np.float64)
        y = self.label > 0
        p = np.clip(np.where(y, prob, 1.0 - prob), K_EPSILON, None)
        return [self._avg(-np.log(p))]


class BinaryErrorMetric(Metric):
    name = "binary_error"

    def eval(self, score, objective):
        prob = np.asarray(objective.convert_output(score[0])
                          if objective is not None else score[0], dtype=np.float64)
        err = np.where(prob <= 0.5, self.label > 0, self.label <= 0)
        return [self._avg(err.astype(np.float64))]


class AUCMetric(Metric):
    """Weighted rank-sum AUC with tie handling (binary_metric.hpp:157-266)."""
    name = "auc"
    is_higher_better = True

    def eval(self, score, objective):
        s = np.asarray(score[0], dtype=np.float64)
        y = self.label > 0
        w = self.weight if self.weight is not None else np.ones_like(s)
        order = np.argsort(s, kind="mergesort")
        s_sorted = s[order]
        pos_w = np.where(y, w, 0.0)[order]
        neg_w = np.where(~y, w, 0.0)[order]
        # group equal scores
        boundary = np.nonzero(np.diff(s_sorted))[0] + 1
        groups = np.split(np.arange(len(s)), boundary)
        auc_sum = 0.0
        neg_cum = 0.0
        for g in groups:
            p_g = pos_w[g].sum()
            n_g = neg_w[g].sum()
            auc_sum += p_g * (neg_cum + 0.5 * n_g)
            neg_cum += n_g
        total_pos = pos_w.sum()
        total_neg = neg_w.sum()
        if total_pos <= 0 or total_neg <= 0:
            log.warning("AUC is undefined with a single class")
            return [1.0]
        return [float(auc_sum / (total_pos * total_neg))]


class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, score, objective):
        p = np.asarray(objective.convert_output(np.asarray(score, np.float64))
                       if objective is not None else score, dtype=np.float64)
        li = self.label.astype(np.int64)
        pt = np.clip(p[li, np.arange(p.shape[1])], K_EPSILON, None)
        return [self._avg(-np.log(pt))]


class MultiErrorMetric(Metric):
    name = "multi_error"

    def eval(self, score, objective):
        s = np.asarray(score, dtype=np.float64)
        pred = s.argmax(axis=0)
        err = (pred != self.label.astype(np.int64)).astype(np.float64)
        return [self._avg(err)]


class XentropyMetric(Metric):
    """xentropy_metric.hpp — cross entropy for labels in [0, 1]."""
    name = "xentropy"

    def eval(self, score, objective):
        p = np.clip(np.asarray(
            objective.convert_output(score[0]) if objective is not None
            else 1.0 / (1.0 + np.exp(-np.asarray(score[0]))), dtype=np.float64),
            K_EPSILON, 1 - K_EPSILON)
        y = self.label
        loss = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        return [self._avg(loss)]


class XentLambdaMetric(Metric):
    """xentropy_metric.hpp — cross entropy with 'lambda' parameterization."""

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.weight is not None and np.asarray(self.weight).min() <= 0:
            log.fatal("[xentlambda]: (metric) all weights must be positive")
    name = "xentlambda"

    def eval(self, score, objective):
        # XentLambdaLoss (xentropy_metric.hpp:50-52): weights scale hhat
        # INSIDE the probability transform — prob = 1 - exp(-w * hhat)
        # with hhat = log1p(exp(s)) — and the final average is a PLAIN
        # mean over rows ("weights have a different meaning than for
        # xentropy", :160); log args clipped at 1e-12 like XentLoss
        s = np.asarray(score[0], dtype=np.float64)
        # during training hhat comes from the OBJECTIVE's ConvertOutput
        # (xentropy_metric.hpp:206-219 — even when the objective is not
        # xentlambda, the reference feeds its transform straight in);
        # standalone eval auto-converts via log1p(exp(s))
        if objective is not None:
            hhat = np.asarray(objective.convert_output(s), np.float64)
        else:
            hhat = np.log1p(np.exp(s))
        w = (np.asarray(self.weight, np.float64)
             if self.weight is not None else 1.0)
        z = 1.0 - np.exp(-w * hhat)
        y = self.label
        eps = 1.0e-12
        loss = -(y * np.log(np.maximum(z, eps))
                 + (1 - y) * np.log(np.maximum(1.0 - z, eps)))
        return [float(np.mean(loss))]


class KLDivMetric(Metric):
    """kldiv = xentropy minus label entropy."""
    name = "kldiv"

    def eval(self, score, objective):
        p = np.clip(1.0 / (1.0 + np.exp(-np.asarray(score[0], np.float64))),
                    K_EPSILON, 1 - K_EPSILON)
        y = np.clip(self.label, 0.0, 1.0)
        # YentLoss: x*log(x) = 0 at x in {0, 1} — mask before log
        ys = np.clip(y, K_EPSILON, 1 - K_EPSILON)
        ent = np.where((y > 0) & (y < 1),
                       y * np.log(ys) + (1 - y) * np.log(1 - ys), 0.0)
        loss = ent - (y * np.log(p) + (1 - y) * np.log(1 - p))
        return [self._avg(loss)]


class _RankMetric(Metric):
    def __init__(self, config):
        super().__init__(config)
        self.eval_at = list(config.ndcg_eval_at)
        self.gains = np.asarray(config.label_gain or default_label_gain(),
                                dtype=np.float64)

    def names(self):
        return [f"{self.name}@{k}" for k in self.eval_at]

    def _query_weights(self) -> Tuple[np.ndarray, np.ndarray]:
        bounds = np.asarray(self.metadata.query_boundaries)
        nq = len(bounds) - 1
        if self.weight is not None:
            qw = np.asarray([self.weight[bounds[q]:bounds[q + 1]].mean()
                             for q in range(nq)])
        else:
            qw = np.ones(nq)
        return bounds, qw


class NDCGMetric(_RankMetric):
    """rank_metric.hpp:16-170 + dcg_calculator.cpp."""
    name = "ndcg"
    is_higher_better = True

    def eval(self, score, objective):
        s = np.asarray(score[0], dtype=np.float64)
        bounds, qw = self._query_weights()
        nq = len(bounds) - 1
        results = np.zeros(len(self.eval_at), dtype=np.float64)
        for q in range(nq):
            ls = self.label[bounds[q]:bounds[q + 1]].astype(np.int64)
            ss = s[bounds[q]:bounds[q + 1]]
            order = np.argsort(-ss, kind="mergesort")
            sorted_gain = self.gains[ls[order]]
            ideal_gain = -np.sort(-self.gains[ls])
            disc = 1.0 / np.log2(np.arange(len(ls)) + 2.0)
            for ki, k in enumerate(self.eval_at):
                kk = min(k, len(ls))
                max_dcg = float((ideal_gain[:kk] * disc[:kk]).sum())
                if max_dcg <= 0.0:
                    results[ki] += qw[q]  # all-zero-relevance query counts as 1
                else:
                    dcg = float((sorted_gain[:kk] * disc[:kk]).sum())
                    results[ki] += qw[q] * dcg / max_dcg
        return list(results / qw.sum())


class MapMetric(_RankMetric):
    """map_metric.hpp — mean average precision at k (binary relevance)."""
    name = "map"
    is_higher_better = True

    def eval(self, score, objective):
        s = np.asarray(score[0], dtype=np.float64)
        bounds, qw = self._query_weights()
        nq = len(bounds) - 1
        results = np.zeros(len(self.eval_at), dtype=np.float64)
        for q in range(nq):
            # binary relevance at label > 0.5 (map_metric.hpp:63)
            ls = (self.label[bounds[q]:bounds[q + 1]] > 0.5).astype(np.float64)
            ss = s[bounds[q]:bounds[q + 1]]
            npos = int(ls.sum())          # positives in the WHOLE query
            order = np.argsort(-ss, kind="mergesort")
            rel = ls[order]
            hits = np.cumsum(rel)
            prec = hits / (np.arange(len(rel)) + 1.0)
            for ki, k in enumerate(self.eval_at):
                kk = min(k, len(rel))
                if npos > 0:
                    # CalMapAtK: sum of precisions at hit positions within
                    # top-k, normalized by min(total positives, k) — NOT by
                    # the hits inside the window
                    ap = float((prec[:kk] * rel[:kk]).sum())
                    results[ki] += qw[q] * ap / min(npos, kk)
                else:
                    results[ki] += qw[q]   # no-positive query counts as 1
        return list(results / qw.sum())


_REGISTRY = {
    "l2": L2Metric, "mean_squared_error": L2Metric, "mse": L2Metric,
    "regression": L2Metric, "regression_l2": L2Metric,
    "rmse": RMSEMetric, "root_mean_squared_error": RMSEMetric, "l2_root": RMSEMetric,
    "l1": L1Metric, "mean_absolute_error": L1Metric, "mae": L1Metric,
    "regression_l1": L1Metric,
    "huber": HuberMetric,
    "fair": FairMetric,
    "poisson": PoissonMetric,
    "binary_logloss": BinaryLoglossMetric, "binary": BinaryLoglossMetric,
    "binary_error": BinaryErrorMetric,
    "auc": AUCMetric,
    "ndcg": NDCGMetric, "lambdarank": NDCGMetric,
    "map": MapMetric, "mean_average_precision": MapMetric,
    "multi_logloss": MultiLoglossMetric, "multiclass": MultiLoglossMetric,
    "softmax": MultiLoglossMetric, "multiclassova": MultiLoglossMetric,
    "multi_error": MultiErrorMetric,
    "xentropy": XentropyMetric, "cross_entropy": XentropyMetric,
    "xentlambda": XentLambdaMetric, "cross_entropy_lambda": XentLambdaMetric,
    "kldiv": KLDivMetric, "kullback_leibler": KLDivMetric,
}


def create_metric(name: str, config: Config) -> Optional[Metric]:
    """Factory (metric.cpp:11-47); returns None for 'None'/'' style names."""
    n = name.lower().strip()
    if n in ("", "none", "null", "na"):
        return None
    if n not in _REGISTRY:
        log.fatal("Unknown metric type name: %s", name)
    return _REGISTRY[n](config)


def default_metric_for_objective(objective: str) -> str:
    """config.cpp behavior: empty metric defaults to the objective's own."""
    mapping = {
        "regression": "l2", "regression_l2": "l2", "mse": "l2", "l2": "l2",
        "regression_l1": "l1", "l1": "l1", "mae": "l1",
        "huber": "huber", "fair": "fair", "poisson": "poisson",
        "binary": "binary_logloss",
        "multiclass": "multi_logloss", "softmax": "multi_logloss",
        "multiclassova": "multi_logloss", "ova": "multi_logloss",
        "lambdarank": "ndcg",
        "xentropy": "xentropy", "cross_entropy": "xentropy",
        "xentlambda": "xentlambda", "cross_entropy_lambda": "xentlambda",
    }
    return mapping.get(objective.lower(), "l2")
