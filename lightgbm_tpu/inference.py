"""High-QPS inference artifact: ensemble SoA node arrays + bucketed,
donated-buffer microbatch executables.

``Predictor.predict`` (the training-side oracle) walks a Python list of
:class:`~lightgbm_tpu.tree.Tree` objects per call — per-tree host
traversal, no caching, no latency story.  This module is the dedicated
serving path the ROADMAP names ("Booster: An Accelerator for Gradient
Boosting Decision Trees" is the layout reference):

* :class:`SoABundle` — the whole ensemble flattened ONCE into contiguous
  ``[T, P]`` structure-of-arrays node tables (feature, threshold rank,
  left/right child, default direction, missing type, categorical mask
  reference), with both axes pow2-bucketed exactly like
  ``trees_scores_binned`` so the jit signature set stays bounded.  Leaf
  values stay host-side ``float64`` shaped ``[iterations, K, P+1]``
  (multiclass is a leaf-value channel axis) so the margin accumulation
  reproduces ``Predictor.predict_raw`` bit for bit.
* **On-device raw-feature binning**: per-column *threshold tables* are
  derived from the ensemble (the sorted unique split thresholds of each
  used column — a model-defined :class:`~lightgbm_tpu.data.binning.BinMapper`)
  and uploaded once; a microbatch executable bins a raw ``[B, F]`` batch
  with one vmapped ``searchsorted`` and traverses every tree in the same
  kernel.  Node thresholds become integer *ranks* into the same tables, so
  the routing comparison is exact integer ``bin <= rank``.
* **Bit-exactness discipline**: the f32 threshold tables are rounded
  toward ``-inf`` from the f64 model thresholds, which makes
  ``v <= t_f64`` and ``v <= floor32(t)`` equivalent for every
  f32-representable ``v`` — serving traffic (f32 feature payloads) routes
  identically to the f64 host oracle.  Inputs that genuinely need f64
  (``float64`` values that do not round-trip through f32) are binned on
  host against the f64 tables instead and traversed by the binned-input
  twin executable: same integer routing, still bit-identical.
* **Microbatch executables**: module-level jitted kernels take every
  model array as an *argument* (nothing is baked in as a constant), so a
  hot-swapped model with the same bucket shape reuses the compiled
  executable — zero recompiles across a swap.  Batch shapes are padded up
  a pow2-ish ladder (default 1/8/64/512/4096; ``serving_buckets`` param)
  and the input buffer is donated on backends that support donation.
  :func:`jit_entries` exposes the compiled-signature count as the
  ``predict_jit_entries`` gauge (the ``grower_jit_entries`` discipline).

Every dispatch lands a ``predict_dispatch`` counter (batch bucket +
executable identity) and the bin/traverse/margin phases run under obs
spans via :class:`~lightgbm_tpu.utils.timer.PhaseTimers`.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .config import parse_serving_buckets
from .obs import memory as obs_memory
from .obs.counters import counters as obs_counters
from .tree import Tree
from .utils import log
from .utils.timer import PhaseTimers

MISSING_NONE, MISSING_ZERO, MISSING_NAN = 0, 1, 2

# default microbatch ladder (rows); the `serving_buckets` param overrides
DEFAULT_BUCKETS: Tuple[int, ...] = (1, 8, 64, 512, 4096)

# kZeroAsMissingValueRange (tree.py ZERO_RANGE), floored to f32 so the
# on-device |v| <= range check matches the host f64 one for f32 inputs
ZERO_RANGE = 1e-20


def _floor_to_f32(a: np.ndarray) -> np.ndarray:
    """Round f64 values toward -inf onto the f32 grid.  For any
    f32-representable ``v``: ``v <= a``  ⟺  ``v <= _floor_to_f32(a)`` —
    the identity the on-device binning's exactness rests on."""
    f = np.asarray(a, np.float64).astype(np.float32)
    over = f.astype(np.float64) > np.asarray(a, np.float64)
    if over.any():
        f[over] = np.nextafter(f[over], np.float32(-np.inf))
    return f


_ZERO_RANGE_F32 = float(_floor_to_f32(np.array([ZERO_RANGE]))[0])


def _pow2_at_least(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


# --------------------------------------------------------------- SoA bundle


@dataclasses.dataclass
class SoABundle:
    """The ensemble flattened once: contiguous ``[Tp, P]`` node arrays on
    device, leaf values + f64 threshold tables on host."""

    num_trees: int                     # real tree count (rest is padding)
    num_class: int
    tp: int                            # pow2 tree bucket
    p: int                             # pow2 node bucket (max num_leaves-1)
    cols: np.ndarray                   # compact column -> original feature
    thr64: List[np.ndarray]            # per compact column: sorted unique f64
    leaf_value: np.ndarray             # [Tp, P+1] f64 (host margin tables)
    # device arrays
    thr_table: jnp.ndarray             # [Fc, B] f32, +inf padded, floor32
    feat: jnp.ndarray                  # [Tp, P] i32 compact column index
    thr: jnp.ndarray                   # [Tp, P] i32 threshold rank
    default_left: jnp.ndarray          # [Tp, P] bool
    miss: jnp.ndarray                  # [Tp, P] i32 missing type
    left: jnp.ndarray                  # [Tp, P] i32 (leaves encoded ~leaf)
    right: jnp.ndarray                 # [Tp, P] i32
    is_cat: jnp.ndarray                # [Tp, P] bool
    cat_ref: jnp.ndarray               # [Tp, P] i32 row of cat_mask
    cat_mask: jnp.ndarray              # [C, W] bool over raw category values
    # packed-node-word traversal (serving_traversal=packed): each node's
    # routing fields folded into TWO i32 words so a traversal step costs
    # one fused node gather + one data gather instead of eight scalar-
    # lowered gathers (the measured ~1.6x XLA:CPU headroom of PR 8).
    # None when the ensemble is not packable (categorical nodes, or field
    # widths past the word budget) — the classic traversal always exists.
    node_w0: Optional[jnp.ndarray] = None  # [Tp, P] i32: feat | thr<<12
    #                                        | default_left<<28 | miss<<29
    node_w1: Optional[jnp.ndarray] = None  # [Tp, P] i32: left | right<<16
    #                                        (int16 two's complement halves)
    max_depth: int = 0                 # fori ladder length (packed path)

    @property
    def num_cols(self) -> int:
        return len(self.cols)

    @property
    def num_bins(self) -> int:
        return int(self.thr_table.shape[1])

    def exec_id(self) -> str:
        """Executable identity tag: everything but the batch bucket that
        keys the compiled signature."""
        return (f"t{self.tp}p{self.p}f{self.num_cols}b{self.num_bins}"
                f"c{self.cat_mask.shape[0]}w{self.cat_mask.shape[1]}")

    @staticmethod
    def build(trees: Sequence[Tree], num_class: int = 1) -> "SoABundle":
        num_trees = len(trees)
        tp = _pow2_at_least(max(num_trees, 1))
        p = _pow2_at_least(max(max((t.num_leaves - 1 for t in trees),
                                   default=1), 1))
        # pass 1: used columns + per-column threshold tables + cat widths
        used: Dict[int, List[float]] = {}
        cat_bits = 1
        cat_rows = 0
        for t in trees:
            for i in range(max(t.num_leaves - 1, 0)):
                f = int(t.split_feature[i])
                vals = used.setdefault(f, [])
                if t.is_categorical(i):
                    cat_rows += 1
                    cat_bits = max(cat_bits, 32 * len(t.cat_bitset(i)))
                else:
                    vals.append(float(t.threshold[i]))
        cols = np.asarray(sorted(used), dtype=np.int32)
        col_of = {int(f): i for i, f in enumerate(cols)}
        thr64 = [np.unique(np.asarray(used[int(f)], np.float64))
                 for f in cols]
        nb = max((len(u) for u in thr64), default=0) or 1
        fc = max(len(cols), 1)
        table = np.full((fc, nb), np.inf, np.float32)
        for i, u in enumerate(thr64):
            table[i, :len(u)] = _floor_to_f32(u)
        # pass 2: node arrays (padding trees are 0-leaf stumps: children -1
        # terminate traversal at leaf 0, whose padded leaf value is 0)
        feat = np.zeros((tp, p), np.int32)
        thr = np.zeros((tp, p), np.int32)
        dl = np.zeros((tp, p), bool)
        miss = np.zeros((tp, p), np.int32)
        lc = np.full((tp, p), -1, np.int32)
        rc = np.full((tp, p), -1, np.int32)
        ic = np.zeros((tp, p), bool)
        cref = np.zeros((tp, p), np.int32)
        cmask = np.zeros((max(cat_rows, 1), cat_bits), bool)
        lv = np.zeros((tp, p + 1), np.float64)
        ci = 0
        for ti, t in enumerate(trees):
            nl = t.num_leaves
            if nl >= 1 and len(t.leaf_value):
                lv[ti, :nl] = t.leaf_value[:nl]
            nn = nl - 1
            if nn <= 0:
                continue
            fcomp = np.asarray([col_of[int(f)] for f in t.split_feature[:nn]],
                               np.int32)
            feat[ti, :nn] = fcomp
            dl[ti, :nn] = (t.decision_type[:nn]
                           & 2) > 0                      # K_DEFAULT_LEFT_MASK
            miss[ti, :nn] = (t.decision_type[:nn].astype(np.int32) >> 2) & 3
            lc[ti, :nn] = t.left_child[:nn]
            rc[ti, :nn] = t.right_child[:nn]
            for i in range(nn):
                if t.is_categorical(i):
                    ic[ti, i] = True
                    cmask[ci] = t.cat_value_mask(i, cat_bits)
                    cref[ti, i] = ci
                    ci += 1
                else:
                    u = thr64[fcomp[i]]
                    thr[ti, i] = int(np.searchsorted(
                        u, float(t.threshold[i])))
        # packed-node-word twin: build whenever the ensemble fits the word
        # budget (numerical-only, <=4096 used columns, <=65535 threshold
        # ranks, <=32767 nodes/leaves).  Routing fields are folded into two
        # i32 words; children are int16 two's complement halves of w1, so
        # ``(w1 << 16) >> 16`` / ``w1 >> 16`` sign-extend them back exactly.
        w0 = w1 = None
        depth = 0
        packable = (not ic.any() and fc <= 4096 and int(thr.max(initial=0))
                    <= 0xffff and p <= 32767 and nb < (1 << 24))
        if packable:
            w0 = (feat.astype(np.int64) | (thr.astype(np.int64) << 12)
                  | (dl.astype(np.int64) << 28)
                  | (miss.astype(np.int64) << 29)).astype(np.int32)
            w1 = ((lc.astype(np.int64) & 0xffff)
                  | ((rc.astype(np.int64) & 0xffff) << 16)).astype(np.int32)
            depth = max((t.max_depth() for t in trees if t.num_leaves > 1),
                        default=0)
        return SoABundle(
            num_trees=num_trees, num_class=max(num_class, 1), tp=tp, p=p,
            cols=cols, thr64=thr64, leaf_value=lv,
            thr_table=jnp.asarray(table), feat=jnp.asarray(feat),
            thr=jnp.asarray(thr), default_left=jnp.asarray(dl),
            miss=jnp.asarray(miss), left=jnp.asarray(lc),
            right=jnp.asarray(rc), is_cat=jnp.asarray(ic),
            cat_ref=jnp.asarray(cref), cat_mask=jnp.asarray(cmask),
            node_w0=jnp.asarray(w0) if w0 is not None else None,
            node_w1=jnp.asarray(w1) if w1 is not None else None,
            max_depth=int(depth))

    def device_args(self) -> tuple:
        return (self.feat, self.thr, self.default_left, self.miss,
                self.left, self.right, self.is_cat, self.cat_ref,
                self.cat_mask)

    def host_nodes(self) -> Dict[str, np.ndarray]:
        """Host copies of the routing arrays (fetched once, cached) —
        the contribution path replays per-node decisions as cheap host
        integer compares over device-binned rows."""
        cached = getattr(self, "_host_nodes", None)
        if cached is None:
            cached = {name: np.asarray(arr) for name, arr in zip(
                ("feat", "thr", "dl", "miss", "lc", "rc", "ic", "cref",
                 "cmask"), self.device_args())}
            self._host_nodes = cached
        return cached

    def go_matrix(self, t: int, num_nodes: int, bins: np.ndarray,
                  cats: np.ndarray, nanm: np.ndarray,
                  zerom: np.ndarray) -> np.ndarray:
        """go-left per (internal node, row) of tree ``t`` from binned
        rows — integer-for-integer the ``_traverse`` routing decision,
        evaluated for every node instead of only the visited ones (the
        TreeSHAP recursion needs the hot child at each node)."""
        h = self.host_nodes()
        n = bins.shape[0]
        go = np.zeros((num_nodes, n), bool)
        w = h["cmask"].shape[1]
        for i in range(num_nodes):
            f = int(h["feat"][t, i])
            b = bins[:, f]
            is_nan = nanm[:, f]
            mt = int(h["miss"][t, i])
            nan_missing = is_nan if mt == MISSING_NAN \
                else np.zeros(n, bool)
            missing = nan_missing | (zerom[:, f] if mt == MISSING_ZERO
                                     else False)
            gl = np.where(missing, bool(h["dl"][t, i]),
                          b <= int(h["thr"][t, i]))
            if h["ic"][t, i]:
                c = cats[:, f]
                cm = h["cmask"][int(h["cref"][t, i]),
                                np.clip(c, 0, w - 1)]
                gl = (~nan_missing) & (c >= 0) & (c < w) & cm
            go[i] = gl
        return go

    # -------------------------------------------------- host-side binning

    def bin_host(self, xc: np.ndarray):
        """Exact f64 binning for inputs that do not round-trip through f32
        (same integer ranks as the device tables — the binned-input twin
        executable routes identically)."""
        nanm = np.isnan(xc)
        xz = np.where(nanm, 0.0, xc)
        zerom = np.abs(xz) <= ZERO_RANGE
        bins = np.zeros(xc.shape, np.int32)
        for i, u in enumerate(self.thr64):
            if len(u):
                bins[:, i] = np.searchsorted(u, xz[:, i], side="left")
        with np.errstate(invalid="ignore"):
            cats = np.clip(np.trunc(xz), np.iinfo(np.int32).min,
                           np.iinfo(np.int32).max).astype(np.int32)
        return bins, cats, nanm, zerom


# --------------------------------------------------- microbatch executables
#
# Module-level jitted kernels: every model array is an ARGUMENT, so two
# engines with the same bucket shapes (e.g. pre- and post-hot-swap models)
# share one compiled executable.  The raw-input kernel fuses device
# binning with traversal; the binned-input twin serves host-binned f64
# batches.


def _traverse(bins, cats, nanm, zerom, feat, thr, dl, miss, lc, rc, ic,
              cat_ref, cat_mask):
    """Vectorized decision-tree descent over pre-binned features.
    ``NumericalDecisionInner`` / ``CategoricalDecision`` semantics
    (tree.h:257-313), on integer threshold ranks -> leaf index [Tp, B]."""
    n = bins.shape[0]
    num_nodes = feat.shape[1]
    w = cat_mask.shape[1]

    def one_tree(feat_t, thr_t, dl_t, miss_t, lc_t, rc_t, ic_t, cref_t):
        def cond(state):
            node, _ = state
            return jnp.any(node >= 0)

        def body(state):
            node, leaf = state
            nd = jnp.clip(node, 0, num_nodes - 1)
            f = feat_t[nd]
            b = jnp.take_along_axis(bins, f[:, None], axis=1)[:, 0]
            c = jnp.take_along_axis(cats, f[:, None], axis=1)[:, 0]
            is_nan = jnp.take_along_axis(nanm, f[:, None], axis=1)[:, 0]
            is_zero = jnp.take_along_axis(zerom, f[:, None], axis=1)[:, 0]
            mt = miss_t[nd]
            nan_missing = (mt == MISSING_NAN) & is_nan
            missing = nan_missing | ((mt == MISSING_ZERO) & is_zero)
            go = jnp.where(missing, dl_t[nd], b <= thr_t[nd])
            cm = cat_mask[cref_t[nd], jnp.clip(c, 0, w - 1)]
            go_cat = (~nan_missing) & (c >= 0) & (c < w) & cm
            go = jnp.where(ic_t[nd], go_cat, go)
            nxt = jnp.where(go, lc_t[nd], rc_t[nd])
            active = node >= 0
            return (jnp.where(active, nxt, node),
                    jnp.where(active & (nxt < 0), ~nxt, leaf))

        _, leaf = lax.while_loop(
            cond, body, (jnp.zeros((n,), jnp.int32),
                         jnp.zeros((n,), jnp.int32)))
        return leaf

    # named_scope twin of the host predict_traverse span: bakes the
    # serving-traversal identity into the lowered HLO so the device-time
    # attributor (obs/devprof.py) can account traversal kernels by scope
    with jax.named_scope("traverse"):
        return jax.vmap(one_tree)(feat, thr, dl, miss, lc, rc, ic, cat_ref)


def _leaves_from_raw_impl(x, thr_table, *node_args):
    """x [B, Fc] f32 -> leaf [Tp, B]: on-device binning (one vmapped
    searchsorted against the resident threshold tables) fused with the
    traversal."""
    nanm = jnp.isnan(x)
    xz = jnp.where(nanm, jnp.float32(0), x)
    zerom = jnp.abs(xz) <= jnp.float32(_ZERO_RANGE_F32)
    bins = jax.vmap(lambda t, v: jnp.searchsorted(t, v, side="left"),
                    in_axes=(0, 1), out_axes=1)(thr_table, xz)
    bins = bins.astype(jnp.int32)
    cats = xz.astype(jnp.int32)
    return _traverse(bins, cats, nanm, zerom, *node_args)


def _leaves_from_binned_impl(bins, cats, nanm, zerom, *node_args):
    return _traverse(bins, cats, nanm, zerom, *node_args)


# ------------------------------------------- packed-node-word traversal
#
# serving_traversal=packed: the whole per-node routing record rides in two
# i32 words and the per-row feature payload in one (bin rank | nan bit |
# zero bit), so each traversal step is ONE node-word gather pair + ONE
# data-word gather — XLA:CPU lowers each separate gather scalar-by-scalar,
# which made the classic 8-gather step the serving bottleneck (PR 8's
# measured ~1.6x offline headroom).  The depth ladder is a ``fori_loop``
# (no per-step ``any(node >= 0)`` reduction): every row reaches its leaf
# within the bundle's max_depth, finished rows self-loop via the
# ``active`` select.  Routing decisions are integer-for-integer identical
# to ``_traverse``, so leaf indices — and therefore raw margins — are
# bit-identical (pinned in tests/test_serving.py).


def _traverse_packed(dat, w0s, w1s, depth):
    n = dat.shape[0]
    num_nodes = w0s.shape[1]

    def one_tree(w0_t, w1_t):
        def step(_, state):
            node, leaf = state
            nd = jnp.clip(node, 0, num_nodes - 1)
            w0 = w0_t[nd]
            w1 = w1_t[nd]
            f = w0 & 0xfff
            thr = (w0 >> 12) & 0xffff
            dl = (w0 >> 28) & 1
            mt = (w0 >> 29) & 3
            dw = jnp.take_along_axis(dat, f[:, None], axis=1)[:, 0]
            b = dw & 0xffffff
            missing = (((mt == MISSING_NAN) & ((dw >> 24) & 1 == 1))
                       | ((mt == MISSING_ZERO) & ((dw >> 25) & 1 == 1)))
            go = jnp.where(missing, dl == 1, b <= thr)
            nxt = jnp.where(go, (w1 << 16) >> 16, w1 >> 16)
            active = node >= 0
            return (jnp.where(active, nxt, node),
                    jnp.where(active & (nxt < 0), ~nxt, leaf))

        return lax.fori_loop(0, depth, step,
                             (jnp.zeros((n,), jnp.int32),
                              jnp.zeros((n,), jnp.int32)))[1]

    with jax.named_scope("traverse"):   # devprof scope twin (see _traverse)
        return jax.vmap(one_tree)(w0s, w1s)


def _pack_data_words(bins, nanm, zerom):
    return (bins.astype(jnp.int32)
            | (nanm.astype(jnp.int32) << 24)
            | (zerom.astype(jnp.int32) << 25))


def _leaves_from_raw_packed_impl(x, thr_table, w0s, w1s, depth):
    nanm = jnp.isnan(x)
    xz = jnp.where(nanm, jnp.float32(0), x)
    zerom = jnp.abs(xz) <= jnp.float32(_ZERO_RANGE_F32)
    bins = jax.vmap(lambda t, v: jnp.searchsorted(t, v, side="left"),
                    in_axes=(0, 1), out_axes=1)(thr_table, xz)
    return _traverse_packed(_pack_data_words(bins, nanm, zerom),
                            w0s, w1s, depth)


def _leaves_from_binned_packed_impl(bins, cats, nanm, zerom, w0s, w1s,
                                    depth):
    del cats     # packed bundles are numerical-only by construction
    return _traverse_packed(_pack_data_words(bins, nanm, zerom),
                            w0s, w1s, depth)


# ------------------------------------------------- auxiliary device kernels
#
# Model-quality plane (obs/model_quality.py): the binning stage of the
# raw-input traversal factored out standalone.  ``_bin_arrays`` hands the
# device-binned rows to the host TreeSHAP recursion
# (``pred_contrib=True``); ``_bin_hist`` folds one microbatch into
# per-feature threshold-rank histograms with a single scatter-add — the
# serving drift monitor's window accumulator.  Deliberately NOT counted
# by :func:`jit_entries`: that gauge pins the serving *traversal*
# executable set, which these do not touch.


def _bin_arrays_impl(x, thr_table):
    nanm = jnp.isnan(x)
    xz = jnp.where(nanm, jnp.float32(0), x)
    zerom = jnp.abs(xz) <= jnp.float32(_ZERO_RANGE_F32)
    bins = jax.vmap(lambda t, v: jnp.searchsorted(t, v, side="left"),
                    in_axes=(0, 1), out_axes=1)(thr_table, xz)
    return bins.astype(jnp.int32), xz.astype(jnp.int32), nanm, zerom


def _bin_hist_impl(x, thr_table, valid):
    nanm = jnp.isnan(x)
    xz = jnp.where(nanm, jnp.float32(0), x)
    bins = jax.vmap(lambda t, v: jnp.searchsorted(t, v, side="left"),
                    in_axes=(0, 1), out_axes=1)(thr_table, xz)
    bins = bins.astype(jnp.int32)
    nb1 = thr_table.shape[1] + 1
    vi = valid.astype(jnp.int32)
    return jax.vmap(
        lambda b: jnp.zeros((nb1,), jnp.int32).at[b].add(vi),
        in_axes=1)(bins)                                    # [Fc, NB+1]


@functools.lru_cache(maxsize=None)
def _aux_jitted():
    return jax.jit(_bin_arrays_impl), jax.jit(_bin_hist_impl)


@functools.lru_cache(maxsize=None)
def _jitted(donate: bool):
    if donate:
        return (jax.jit(_leaves_from_raw_impl, donate_argnums=(0,)),
                jax.jit(_leaves_from_binned_impl,
                        donate_argnums=(0, 1, 2, 3)))
    return (jax.jit(_leaves_from_raw_impl),
            jax.jit(_leaves_from_binned_impl))


@functools.lru_cache(maxsize=None)
def _jitted_packed(donate: bool):
    """Packed-node-word twins (serving_traversal=packed).  ``depth`` is a
    traced scalar, so one executable pair serves every same-shape model —
    the hot-swap zero-recompile contract is unchanged."""
    if donate:
        return (jax.jit(_leaves_from_raw_packed_impl, donate_argnums=(0,)),
                jax.jit(_leaves_from_binned_packed_impl,
                        donate_argnums=(0, 1, 2, 3)))
    return (jax.jit(_leaves_from_raw_packed_impl),
            jax.jit(_leaves_from_binned_packed_impl))


def _donate_ok() -> bool:
    """Donate the microbatch input buffers only where donation is real —
    the CPU backend warns 'donated buffers were not usable' per compile."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:           # pragma: no cover - backend init failures
        return False


def jit_entries() -> int:
    """Compiled-signature count across both microbatch kernels — the
    ``predict_jit_entries`` gauge (``grower_jit_entries`` discipline): a
    mixed-size request replay over a warmed ladder must not move it.
    (Wrapping via ``_jitted`` is free — only executions compile.)"""
    total = 0
    for donate in (False, True):
        for fn in _jitted(donate) + _jitted_packed(donate):
            try:
                total += int(fn._cache_size())
            except Exception:       # pragma: no cover - jax API drift
                return -1
    return total


# ----------------------------------------------------------------- engine


class PredictEngine:
    """The serving-side prediction engine: one SoA flatten at build, then
    bucketed microbatch executables with cached device-resident threshold
    tables.  ``raw_scores`` is bit-identical to
    ``Predictor.predict_raw_trees`` (pinned in tests/test_serving.py).

    ``backend`` picks the traversal that serves margin requests — the
    repo's ``auto`` ladder discipline:

    * ``xla`` — the SoA microbatch executables (this module).  Always
      built (it is the leaf-index path and the hot-swap-ready artifact)
      and the default wherever an accelerator backs jax.
    * ``native`` — the OpenMP C++ predictor (``lightgbm_tpu.native``),
      selected by ``auto`` on a bare-CPU backend when the library is
      available: a single host core walks trees ~4x faster through C++
      than through XLA:CPU's gather lowering (bench `serving` rung
      measures both).  Raw margins are bit-identical either way.
    """

    def __init__(self, trees: Sequence[Tree], num_class: int = 1,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 prewarm: bool = False, backend: str = "auto",
                 model_str: Optional[str] = None,
                 traversal: str = "auto"):
        self.bundle = SoABundle.build(list(trees), num_class)
        self.buckets = parse_serving_buckets(buckets)
        self.num_class = max(num_class, 1)
        self.timers = PhaseTimers()
        self._donate = _donate_ok()
        self._warmed = False
        if backend not in ("auto", "xla", "native"):
            raise ValueError(f"predict engine backend must be auto, xla, or "
                             f"native; got {backend!r}")
        self._native = None
        self.backend = self._resolve_backend(backend, model_str)
        if traversal not in ("auto", "xla", "packed"):
            raise ValueError(f"predict engine traversal must be auto, xla, "
                             f"or packed; got {traversal!r}")
        self.traversal = self._resolve_traversal(traversal)
        # serving drift monitor (obs/model_quality.DriftMonitor); attached
        # by the ModelServer when the model carries a training
        # distribution — every microbatch's binned rows fold into it
        self.drift = None
        if prewarm:
            self.prewarm()

    def _resolve_traversal(self, want: str) -> str:
        """serving_traversal ladder: ``packed`` walks two folded node
        words down a fixed max-depth fori ladder — the XLA:CPU headroom
        variant (the classic 8-gather step lowers scalar-by-scalar
        there).  ``auto`` picks it on a bare-CPU backend whenever the
        bundle packed; an explicit ``packed`` request on an unpackable
        ensemble degrades LOUDLY to xla (never silently mislabeled)."""
        packable = self.bundle.node_w0 is not None
        if want == "xla":
            return "xla"
        if want == "packed":
            if not packable:
                log.warning("serving_traversal=packed unavailable "
                            "(categorical nodes or field widths past the "
                            "node-word budget); using the xla traversal")
                obs_counters.event(
                    "layout_downgrade", stage="serving",
                    requested="serving_traversal=packed", resolved="xla",
                    reason="bundle not packable (categorical nodes or "
                           "field width)")
                return "xla"
            return "packed"
        try:
            backend_cpu = jax.default_backend() == "cpu"
        except Exception:       # pragma: no cover - backend init failure
            backend_cpu = True
        return "packed" if (packable and backend_cpu) else "xla"

    def _resolve_backend(self, want: str, model_str: Optional[str]) -> str:
        if want == "xla":
            return "xla"
        native_ok = False
        if model_str is not None:
            from . import native
            try:
                backend_cpu = jax.default_backend() == "cpu"
            except Exception:   # pragma: no cover - backend init failure
                backend_cpu = True
            if native.available() and (want == "native" or backend_cpu):
                try:
                    self._native = native.NativePredictor(model_str=model_str)
                    native_ok = True
                except Exception as e:   # fall back to the jitted path
                    log.debug("serving native backend unavailable (%s); "
                              "using xla", e)
        if want == "native" and not native_ok:
            raise ValueError("predict engine backend=native needs the "
                             "native library and a model_str")
        return "native" if native_ok else "xla"

    # ------------------------------------------------------------- shapes

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    def _bucket_rows(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_bucket

    def memory_prediction(self) -> Dict[str, int]:
        """The serving term of the ``predict_hbm`` fit model for THIS
        bundle + ladder (obs/memory.py), used by the pre-flight."""
        b = self.bundle
        return obs_memory.predict_hbm(
            rows=0, features=0, bins=0, leaves=1,
            serving_trees=b.tp, serving_nodes=b.p, serving_cols=b.num_cols,
            serving_bins=b.num_bins, serving_buckets=self.buckets)

    def preflight(self, hbm_budget: float = 0.0) -> Dict:
        """Warn (or raise under an explicit ``hbm_budget``) BEFORE the
        first executable compiles when the node arrays + per-bucket batch
        buffers oversubscribe the device."""
        return obs_memory.preflight(self.memory_prediction(),
                                    hbm_budget=hbm_budget, context="serving")

    # -------------------------------------------------------------- warmup

    def prewarm(self, hbm_budget: float = 0.0) -> "PredictEngine":
        """Compile every ladder bucket now so the first request never pays
        a compile; a hot-swapped same-shape model reuses these
        executables."""
        self.preflight(hbm_budget)
        for b in self.buckets:
            x = jnp.zeros((b, max(self.bundle.num_cols, 1)), jnp.float32)
            jax.block_until_ready(self._raw_fn()(x, *self._raw_args()))
        obs_counters.gauge("predict_jit_entries", jit_entries())
        self._warmed = True
        return self

    # ------------------------------------------------- traversal plumbing

    def _raw_fn(self):
        return (_jitted_packed(self._donate)[0] if self.traversal == "packed"
                else _jitted(self._donate)[0])

    def _binned_fn(self):
        return (_jitted_packed(self._donate)[1] if self.traversal == "packed"
                else _jitted(self._donate)[1])

    def _raw_args(self) -> tuple:
        """Model-side arguments of the raw-input executable (after the
        donated batch buffer)."""
        b = self.bundle
        if self.traversal == "packed":
            return (b.thr_table, b.node_w0, b.node_w1,
                    jnp.asarray(b.max_depth, jnp.int32))
        return (b.thr_table,) + b.device_args()

    def _binned_args(self) -> tuple:
        b = self.bundle
        if self.traversal == "packed":
            return (b.node_w0, b.node_w1, jnp.asarray(b.max_depth, jnp.int32))
        return b.device_args()

    # ------------------------------------------------------------ leaves

    def _run_bucket(self, xc: np.ndarray, f32_safe: bool) -> np.ndarray:
        """One microbatch: pad rows up the ladder, dispatch the raw-input
        executable (f32-safe input) or the host-binned twin, return leaf
        [T, n]."""
        n = xc.shape[0]
        nb = self._bucket_rows(n)
        bundle = self.bundle
        path = "raw" if f32_safe else "binned"
        with self.timers.phase("predict_bin"):
            if f32_safe:
                xp = np.zeros((nb, max(bundle.num_cols, 1)), np.float32)
                xp[:n, :bundle.num_cols] = xc.astype(np.float32)
                dev_in = (jax.device_put(xp),) + self._raw_args()
                fn = self._raw_fn()
                if self.drift is not None:
                    self.drift.add_counts(np.asarray(_aux_jitted()[1](
                        xp, bundle.thr_table, np.arange(nb) < n)), n)
            else:
                bins, cats, nanm, zerom = bundle.bin_host(xc)
                if self.drift is not None:
                    self.drift.add_bins(bins)
                pad = ((0, nb - n), (0, max(bundle.num_cols, 1) - xc.shape[1]))
                dev_in = tuple(jax.device_put(np.pad(a, pad))
                               for a in (bins, cats, nanm, zerom)) \
                    + self._binned_args()
                fn = self._binned_fn()
        with self.timers.phase("predict_traverse"):
            leaves = fn(*dev_in)
            out = np.asarray(leaves)[:bundle.num_trees, :n]
        obs_counters.inc("predict_dispatch", bucket=nb, path=path,
                         traversal=self.traversal, exec=bundle.exec_id())
        obs_counters.gauge("predict_jit_entries", jit_entries())
        return out

    def leaves(self, X: np.ndarray) -> np.ndarray:
        """Leaf index per (tree, row) -> int32 [T, N]; batches above the
        largest ladder bucket run as consecutive max-bucket microbatches."""
        X = np.atleast_2d(np.asarray(X, np.float64))
        bundle = self.bundle
        if len(bundle.cols) and X.shape[1] <= int(bundle.cols[-1]):
            log.fatal("predict engine: input has %d features but the model "
                      "splits on feature %d", X.shape[1],
                      int(bundle.cols[-1]))
        xc = X[:, bundle.cols] if len(bundle.cols) else \
            np.zeros((X.shape[0], 0), np.float64)
        with np.errstate(invalid="ignore"):
            f32_safe = bool(np.all((xc == xc.astype(np.float32)
                                    .astype(np.float64)) | np.isnan(xc)))
        out = np.empty((bundle.num_trees, X.shape[0]), np.int32)
        step = self.max_bucket
        for lo in range(0, X.shape[0], step):
            chunk = xc[lo:lo + step]
            out[:, lo:lo + chunk.shape[0]] = self._run_bucket(chunk, f32_safe)
        return out

    # ---------------------------------------------------------- binned rows

    def binned_arrays(self, X: np.ndarray):
        """Device-binned rows ``(bins, cats, nanm, zerom)`` in compact-
        column rank space, each [N, Fc] — the ``pred_contrib`` traversal
        rides these through the same bucket ladder / f32-safety
        discipline as :meth:`leaves`, so the per-node decisions replayed
        from them route identically to the serving traversal."""
        X = np.atleast_2d(np.asarray(X, np.float64))
        bundle = self.bundle
        fc = max(bundle.num_cols, 1)
        xc = X[:, bundle.cols] if len(bundle.cols) else \
            np.zeros((X.shape[0], 0), np.float64)
        with np.errstate(invalid="ignore"):
            f32_safe = bool(np.all((xc == xc.astype(np.float32)
                                    .astype(np.float64)) | np.isnan(xc)))
        n = X.shape[0]
        bins = np.zeros((n, fc), np.int32)
        cats = np.zeros((n, fc), np.int32)
        nanm = np.zeros((n, fc), bool)
        zerom = np.zeros((n, fc), bool)
        step = self.max_bucket
        for lo in range(0, n, step):
            chunk = xc[lo:lo + step]
            m = chunk.shape[0]
            if f32_safe:
                nb = self._bucket_rows(m)
                xp = np.zeros((nb, fc), np.float32)
                xp[:m, :bundle.num_cols] = chunk.astype(np.float32)
                out = _aux_jitted()[0](xp, bundle.thr_table)
                for dst, arr in zip((bins, cats, nanm, zerom), out):
                    dst[lo:lo + m] = np.asarray(arr)[:m]
            else:
                for dst, arr in zip((bins, cats, nanm, zerom),
                                    bundle.bin_host(chunk)):
                    dst[lo:lo + m, :arr.shape[1]] = arr
        return bins, cats, nanm, zerom

    # ------------------------------------------------------------- scores

    def raw_scores(self, X: np.ndarray,
                   num_trees: int = -1) -> np.ndarray:
        """Raw margin scores [K, N], bit-identical to the per-tree host
        loop on either backend: the xla path gathers leaf indices from
        the microbatch executables and walks the same f64 leaf tables in
        the same iteration-major order; the native path is the C++
        predictor's identical sequential f64 accumulation."""
        bundle = self.bundle
        k = self.num_class
        total = bundle.num_trees if num_trees is None or num_trees < 0 \
            else min(num_trees, bundle.num_trees)
        if self._native is not None:
            with self.timers.phase("predict_traverse"):
                x = np.atleast_2d(np.asarray(X, np.float64))
                if self.drift is not None and len(bundle.cols):
                    # the native traversal never bins — fold the window
                    # histogram from a host bin pass over the compact
                    # columns so drift sees the same rank space
                    self.drift.add_bins(bundle.bin_host(x[:, bundle.cols])[0])
                out = self._native.predict(x, num_iteration=total // k,
                                           raw_score=True)
                out = out[None, :] if out.ndim == 1 \
                    else np.ascontiguousarray(out.T)
            obs_counters.inc("predict_dispatch", bucket=x.shape[0],
                             path="native", exec=bundle.exec_id())
            return out
        leaves = self.leaves(X)
        with self.timers.phase("predict_margin"):
            n = leaves.shape[1]
            out = np.zeros((k, n), np.float64)
            # the leaf-value channel axis: tree t serves class t % K; per
            # class the per-iteration adds run oldest-first, matching
            # Predictor.predict_raw_trees' accumulation order exactly
            for t in range(total):
                out[t % k] += bundle.leaf_value[t][leaves[t]]
        return out
