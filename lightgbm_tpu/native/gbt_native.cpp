// Native host runtime for lightgbm_tpu.
//
// The reference keeps its data layer and serving path in C++ (src/io/parser.hpp,
// src/io/bin.cpp, src/application/predictor.hpp); this library is the
// TPU-framework equivalent: text parsing (CSV/TSV/LibSVM with format
// sniffing), value->bin quantization, and model-file prediction, all
// OpenMP-parallel, exported through a C ABI consumed via ctypes
// (lightgbm_tpu/native/__init__.py).  The TPU compute path (histograms,
// split scans, training) stays in JAX/XLA/Pallas — this is the host side.
//
// Semantics mirrored from the reference (file:line cites):
//   format sniffing            src/io/parser.cpp:72+
//   ValueToBin binary search   include/LightGBM/bin.h:451-483
//   decision_type bit layout   include/LightGBM/tree.h:157-176
//   Numerical/CategoricalDecision  include/LightGBM/tree.h:231-313
//   model text format          src/io/tree.cpp:192-227, src/boosting/gbdt.cpp:948+

#include <algorithm>
#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <limits>
#include <map>
#include <sstream>
#include <string>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

constexpr double kZeroRange = 1e-35;   // reference kZeroThreshold
constexpr int kMissingNone = 0;
constexpr int kMissingZero = 1;
constexpr int kMissingNan = 2;
constexpr int kCategoricalMask = 1;    // decision_type bit 0
constexpr int kDefaultLeftMask = 2;    // decision_type bit 1

// ----------------------------------------------------------------- parsing

inline bool is_na_token(const std::string& t) {
  return t.empty() || t == "na" || t == "nan" || t == "NA" || t == "NaN" ||
         t == "null" || t == "NULL" || t == "N/A";
}

inline double parse_cell(const char* s, const char* e) {
  while (s < e && std::isspace(static_cast<unsigned char>(*s))) ++s;
  while (e > s && std::isspace(static_cast<unsigned char>(*(e - 1)))) --e;
  if (s == e) return std::nan("");
  std::string tok(s, e);
  if (is_na_token(tok)) return std::nan("");
  char* endp = nullptr;
  double v = std::strtod(tok.c_str(), &endp);
  if (endp == tok.c_str()) return std::nan("");
  return v;
}

struct ParseResult {
  int64_t rows = 0;
  int64_t cols = 0;              // feature columns (label removed)
  std::vector<double> features;  // row-major [rows, cols]
  std::vector<float> labels;
  std::string error;
};

// Sniff format from sample lines: libsvm if any "i:v" token appears past the
// first, else tab-, comma- or space-separated (parser.cpp:72+ discipline).
enum class Format { kCSV, kTSV, kLibSVM, kSpace };

Format sniff_format(const std::vector<std::string>& lines) {
  for (const auto& line : lines) {
    if (line.empty()) continue;
    std::istringstream iss(line);
    std::string tok;
    int i = 0;
    bool has_colon = false;
    while (iss >> tok) {
      if (i > 0 && tok.find(':') != std::string::npos) has_colon = true;
      ++i;
    }
    if (has_colon) return Format::kLibSVM;
    if (line.find('\t') != std::string::npos) return Format::kTSV;
    if (line.find(',') != std::string::npos) return Format::kCSV;
    if (i > 1) return Format::kSpace;
  }
  return Format::kCSV;
}

void parse_delim_lines(const std::vector<std::string>& lines, char delim,
                       bool any_space, int label_idx, ParseResult* out) {
  int64_t n = static_cast<int64_t>(lines.size());
  // column count from the first non-empty line
  int64_t ncol = 0;
  for (const auto& line : lines) {
    if (line.empty()) continue;
    if (any_space) {
      std::istringstream iss(line);
      std::string t;
      while (iss >> t) ++ncol;
    } else {
      ncol = 1 + std::count(line.begin(), line.end(), delim);
    }
    break;
  }
  if (ncol == 0) { out->error = "empty data"; return; }
  bool has_label = label_idx >= 0 && label_idx < ncol;
  int64_t fcols = ncol - (has_label ? 1 : 0);
  out->rows = n;
  out->cols = fcols;
  // short/ragged rows leave their trailing cells as NaN (missing), matching
  // the python loader's missing-value convention
  out->features.assign(n * fcols, std::nan(""));
  out->labels.assign(n, 0.0f);

#pragma omp parallel for schedule(static)
  for (int64_t r = 0; r < n; ++r) {
    const std::string& line = lines[r];
    int64_t col = 0, fcol = 0;
    if (any_space) {
      std::istringstream iss(line);
      std::string t;
      while (iss >> t && col < ncol) {
        double v = parse_cell(t.data(), t.data() + t.size());
        if (has_label && col == label_idx) out->labels[r] = (float)v;
        else if (fcol < fcols) out->features[r * fcols + fcol++] = v;
        ++col;
      }
    } else {
      const char* s = line.c_str();
      const char* end = s + line.size();
      while (col < ncol) {
        const char* e = static_cast<const char*>(memchr(s, delim, end - s));
        if (e == nullptr) e = end;
        double v = parse_cell(s, e);
        if (has_label && col == label_idx) out->labels[r] = (float)v;
        else if (fcol < fcols) out->features[r * fcols + fcol++] = v;
        ++col;
        if (e == end) break;
        s = e + 1;
      }
    }
  }
}

void parse_libsvm_lines(const std::vector<std::string>& lines, int label_idx,
                        ParseResult* out) {
  int64_t n = static_cast<int64_t>(lines.size());
  std::vector<std::vector<std::pair<int, double>>> rows(n);
  std::vector<float> labels(n, 0.0f);
  int max_idx = -1;
#pragma omp parallel
  {
    int local_max = -1;
#pragma omp for schedule(static)
    for (int64_t r = 0; r < n; ++r) {
      std::istringstream iss(lines[r]);
      std::string tok;
      bool first = true;
      while (iss >> tok) {
        auto colon = tok.find(':');
        if (first && label_idx >= 0 && colon == std::string::npos) {
          labels[r] = (float)std::strtod(tok.c_str(), nullptr);
          first = false;
          continue;
        }
        first = false;
        if (colon == std::string::npos) continue;
        int idx = std::atoi(tok.substr(0, colon).c_str());
        double v = std::strtod(tok.c_str() + colon + 1, nullptr);
        rows[r].emplace_back(idx, v);
        local_max = std::max(local_max, idx);
      }
    }
#pragma omp critical
    max_idx = std::max(max_idx, local_max);
  }
  int64_t fcols = max_idx + 1;
  out->rows = n;
  out->cols = fcols;
  out->features.assign(n * fcols, 0.0);
  out->labels = std::move(labels);
#pragma omp parallel for schedule(static)
  for (int64_t r = 0; r < n; ++r)
    for (auto& iv : rows[r]) out->features[r * fcols + iv.first] = iv.second;
}

// --------------------------------------------------------------- predictor

struct NativeTree {
  int num_leaves = 1;
  int num_cat = 0;
  std::vector<int> split_feature;
  std::vector<double> threshold;
  std::vector<int8_t> decision_type;
  std::vector<int> left_child;
  std::vector<int> right_child;
  std::vector<double> leaf_value;
  std::vector<int> cat_boundaries;
  std::vector<uint32_t> cat_threshold;

  inline bool cat_decision(double fval, int node) const {
    // CategoricalDecision (tree.h:268-283)
    if (std::isnan(fval)) {
      if (((decision_type[node] >> 2) & 3) == kMissingNan) return false;
      fval = 0.0;
    }
    int iv = static_cast<int>(fval);
    if (iv < 0) return false;
    int ci = static_cast<int>(threshold[node]);
    int lo = cat_boundaries[ci], hi = cat_boundaries[ci + 1];
    int i1 = iv / 32, i2 = iv % 32;
    if (lo + i1 < hi) return (cat_threshold[lo + i1] >> i2) & 1u;
    return false;
  }

  inline int get_leaf(const double* fv) const {
    // NumericalDecision walk (tree.h:231-313,426-438)
    if (num_leaves <= 1) return 0;
    int node = 0;
    while (node >= 0) {
      double v = fv[split_feature[node]];
      bool go_left;
      int8_t dt = decision_type[node];
      if (dt & kCategoricalMask) {
        go_left = cat_decision(v, node);
      } else {
        int mt = (dt >> 2) & 3;
        bool dl = dt & kDefaultLeftMask;
        if (std::isnan(v) && mt != kMissingNan) v = 0.0;
        bool missing = (mt == kMissingZero && std::fabs(v) <= kZeroRange) ||
                       (mt == kMissingNan && std::isnan(v));
        go_left = missing ? dl : (v <= threshold[node]);
      }
      node = go_left ? left_child[node] : right_child[node];
    }
    return ~node;
  }

  inline double predict(const double* fv) const {
    return leaf_value[get_leaf(fv)];
  }
};

struct NativeModel {
  int num_class = 1;
  int max_feature_idx = 0;
  bool average_output = false;
  std::string objective;         // e.g. "binary sigmoid:1"
  double sigmoid = 1.0;
  std::vector<NativeTree> trees;
  std::string error;

  int num_features() const { return max_feature_idx + 1; }
  int num_iterations() const {
    return num_class > 0 ? (int)trees.size() / num_class : 0;
  }
};

template <typename T>
std::vector<T> parse_array(const std::string& s) {
  std::vector<T> out;
  std::istringstream iss(s);
  double v;
  while (iss >> v) out.push_back(static_cast<T>(v));
  return out;
}

NativeModel* load_model_from_string(const std::string& text) {
  auto* model = new NativeModel();
  std::istringstream in(text);
  std::string line;
  // header section until the first blank line / "Tree=" block
  std::map<std::string, std::string> kv;
  std::vector<std::map<std::string, std::string>> tree_blocks;
  std::map<std::string, std::string>* cur = &kv;
  bool in_trees = false;
  while (std::getline(in, line)) {
    if (!line.empty() && line.back() == '\r') line.pop_back();
    if (line.rfind("Tree=", 0) == 0) {
      tree_blocks.emplace_back();
      cur = &tree_blocks.back();
      in_trees = true;
      continue;
    }
    if (line.rfind("feature importances", 0) == 0) break;
    if (line == "boost_from_average") { kv["boost_from_average"] = "1"; continue; }
    if (line == "average_output") { kv["average_output"] = "1"; continue; }
    auto eq = line.find('=');
    if (eq == std::string::npos) continue;
    (*cur)[line.substr(0, eq)] = line.substr(eq + 1);
  }
  (void)in_trees;
  if (kv.count("num_class")) model->num_class = std::atoi(kv["num_class"].c_str());
  if (kv.count("max_feature_idx"))
    model->max_feature_idx = std::atoi(kv["max_feature_idx"].c_str());
  model->average_output = kv.count("average_output") > 0;
  if (kv.count("objective")) {
    model->objective = kv["objective"];
    auto sp = model->objective.find("sigmoid:");
    if (sp != std::string::npos)
      model->sigmoid = std::strtod(model->objective.c_str() + sp + 8, nullptr);
  }
  for (auto& tb : tree_blocks) {
    NativeTree t;
    t.num_leaves = tb.count("num_leaves") ? std::atoi(tb["num_leaves"].c_str()) : 1;
    t.num_cat = tb.count("num_cat") ? std::atoi(tb["num_cat"].c_str()) : 0;
    int n = t.num_leaves - 1;
    if (n > 0) {
      t.split_feature = parse_array<int>(tb["split_feature"]);
      t.threshold = parse_array<double>(tb["threshold"]);
      t.decision_type = parse_array<int8_t>(tb["decision_type"]);
      t.left_child = parse_array<int>(tb["left_child"]);
      t.right_child = parse_array<int>(tb["right_child"]);
      if ((int)t.split_feature.size() != n || (int)t.threshold.size() != n ||
          (int)t.decision_type.size() != n || (int)t.left_child.size() != n ||
          (int)t.right_child.size() != n) {
        model->error = "malformed tree block (array length mismatch)";
        return model;
      }
    }
    t.leaf_value = parse_array<double>(tb["leaf_value"]);
    if ((int)t.leaf_value.size() < t.num_leaves) {
      model->error = "malformed tree block (leaf_value)";
      return model;
    }
    if (t.num_cat > 0) {
      t.cat_boundaries = parse_array<int>(tb["cat_boundaries"]);
      t.cat_threshold = parse_array<uint32_t>(tb["cat_threshold"]);
    }
    model->trees.push_back(std::move(t));
  }
  return model;
}

}  // namespace

// =================================================================== C ABI

extern "C" {

// ------------------------------------------------------------------ parser

void* GBTN_ParseFile(const char* path, int has_header, int label_idx) {
  auto* out = new ParseResult();
  std::ifstream f(path);
  if (!f) { out->error = std::string("cannot open ") + path; return out; }
  std::vector<std::string> lines;
  std::string line;
  bool first = true;
  std::string header;
  while (std::getline(f, line)) {
    if (!line.empty() && line.back() == '\r') line.pop_back();
    if (first && has_header) { header = line; first = false; continue; }
    first = false;
    if (!line.empty()) lines.push_back(std::move(line));
  }
  if (lines.empty()) { out->error = "empty data file"; return out; }
  std::vector<std::string> head(lines.begin(),
                                lines.begin() + std::min<size_t>(32, lines.size()));
  switch (sniff_format(head)) {
    case Format::kLibSVM: parse_libsvm_lines(lines, label_idx, out); break;
    case Format::kTSV:    parse_delim_lines(lines, '\t', false, label_idx, out); break;
    case Format::kCSV:    parse_delim_lines(lines, ',', false, label_idx, out); break;
    case Format::kSpace:  parse_delim_lines(lines, ' ', true, label_idx, out); break;
  }
  return out;
}

long long GBTN_ParsedRows(void* h) { return static_cast<ParseResult*>(h)->rows; }
long long GBTN_ParsedCols(void* h) { return static_cast<ParseResult*>(h)->cols; }
const char* GBTN_ParsedError(void* h) {
  return static_cast<ParseResult*>(h)->error.c_str();
}

void GBTN_ParsedCopy(void* h, double* features, float* labels) {
  auto* p = static_cast<ParseResult*>(h);
  if (!p->features.empty())
    std::memcpy(features, p->features.data(), p->features.size() * sizeof(double));
  if (!p->labels.empty())
    std::memcpy(labels, p->labels.data(), p->labels.size() * sizeof(float));
}

void GBTN_ParsedFree(void* h) { delete static_cast<ParseResult*>(h); }

// ----------------------------------------------------------------- binning

// Vectorized ValueToBin (bin.h:451-483): first bin whose upper bound >= v.
// bounds: strictly increasing uppers, n_search entries used for the search
// (excludes a trailing NaN bin); nan_bin: bin for NaN rows (-1: treat as 0).
void GBTN_BinColumn(const double* values, long long n, const double* bounds,
                    int n_search, int nan_bin, int out_bits, void* out) {
#pragma omp parallel for schedule(static)
  for (long long i = 0; i < n; ++i) {
    double v = values[i];
    int b;
    if (std::isnan(v)) {
      if (nan_bin >= 0) b = nan_bin;
      else { v = 0.0; b = -1; }
    } else {
      b = -1;
    }
    if (b < 0) {
      // lower_bound over bounds[0..n_search-2]; last bin catches the rest
      int lo = 0, hi = n_search - 1;
      while (lo < hi) {
        int mid = (lo + hi) / 2;
        if (bounds[mid] < v) lo = mid + 1; else hi = mid;
      }
      b = lo;
    }
    if (out_bits == 8) static_cast<uint8_t*>(out)[i] = (uint8_t)b;
    else static_cast<uint16_t*>(out)[i] = (uint16_t)b;
  }
}

// Greedy equal-count bin boundary search over (distinct value, count)
// pairs — the hot inner loop of BinMapper fitting (bin.cpp:72-141
// semantics, mirroring data/binning.py::greedy_find_bin exactly; the
// Python loop costs ~17 ms per continuous feature at 50k distinct
// values, which dominates wide-dataset construction).  Writes at most
// max(max_bin, 1) boundaries (last one +inf) into out; returns the count.
int GBTN_GreedyFindBin(const double* distinct, const long long* counts,
                       int num_distinct, int max_bin, long long total_cnt,
                       int min_data_in_bin, double* out) {
  const double kInf = std::numeric_limits<double>::infinity();
  int n_out = 0;
  if (max_bin <= 0) {
    out[n_out++] = kInf;
    return n_out;
  }
  if (num_distinct <= max_bin) {
    long long cur = 0;
    for (int i = 0; i < num_distinct - 1; ++i) {
      cur += counts[i];
      if (cur >= min_data_in_bin) {
        out[n_out++] = (distinct[i] + distinct[i + 1]) / 2.0;
        cur = 0;
      }
    }
    out[n_out++] = kInf;
    return n_out;
  }
  if (min_data_in_bin > 0) {
    long long cap = total_cnt / min_data_in_bin;
    if (cap < max_bin) max_bin = (int)cap;
    if (max_bin < 1) max_bin = 1;
  }
  double mean_bin_size = (double)total_cnt / max_bin;
  std::vector<char> is_big(num_distinct);
  int rest_bin_cnt = max_bin;
  long long rest_sample_cnt = total_cnt;
  for (int i = 0; i < num_distinct; ++i) {
    is_big[i] = (double)counts[i] >= mean_bin_size;
    if (is_big[i]) {
      --rest_bin_cnt;
      rest_sample_cnt -= counts[i];
    }
  }
  mean_bin_size = (double)rest_sample_cnt / std::max(rest_bin_cnt, 1);
  std::vector<double> upper(max_bin, kInf), lower(max_bin, kInf);
  int bin_cnt = 0;
  lower[0] = distinct[0];
  long long cur = 0;
  for (int i = 0; i < num_distinct - 1; ++i) {
    if (!is_big[i]) rest_sample_cnt -= counts[i];
    cur += counts[i];
    if (is_big[i] || (double)cur >= mean_bin_size ||
        (is_big[i + 1] &&
         (double)cur >= std::max(1.0, mean_bin_size * 0.5))) {
      upper[bin_cnt] = distinct[i];
      ++bin_cnt;
      lower[bin_cnt] = distinct[i + 1];
      if (bin_cnt >= max_bin - 1) break;
      cur = 0;
      if (!is_big[i]) {
        --rest_bin_cnt;
        mean_bin_size = (double)rest_sample_cnt / std::max(rest_bin_cnt, 1);
      }
    }
  }
  bin_cnt += 1;
  for (int i = 0; i < bin_cnt - 1; ++i)
    out[n_out++] = (upper[i] + lower[i + 1]) / 2.0;
  out[n_out++] = kInf;
  return n_out;
}

// Categorical value->bin through a sorted (category, bin) table.
void GBTN_BinColumnCategorical(const double* values, long long n,
                               const long long* cats, const int* bins,
                               int n_cats, int overflow_bin, int out_bits,
                               void* out) {
#pragma omp parallel for schedule(static)
  for (long long i = 0; i < n; ++i) {
    double v = values[i];
    int b = overflow_bin;
    if (!std::isnan(v)) {
      long long iv = (long long)v;
      int lo = 0, hi = n_cats;
      while (lo < hi) {
        int mid = (lo + hi) / 2;
        if (cats[mid] < iv) lo = mid + 1; else hi = mid;
      }
      if (lo < n_cats && cats[lo] == iv) b = bins[lo];
    }
    if (out_bits == 8) static_cast<uint8_t*>(out)[i] = (uint8_t)b;
    else static_cast<uint16_t*>(out)[i] = (uint16_t)b;
  }
}

// --------------------------------------------------------------- predictor

void* GBTN_LoadModelString(const char* s) {
  return load_model_from_string(std::string(s));
}

void* GBTN_LoadModelFile(const char* path) {
  std::ifstream f(path);
  if (!f) {
    auto* m = new NativeModel();
    m->error = std::string("cannot open ") + path;
    return m;
  }
  std::stringstream ss;
  ss << f.rdbuf();
  return load_model_from_string(ss.str());
}

const char* GBTN_ModelError(void* h) {
  return static_cast<NativeModel*>(h)->error.c_str();
}
int GBTN_ModelNumClass(void* h) { return static_cast<NativeModel*>(h)->num_class; }
int GBTN_ModelNumTrees(void* h) {
  return (int)static_cast<NativeModel*>(h)->trees.size();
}
int GBTN_ModelNumFeatures(void* h) {
  return static_cast<NativeModel*>(h)->num_features();
}

// Raw-score batch prediction (Predictor analogue, predictor.hpp:24-195):
// X row-major [n, f]; out [n, num_class]; num_iteration <= 0 -> all.
void GBTN_Predict(void* h, const double* X, long long n, int f,
                  int num_iteration, int raw_score, double* out) {
  auto* m = static_cast<NativeModel*>(h);
  int k = std::max(m->num_class, 1);
  int iters = m->num_iterations();
  if (num_iteration > 0 && num_iteration < iters) iters = num_iteration;
  int total = iters * k;
  (void)f;
#pragma omp parallel for schedule(static)
  for (long long r = 0; r < n; ++r) {
    const double* fv = X + r * f;
    double* o = out + r * k;
    for (int c = 0; c < k; ++c) o[c] = 0.0;
    for (int t = 0; t < total; ++t) o[t % k] += m->trees[t].predict(fv);
    // GBDT::Predict semantics (gbdt_prediction.cpp:29-38): raw score is
    // the plain SUM; average_output (RF) divides by the iteration count
    // and applies NO objective transform; otherwise ConvertOutput.
    if (!raw_score && m->average_output) {
      if (iters > 0)
        for (int c = 0; c < k; ++c) o[c] /= iters;
    } else if (!raw_score) {
      if (m->objective.rfind("binary", 0) == 0) {
        o[0] = 1.0 / (1.0 + std::exp(-m->sigmoid * o[0]));
      } else if (m->objective.rfind("multiclassova", 0) == 0) {
        for (int c = 0; c < k; ++c)
          o[c] = 1.0 / (1.0 + std::exp(-m->sigmoid * o[c]));
      } else if (m->objective.rfind("multiclass", 0) == 0) {
        double mx = o[0];
        for (int c = 1; c < k; ++c) mx = std::max(mx, o[c]);
        double s = 0.0;
        for (int c = 0; c < k; ++c) { o[c] = std::exp(o[c] - mx); s += o[c]; }
        for (int c = 0; c < k; ++c) o[c] /= s;
      } else if (m->objective.rfind("xentlambda", 0) == 0 ||
                 m->objective.rfind("cross_entropy_lambda", 0) == 0) {
        o[0] = std::log1p(std::exp(o[0]));
      } else if (m->objective.rfind("xentropy", 0) == 0 ||
                 m->objective.rfind("cross_entropy", 0) == 0) {
        o[0] = 1.0 / (1.0 + std::exp(-o[0]));
      }
      // poisson is IDENTITY in the reference v2.0.5 (linear-score form,
      // regression_objective.hpp:299-358 defines no ConvertOutput)
    }
  }
}

// Per-tree leaf index prediction (PredictLeafIndex): out [n, total_trees].
void GBTN_PredictLeaf(void* h, const double* X, long long n, int f,
                      int num_iteration, int* out) {
  auto* m = static_cast<NativeModel*>(h);
  int k = std::max(m->num_class, 1);
  int iters = m->num_iterations();
  if (num_iteration > 0 && num_iteration < iters) iters = num_iteration;
  int total = iters * k;
#pragma omp parallel for schedule(static)
  for (long long r = 0; r < n; ++r) {
    const double* fv = X + r * f;
    for (int t = 0; t < total; ++t)
      out[r * total + t] = m->trees[t].get_leaf(fv);
  }
}

void GBTN_FreeModel(void* h) { delete static_cast<NativeModel*>(h); }

int GBTN_OpenMPThreads() {
#ifdef _OPENMP
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
