// Training C ABI — the analogue of the reference's training c_api surface
// (include/LightGBM/c_api.h: LGBM_DatasetCreateFromMat, LGBM_BoosterCreate,
// LGBM_BoosterUpdateOneIter, LGBM_BoosterSaveModel, ...).
//
// Architecture note: the reference's c_api.cpp is a thin C shim over its C++
// GBDT runtime.  Here the training runtime IS the JAX/XLA engine, so the C
// shim delegates into it through CPython embedding: handles are Python
// objects, every entry point bridges via lightgbm_tpu.native.capi_bridge.
// A standalone C program gets a working training ABI (the interpreter is
// bootstrapped on first use); in-process (ctypes) callers share the live
// interpreter.  The serving-side functions (GBTN_Predict & co in
// gbt_native.cpp) stay pure C++ with no Python dependency.
#include <Python.h>

#include <cstring>
#include <string>

namespace {

thread_local std::string g_last_error;

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  g_last_error = "unknown python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
}

// Bootstraps the interpreter for standalone C callers; no-op in-process.
bool ensure_python() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    if (!Py_IsInitialized()) {
      g_last_error = "failed to initialize the Python runtime";
      return false;
    }
    // release the GIL acquired by initialization so OTHER caller threads
    // can enter through PyGILState_Ensure (multithreaded standalone use)
    PyEval_SaveThread();
  }
  return true;
}

// Calls lightgbm_tpu.native.capi_bridge.<fn>(*args).  Returns a new
// reference, or nullptr with g_last_error set.
PyObject* call_bridge(const char* fn, PyObject* args) {
  if (args == nullptr) {   // failed Py_BuildValue / memoryview construction
    set_error_from_python();
    return nullptr;
  }
  PyObject* mod = PyImport_ImportModule("lightgbm_tpu.native.capi_bridge");
  if (mod == nullptr) {
    set_error_from_python();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* f = PyObject_GetAttrString(mod, fn);
  Py_DECREF(mod);
  if (f == nullptr) {
    set_error_from_python();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (out == nullptr) set_error_from_python();
  return out;
}

struct Gil {
  PyGILState_STATE state;
  Gil() : state(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state); }
};

}  // namespace

extern "C" {

const char* GBTN_GetLastError() { return g_last_error.c_str(); }

// data: row-major [nrow, ncol] f64; label: [nrow] f32 or null.
// params: space-separated key=value pairs (reference c_api convention).
// On success *out is a dataset handle; returns 0, else -1.
int GBTN_DatasetCreateFromMat(const double* data, long long nrow, int ncol,
                              const char* params, const float* label,
                              void** out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* mv_data = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<double*>(data)),
      static_cast<Py_ssize_t>(nrow) * ncol * sizeof(double), PyBUF_READ);
  PyObject* mv_label =
      label == nullptr
          ? (Py_INCREF(Py_None), Py_None)
          : PyMemoryView_FromMemory(
                reinterpret_cast<char*>(const_cast<float*>(label)),
                static_cast<Py_ssize_t>(nrow) * sizeof(float), PyBUF_READ);
  PyObject* args = Py_BuildValue("(OLisO)", mv_data, nrow, ncol,
                                 params == nullptr ? "" : params, mv_label);
  Py_XDECREF(mv_data);
  Py_XDECREF(mv_label);
  PyObject* ds = call_bridge("dataset_from_mat", args);
  if (ds == nullptr) return -1;
  *out = ds;  // owned reference == handle
  return 0;
}

int GBTN_DatasetFree(void* handle) {
  if (!Py_IsInitialized() || handle == nullptr) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
}

int GBTN_BoosterCreate(void* dataset, const char* params, void** out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Os)", static_cast<PyObject*>(dataset),
      params == nullptr ? "" : params);
  PyObject* bst = call_bridge("booster_create", args);
  if (bst == nullptr) return -1;
  *out = bst;
  return 0;
}

// *is_finished = 1 when no further splits are possible (reference
// LGBM_BoosterUpdateOneIter contract).
int GBTN_BoosterUpdateOneIter(void* booster, int* is_finished) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(booster));
  PyObject* r = call_bridge("booster_update", args);
  if (r == nullptr) return -1;
  if (is_finished != nullptr) *is_finished = PyObject_IsTrue(r) ? 1 : 0;
  Py_DECREF(r);
  return 0;
}

int GBTN_BoosterSaveModel(void* booster, int num_iteration,
                          const char* filename) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* args = Py_BuildValue("(Ois)", static_cast<PyObject*>(booster),
                                 num_iteration, filename);
  PyObject* r = call_bridge("booster_save", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// out must hold nrow * num_class doubles (transformed predictions).
int GBTN_BoosterPredictForMat(void* booster, const double* data,
                              long long nrow, int ncol, double* out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* mv_in = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<double*>(data)),
      static_cast<Py_ssize_t>(nrow) * ncol * sizeof(double), PyBUF_READ);
  PyObject* r = nullptr;
  {
    PyObject* num_class =
        call_bridge("booster_num_class",
                    Py_BuildValue("(O)", static_cast<PyObject*>(booster)));
    if (num_class == nullptr) {
      Py_XDECREF(mv_in);
      return -1;
    }
    long k = PyLong_AsLong(num_class);
    Py_DECREF(num_class);
    PyObject* mv_out = PyMemoryView_FromMemory(
        reinterpret_cast<char*>(out),
        static_cast<Py_ssize_t>(nrow) * k * sizeof(double), PyBUF_WRITE);
    PyObject* args = Py_BuildValue("(OOLiO)",
                                   static_cast<PyObject*>(booster), mv_in,
                                   nrow, ncol, mv_out);
    Py_XDECREF(mv_out);
    r = call_bridge("booster_predict_into", args);
  }
  Py_XDECREF(mv_in);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int GBTN_BoosterGetNumClass(void* booster, int* out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* r = call_bridge(
      "booster_num_class",
      Py_BuildValue("(O)", static_cast<PyObject*>(booster)));
  if (r == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int GBTN_BoosterFree(void* handle) {
  if (!Py_IsInitialized() || handle == nullptr) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
}

}  // extern "C"
