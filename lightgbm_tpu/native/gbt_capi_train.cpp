// Training C ABI — the analogue of the reference's training c_api surface
// (include/LightGBM/c_api.h: LGBM_DatasetCreateFromMat, LGBM_BoosterCreate,
// LGBM_BoosterUpdateOneIter, LGBM_BoosterSaveModel, ...).
//
// Architecture note: the reference's c_api.cpp is a thin C shim over its C++
// GBDT runtime.  Here the training runtime IS the JAX/XLA engine, so the C
// shim delegates into it through CPython embedding: handles are Python
// objects, every entry point bridges via lightgbm_tpu.native.capi_bridge.
// A standalone C program gets a working training ABI (the interpreter is
// bootstrapped on first use); in-process (ctypes) callers share the live
// interpreter.  The serving-side functions (GBTN_Predict & co in
// gbt_native.cpp) stay pure C++ with no Python dependency.
#include <Python.h>

#include <cstring>
#include <string>

namespace {

thread_local std::string g_last_error;

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  g_last_error = "unknown python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
}

// Bootstraps the interpreter for standalone C callers; no-op in-process.
bool ensure_python() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    if (!Py_IsInitialized()) {
      g_last_error = "failed to initialize the Python runtime";
      return false;
    }
    // release the GIL acquired by initialization so OTHER caller threads
    // can enter through PyGILState_Ensure (multithreaded standalone use)
    PyEval_SaveThread();
  }
  return true;
}

// Calls lightgbm_tpu.native.capi_bridge.<fn>(*args).  Returns a new
// reference, or nullptr with g_last_error set.
PyObject* call_bridge(const char* fn, PyObject* args) {
  if (args == nullptr) {   // failed Py_BuildValue / memoryview construction
    set_error_from_python();
    return nullptr;
  }
  PyObject* mod = PyImport_ImportModule("lightgbm_tpu.native.capi_bridge");
  if (mod == nullptr) {
    set_error_from_python();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* f = PyObject_GetAttrString(mod, fn);
  Py_DECREF(mod);
  if (f == nullptr) {
    set_error_from_python();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (out == nullptr) set_error_from_python();
  return out;
}

struct Gil {
  PyGILState_STATE state;
  Gil() : state(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state); }
};

// ------------------------------------------------------------------ helpers
// The bridge functions return small typed results; these adapters collapse
// the "call, convert, decref, error-check" pattern.  All must be called with
// the GIL held.

PyObject* none_incref() {
  Py_INCREF(Py_None);
  return Py_None;
}

// Borrowed handle -> object for Py_BuildValue "O" (which increfs).
PyObject* handle_or_none(void* h) {
  return h == nullptr ? Py_None : static_cast<PyObject*>(h);
}

PyObject* mv_read(const void* data, Py_ssize_t bytes) {
  if (data == nullptr) return none_incref();
  return PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<void*>(data)), bytes, PyBUF_READ);
}

PyObject* mv_write(void* data, Py_ssize_t bytes) {
  return PyMemoryView_FromMemory(reinterpret_cast<char*>(data), bytes,
                                 PyBUF_WRITE);
}

// Bridge call whose result is discarded (success/failure only).
int bridge_ok(const char* fn, PyObject* args) {
  PyObject* r = call_bridge(fn, args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// Bridge call returning a new handle into *out.
int bridge_handle(const char* fn, PyObject* args, void** out) {
  PyObject* r = call_bridge(fn, args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

int bridge_ll(const char* fn, PyObject* args, long long* out) {
  PyObject* r = call_bridge(fn, args);
  if (r == nullptr) return -1;
  *out = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int bridge_int(const char* fn, PyObject* args, int* out) {
  long long v = 0;
  if (bridge_ll(fn, args, &v) != 0) return -1;
  *out = static_cast<int>(v);
  return 0;
}

int bridge_double(const char* fn, PyObject* args, double* out) {
  PyObject* r = call_bridge(fn, args);
  if (r == nullptr) return -1;
  *out = PyFloat_AsDouble(r);
  Py_DECREF(r);
  return 0;
}

// Bridge call returning str; copied into a caller buffer with the
// reference's SaveModelToString convention: *out_len = needed size
// including NUL; the copy happens only when buffer_len suffices.
int bridge_string(const char* fn, PyObject* args, long long buffer_len,
                  long long* out_len, char* out_str) {
  PyObject* r = call_bridge(fn, args);
  if (r == nullptr) return -1;
  Py_ssize_t size = 0;
  const char* c = PyUnicode_AsUTF8AndSize(r, &size);
  if (c == nullptr) {
    set_error_from_python();
    Py_DECREF(r);
    return -1;
  }
  if (out_len != nullptr) *out_len = static_cast<long long>(size) + 1;
  if (out_str != nullptr && buffer_len >= size + 1) {
    std::memcpy(out_str, c, size + 1);
  }
  Py_DECREF(r);
  return 0;
}

// Bridge call returning list[str]; strings copied into caller-allocated
// out_strs[i] buffers of buffer_len bytes each (LGBM_BoosterGetEvalNames
// convention), *out_n = element count.  A name that does not fit is an
// ERROR (g_last_error reports the required size) — never a silent
// truncation; pass out_strs == null to probe only the count.
int bridge_string_list(const char* fn, PyObject* args, char** out_strs,
                       int buffer_len, int* out_n) {
  PyObject* r = call_bridge(fn, args);
  if (r == nullptr) return -1;
  if (!PyList_Check(r)) {
    g_last_error = "bridge did not return a list";
    Py_DECREF(r);
    return -1;
  }
  Py_ssize_t n = PyList_Size(r);
  if (out_n != nullptr) *out_n = static_cast<int>(n);
  if (out_strs != nullptr) {
    if (buffer_len <= 0) {
      g_last_error = "string buffer_len must be positive";
      Py_DECREF(r);
      return -1;
    }
    for (Py_ssize_t i = 0; i < n; ++i) {
      Py_ssize_t size = 0;
      const char* c = PyUnicode_AsUTF8AndSize(PyList_GetItem(r, i), &size);
      if (c == nullptr) {
        set_error_from_python();
        Py_DECREF(r);
        return -1;
      }
      if (size + 1 > buffer_len) {
        g_last_error = "string buffer too small: need " +
                       std::to_string(size + 1) + " bytes, have " +
                       std::to_string(buffer_len);
        Py_DECREF(r);
        return -1;
      }
      std::memcpy(out_strs[i], c, size + 1);
    }
  }
  Py_DECREF(r);
  return 0;
}

// Bridge call returning (address, length[, dtype]) of an array cached on
// the handle; copies length*elem_size bytes into out (when out != null).
int bridge_buffer_copy(const char* fn, PyObject* args, void* out,
                       size_t elem_size, long long* out_len,
                       int* out_type) {
  PyObject* r = call_bridge(fn, args);
  if (r == nullptr) return -1;
  if (!PyTuple_Check(r) || PyTuple_Size(r) < 2) {
    g_last_error = "bridge did not return (addr, len) tuple";
    Py_DECREF(r);
    return -1;
  }
  long long addr = PyLong_AsLongLong(PyTuple_GetItem(r, 0));
  long long len = PyLong_AsLongLong(PyTuple_GetItem(r, 1));
  if (out_type != nullptr && PyTuple_Size(r) >= 3) {
    *out_type = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(r, 2)));
  }
  if (out_len != nullptr) *out_len = len;
  if (out != nullptr && addr != 0 && len > 0) {
    std::memcpy(out, reinterpret_cast<const void*>(addr), len * elem_size);
  }
  Py_DECREF(r);
  return 0;
}

}  // namespace

extern "C" {

const char* GBTN_GetLastError() { return g_last_error.c_str(); }

// data: row-major [nrow, ncol] f64; label: [nrow] f32 or null; reference:
// existing dataset handle whose bin mappers align the new data (validation
// sets — LGBM_DatasetCreateFromMat's reference param), or null.
// params: space-separated key=value pairs (reference c_api convention).
// On success *out is a dataset handle; returns 0, else -1.
int GBTN_DatasetCreateFromMat(const double* data, long long nrow, int ncol,
                              const char* params, const float* label,
                              void* reference, void** out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* mv_data = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<double*>(data)),
      static_cast<Py_ssize_t>(nrow) * ncol * sizeof(double), PyBUF_READ);
  PyObject* mv_label =
      label == nullptr
          ? (Py_INCREF(Py_None), Py_None)
          : PyMemoryView_FromMemory(
                reinterpret_cast<char*>(const_cast<float*>(label)),
                static_cast<Py_ssize_t>(nrow) * sizeof(float), PyBUF_READ);
  PyObject* args = Py_BuildValue("(OLisOO)", mv_data, nrow, ncol,
                                 params == nullptr ? "" : params, mv_label,
                                 handle_or_none(reference));
  Py_XDECREF(mv_data);
  Py_XDECREF(mv_label);
  PyObject* ds = call_bridge("dataset_from_mat", args);
  if (ds == nullptr) return -1;
  *out = ds;  // owned reference == handle
  return 0;
}

int GBTN_DatasetFree(void* handle) {
  if (!Py_IsInitialized() || handle == nullptr) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
}

int GBTN_BoosterCreate(void* dataset, const char* params, void** out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Os)", static_cast<PyObject*>(dataset),
      params == nullptr ? "" : params);
  PyObject* bst = call_bridge("booster_create", args);
  if (bst == nullptr) return -1;
  *out = bst;
  return 0;
}

// *is_finished = 1 when no further splits are possible (reference
// LGBM_BoosterUpdateOneIter contract).
int GBTN_BoosterUpdateOneIter(void* booster, int* is_finished) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(booster));
  PyObject* r = call_bridge("booster_update", args);
  if (r == nullptr) return -1;
  if (is_finished != nullptr) *is_finished = PyObject_IsTrue(r) ? 1 : 0;
  Py_DECREF(r);
  return 0;
}

int GBTN_BoosterSaveModel(void* booster, int num_iteration,
                          const char* filename) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* args = Py_BuildValue("(Ois)", static_cast<PyObject*>(booster),
                                 num_iteration, filename);
  PyObject* r = call_bridge("booster_save", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// out must hold nrow * num_class doubles (transformed predictions).
int GBTN_BoosterPredictForMat(void* booster, const double* data,
                              long long nrow, int ncol, double* out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* mv_in = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<double*>(data)),
      static_cast<Py_ssize_t>(nrow) * ncol * sizeof(double), PyBUF_READ);
  PyObject* r = nullptr;
  {
    PyObject* num_class =
        call_bridge("booster_num_class",
                    Py_BuildValue("(O)", static_cast<PyObject*>(booster)));
    if (num_class == nullptr) {
      Py_XDECREF(mv_in);
      return -1;
    }
    long k = PyLong_AsLong(num_class);
    Py_DECREF(num_class);
    PyObject* mv_out = PyMemoryView_FromMemory(
        reinterpret_cast<char*>(out),
        static_cast<Py_ssize_t>(nrow) * k * sizeof(double), PyBUF_WRITE);
    PyObject* args = Py_BuildValue("(OOLiO)",
                                   static_cast<PyObject*>(booster), mv_in,
                                   nrow, ncol, mv_out);
    Py_XDECREF(mv_out);
    r = call_bridge("booster_predict_into", args);
  }
  Py_XDECREF(mv_in);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int GBTN_BoosterGetNumClass(void* booster, int* out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* r = call_bridge(
      "booster_num_class",
      Py_BuildValue("(O)", static_cast<PyObject*>(booster)));
  if (r == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int GBTN_BoosterFree(void* handle) {
  if (!Py_IsInitialized() || handle == nullptr) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
}

// ------------------------------------------------------ dataset surface
// (LGBM_Dataset* analogues, c_api.h:37-244)

int GBTN_DatasetCreateFromFile(const char* filename, const char* params,
                               void* reference, void** out) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_handle(
      "dataset_from_file",
      Py_BuildValue("(ssO)", filename, params == nullptr ? "" : params,
                    handle_or_none(reference)),
      out);
}

int GBTN_DatasetCreateFromCSR(const int* indptr, long long nindptr,
                              const int* indices, const double* data,
                              long long nelem, long long ncol,
                              const char* params, void* reference,
                              void** out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* mv_p = mv_read(indptr, nindptr * sizeof(int));
  PyObject* mv_i = mv_read(indices, nelem * sizeof(int));
  PyObject* mv_d = mv_read(data, nelem * sizeof(double));
  PyObject* args = Py_BuildValue(
      "(OLOOLLsO)", mv_p, nindptr, mv_i, mv_d, nelem, ncol,
      params == nullptr ? "" : params, handle_or_none(reference));
  Py_XDECREF(mv_p);
  Py_XDECREF(mv_i);
  Py_XDECREF(mv_d);
  return bridge_handle("dataset_from_csr", args, out);
}

int GBTN_DatasetCreateFromCSC(const int* colptr, long long ncolptr,
                              const int* indices, const double* data,
                              long long nelem, long long nrow,
                              const char* params, void* reference,
                              void** out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* mv_p = mv_read(colptr, ncolptr * sizeof(int));
  PyObject* mv_i = mv_read(indices, nelem * sizeof(int));
  PyObject* mv_d = mv_read(data, nelem * sizeof(double));
  PyObject* args = Py_BuildValue(
      "(OLOOLLsO)", mv_p, ncolptr, mv_i, mv_d, nelem, nrow,
      params == nullptr ? "" : params, handle_or_none(reference));
  Py_XDECREF(mv_p);
  Py_XDECREF(mv_i);
  Py_XDECREF(mv_d);
  return bridge_handle("dataset_from_csc", args, out);
}

// Streaming construction: preallocate [nrow, ncol], fill via PushRows
// (LGBM_DatasetCreateFromSampledColumn + LGBM_DatasetPushRows flow).
int GBTN_DatasetCreateEmpty(long long nrow, int ncol, const char* params,
                            void* reference, void** out) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_handle(
      "dataset_empty",
      Py_BuildValue("(LisO)", nrow, ncol, params == nullptr ? "" : params,
                    handle_or_none(reference)),
      out);
}

int GBTN_DatasetPushRows(void* dataset, const double* data, long long nrow,
                         int ncol, long long start_row) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* mv = mv_read(data, nrow * ncol * sizeof(double));
  PyObject* args = Py_BuildValue("(OOLiL)", handle_or_none(dataset), mv,
                                 nrow, ncol, start_row);
  Py_XDECREF(mv);
  return bridge_ok("dataset_push_rows", args);
}

int GBTN_DatasetPushRowsByCSR(void* dataset, const int* indptr,
                              long long nindptr, const int* indices,
                              const double* data, long long nelem,
                              long long ncol, long long start_row) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* mv_p = mv_read(indptr, nindptr * sizeof(int));
  PyObject* mv_i = mv_read(indices, nelem * sizeof(int));
  PyObject* mv_d = mv_read(data, nelem * sizeof(double));
  PyObject* args = Py_BuildValue("(OOLOOLLL)", handle_or_none(dataset),
                                 mv_p, nindptr, mv_i, mv_d, nelem, ncol,
                                 start_row);
  Py_XDECREF(mv_p);
  Py_XDECREF(mv_i);
  Py_XDECREF(mv_d);
  return bridge_ok("dataset_push_rows_csr", args);
}

// dtype codes follow the reference c_api: 0=f32, 1=f64, 2=i32.
int GBTN_DatasetSetField(void* dataset, const char* name, const void* data,
                         long long num_el, int dtype) {
  if (!ensure_python()) return -1;
  Gil gil;
  size_t elem = dtype == 1 ? sizeof(double)
                           : dtype == 2 ? sizeof(int) : sizeof(float);
  PyObject* mv = mv_read(data, num_el * elem);
  PyObject* args = Py_BuildValue("(OsOLi)", handle_or_none(dataset), name,
                                 mv, num_el, dtype);
  Py_XDECREF(mv);
  return bridge_ok("dataset_set_field", args);
}

// *out_ptr points into storage owned by the dataset handle (valid until
// the handle is freed) — the reference LGBM_DatasetGetField contract.
int GBTN_DatasetGetField(void* dataset, const char* name,
                         long long* out_len, const void** out_ptr,
                         int* out_type) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* r = call_bridge(
      "dataset_get_field",
      Py_BuildValue("(Os)", handle_or_none(dataset), name));
  if (r == nullptr) return -1;
  long long addr = PyLong_AsLongLong(PyTuple_GetItem(r, 0));
  if (out_len != nullptr) {
    *out_len = PyLong_AsLongLong(PyTuple_GetItem(r, 1));
  }
  if (out_type != nullptr) {
    *out_type = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(r, 2)));
  }
  if (out_ptr != nullptr) *out_ptr = reinterpret_cast<const void*>(addr);
  Py_DECREF(r);
  return 0;
}

int GBTN_DatasetGetNumData(void* dataset, long long* out) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_ll("dataset_num_data",
                   Py_BuildValue("(O)", handle_or_none(dataset)), out);
}

int GBTN_DatasetGetNumFeature(void* dataset, int* out) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_int("dataset_num_feature",
                    Py_BuildValue("(O)", handle_or_none(dataset)), out);
}

int GBTN_DatasetSetFeatureNames(void* dataset, const char** names, int n) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* list = PyList_New(n);
  if (list == nullptr) {
    set_error_from_python();
    return -1;
  }
  for (int i = 0; i < n; ++i) {
    PyList_SetItem(list, i, PyUnicode_FromString(names[i]));
  }
  PyObject* args = Py_BuildValue("(OO)", handle_or_none(dataset), list);
  Py_DECREF(list);
  return bridge_ok("dataset_set_feature_names", args);
}

int GBTN_DatasetGetFeatureNames(void* dataset, char** out_strs,
                                int buffer_len, int* out_n) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_string_list("dataset_feature_names",
                            Py_BuildValue("(O)", handle_or_none(dataset)),
                            out_strs, buffer_len, out_n);
}

int GBTN_DatasetSaveBinary(void* dataset, const char* filename) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_ok("dataset_save_binary",
                   Py_BuildValue("(Os)", handle_or_none(dataset), filename));
}

int GBTN_DatasetLoadBinary(const char* filename, void** out) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_handle("dataset_load_binary",
                       Py_BuildValue("(s)", filename), out);
}

int GBTN_DatasetGetSubset(void* dataset, const int* used_row_indices,
                          long long num, const char* params, void** out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* mv = mv_read(used_row_indices, num * sizeof(int));
  PyObject* args = Py_BuildValue("(OOLs)", handle_or_none(dataset), mv, num,
                                 params == nullptr ? "" : params);
  Py_XDECREF(mv);
  return bridge_handle("dataset_subset", args, out);
}

// ------------------------------------------------------ booster surface
// (LGBM_Booster* analogues, c_api.h:246-719)

int GBTN_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations, void** out) {
  if (!ensure_python()) return -1;
  Gil gil;
  if (bridge_handle("booster_from_file", Py_BuildValue("(s)", filename),
                    out) != 0) {
    return -1;
  }
  if (out_num_iterations != nullptr) {
    return bridge_int("booster_current_iteration",
                      Py_BuildValue("(O)", handle_or_none(*out)),
                      out_num_iterations);
  }
  return 0;
}

int GBTN_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations, void** out) {
  if (!ensure_python()) return -1;
  Gil gil;
  if (bridge_handle("booster_from_string",
                    Py_BuildValue("(s)", model_str), out) != 0) {
    return -1;
  }
  if (out_num_iterations != nullptr) {
    return bridge_int("booster_current_iteration",
                      Py_BuildValue("(O)", handle_or_none(*out)),
                      out_num_iterations);
  }
  return 0;
}

int GBTN_BoosterMerge(void* booster, void* other_booster) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_ok("booster_merge",
                   Py_BuildValue("(OO)", handle_or_none(booster),
                                 handle_or_none(other_booster)));
}

int GBTN_BoosterAddValidData(void* booster, void* valid_data,
                             const char* name) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_ok("booster_add_valid",
                   Py_BuildValue("(OOs)", handle_or_none(booster),
                                 handle_or_none(valid_data),
                                 name == nullptr ? "valid" : name));
}

int GBTN_BoosterResetTrainingData(void* booster, void* train_data) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_ok("booster_reset_training_data",
                   Py_BuildValue("(OO)", handle_or_none(booster),
                                 handle_or_none(train_data)));
}

int GBTN_BoosterResetParameter(void* booster, const char* params) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_ok("booster_reset_parameter",
                   Py_BuildValue("(Os)", handle_or_none(booster),
                                 params == nullptr ? "" : params));
}

// grad/hess: [n] f32 = num_data * num_class, the caller-computed gradients
// (LGBM_BoosterUpdateOneIterCustom).
int GBTN_BoosterUpdateOneIterCustom(void* booster, const float* grad,
                                    const float* hess, long long n,
                                    int* is_finished) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* mv_g = mv_read(grad, n * sizeof(float));
  PyObject* mv_h = mv_read(hess, n * sizeof(float));
  PyObject* args = Py_BuildValue("(OOOL)", handle_or_none(booster), mv_g,
                                 mv_h, n);
  Py_XDECREF(mv_g);
  Py_XDECREF(mv_h);
  PyObject* r = call_bridge("booster_update_custom", args);
  if (r == nullptr) return -1;
  if (is_finished != nullptr) *is_finished = PyObject_IsTrue(r) ? 1 : 0;
  Py_DECREF(r);
  return 0;
}

int GBTN_BoosterRollbackOneIter(void* booster) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_ok("booster_rollback",
                   Py_BuildValue("(O)", handle_or_none(booster)));
}

int GBTN_BoosterGetCurrentIteration(void* booster, int* out) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_int("booster_current_iteration",
                    Py_BuildValue("(O)", handle_or_none(booster)), out);
}

int GBTN_BoosterGetNumFeature(void* booster, int* out) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_int("booster_num_feature",
                    Py_BuildValue("(O)", handle_or_none(booster)), out);
}

int GBTN_BoosterGetFeatureNames(void* booster, char** out_strs,
                                int buffer_len, int* out_n) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_string_list("booster_feature_names",
                            Py_BuildValue("(O)", handle_or_none(booster)),
                            out_strs, buffer_len, out_n);
}

int GBTN_BoosterGetEvalCounts(void* booster, int* out) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_int("booster_eval_counts",
                    Py_BuildValue("(O)", handle_or_none(booster)), out);
}

int GBTN_BoosterGetEvalNames(void* booster, char** out_strs, int buffer_len,
                             int* out_n) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_string_list("booster_eval_names",
                            Py_BuildValue("(O)", handle_or_none(booster)),
                            out_strs, buffer_len, out_n);
}

// data_idx: 0 = train, i > 0 = i-th validation set.  out must hold
// GetEvalCounts doubles.
int GBTN_BoosterGetEval(void* booster, int data_idx, int* out_len,
                        double* out) {
  if (!ensure_python()) return -1;
  Gil gil;
  long long len = 0;
  if (bridge_buffer_copy("booster_get_eval",
                         Py_BuildValue("(Oi)", handle_or_none(booster),
                                       data_idx),
                         out, sizeof(double), &len, nullptr) != 0) {
    return -1;
  }
  if (out_len != nullptr) *out_len = static_cast<int>(len);
  return 0;
}

int GBTN_BoosterGetNumPredict(void* booster, int data_idx, long long* out) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_ll("booster_num_predict",
                   Py_BuildValue("(Oi)", handle_or_none(booster), data_idx),
                   out);
}

// Raw scores of the train/valid data, [num_data, num_class] row-major.
int GBTN_BoosterGetPredict(void* booster, int data_idx, long long* out_len,
                           double* out) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_buffer_copy(
      "booster_get_predict",
      Py_BuildValue("(Oi)", handle_or_none(booster), data_idx), out,
      sizeof(double), out_len, nullptr);
}

int GBTN_BoosterGetLeafValue(void* booster, int tree_idx, int leaf_idx,
                             double* out) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_double("booster_get_leaf_value",
                       Py_BuildValue("(Oii)", handle_or_none(booster),
                                     tree_idx, leaf_idx),
                       out);
}

int GBTN_BoosterSetLeafValue(void* booster, int tree_idx, int leaf_idx,
                             double value) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_ok("booster_set_leaf_value",
                   Py_BuildValue("(Oiid)", handle_or_none(booster),
                                 tree_idx, leaf_idx, value));
}

// *out_len = needed bytes (incl. NUL); the copy happens only when
// buffer_len suffices — the reference SaveModelToString convention.
int GBTN_BoosterSaveModelToString(void* booster, int num_iteration,
                                  long long buffer_len, long long* out_len,
                                  char* out_str) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_string("booster_model_string",
                       Py_BuildValue("(Oi)", handle_or_none(booster),
                                     num_iteration),
                       buffer_len, out_len, out_str);
}

int GBTN_BoosterDumpModel(void* booster, int num_iteration,
                          long long buffer_len, long long* out_len,
                          char* out_str) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_string("booster_dump_json",
                       Py_BuildValue("(Oi)", handle_or_none(booster),
                                     num_iteration),
                       buffer_len, out_len, out_str);
}

// predict_type: 0 normal, 1 raw score, 2 leaf index (C_API_PREDICT_*).
int GBTN_BoosterCalcNumPredict(void* booster, long long nrow,
                               int predict_type, int num_iteration,
                               long long* out) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_ll("booster_calc_num_predict",
                   Py_BuildValue("(OLii)", handle_or_none(booster), nrow,
                                 predict_type, num_iteration),
                   out);
}

int GBTN_BoosterPredict(void* booster, const double* data, long long nrow,
                        int ncol, int predict_type, int num_iteration,
                        long long out_capacity, long long* out_len,
                        double* out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* mv_in = mv_read(data, nrow * ncol * sizeof(double));
  PyObject* mv_out = mv_write(out, out_capacity * sizeof(double));
  PyObject* args = Py_BuildValue("(OOLiiiOL)", handle_or_none(booster),
                                 mv_in, nrow, ncol, predict_type,
                                 num_iteration, mv_out, out_capacity);
  Py_XDECREF(mv_in);
  Py_XDECREF(mv_out);
  long long written = 0;
  if (bridge_ll("booster_predict_full_into", args, &written) != 0) return -1;
  if (out_len != nullptr) *out_len = written;
  return 0;
}

int GBTN_BoosterPredictForCSR(void* booster, const int* indptr,
                              long long nindptr, const int* indices,
                              const double* data, long long nelem,
                              long long ncol, int predict_type,
                              int num_iteration, long long out_capacity,
                              long long* out_len, double* out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* mv_p = mv_read(indptr, nindptr * sizeof(int));
  PyObject* mv_i = mv_read(indices, nelem * sizeof(int));
  PyObject* mv_d = mv_read(data, nelem * sizeof(double));
  PyObject* mv_out = mv_write(out, out_capacity * sizeof(double));
  PyObject* args = Py_BuildValue(
      "(OOLOOLLiiOL)", handle_or_none(booster), mv_p, nindptr, mv_i, mv_d,
      nelem, ncol, predict_type, num_iteration, mv_out, out_capacity);
  Py_XDECREF(mv_p);
  Py_XDECREF(mv_i);
  Py_XDECREF(mv_d);
  Py_XDECREF(mv_out);
  long long written = 0;
  if (bridge_ll("booster_predict_csr_into", args, &written) != 0) return -1;
  if (out_len != nullptr) *out_len = written;
  return 0;
}

int GBTN_BoosterPredictForCSC(void* booster, const int* colptr,
                              long long ncolptr, const int* indices,
                              const double* data, long long nelem,
                              long long nrow, int predict_type,
                              int num_iteration, long long out_capacity,
                              long long* out_len, double* out) {
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* mv_p = mv_read(colptr, ncolptr * sizeof(int));
  PyObject* mv_i = mv_read(indices, nelem * sizeof(int));
  PyObject* mv_d = mv_read(data, nelem * sizeof(double));
  PyObject* mv_out = mv_write(out, out_capacity * sizeof(double));
  PyObject* args = Py_BuildValue(
      "(OOLOOLLiiOL)", handle_or_none(booster), mv_p, ncolptr, mv_i, mv_d,
      nelem, nrow, predict_type, num_iteration, mv_out, out_capacity);
  Py_XDECREF(mv_p);
  Py_XDECREF(mv_i);
  Py_XDECREF(mv_d);
  Py_XDECREF(mv_out);
  long long written = 0;
  if (bridge_ll("booster_predict_csc_into", args, &written) != 0) return -1;
  if (out_len != nullptr) *out_len = written;
  return 0;
}

int GBTN_BoosterPredictForFile(void* booster, const char* data_filename,
                               int has_header, const char* result_filename,
                               int predict_type, int num_iteration) {
  if (!ensure_python()) return -1;
  Gil gil;
  return bridge_ok("booster_predict_for_file",
                   Py_BuildValue("(Osisii)", handle_or_none(booster),
                                 data_filename, has_header, result_filename,
                                 predict_type, num_iteration));
}

}  // extern "C"
