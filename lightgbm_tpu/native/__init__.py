"""ctypes bindings for the native host runtime (gbt_native.cpp).

The reference's data layer and serving path are C++ (parser.hpp, bin.cpp,
predictor.hpp); this package provides the same split for the TPU framework:
text parsing, value->bin quantization and model prediction run in an
OpenMP-parallel shared library, while training compute stays on TPU.

The library builds on demand with g++ (cached next to the source); when no
toolchain is available every entry point degrades to the pure-python
implementations, so the native layer is an accelerator, not a dependency.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "gbt_native.cpp")
_SRC_TRAIN = os.path.join(_DIR, "gbt_capi_train.cpp")
_LIB_PATH = os.path.join(_DIR, "_gbt_native.so")

_lock = threading.Lock()
_lib = None
_load_failed = False
_has_train_api = False


def _build() -> bool:
    import sys
    import sysconfig
    base = ["g++", "-O3", "-shared", "-fPIC", "-o", _LIB_PATH]
    # preferred: serving runtime + the CPython-embedding training ABI,
    # linked against libpython so standalone C callers (and hosts whose
    # python binary does not re-export libpython symbols) resolve Py_*
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    pylib = f"python{sys.version_info.major}.{sys.version_info.minor}"
    link = ([f"-L{libdir}", f"-l{pylib}", f"-Wl,-rpath,{libdir}"]
            if libdir else [])
    with_train = base + ["-std=c++14", "-fopenmp", _SRC, _SRC_TRAIN,
                         "-I" + sysconfig.get_paths()["include"]] + link
    # fallbacks: unlinked shim (static-python hosts), no training shim
    # (no Python headers), then no OpenMP
    attempts = [with_train,
                [c for c in with_train if c not in link],
                [c for c in with_train if c != "-fopenmp"],
                base + ["-std=c++11", "-fopenmp", _SRC],
                base + ["-std=c++11", _SRC]]
    for cmd in attempts:
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=300)
        except (OSError, subprocess.TimeoutExpired):
            return False
        if proc.returncode == 0:
            return True
    return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c_ll, c_i, c_p = ctypes.c_longlong, ctypes.c_int, ctypes.c_void_p
    c_d_p = ctypes.POINTER(ctypes.c_double)
    c_f_p = ctypes.POINTER(ctypes.c_float)
    c_i_p = ctypes.POINTER(ctypes.c_int)
    c_ll_p = ctypes.POINTER(ctypes.c_longlong)

    lib.GBTN_ParseFile.restype = c_p
    lib.GBTN_ParseFile.argtypes = [ctypes.c_char_p, c_i, c_i]
    lib.GBTN_ParsedRows.restype = c_ll
    lib.GBTN_ParsedRows.argtypes = [c_p]
    lib.GBTN_ParsedCols.restype = c_ll
    lib.GBTN_ParsedCols.argtypes = [c_p]
    lib.GBTN_ParsedError.restype = ctypes.c_char_p
    lib.GBTN_ParsedError.argtypes = [c_p]
    lib.GBTN_ParsedCopy.restype = None
    lib.GBTN_ParsedCopy.argtypes = [c_p, c_d_p, c_f_p]
    lib.GBTN_ParsedFree.restype = None
    lib.GBTN_ParsedFree.argtypes = [c_p]

    lib.GBTN_BinColumn.restype = None
    lib.GBTN_BinColumn.argtypes = [c_d_p, c_ll, c_d_p, c_i, c_i, c_i, c_p]
    lib.GBTN_GreedyFindBin.restype = c_i
    lib.GBTN_GreedyFindBin.argtypes = [c_d_p, c_ll_p, c_i, c_i, c_ll, c_i,
                                       c_d_p]
    lib.GBTN_BinColumnCategorical.restype = None
    lib.GBTN_BinColumnCategorical.argtypes = [c_d_p, c_ll, c_ll_p, c_i_p,
                                              c_i, c_i, c_i, c_p]

    lib.GBTN_LoadModelString.restype = c_p
    lib.GBTN_LoadModelString.argtypes = [ctypes.c_char_p]
    lib.GBTN_LoadModelFile.restype = c_p
    lib.GBTN_LoadModelFile.argtypes = [ctypes.c_char_p]
    lib.GBTN_ModelError.restype = ctypes.c_char_p
    lib.GBTN_ModelError.argtypes = [c_p]
    lib.GBTN_ModelNumClass.restype = c_i
    lib.GBTN_ModelNumClass.argtypes = [c_p]
    lib.GBTN_ModelNumTrees.restype = c_i
    lib.GBTN_ModelNumTrees.argtypes = [c_p]
    lib.GBTN_ModelNumFeatures.restype = c_i
    lib.GBTN_ModelNumFeatures.argtypes = [c_p]
    lib.GBTN_Predict.restype = None
    lib.GBTN_Predict.argtypes = [c_p, c_d_p, c_ll, c_i, c_i, c_i, c_d_p]
    lib.GBTN_PredictLeaf.restype = None
    lib.GBTN_PredictLeaf.argtypes = [c_p, c_d_p, c_ll, c_i, c_i, c_i_p]
    lib.GBTN_FreeModel.restype = None
    lib.GBTN_FreeModel.argtypes = [c_p]
    lib.GBTN_OpenMPThreads.restype = c_i
    lib.GBTN_OpenMPThreads.argtypes = []

    # training ABI (absent when built without Python headers)
    global _has_train_api
    try:
        lib.GBTN_GetLastError.restype = ctypes.c_char_p
        lib.GBTN_GetLastError.argtypes = []
        lib.GBTN_DatasetCreateFromMat.restype = c_i
        lib.GBTN_DatasetCreateFromMat.argtypes = [
            c_d_p, c_ll, c_i, ctypes.c_char_p, c_f_p, c_p,
            ctypes.POINTER(c_p)]
        lib.GBTN_DatasetFree.restype = c_i
        lib.GBTN_DatasetFree.argtypes = [c_p]
        lib.GBTN_BoosterCreate.restype = c_i
        lib.GBTN_BoosterCreate.argtypes = [c_p, ctypes.c_char_p,
                                           ctypes.POINTER(c_p)]
        lib.GBTN_BoosterUpdateOneIter.restype = c_i
        lib.GBTN_BoosterUpdateOneIter.argtypes = [c_p, c_i_p]
        lib.GBTN_BoosterSaveModel.restype = c_i
        lib.GBTN_BoosterSaveModel.argtypes = [c_p, c_i, ctypes.c_char_p]
        lib.GBTN_BoosterPredictForMat.restype = c_i
        lib.GBTN_BoosterPredictForMat.argtypes = [c_p, c_d_p, c_ll, c_i,
                                                  c_d_p]
        lib.GBTN_BoosterGetNumClass.restype = c_i
        lib.GBTN_BoosterGetNumClass.argtypes = [c_p, c_i_p]
        lib.GBTN_BoosterFree.restype = c_i
        lib.GBTN_BoosterFree.argtypes = [c_p]

        c_c_p = ctypes.c_char_p
        c_cpp = ctypes.POINTER(c_c_p)       # char** (string arrays)
        c_pp = ctypes.POINTER(c_p)
        c_vpp = ctypes.POINTER(c_p)         # const void** out
        lib.GBTN_DatasetCreateFromFile.restype = c_i
        lib.GBTN_DatasetCreateFromFile.argtypes = [c_c_p, c_c_p, c_p, c_pp]
        lib.GBTN_DatasetCreateFromCSR.restype = c_i
        lib.GBTN_DatasetCreateFromCSR.argtypes = [
            c_i_p, c_ll, c_i_p, c_d_p, c_ll, c_ll, c_c_p, c_p, c_pp]
        lib.GBTN_DatasetCreateFromCSC.restype = c_i
        lib.GBTN_DatasetCreateFromCSC.argtypes = [
            c_i_p, c_ll, c_i_p, c_d_p, c_ll, c_ll, c_c_p, c_p, c_pp]
        lib.GBTN_DatasetCreateEmpty.restype = c_i
        lib.GBTN_DatasetCreateEmpty.argtypes = [c_ll, c_i, c_c_p, c_p, c_pp]
        lib.GBTN_DatasetPushRows.restype = c_i
        lib.GBTN_DatasetPushRows.argtypes = [c_p, c_d_p, c_ll, c_i, c_ll]
        lib.GBTN_DatasetPushRowsByCSR.restype = c_i
        lib.GBTN_DatasetPushRowsByCSR.argtypes = [
            c_p, c_i_p, c_ll, c_i_p, c_d_p, c_ll, c_ll, c_ll]
        lib.GBTN_DatasetSetField.restype = c_i
        lib.GBTN_DatasetSetField.argtypes = [c_p, c_c_p, c_p, c_ll, c_i]
        lib.GBTN_DatasetGetField.restype = c_i
        lib.GBTN_DatasetGetField.argtypes = [c_p, c_c_p, c_ll_p, c_vpp,
                                             c_i_p]
        lib.GBTN_DatasetGetNumData.restype = c_i
        lib.GBTN_DatasetGetNumData.argtypes = [c_p, c_ll_p]
        lib.GBTN_DatasetGetNumFeature.restype = c_i
        lib.GBTN_DatasetGetNumFeature.argtypes = [c_p, c_i_p]
        lib.GBTN_DatasetSetFeatureNames.restype = c_i
        lib.GBTN_DatasetSetFeatureNames.argtypes = [c_p, c_cpp, c_i]
        lib.GBTN_DatasetGetFeatureNames.restype = c_i
        lib.GBTN_DatasetGetFeatureNames.argtypes = [c_p, c_cpp, c_i, c_i_p]
        lib.GBTN_DatasetSaveBinary.restype = c_i
        lib.GBTN_DatasetSaveBinary.argtypes = [c_p, c_c_p]
        lib.GBTN_DatasetLoadBinary.restype = c_i
        lib.GBTN_DatasetLoadBinary.argtypes = [c_c_p, c_pp]
        lib.GBTN_DatasetGetSubset.restype = c_i
        lib.GBTN_DatasetGetSubset.argtypes = [c_p, c_i_p, c_ll, c_c_p, c_pp]

        lib.GBTN_BoosterCreateFromModelfile.restype = c_i
        lib.GBTN_BoosterCreateFromModelfile.argtypes = [c_c_p, c_i_p, c_pp]
        lib.GBTN_BoosterLoadModelFromString.restype = c_i
        lib.GBTN_BoosterLoadModelFromString.argtypes = [c_c_p, c_i_p, c_pp]
        lib.GBTN_BoosterMerge.restype = c_i
        lib.GBTN_BoosterMerge.argtypes = [c_p, c_p]
        lib.GBTN_BoosterAddValidData.restype = c_i
        lib.GBTN_BoosterAddValidData.argtypes = [c_p, c_p, c_c_p]
        lib.GBTN_BoosterResetTrainingData.restype = c_i
        lib.GBTN_BoosterResetTrainingData.argtypes = [c_p, c_p]
        lib.GBTN_BoosterResetParameter.restype = c_i
        lib.GBTN_BoosterResetParameter.argtypes = [c_p, c_c_p]
        lib.GBTN_BoosterUpdateOneIterCustom.restype = c_i
        lib.GBTN_BoosterUpdateOneIterCustom.argtypes = [c_p, c_f_p, c_f_p,
                                                        c_ll, c_i_p]
        lib.GBTN_BoosterRollbackOneIter.restype = c_i
        lib.GBTN_BoosterRollbackOneIter.argtypes = [c_p]
        lib.GBTN_BoosterGetCurrentIteration.restype = c_i
        lib.GBTN_BoosterGetCurrentIteration.argtypes = [c_p, c_i_p]
        lib.GBTN_BoosterGetNumFeature.restype = c_i
        lib.GBTN_BoosterGetNumFeature.argtypes = [c_p, c_i_p]
        lib.GBTN_BoosterGetFeatureNames.restype = c_i
        lib.GBTN_BoosterGetFeatureNames.argtypes = [c_p, c_cpp, c_i, c_i_p]
        lib.GBTN_BoosterGetEvalCounts.restype = c_i
        lib.GBTN_BoosterGetEvalCounts.argtypes = [c_p, c_i_p]
        lib.GBTN_BoosterGetEvalNames.restype = c_i
        lib.GBTN_BoosterGetEvalNames.argtypes = [c_p, c_cpp, c_i, c_i_p]
        lib.GBTN_BoosterGetEval.restype = c_i
        lib.GBTN_BoosterGetEval.argtypes = [c_p, c_i, c_i_p, c_d_p]
        lib.GBTN_BoosterGetNumPredict.restype = c_i
        lib.GBTN_BoosterGetNumPredict.argtypes = [c_p, c_i, c_ll_p]
        lib.GBTN_BoosterGetPredict.restype = c_i
        lib.GBTN_BoosterGetPredict.argtypes = [c_p, c_i, c_ll_p, c_d_p]
        lib.GBTN_BoosterGetLeafValue.restype = c_i
        lib.GBTN_BoosterGetLeafValue.argtypes = [c_p, c_i, c_i,
                                                 ctypes.POINTER(
                                                     ctypes.c_double)]
        lib.GBTN_BoosterSetLeafValue.restype = c_i
        lib.GBTN_BoosterSetLeafValue.argtypes = [c_p, c_i, c_i,
                                                 ctypes.c_double]
        lib.GBTN_BoosterSaveModelToString.restype = c_i
        lib.GBTN_BoosterSaveModelToString.argtypes = [c_p, c_i, c_ll,
                                                      c_ll_p, c_c_p]
        lib.GBTN_BoosterDumpModel.restype = c_i
        lib.GBTN_BoosterDumpModel.argtypes = [c_p, c_i, c_ll, c_ll_p, c_c_p]
        lib.GBTN_BoosterCalcNumPredict.restype = c_i
        lib.GBTN_BoosterCalcNumPredict.argtypes = [c_p, c_ll, c_i, c_i,
                                                   c_ll_p]
        lib.GBTN_BoosterPredict.restype = c_i
        lib.GBTN_BoosterPredict.argtypes = [c_p, c_d_p, c_ll, c_i, c_i, c_i,
                                            c_ll, c_ll_p, c_d_p]
        lib.GBTN_BoosterPredictForCSR.restype = c_i
        lib.GBTN_BoosterPredictForCSR.argtypes = [
            c_p, c_i_p, c_ll, c_i_p, c_d_p, c_ll, c_ll, c_i, c_i, c_ll,
            c_ll_p, c_d_p]
        lib.GBTN_BoosterPredictForCSC.restype = c_i
        lib.GBTN_BoosterPredictForCSC.argtypes = [
            c_p, c_i_p, c_ll, c_i_p, c_d_p, c_ll, c_ll, c_i, c_i, c_ll,
            c_ll_p, c_d_p]
        lib.GBTN_BoosterPredictForFile.restype = c_i
        lib.GBTN_BoosterPredictForFile.argtypes = [c_p, c_c_p, c_i, c_c_p,
                                                   c_i, c_i]
        _has_train_api = True
    except AttributeError:
        _has_train_api = False
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if os.environ.get("LGBM_TPU_NO_NATIVE"):
            _load_failed = True
            return None
        try:
            src_mtime = max(os.path.getmtime(_SRC),
                            os.path.getmtime(_SRC_TRAIN)
                            if os.path.exists(_SRC_TRAIN) else 0.0)
            if (not os.path.exists(_LIB_PATH)
                    or os.path.getmtime(_LIB_PATH) < src_mtime):
                if not _build():
                    _load_failed = True
                    return None
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
        except OSError:
            _load_failed = True
            return None
    return _lib


def available() -> bool:
    return get_lib() is not None


def train_api_available() -> bool:
    """True when the training C ABI (gbt_capi_train.cpp) was built in."""
    return get_lib() is not None and _has_train_api


# ---------------------------------------------------------------- wrappers

def parse_file(path: str, has_header: bool, label_idx: int
               ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Native text parse -> (features [N, F] f64, labels [N] f32)."""
    lib = get_lib()
    if lib is None:
        return None
    h = lib.GBTN_ParseFile(path.encode(), int(has_header), int(label_idx))
    try:
        err = lib.GBTN_ParsedError(h)
        if err:
            raise ValueError(f"native parser: {err.decode()}")
        n, f = lib.GBTN_ParsedRows(h), lib.GBTN_ParsedCols(h)
        feats = np.empty((n, f), dtype=np.float64)
        labels = np.empty((n,), dtype=np.float32)
        lib.GBTN_ParsedCopy(
            h, feats.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return feats, labels
    finally:
        lib.GBTN_ParsedFree(h)


def greedy_find_bin(distinct: np.ndarray, counts: np.ndarray, max_bin: int,
                    total_cnt: int, min_data_in_bin: int):
    """Native greedy bin-boundary search; None when the library is absent
    (caller falls back to the pure-Python loop in data/binning.py)."""
    lib = get_lib()
    if lib is None:
        return None
    distinct = np.ascontiguousarray(distinct, dtype=np.float64)
    counts = np.ascontiguousarray(counts, dtype=np.int64)
    out = np.empty(max(int(max_bin), 1), dtype=np.float64)
    n = lib.GBTN_GreedyFindBin(
        distinct.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        len(distinct), int(max_bin), int(total_cnt), int(min_data_in_bin),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return out[:n].tolist()


def bin_column(values: np.ndarray, bounds: np.ndarray, n_search: int,
               nan_bin: int, out: np.ndarray) -> bool:
    """Native numerical value->bin into preallocated uint8/uint16 ``out``."""
    lib = get_lib()
    if lib is None:
        return False
    values = np.ascontiguousarray(values, dtype=np.float64)
    bounds = np.ascontiguousarray(bounds, dtype=np.float64)
    bits = 8 if out.dtype == np.uint8 else 16
    lib.GBTN_BinColumn(
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), len(values),
        bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        int(n_search), int(nan_bin), bits, out.ctypes.data_as(ctypes.c_void_p))
    return True


def bin_column_categorical(values: np.ndarray, cat_to_bin: dict,
                           overflow_bin: int, out: np.ndarray) -> bool:
    lib = get_lib()
    if lib is None:
        return False
    values = np.ascontiguousarray(values, dtype=np.float64)
    cats = np.asarray(sorted(cat_to_bin), dtype=np.int64)
    bins = np.asarray([cat_to_bin[c] for c in cats], dtype=np.int32)
    bits = 8 if out.dtype == np.uint8 else 16
    lib.GBTN_BinColumnCategorical(
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), len(values),
        cats.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        bins.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        len(cats), int(overflow_bin), bits,
        out.ctypes.data_as(ctypes.c_void_p))
    return True


class NativePredictor:
    """Native model predictor (serving path; predictor.hpp analogue)."""

    def __init__(self, model_str: Optional[str] = None,
                 model_file: Optional[str] = None):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        if model_file is not None:
            self._h = lib.GBTN_LoadModelFile(model_file.encode())
        else:
            self._h = lib.GBTN_LoadModelString(model_str.encode())
        err = lib.GBTN_ModelError(self._h)
        if err:
            msg = err.decode()
            lib.GBTN_FreeModel(self._h)
            self._h = None
            raise ValueError(f"native model load: {msg}")
        self.num_class = lib.GBTN_ModelNumClass(self._h)
        self.num_trees = lib.GBTN_ModelNumTrees(self._h)
        self.num_features = lib.GBTN_ModelNumFeatures(self._h)

    def _prepare(self, X: np.ndarray) -> np.ndarray:
        """Contiguous f64 matrix padded/validated to the model's feature
        count (sparse prediction files may have fewer trailing columns)."""
        X = np.ascontiguousarray(np.atleast_2d(X), dtype=np.float64)
        f = X.shape[1]
        if f < self.num_features:
            X = np.pad(X, ((0, 0), (0, self.num_features - f)))
        elif f > self.num_features:
            X = np.ascontiguousarray(X[:, :self.num_features])
        return X

    def predict(self, X: np.ndarray, num_iteration: int = -1,
                raw_score: bool = False) -> np.ndarray:
        X = self._prepare(X)
        n, f = X.shape
        k = max(self.num_class, 1)
        out = np.empty((n, k), dtype=np.float64)
        self._lib.GBTN_Predict(
            self._h, X.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            n, f, int(num_iteration), int(raw_score),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        return out[:, 0] if k == 1 else out

    def predict_leaf(self, X: np.ndarray, num_iteration: int = -1) -> np.ndarray:
        X = self._prepare(X)
        n, f = X.shape
        k = max(self.num_class, 1)
        iters = self.num_trees // k if k else 0
        if num_iteration > 0:
            iters = min(num_iteration, iters)
        total = iters * k
        out = np.empty((n, total), dtype=np.int32)
        self._lib.GBTN_PredictLeaf(
            self._h, X.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            n, f, int(num_iteration),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int)))
        return out

    def __del__(self):
        if getattr(self, "_h", None) is not None:
            self._lib.GBTN_FreeModel(self._h)
