"""Python side of the training C ABI (gbt_capi_train.cpp).

Each function is called from the C shim with plain buffers/handles and
delegates into the engine — mirroring how the reference's ``c_api.cpp`` is a
thin shim over its C++ ``GBDT`` (``include/LightGBM/c_api.h:37-719``).
Buffers arriving from C are COPIED before use: the caller may free them as
soon as the call returns (reference ``LGBM_DatasetCreateFromMat`` contract).
"""
from __future__ import annotations

from typing import Dict

import numpy as np


def _parse_params(params: str) -> Dict[str, str]:
    """Space-separated ``key=value`` pairs — the reference c_api params
    convention (c_api.cpp ConfigStr2Map)."""
    out: Dict[str, str] = {}
    for tok in (params or "").split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def dataset_from_mat(mv_data, nrow, ncol, params, mv_label, reference=None):
    from ..basic import Dataset
    X = np.frombuffer(mv_data, dtype=np.float64,
                      count=nrow * ncol).reshape(nrow, ncol).copy()
    label = (None if mv_label is None
             else np.frombuffer(mv_label, dtype=np.float32,
                                count=nrow).copy())
    return Dataset(X, label=label, reference=reference,
                   params=_parse_params(params))


def booster_create(dataset, params):
    from ..basic import Booster
    return Booster(params=_parse_params(params), train_set=dataset)


def booster_update(booster) -> bool:
    return bool(booster.update())


def booster_save(booster, num_iteration, filename) -> bool:
    booster.save_model(filename, num_iteration=num_iteration)
    return True


def booster_num_class(booster) -> int:
    return int(max(booster.inner.num_class, 1))


def booster_predict_into(booster, mv_in, nrow, ncol, mv_out) -> bool:
    X = np.frombuffer(mv_in, dtype=np.float64,
                      count=nrow * ncol).reshape(nrow, ncol)
    pred = np.asarray(booster.predict(X), dtype=np.float64)
    k = booster_num_class(booster)
    out = np.frombuffer(mv_out, dtype=np.float64,
                        count=nrow * k).reshape(nrow, k)
    out[:] = pred.reshape(nrow, k)
    return True


# ------------------------------------------------------------------ datasets
# Field dtype codes follow the reference (c_api.h C_API_DTYPE_*):
# 0 = float32, 1 = float64, 2 = int32.
_DTYPES = {0: np.float32, 1: np.float64, 2: np.int32}
_DTYPE_CODES = {np.dtype(np.float32): 0, np.dtype(np.float64): 1,
                np.dtype(np.int32): 2}


def dataset_from_file(filename, params, reference):
    from ..basic import Dataset
    return Dataset(filename, reference=reference,
                   params=_parse_params(params))


def _csr_matrix(mv_indptr, nindptr, mv_indices, mv_data, nelem, ncol):
    """Copied CSR triplet as a host :class:`~..data.sparse.CsrMatrix`.

    The framework's storage IS dense binned columns (SURVEY §7: TPUs
    have no fast gather/scatter; EFB re-compresses mutually-exclusive
    sparse columns at construct), but densification happens one
    budget-bounded row chunk at a time (data/sparse.py) — the full
    ``[nrow, ncol]`` float64 matrix never materializes on ingest."""
    from ..data.sparse import CsrMatrix
    indptr = np.frombuffer(mv_indptr, dtype=np.int32, count=nindptr)
    indices = np.frombuffer(mv_indices, dtype=np.int32, count=nelem)
    data = np.frombuffer(mv_data, dtype=np.float64, count=nelem)
    return CsrMatrix(indptr, indices, data, ncol)


def dataset_from_csr(mv_indptr, nindptr, mv_indices, mv_data, nelem, ncol,
                     params, reference):
    from ..basic import Dataset
    csr = _csr_matrix(mv_indptr, nindptr, mv_indices, mv_data, nelem, ncol)
    return Dataset(csr, reference=reference, params=_parse_params(params))


def dataset_from_csc(mv_colptr, ncolptr, mv_indices, mv_data, nelem, nrow,
                     params, reference):
    from ..basic import Dataset
    colptr = np.frombuffer(mv_colptr, dtype=np.int32, count=ncolptr)
    indices = np.frombuffer(mv_indices, dtype=np.int32, count=nelem)
    data = np.frombuffer(mv_data, dtype=np.float64, count=nelem)
    ncol = ncolptr - 1
    X = np.zeros((nrow, ncol), dtype=np.float64)
    col_of = np.repeat(np.arange(ncol), np.diff(colptr).astype(np.int64))
    X[indices, col_of] = data
    return Dataset(X, reference=reference, params=_parse_params(params))


def dataset_empty(nrow, ncol, params, reference):
    """Streaming construction start (LGBM_DatasetCreateFromSampledColumn +
    PushRows flow): rows arrive later; construction stays lazy until the
    first consumer."""
    from ..basic import Dataset
    X = np.zeros((nrow, ncol), dtype=np.float64)
    return Dataset(X, reference=reference, params=_parse_params(params))


def _push_target(ds, nrow, ncol, start_row) -> np.ndarray:
    """The preallocated dataset matrix a PushRows block lands in, with
    the shared contract checks."""
    X = ds.data
    if ds._constructed is not None or not isinstance(X, np.ndarray):
        raise RuntimeError("PushRows on an already-constructed dataset")
    if ncol != X.shape[1] or start_row + nrow > X.shape[0]:
        raise ValueError(f"push block [{start_row}:{start_row + nrow}) x "
                         f"{ncol} outside dataset {X.shape}")
    return X


def dataset_push_rows(ds, mv_data, nrow, ncol, start_row) -> bool:
    X = _push_target(ds, nrow, ncol, start_row)
    X[start_row:start_row + nrow] = np.frombuffer(
        mv_data, dtype=np.float64, count=nrow * ncol).reshape(nrow, ncol)
    return True


def dataset_push_rows_csr(ds, mv_indptr, nindptr, mv_indices, mv_data,
                          nelem, ncol, start_row) -> bool:
    csr = _csr_matrix(mv_indptr, nindptr, mv_indices, mv_data, nelem, ncol)
    X = _push_target(ds, csr.nrow, ncol, start_row)
    # budget-bounded chunks write straight into the preallocated rows —
    # no full dense copy of the pushed block ever exists
    for r0, block in csr.iter_dense_chunks():
        X[start_row + r0:start_row + r0 + len(block)] = block
    return True


def dataset_set_field(ds, name, mv_data, num_el, dtype_code) -> bool:
    if dtype_code not in _DTYPES:
        raise ValueError(f"unknown field dtype code {dtype_code}")
    data = None if mv_data is None else np.frombuffer(
        mv_data, dtype=_DTYPES[dtype_code], count=num_el).copy()
    ds.set_field(name, data)
    return True


def dataset_get_field(ds, name):
    """Returns (buffer_address, length, dtype_code) with the backing array
    cached on the handle so the pointer stays valid (reference GetField
    returns a pointer into the Dataset's own storage)."""
    if name in ("group", "query"):
        # the C contract returns CUMULATIVE query boundaries, int32,
        # num_queries+1 entries (c_api.cpp DatasetGetField "group") — not
        # the per-query counts the python-level get_field uses
        qb = ds.construct()._constructed.metadata.query_boundaries
        if qb is None:
            return (0, 0, 0)
        val = np.asarray(qb, dtype=np.int32)
    else:
        val = ds.get_field(name)
    if val is None:
        return (0, 0, 0)
    arr = np.ascontiguousarray(val)
    if arr.dtype not in _DTYPE_CODES:
        arr = arr.astype(np.float64)
    cache = getattr(ds, "_capi_field_cache", {})
    old = cache.get(name)
    if (old is not None and old.dtype == arr.dtype
            and np.array_equal(old, arr)):
        arr = old          # unchanged field: keep earlier pointers valid
    else:
        cache[name] = arr  # changed (SetField): old pointer goes stale,
        ds._capi_field_cache = cache        # like the reference's storage
    return (arr.ctypes.data, int(arr.size), _DTYPE_CODES[arr.dtype])


def dataset_num_data(ds) -> int:
    return int(ds.num_data())


def dataset_num_feature(ds) -> int:
    return int(ds.num_feature())


def dataset_set_feature_names(ds, names) -> bool:
    ds.set_feature_name(list(names))
    return True


def dataset_feature_names(ds):
    c = ds.construct()._constructed
    return list(c.feature_names or [])


def dataset_save_binary(ds, filename) -> bool:
    ds.save_binary(filename)
    return True


def dataset_load_binary(filename):
    from ..basic import Dataset
    return Dataset.load_binary(filename)


def dataset_subset(ds, mv_indices, num, params):
    idx = np.frombuffer(mv_indices, dtype=np.int32, count=num).copy()
    return ds.subset(idx, params=_parse_params(params) or None)


# ------------------------------------------------------------------ boosters

def booster_from_file(filename):
    from ..basic import Booster
    return Booster(model_file=filename)


def booster_from_string(model_str):
    from ..basic import Booster
    return Booster(model_str=model_str)


def booster_merge(dst, src) -> bool:
    dst.merge(src)
    return True


def booster_add_valid(bst, ds, name) -> bool:
    bst.add_valid(ds, name)
    return True


def booster_reset_training_data(bst, ds) -> bool:
    """Reference GBDT::ResetTrainingData: the model keeps its trees and
    continues boosting on the new data — so the rebuilt trainer's scores
    must start from the existing model's raw predictions on that data
    (the same recipe as continued training, engine.py init_model path).
    Validation sets stay attached, like the reference (which only swaps
    the train data)."""
    from ..basic import Booster
    prev = bst.inner
    prev_valid_ds = list(getattr(bst, "_valid_datasets", []))
    prev_valid_names = [vs.name for vs in prev.valid_sets]
    fresh = Booster(params=bst.params, train_set=ds)
    inner = fresh.inner
    if prev.models:
        raw = ds.raw if ds.raw is not None else ds.data
        if raw is None:
            raise RuntimeError("ResetTrainingData requires in-memory raw "
                               "data (free_raw_data=False)")
        init = prev.predictor().predict_raw(np.asarray(raw))
        inner.scores = inner.scores + np.asarray(init, np.float32)
        inner.models = list(prev.models)
        inner.num_init_iteration = prev.current_iteration()
        inner.boost_from_average_ = prev.boost_from_average_
    bst.inner = inner
    bst._train_dataset = ds
    bst._valid_datasets = []
    for vds, name in zip(prev_valid_ds, prev_valid_names):
        bst.add_valid(vds, name)   # replays the model onto the valid scores
    return True


def booster_reset_parameter(bst, params) -> bool:
    bst.reset_parameter(_parse_params(params))
    return True


def booster_update_custom(bst, mv_grad, mv_hess, n) -> bool:
    grad = np.frombuffer(mv_grad, dtype=np.float32, count=n).copy()
    hess = np.frombuffer(mv_hess, dtype=np.float32, count=n).copy()
    return bool(bst.inner.train_one_iter(grad, hess))


def booster_rollback(bst) -> bool:
    bst.rollback_one_iter()
    return True


def booster_current_iteration(bst) -> int:
    return int(bst.current_iteration())


def booster_num_feature(bst) -> int:
    return int(bst.num_feature())


def booster_feature_names(bst):
    return list(bst.feature_name())


def _eval_results(bst, data_idx):
    """(name, metric, value, higher_better) rows for one data index:
    0 = train, i>0 = i-th validation set (reference GetEval convention)."""
    if data_idx == 0:
        return bst.eval_train()
    sets = bst.inner.valid_sets
    if data_idx > len(sets):
        raise IndexError(f"data_idx {data_idx} out of range "
                         f"({len(sets)} valid sets)")
    vs = sets[data_idx - 1]
    return bst.inner._eval(vs.name, vs.metrics,
                           np.asarray(vs.scores, np.float64))


def booster_eval_counts(bst) -> int:
    metrics = bst.inner.train_metrics or (
        bst.inner.valid_sets[0].metrics if bst.inner.valid_sets else [])
    return sum(len(m.names()) for m in metrics)


def booster_eval_names(bst):
    metrics = bst.inner.train_metrics or (
        bst.inner.valid_sets[0].metrics if bst.inner.valid_sets else [])
    return [n for m in metrics for n in m.names()]


def booster_get_eval(bst, data_idx):
    vals = np.asarray([v for (_, _, v, _) in _eval_results(bst, data_idx)],
                      dtype=np.float64)
    cache = getattr(bst, "_capi_eval_cache", {})
    cache[data_idx] = vals
    bst._capi_eval_cache = cache
    return (vals.ctypes.data, int(vals.size))


def booster_num_predict(bst, data_idx) -> int:
    """O(1) element count of GetPredict's output (no conversion work)."""
    if data_idx == 0:
        scores = bst.inner.scores
    else:
        sets = bst.inner.valid_sets
        if data_idx > len(sets):
            raise IndexError(f"data_idx {data_idx} out of range")
        scores = sets[data_idx - 1].scores
    return int(np.prod(scores.shape))


def booster_get_predict(bst, data_idx):
    """Predictions of the train (0) / i-th valid (i) set, row-major
    [n, num_class] — reference LGBM_BoosterGetPredict semantics
    (GBDT::GetPredictAt, gbdt.cpp:756): ConvertOutput (sigmoid/softmax)
    applies only when the model is NOT average_output; RF models return
    the raw scores untouched."""
    if data_idx == 0:
        scores = np.asarray(bst.inner.scores, np.float64)
    else:
        sets = bst.inner.valid_sets
        if data_idx > len(sets):
            raise IndexError(f"data_idx {data_idx} out of range")
        scores = np.asarray(sets[data_idx - 1].scores, np.float64)
    if bst.inner.objective is not None and not bst.inner.average_output:
        # GBDT::GetPredictAt converts only when NOT average_output (RF
        # returns the raw scores untouched, gbdt.cpp:756)
        scores = np.asarray(bst.inner.objective.convert_output(scores),
                            np.float64)
    out = np.ascontiguousarray(scores.T)         # [n, k]
    cache = getattr(bst, "_capi_pred_cache", {})
    cache[data_idx] = out
    bst._capi_pred_cache = cache
    return (out.ctypes.data, int(out.size))


def booster_get_leaf_value(bst, tree_idx, leaf_idx) -> float:
    return float(bst.get_leaf_output(tree_idx, leaf_idx))


def booster_set_leaf_value(bst, tree_idx, leaf_idx, value) -> bool:
    bst.set_leaf_output(tree_idx, leaf_idx, value)
    return True


def booster_model_string(bst, num_iteration) -> str:
    return bst.model_to_string(num_iteration)


def booster_dump_json(bst, num_iteration) -> str:
    import json
    return json.dumps(bst.dump_model(num_iteration))


def booster_calc_num_predict(bst, nrow, predict_type, num_iteration) -> int:
    k = booster_num_class(bst)
    if predict_type == 2:   # C_API_PREDICT_LEAF_INDEX
        iters = len(bst.inner.models) // max(k, 1)
        if num_iteration > 0:
            iters = min(num_iteration, iters)
        return int(nrow * iters * k)
    return int(nrow * k)


def _predict_array(bst, X, predict_type, num_iteration):
    ni = num_iteration if num_iteration and num_iteration > 0 else -1
    if predict_type == 2:
        return np.asarray(bst.predict(X, num_iteration=ni, pred_leaf=True),
                          dtype=np.float64)
    raw = predict_type == 1    # C_API_PREDICT_RAW_SCORE
    return np.asarray(bst.predict(X, num_iteration=ni, raw_score=raw),
                      dtype=np.float64)


def booster_predict_full_into(bst, mv_in, nrow, ncol, predict_type,
                              num_iteration, mv_out, out_capacity) -> int:
    """Dense predict with the reference's predict_type codes
    (0 normal / 1 raw / 2 leaf index); returns the element count."""
    X = np.frombuffer(mv_in, dtype=np.float64,
                      count=nrow * ncol).reshape(nrow, ncol)
    pred = _predict_array(bst, X, predict_type, num_iteration)
    flat = pred.reshape(-1)
    if flat.size > out_capacity:
        raise ValueError(f"output buffer too small: need {flat.size}, "
                         f"have {out_capacity}")
    out = np.frombuffer(mv_out, dtype=np.float64, count=flat.size)
    out[:] = flat
    return int(flat.size)


def booster_predict_csr_into(bst, mv_indptr, nindptr, mv_indices, mv_data,
                             nelem, ncol, predict_type, num_iteration,
                             mv_out, out_capacity) -> int:
    csr = _csr_matrix(mv_indptr, nindptr, mv_indices, mv_data, nelem, ncol)
    out = np.frombuffer(mv_out, dtype=np.float64, count=out_capacity)
    wrote = 0
    # predict one budget-bounded dense chunk at a time; per-row output
    # width is fixed, so chunk outputs concatenate contiguously
    for r0, block in csr.iter_dense_chunks():
        flat = _predict_array(bst, block, predict_type,
                              num_iteration).reshape(-1)
        if wrote + flat.size > out_capacity:
            raise ValueError(f"output buffer too small: need at least "
                             f"{wrote + flat.size}, have {out_capacity}")
        out[wrote:wrote + flat.size] = flat
        wrote += flat.size
    return int(wrote)


def booster_predict_csc_into(bst, mv_colptr, ncolptr, mv_indices, mv_data,
                             nelem, nrow, predict_type, num_iteration,
                             mv_out, out_capacity) -> int:
    colptr = np.frombuffer(mv_colptr, dtype=np.int32, count=ncolptr)
    indices = np.frombuffer(mv_indices, dtype=np.int32, count=nelem)
    data = np.frombuffer(mv_data, dtype=np.float64, count=nelem)
    ncol = ncolptr - 1
    X = np.zeros((nrow, ncol), dtype=np.float64)
    col_of = np.repeat(np.arange(ncol), np.diff(colptr).astype(np.int64))
    X[indices, col_of] = data
    pred = _predict_array(bst, X, predict_type, num_iteration)
    flat = pred.reshape(-1)
    if flat.size > out_capacity:
        raise ValueError(f"output buffer too small: need {flat.size}, "
                         f"have {out_capacity}")
    out = np.frombuffer(mv_out, dtype=np.float64, count=flat.size)
    out[:] = flat
    return int(flat.size)


def booster_predict_for_file(bst, data_filename, has_header,
                             result_filename, predict_type,
                             num_iteration) -> bool:
    """LGBM_BoosterPredictForFile: stream a text file through predict and
    write one line per row (tab-separated for multi-output)."""
    from ..data.parser import load_text_file
    feats, _, _ = load_text_file(data_filename, has_header=bool(has_header))
    pred = _predict_array(bst, feats, predict_type, num_iteration)
    pred2d = pred.reshape(len(feats), -1)
    with open(result_filename, "w") as f:
        for row in pred2d:
            f.write("\t".join(repr(float(v)) for v in row) + "\n")
    return True
