"""Python side of the training C ABI (gbt_capi_train.cpp).

Each function is called from the C shim with plain buffers/handles and
delegates into the engine — mirroring how the reference's ``c_api.cpp`` is a
thin shim over its C++ ``GBDT`` (``include/LightGBM/c_api.h:37-719``).
Buffers arriving from C are COPIED before use: the caller may free them as
soon as the call returns (reference ``LGBM_DatasetCreateFromMat`` contract).
"""
from __future__ import annotations

from typing import Dict

import numpy as np


def _parse_params(params: str) -> Dict[str, str]:
    """Space-separated ``key=value`` pairs — the reference c_api params
    convention (c_api.cpp ConfigStr2Map)."""
    out: Dict[str, str] = {}
    for tok in (params or "").split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def dataset_from_mat(mv_data, nrow, ncol, params, mv_label):
    from ..basic import Dataset
    X = np.frombuffer(mv_data, dtype=np.float64,
                      count=nrow * ncol).reshape(nrow, ncol).copy()
    label = (None if mv_label is None
             else np.frombuffer(mv_label, dtype=np.float32,
                                count=nrow).copy())
    return Dataset(X, label=label, params=_parse_params(params))


def booster_create(dataset, params):
    from ..basic import Booster
    return Booster(params=_parse_params(params), train_set=dataset)


def booster_update(booster) -> bool:
    return bool(booster.update())


def booster_save(booster, num_iteration, filename) -> bool:
    booster.save_model(filename, num_iteration=num_iteration)
    return True


def booster_num_class(booster) -> int:
    return int(max(booster.inner.num_class, 1))


def booster_predict_into(booster, mv_in, nrow, ncol, mv_out) -> bool:
    X = np.frombuffer(mv_in, dtype=np.float64,
                      count=nrow * ncol).reshape(nrow, ncol)
    pred = np.asarray(booster.predict(X), dtype=np.float64)
    k = booster_num_class(booster)
    out = np.frombuffer(mv_out, dtype=np.float64,
                        count=nrow * k).reshape(nrow, k)
    out[:] = pred.reshape(nrow, k)
    return True
