"""Text data parsers: CSV / TSV / LibSVM with format auto-detection.

Mirrors the reference parser surface (``src/io/parser.{hpp,cpp}``): the format
is sniffed from the first lines (``CreateParser``), labels sit in a
configurable column, LibSVM rows are ``label idx:val ...`` sparse pairs.
Implemented with numpy batch parsing rather than per-line virtual calls.
"""
from __future__ import annotations

import io
from typing import List, Optional, Tuple

import numpy as np

from ..utils import log


NA_VALUES = ["", "na", "nan", "NA", "NaN", "null"]


def _read_head(path: str, n_lines: int = 32) -> List[str]:
    """First lines of a file for sniffing; fatal on an empty file."""
    with open(path, "r") as f:
        head = [line for _, line in zip(range(n_lines), f)]
    if not head:
        log.fatal("Data file %s is empty", path)
    return head


def sniff_file(path: str, has_header: bool) -> Tuple[str, int]:
    """(format, num_columns) for a data file — blank lines skipped."""
    head = _read_head(path)
    start = 1 if has_header else 0
    return _sniff_format(head[start:] or head)


def read_header_names(path: str, label_idx: int = 0) -> Optional[List[str]]:
    """Column names from a header line, label column removed (None for
    libsvm, which has no per-column header)."""
    head = _read_head(path)
    fmt, _ = _sniff_format(head[1:] or head)
    if fmt == "libsvm":
        return None
    sep = "," if fmt == "csv" else "\t"
    names = [t.strip() for t in head[0].strip().split(sep)]
    if label_idx >= 0:
        names = [h for i, h in enumerate(names) if i != label_idx]
    return names


def _sniff_format(lines: List[str]) -> Tuple[str, int]:
    """Return (format, num_columns). format in {csv, tsv, libsvm}."""
    for line in lines:
        line = line.strip()
        if not line:
            continue
        tokens_tab = line.split("\t")
        tokens_comma = line.split(",")
        tokens_space = line.split()
        if any(":" in t for t in tokens_space[1:]):
            return "libsvm", 0
        if len(tokens_tab) > 1:
            return "tsv", len(tokens_tab)
        if len(tokens_comma) > 1:
            return "csv", len(tokens_comma)
        if len(tokens_space) > 1:
            return "tsv", len(tokens_space)  # space-separated handled like TSV
    return "csv", 1


def load_text_file(path: str, has_header: bool = False,
                   label_idx: int = 0) -> Tuple[np.ndarray, np.ndarray, Optional[List[str]]]:
    """Parse a data file into (features [N, F] float64, labels [N], feature_names).

    Missing values (empty CSV cells, "na"/"nan") become NaN.  LibSVM zero
    default is 0.0 as in the reference.
    """
    head = _read_head(path)
    start = 1 if has_header else 0
    fmt, _ = _sniff_format(head[start:] or head)

    header_names: Optional[List[str]] = None
    if has_header and fmt != "libsvm":
        sep_h = "," if fmt == "csv" else "\t"
        header_names = [t.strip() for t in head[0].strip().split(sep_h)]

    # native OpenMP parser fast path (same sniffing/NA semantics)
    from .. import native
    parsed = native.parse_file(path, has_header, label_idx) \
        if native.available() else None
    if parsed is not None:
        features, labels = parsed
        if header_names is not None and label_idx >= 0:
            header_names = [h for i, h in enumerate(header_names)
                            if i != label_idx]
        return features, labels, header_names

    if fmt == "libsvm":
        return _load_libsvm(path, has_header, label_idx) + (None,)

    delim = "," if fmt == "csv" else None  # None -> any whitespace incl. tab

    def conv(text: str) -> np.ndarray:
        return np.genfromtxt(io.StringIO(text), delimiter=delim,
                             skip_header=start, dtype=np.float64,
                             missing_values=NA_VALUES,
                             filling_values=np.nan)

    with open(path, "r") as f:
        mat = conv(f.read())
    if mat.ndim == 1:
        mat = mat.reshape(-1, 1) if mat.size else mat.reshape(0, 1)
    if label_idx >= 0:
        labels = mat[:, label_idx].astype(np.float32)
        features = np.delete(mat, label_idx, axis=1)
        if header_names is not None:
            header_names = [h for i, h in enumerate(header_names) if i != label_idx]
    else:
        labels = np.zeros(mat.shape[0], dtype=np.float32)
        features = mat
    return features, labels, header_names


def count_data_rows(path: str, has_header: bool,
                    label_idx: int = 0) -> Tuple[int, int]:
    """Round-0 scan of the streamed loader: (num_rows, num_features)
    without materializing any floats (dataset_loader.cpp CountLine).

    CSV/TSV: a newline scan plus the sniffed column count.  LibSVM: the
    scan must also tokenize to learn the feature-space width (the maximum
    index may appear on any line) — the price of a headerless sparse
    format."""
    fmt, ncol = sniff_file(path, has_header)
    n = 0
    if fmt == "libsvm":
        max_idx = -1
        with open(path, "r") as f:
            if has_header:
                f.readline()
            for line in f:
                if not line.strip():
                    continue
                n += 1
                for tok in line.split():
                    i, _, _v = tok.partition(":")
                    if _v and i.isdigit():
                        idx = int(i)
                        if idx > max_idx:
                            max_idx = idx
        return n, max_idx + 1
    with open(path, "r") as f:
        if has_header:
            f.readline()
        for line in f:
            if line.strip():
                n += 1
    return n, ncol - (1 if label_idx >= 0 else 0)


def iter_parsed_chunks(path: str, has_header: bool, label_idx: int,
                       chunk_rows: int = 200_000, ncol: int = None):
    """Stream (features [c, F] f64, labels [c] f32) chunks — the per-chunk
    worker of the two-round loader.  ``ncol`` fixes the feature count
    (required for libsvm, where any single chunk may not witness the
    maximum feature index)."""
    fmt, _ = sniff_file(path, has_header)

    def flush_csv(lines):
        mat = np.genfromtxt(io.StringIO("".join(lines)),
                            delimiter="," if fmt == "csv" else None,
                            dtype=np.float64,
                            missing_values=NA_VALUES,
                            filling_values=np.nan)
        if mat.ndim == 1:
            mat = mat.reshape(len(lines), -1)
        if label_idx >= 0:
            return (np.delete(mat, label_idx, axis=1),
                    mat[:, label_idx].astype(np.float32))
        return mat, np.zeros(len(mat), dtype=np.float32)

    def flush_libsvm(lines):
        feats = np.zeros((len(lines), ncol), dtype=np.float64)
        labs = np.zeros(len(lines), dtype=np.float32)
        for r, line in enumerate(lines):
            toks = line.split()
            if label_idx >= 0 and toks and ":" not in toks[0]:
                labs[r] = float(toks[0])
                toks = toks[1:]
            for t in toks:
                i, _, v = t.partition(":")
                # non-numeric ids (e.g. ranking "qid:3") are skipped, same
                # as in the counting pass
                if v and i.isdigit():
                    feats[r, int(i)] = float(v)
        return feats, labs

    flush = flush_libsvm if fmt == "libsvm" else flush_csv
    buf = []
    with open(path, "r") as f:
        if has_header:
            f.readline()
        for line in f:
            if not line.strip():
                continue
            buf.append(line)
            if len(buf) >= chunk_rows:
                yield flush(buf)
                buf = []
    if buf:
        yield flush(buf)


def _load_libsvm(path: str, has_header: bool, label_idx: int) -> Tuple[np.ndarray, np.ndarray]:
    rows: List[List[Tuple[int, float]]] = []
    labels: List[float] = []
    max_idx = -1
    with open(path, "r") as f:
        if has_header:
            f.readline()
        for line in f:
            line = line.strip()
            if not line:
                continue
            toks = line.split()
            if label_idx >= 0:
                labels.append(float(toks[0]))
                toks = toks[1:]
            else:
                labels.append(0.0)
            row = []
            for t in toks:
                if ":" not in t:
                    continue
                i, v = t.split(":", 1)
                i = int(i)
                row.append((i, float(v)))
                max_idx = max(max_idx, i)
            rows.append(row)
    mat = np.zeros((len(rows), max_idx + 1), dtype=np.float64)
    for r, row in enumerate(rows):
        for i, v in row:
            mat[r, i] = v
    return mat, np.asarray(labels, dtype=np.float32)
