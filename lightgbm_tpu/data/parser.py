"""Text data parsers: CSV / TSV / LibSVM with format auto-detection.

Mirrors the reference parser surface (``src/io/parser.{hpp,cpp}``): the format
is sniffed from the first lines (``CreateParser``), labels sit in a
configurable column, LibSVM rows are ``label idx:val ...`` sparse pairs.
Implemented with numpy batch parsing rather than per-line virtual calls.
"""
from __future__ import annotations

import io
from typing import List, Optional, Tuple

import numpy as np

from ..utils import log


def _sniff_format(lines: List[str]) -> Tuple[str, int]:
    """Return (format, num_columns). format in {csv, tsv, libsvm}."""
    for line in lines:
        line = line.strip()
        if not line:
            continue
        tokens_tab = line.split("\t")
        tokens_comma = line.split(",")
        tokens_space = line.split()
        if any(":" in t for t in tokens_space[1:]):
            return "libsvm", 0
        if len(tokens_tab) > 1:
            return "tsv", len(tokens_tab)
        if len(tokens_comma) > 1:
            return "csv", len(tokens_comma)
        if len(tokens_space) > 1:
            return "tsv", len(tokens_space)  # space-separated handled like TSV
    return "csv", 1


def load_text_file(path: str, has_header: bool = False,
                   label_idx: int = 0) -> Tuple[np.ndarray, np.ndarray, Optional[List[str]]]:
    """Parse a data file into (features [N, F] float64, labels [N], feature_names).

    Missing values (empty CSV cells, "na"/"nan") become NaN.  LibSVM zero
    default is 0.0 as in the reference.
    """
    with open(path, "r") as f:
        head = []
        for _ in range(32):
            line = f.readline()
            if not line:
                break
            head.append(line)
    if not head:
        log.fatal("Data file %s is empty", path)
    start = 1 if has_header else 0
    fmt, _ = _sniff_format(head[start:] or head)

    header_names: Optional[List[str]] = None
    if has_header and fmt != "libsvm":
        sep_h = "," if fmt == "csv" else "\t"
        header_names = [t.strip() for t in head[0].strip().split(sep_h)]

    # native OpenMP parser fast path (same sniffing/NA semantics)
    from .. import native
    parsed = native.parse_file(path, has_header, label_idx) \
        if native.available() else None
    if parsed is not None:
        features, labels = parsed
        if header_names is not None and label_idx >= 0:
            header_names = [h for i, h in enumerate(header_names)
                            if i != label_idx]
        return features, labels, header_names

    if fmt == "libsvm":
        return _load_libsvm(path, has_header, label_idx) + (None,)

    delim = "," if fmt == "csv" else None  # None -> any whitespace incl. tab

    def conv(text: str) -> np.ndarray:
        return np.genfromtxt(io.StringIO(text), delimiter=delim,
                             skip_header=start, dtype=np.float64,
                             missing_values=["", "na", "nan", "NA", "NaN", "null"],
                             filling_values=np.nan)

    with open(path, "r") as f:
        mat = conv(f.read())
    if mat.ndim == 1:
        mat = mat.reshape(-1, 1) if mat.size else mat.reshape(0, 1)
    if label_idx >= 0:
        labels = mat[:, label_idx].astype(np.float32)
        features = np.delete(mat, label_idx, axis=1)
        if header_names is not None:
            header_names = [h for i, h in enumerate(header_names) if i != label_idx]
    else:
        labels = np.zeros(mat.shape[0], dtype=np.float32)
        features = mat
    return features, labels, header_names


def _load_libsvm(path: str, has_header: bool, label_idx: int) -> Tuple[np.ndarray, np.ndarray]:
    rows: List[List[Tuple[int, float]]] = []
    labels: List[float] = []
    max_idx = -1
    with open(path, "r") as f:
        if has_header:
            f.readline()
        for line in f:
            line = line.strip()
            if not line:
                continue
            toks = line.split()
            if label_idx >= 0:
                labels.append(float(toks[0]))
                toks = toks[1:]
            else:
                labels.append(0.0)
            row = []
            for t in toks:
                if ":" not in t:
                    continue
                i, v = t.split(":", 1)
                i = int(i)
                row.append((i, float(v)))
                max_idx = max(max_idx, i)
            rows.append(row)
    mat = np.zeros((len(rows), max_idx + 1), dtype=np.float64)
    for r, row in enumerate(rows):
        for i, v in row:
            mat[r, i] = v
    return mat, np.asarray(labels, dtype=np.float32)
