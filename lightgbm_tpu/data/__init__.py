from . import binning, dataset, metadata, parser, sparse  # noqa: F401
from .binning import BinMapper  # noqa: F401
from .dataset import (TrainingData, construct, construct_csr,  # noqa: F401
                      construct_streamed)
from .metadata import Metadata  # noqa: F401
from .sparse import CsrMatrix  # noqa: F401
