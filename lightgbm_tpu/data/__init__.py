from . import binning, dataset, metadata, parser  # noqa: F401
from .binning import BinMapper  # noqa: F401
from .dataset import TrainingData, construct, construct_streamed  # noqa: F401
from .metadata import Metadata  # noqa: F401
