"""Host-side CSR container: bounded-peak densification for the C ABI.

The framework's device storage IS dense binned columns (SURVEY §7: TPUs
have no fast gather/scatter; EFB re-compresses mutually-exclusive sparse
columns at construct) — but getting from a sparse C-API matrix to those
uint8 columns used to materialize the FULL ``[nrow, ncol]`` float64
matrix first: an 8-byte-per-cell spike dwarfing both the nnz-sized
source and the 1-byte-per-cell destination.  :class:`CsrMatrix` keeps
the copied CSR triplet host-side and densifies one bounded row chunk at
a time (:data:`CSR_CHUNK_BUDGET_BYTES`), so dataset construction
(``dataset.construct_csr`` bins each chunk straight into the final
uint8/16 matrix), PushRows ingest and predict all peak at one chunk's
worth of dense float64, never the whole matrix.
"""
from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

# dense-densify working-set ceiling: one yielded chunk is at most this
# many bytes of float64 (the peak the memory-budget test pins)
CSR_CHUNK_BUDGET_BYTES = 64 << 20


def csr_chunk_rows(ncol: int, budget_bytes: Optional[int] = None) -> int:
    """Rows per dense chunk so one chunk stays under the byte budget."""
    budget = CSR_CHUNK_BUDGET_BYTES if budget_bytes is None else budget_bytes
    return max(1, int(budget) // max(1, int(ncol) * 8))


class CsrMatrix:
    """Copied CSR triplet (``indptr``/``indices``/``data``) + shape.

    Buffers are copied on construction — C-ABI callers may free theirs
    the moment the call returns (reference ``LGBM_DatasetCreateFromCSR``
    contract).  ``np.asarray`` still works (full chunk-assembled
    densify) so legacy consumers that genuinely need the whole matrix —
    cv, subset, continued training — keep functioning; the construction
    / push / predict fast paths never call it."""

    def __init__(self, indptr, indices, data, ncol: int):
        self.indptr = np.array(indptr, dtype=np.int64, copy=True)
        self.indices = np.array(indices, dtype=np.int64, copy=True)
        self.data = np.array(data, dtype=np.float64, copy=True)
        if self.indptr.ndim != 1 or len(self.indptr) < 1:
            raise ValueError("CSR indptr must be a non-empty 1-D array")
        nnz = int(self.indptr[-1])
        if nnz != len(self.indices) or nnz != len(self.data):
            raise ValueError(
                f"CSR buffers disagree: indptr ends at {nnz}, "
                f"{len(self.indices)} indices / {len(self.data)} values")
        self.nrow = len(self.indptr) - 1
        self.ncol = int(ncol)
        self.shape: Tuple[int, int] = (self.nrow, self.ncol)

    @property
    def nnz(self) -> int:
        return len(self.data)

    @property
    def nbytes(self) -> int:
        """Host bytes the triplet holds (the sparse footprint the chunked
        densify keeps us near)."""
        return int(self.indptr.nbytes + self.indices.nbytes
                   + self.data.nbytes)

    def __len__(self) -> int:
        return self.nrow

    def rows(self, idx) -> np.ndarray:
        """Dense float64 ``[len(idx), ncol]`` of the selected rows, in
        the given order — CSR rows are O(nnz_row) random access, so the
        bin-mapper sample pass needs no full densify."""
        idx = np.asarray(idx, dtype=np.int64)
        counts = self.indptr[idx + 1] - self.indptr[idx]
        out = np.zeros((len(idx), self.ncol), dtype=np.float64)
        total = int(counts.sum())
        if total:
            # element e of the gather = row_start[its row] + its rank
            # within that row, all vectorized
            offs = np.concatenate(([0], np.cumsum(counts)[:-1]))
            take = (np.repeat(self.indptr[idx], counts)
                    + np.arange(total) - np.repeat(offs, counts))
            out[np.repeat(np.arange(len(idx)), counts),
                self.indices[take]] = self.data[take]
        return out

    def iter_dense_chunks(
            self, chunk_rows: Optional[int] = None,
    ) -> Iterator[Tuple[int, np.ndarray]]:
        """Yield ``(row0, dense_chunk)`` pairs covering every row once;
        each chunk is at most ``chunk_rows`` (budget-derived by default)
        rows of dense float64 — the bounded working set that replaces
        the old full-matrix densify."""
        chunk = (csr_chunk_rows(self.ncol) if chunk_rows is None
                 else max(1, int(chunk_rows)))
        for r0 in range(0, self.nrow, chunk):
            r1 = min(self.nrow, r0 + chunk)
            lo = int(self.indptr[r0])
            hi = int(self.indptr[r1])
            block = np.zeros((r1 - r0, self.ncol), dtype=np.float64)
            row_of = np.repeat(np.arange(r1 - r0),
                               np.diff(self.indptr[r0:r1 + 1]))
            block[row_of, self.indices[lo:hi]] = self.data[lo:hi]
            yield r0, block

    def __array__(self, dtype=None, copy=None):
        """Full densify, chunk-assembled (compat fallback only)."""
        out = np.zeros(self.shape, dtype=np.float64)
        for r0, block in self.iter_dense_chunks():
            out[r0:r0 + len(block)] = block
        if dtype is not None:
            out = out.astype(dtype, copy=False)
        return out
