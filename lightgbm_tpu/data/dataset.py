"""Binned dataset container — the TPU-native analogue of ``Dataset``.

The reference (``include/LightGBM/dataset.h:280-570``, ``src/io/dataset.cpp``)
stores features as per-group virtual ``Bin`` columns (dense / sparse /
4-bit).  On TPU we keep one dense row-major matrix of bin indices
(uint8 when every feature has <= 256 bins, else uint16) that is uploaded
once to HBM — the layout the reference itself uses for its GPU learner
(``GPU-Performance.md`` recipe: ``sparse_threshold=1`` densifies everything).

Construction = sample rows (``bin_construct_sample_cnt``), fit a
:class:`~lightgbm_tpu.data.binning.BinMapper` per feature, then vectorized
``value_to_bin`` over every column.  Valid datasets are aligned to their
training dataset's bin mappers (reference ``create_valid`` convention).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from ..config import Config
from ..utils import log
from ..utils.random import make_rng, sample_k
from .binning import (BIN_TYPE_CATEGORICAL, BIN_TYPE_NUMERICAL, BinMapper,
                      MISSING_NAN, MISSING_NONE, MISSING_ZERO)
from .bundling import BundleLayout, build_bundled_column, find_bundles
from .metadata import Metadata


def jax_process_index() -> int:
    import jax
    return jax.process_index()


class TrainingData:
    """Fully constructed binned dataset (host side)."""

    def __init__(self):
        self.num_data: int = 0
        self.num_total_features: int = 0
        self.bin_mappers: List[BinMapper] = []
        self.used_features: List[int] = []         # original feature index per LOGICAL column
        self.binned: Optional[np.ndarray] = None   # [N, F_physical] uint8/uint16
        self.layout: Optional[BundleLayout] = None  # EFB layout (None: 1:1)
        self.metadata: Metadata = Metadata()
        self.feature_names: List[str] = []
        self.reference: Optional["TrainingData"] = None

    # -- feature meta arrays consumed by the jitted grower --------------------

    @property
    def num_used_features(self) -> int:
        return len(self.used_features)

    def feature_meta(self) -> Dict[str, np.ndarray]:
        """Per-LOGICAL-feature meta (+ bundle decode maps when EFB is on)."""
        mappers = [self.bin_mappers[i] for i in self.used_features]
        out = {
            "num_bin": np.asarray([m.num_bin for m in mappers], dtype=np.int32),
            "missing_type": np.asarray([m.missing_type for m in mappers], dtype=np.int32),
            "default_bin": np.asarray([m.default_bin for m in mappers], dtype=np.int32),
            "is_categorical": np.asarray(
                [m.bin_type == BIN_TYPE_CATEGORICAL for m in mappers], dtype=bool),
        }
        if self.layout is not None and self.layout.has_bundles:
            out["col"] = np.asarray(self.layout.sub_col, dtype=np.int32)
            out["offset"] = np.asarray(self.layout.sub_offset, dtype=np.int32)
        return out

    def to_blocks(self, chunk_rows: int):
        """Block-resident variant of this dataset for streamed training
        (``data_stream=chunked``): the binned matrix cut into
        static-shape host row blocks a :class:`~.stream.BlockStreamer`
        pipelines through the device (data/stream.py).  The matrix
        itself stays host-side — full blocks are views, only the padded
        tail is copied."""
        from .stream import make_block_store
        if self.binned is None:
            log.fatal("Cannot build streamed blocks: dataset has no "
                      "binned matrix")
        return make_block_store(self.binned, chunk_rows)

    def max_num_bin(self) -> int:
        """Histogram width: max bins over PHYSICAL columns."""
        if self.layout is not None and self.layout.has_bundles:
            return self.layout.max_col_bins()
        if not self.used_features:
            return 1
        return max(self.bin_mappers[i].num_bin for i in self.used_features)


def construct(data: np.ndarray,
              config: Config,
              label: Optional[np.ndarray] = None,
              weight: Optional[np.ndarray] = None,
              group: Optional[np.ndarray] = None,
              init_score: Optional[np.ndarray] = None,
              feature_names: Optional[Sequence[str]] = None,
              categorical_features: Optional[Sequence[int]] = None,
              reference: Optional[TrainingData] = None) -> TrainingData:
    """Build a TrainingData from a raw feature matrix.

    Follows ``DatasetLoader::CostructFromSampleData`` (dataset_loader.cpp:482+):
    sample up to ``bin_construct_sample_cnt`` rows, fit per-feature bin mappers
    (in one shot — no two-round streaming needed since the matrix is already
    in memory), then bin every column.
    """
    data = np.asarray(data)
    if data.ndim != 2:
        log.fatal("Training data must be 2-dimensional")
    num_data, num_features = data.shape
    ds = TrainingData()
    ds.num_data = num_data
    ds.num_total_features = num_features
    ds.feature_names = (list(feature_names) if feature_names
                        else [f"Column_{i}" for i in range(num_features)])
    cat_set = set(int(c) for c in (categorical_features or []))

    if reference is not None:
        # valid set aligned to training bin mappers (basic.py reference semantics)
        ds.reference = reference
        ds.bin_mappers = reference.bin_mappers
        ds.used_features = reference.used_features
        ds.feature_names = reference.feature_names
        ds.layout = reference.layout
        if num_features != reference.num_total_features:
            log.fatal("Validation data has %d features, training data has %d",
                      num_features, reference.num_total_features)
    else:
        sample_cnt = min(config.bin_construct_sample_cnt, num_data)
        if sample_cnt < num_data:
            rng = make_rng(config.data_random_seed)
            sample_idx = sample_k(rng, num_data, sample_cnt)
            sample = np.asarray(data[sample_idx], dtype=np.float64)
        else:
            sample = np.asarray(data, dtype=np.float64)
        _fit_from_sample(ds, sample, config, cat_set)

    # bin all columns (native OpenMP binner when available)
    dtype = np.uint8 if ds.max_num_bin() <= 256 else np.uint16
    ncols = (ds.layout.num_columns
             if ds.layout is not None and ds.layout.has_bundles
             else len(ds.used_features))
    binned = np.empty((num_data, ncols), dtype=dtype)
    _bin_rows(ds, np.asarray(data), binned)
    ds.binned = binned

    _set_metadata(ds, num_data, label, weight, group, init_score)
    return ds


def _columns_T(data: np.ndarray, cols, chunk_rows: int = 4096) -> np.ndarray:
    """Contiguous ``[len(cols), N]`` float64 transpose of ``data[:, cols]``.

    Reading a single column of a row-major matrix pulls one cache line per
    element (64 bytes for 8 useful) — per-column loops over wide matrices
    were the second-largest construction cost after bin fitting.  Copying
    row chunks keeps every read sequential and the working set in cache.
    """
    cols = np.asarray(cols, dtype=np.intp)
    n = data.shape[0]
    out = np.empty((len(cols), n), dtype=np.float64)
    for r0 in range(0, n, chunk_rows):
        r1 = min(n, r0 + chunk_rows)
        out[:, r0:r1] = data[r0:r1, cols].T
    return out


# features per block in the construction loops: at 64 float64 columns the
# per-block transpose working set is ~2 MB (in L2/L3), and 64 uint8 output
# columns span exactly one cache line per row on write-back
_COL_BLOCK = 64


def _fit_from_sample(ds: TrainingData, sample: np.ndarray, config: Config,
                     cat_set) -> None:
    """Fit per-feature BinMappers from the sampled rows, filter trivial
    features, and decide the EFB bundle layout (FindBin + FindGroups)."""
    num_features = ds.num_total_features
    num_data = ds.num_data
    # distributed FindBin (dataset_loader.cpp:737-816): with each process
    # holding its own row partition, process p fits mappers only for
    # features j = p (mod P) from ITS sample, then the mapper sets are
    # allgathered so every process bins with the identical mappers
    from ..parallel.sync import allgather_object, process_count
    n_proc = process_count()
    my_features = [j for j in range(num_features)
                   if n_proc == 1 or j % n_proc == jax_process_index()]
    fitted = {}
    min_split_data = _filter_cnt(config, len(sample), num_data)
    for b0 in range(0, len(my_features), _COL_BLOCK):
        chunk = my_features[b0:b0 + _COL_BLOCK]
        cols_t = _columns_T(sample, chunk)
        for k, j in enumerate(chunk):
            col = cols_t[k]
            # sparse convention: pass non-zero values; zeros implied by total count
            nz = col[(col != 0) | np.isnan(col)]
            bin_type = (BIN_TYPE_CATEGORICAL if j in cat_set
                        else BIN_TYPE_NUMERICAL)
            fitted[j] = BinMapper.fit(nz, total_sample_cnt=len(col),
                                      max_bin=config.max_bin,
                                      min_data_in_bin=config.min_data_in_bin,
                                      min_split_data=min_split_data,
                                      bin_type=bin_type,
                                      use_missing=config.use_missing,
                                      zero_as_missing=config.zero_as_missing)
    if n_proc > 1:
        for part in allgather_object(fitted):
            fitted.update(part)
    ds.bin_mappers = [fitted[j] for j in range(num_features)]
    ds.used_features = [j for j, m in enumerate(ds.bin_mappers)
                        if not m.is_trivial]
    if not ds.used_features:
        log.fatal("Cannot construct Dataset: all features are trivial (constant)")

    # EFB: greedily bundle mutually-exclusive sparse features
    # (FindGroups/FastFeatureBundling, dataset.cpp:66-210).  All tree
    # learners consume bundles: serial/data expand physical histograms
    # globally, feature-parallel expands its column window, voting
    # expands locally before casting votes (parallel/learner.py)
    if config.enable_bundle and len(ds.used_features) > 1:
        if n_proc > 1 and jax_process_index() != 0:
            bundles = None     # rank 0 decides, everyone else receives
        else:
            bs = sample[:min(len(sample), 20000)]
            nonzero = np.zeros((bs.shape[0], len(ds.used_features)),
                               dtype=bool)
            for b0 in range(0, len(ds.used_features), _COL_BLOCK):
                chunk = ds.used_features[b0:b0 + _COL_BLOCK]
                cols_t = _columns_T(bs, chunk)
                for k, _ in enumerate(chunk):
                    nonzero[:, b0 + k] = (cols_t[k] != 0) | np.isnan(cols_t[k])
            bundles_local = find_bundles(
                nonzero,
                [ds.bin_mappers[j].num_bin for j in ds.used_features],
                config.max_conflict_rate)
            bundles = [[ds.used_features[k] for k in b]
                       for b in bundles_local]
        if n_proc > 1:
            # the bundle plan must be identical everywhere; rank 0's
            # local sample decides (the mapper set is already global)
            from ..parallel.sync import broadcast_object
            bundles = broadcast_object(bundles)
        layout = BundleLayout(bundles, ds.bin_mappers, ds.used_features)
        if layout.has_bundles:
            ds.layout = layout
            ds.used_features = layout.sub_features
            log.info("EFB bundled %d features into %d columns",
                     len(layout.sub_features), layout.num_columns)


def _bin_rows(ds: TrainingData, data: np.ndarray, out: np.ndarray) -> None:
    """Bin a block of raw rows into ``out`` (same row count) using the
    fitted mappers/layout — shared by the in-memory path and each chunk of
    the streamed two-round path."""
    n = data.shape[0]
    dtype = out.dtype
    col_buf = np.empty(n, dtype=dtype)
    if ds.layout is not None and ds.layout.has_bundles:
        lay = ds.layout
        # block by SOURCE-feature count, not bundle count: one bundle can
        # hold many features on sparse data, and the whole point of the
        # blocking is a bounded transpose working set
        blocks, cur, cur_src = [], [], set()
        for col, bundle in enumerate(lay.bundles):
            if cur and len(cur_src) + len(bundle) > _COL_BLOCK:
                blocks.append(cur)
                cur, cur_src = [], set()
            cur.append((col, bundle))
            cur_src.update(bundle)
        if cur:
            blocks.append(cur)
        for block in blocks:
            src = sorted({j for _, b in block for j in b})
            cols_t = _columns_T(data, src)
            lookup = {j: cols_t[k] for k, j in enumerate(src)}
            for col, bundle in block:
                if len(bundle) == 1:
                    ds.bin_mappers[bundle[0]].bin_into(
                        lookup[bundle[0]], col_buf)
                    out[:, col] = col_buf
                else:
                    offsets = [lay.sub_offset[k]
                               for k in range(len(lay.sub_col))
                               if lay.sub_col[k] == col]
                    out[:, col] = build_bundled_column(
                        lookup, bundle, ds.bin_mappers, offsets, dtype,
                        col_buf)
    else:
        for b0 in range(0, len(ds.used_features), _COL_BLOCK):
            chunk = ds.used_features[b0:b0 + _COL_BLOCK]
            cols_t = _columns_T(data, chunk)
            for k, _j in enumerate(chunk):
                ds.bin_mappers[_j].bin_into(cols_t[k], col_buf)
                out[:, b0 + k] = col_buf


def _set_metadata(ds: TrainingData, num_data: int, label, weight, group,
                  init_score) -> None:
    ds.metadata = Metadata(num_data)
    if label is not None:
        ds.metadata.set_label(label)
    else:
        ds.metadata.set_label(np.zeros(num_data, dtype=np.float32))
    ds.metadata.set_weight(weight)
    ds.metadata.set_query(group)
    ds.metadata.set_init_score(init_score)


def construct_streamed(path: str,
                       config: Config,
                       label: Optional[np.ndarray] = None,
                       weight: Optional[np.ndarray] = None,
                       group: Optional[np.ndarray] = None,
                       init_score: Optional[np.ndarray] = None,
                       feature_names: Optional[Sequence[str]] = None,
                       categorical_features: Optional[Sequence[int]] = None,
                       label_idx: int = 0,
                       chunk_rows: int = 200_000) -> TrainingData:
    """Two-round streamed construction from a text file
    (``use_two_round_loading``; dataset_loader.cpp:181-207, 265+).

    Round 1 streams the file once to pull the sampled rows (indices chosen
    exactly like the in-memory path, so mappers are bit-identical) and all
    labels; round 2 streams again, binning each chunk straight into the
    preallocated uint8/16 matrix.  Peak memory is the binned matrix plus one
    raw chunk — the full float64 feature matrix never exists."""
    from .parser import count_data_rows, iter_parsed_chunks

    num_data, num_features = count_data_rows(path, config.has_header,
                                             label_idx)
    ds = TrainingData()
    ds.num_data = num_data
    ds.num_total_features = num_features
    ds.feature_names = (list(feature_names) if feature_names
                        else [f"Column_{i}" for i in range(num_features)])
    cat_set = set(int(c) for c in (categorical_features or []))

    sample_cnt = min(config.bin_construct_sample_cnt, num_data)
    rng = make_rng(config.data_random_seed)
    sample_idx = (sample_k(rng, num_data, sample_cnt)
                  if sample_cnt < num_data
                  else np.arange(num_data))

    # ---- round 1: sampled rows + labels ------------------------------------
    sample = np.empty((len(sample_idx), num_features), dtype=np.float64)
    labels = np.empty(num_data, dtype=np.float32)
    row0 = 0
    for feats, labs in iter_parsed_chunks(path, config.has_header, label_idx,
                                          chunk_rows, ncol=num_features):
        row1 = row0 + len(labs)
        labels[row0:row1] = labs
        lo = np.searchsorted(sample_idx, row0)
        hi = np.searchsorted(sample_idx, row1)
        if hi > lo:
            sample[lo:hi] = feats[sample_idx[lo:hi] - row0]
        row0 = row1
    if row0 != num_data:
        log.fatal("Streamed loading row mismatch: counted %d, parsed %d",
                  num_data, row0)
    _fit_from_sample(ds, sample, config, cat_set)
    del sample

    # ---- round 2: bin chunks straight into the final matrix ----------------
    dtype = np.uint8 if ds.max_num_bin() <= 256 else np.uint16
    ncols = (ds.layout.num_columns
             if ds.layout is not None and ds.layout.has_bundles
             else len(ds.used_features))
    binned = np.empty((num_data, ncols), dtype=dtype)
    row0 = 0
    for feats, _ in iter_parsed_chunks(path, config.has_header, label_idx,
                                       chunk_rows, ncol=num_features):
        _bin_rows(ds, feats, binned[row0:row0 + len(feats)])
        row0 += len(feats)
    ds.binned = binned

    _set_metadata(ds, num_data, labels if label is None else label,
                  weight, group, init_score)
    return ds


def construct_csr(csr,
                  config: Config,
                  label: Optional[np.ndarray] = None,
                  weight: Optional[np.ndarray] = None,
                  group: Optional[np.ndarray] = None,
                  init_score: Optional[np.ndarray] = None,
                  feature_names: Optional[Sequence[str]] = None,
                  categorical_features: Optional[Sequence[int]] = None,
                  reference: Optional[TrainingData] = None) -> TrainingData:
    """Two-round construction from a host :class:`~.sparse.CsrMatrix`
    without densifying it (the C-ABI sparse ingest).

    Round 1 densifies ONLY the sampled rows — CSR rows are O(nnz) random
    access, so unlike the text-file path no full pass is needed; round 2
    streams budget-bounded dense chunks through :func:`_bin_rows`
    straight into the final uint8/16 matrix.  Peak extra memory is the
    sample matrix plus one chunk; the full ``[nrow, ncol]`` float64
    matrix never exists.  Sample indices and ordering match the
    in-memory path exactly, so the fitted mappers — and therefore the
    trained model — are bit-identical to densify-then-construct."""
    num_data, num_features = csr.shape
    ds = TrainingData()
    ds.num_data = num_data
    ds.num_total_features = num_features
    ds.feature_names = (list(feature_names) if feature_names
                        else [f"Column_{i}" for i in range(num_features)])
    cat_set = set(int(c) for c in (categorical_features or []))

    if reference is not None:
        ds.reference = reference
        ds.bin_mappers = reference.bin_mappers
        ds.used_features = reference.used_features
        ds.feature_names = reference.feature_names
        ds.layout = reference.layout
        if num_features != reference.num_total_features:
            log.fatal("Validation data has %d features, training data has %d",
                      num_features, reference.num_total_features)
    else:
        sample_cnt = min(config.bin_construct_sample_cnt, num_data)
        if sample_cnt < num_data:
            rng = make_rng(config.data_random_seed)
            sample_idx = sample_k(rng, num_data, sample_cnt)
        else:
            sample_idx = np.arange(num_data)
        sample = csr.rows(sample_idx)
        _fit_from_sample(ds, sample, config, cat_set)
        del sample

    dtype = np.uint8 if ds.max_num_bin() <= 256 else np.uint16
    ncols = (ds.layout.num_columns
             if ds.layout is not None and ds.layout.has_bundles
             else len(ds.used_features))
    binned = np.empty((num_data, ncols), dtype=dtype)
    for r0, block in csr.iter_dense_chunks():
        _bin_rows(ds, block, binned[r0:r0 + len(block)])
    ds.binned = binned

    _set_metadata(ds, num_data, label, weight, group, init_score)
    return ds


def _filter_cnt(config: Config, sample_cnt: int, num_data: int) -> int:
    """min_split_data for the trivial-feature pre-filter, scaled to the
    sample size (dataset_loader.cpp:495-496 semantics)."""
    return int(config.min_data_in_leaf * sample_cnt / max(num_data, 1))
