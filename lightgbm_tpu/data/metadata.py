"""Per-dataset metadata: labels, weights, query boundaries, init scores.

Equivalent of the reference ``Metadata`` (``include/LightGBM/dataset.h:36-248``,
``src/io/metadata.cpp``): owns label/weight/group/init-score vectors and loads
the ``.weight`` / ``.query`` / ``.init`` side files that accompany a data file.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..utils import log


class Metadata:
    def __init__(self, num_data: int = 0):
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None        # [N] f32
        self.weight: Optional[np.ndarray] = None       # [N] f32 or None
        self.query_boundaries: Optional[np.ndarray] = None  # [num_queries+1] i32
        self.init_score: Optional[np.ndarray] = None   # [N * num_class] f64 or None

    # -- setters (mirror Metadata::SetLabel/SetWeights/SetQuery/SetInitScore) --

    def set_label(self, label: np.ndarray) -> None:
        label = np.asarray(label, dtype=np.float32).ravel()
        if self.num_data and len(label) != self.num_data:
            log.fatal("Length of label (%d) != num_data (%d)", len(label), self.num_data)
        self.num_data = len(label)
        self.label = label

    def set_weight(self, weight: Optional[np.ndarray]) -> None:
        if weight is None:
            self.weight = None
            return
        weight = np.asarray(weight, dtype=np.float32).ravel()
        if self.num_data and len(weight) != self.num_data:
            log.fatal("Length of weight (%d) != num_data (%d)", len(weight), self.num_data)
        self.weight = weight

    def set_query(self, group: Optional[np.ndarray]) -> None:
        """``group`` is per-query sizes (Python API convention); stored as boundaries."""
        if group is None:
            self.query_boundaries = None
            return
        group = np.asarray(group, dtype=np.int64).ravel()
        bounds = np.concatenate([[0], np.cumsum(group)]).astype(np.int32)
        if self.num_data and bounds[-1] != self.num_data:
            log.fatal("Sum of query counts (%d) != num_data (%d)", int(bounds[-1]), self.num_data)
        self.query_boundaries = bounds

    def set_init_score(self, init_score: Optional[np.ndarray]) -> None:
        if init_score is None:
            self.init_score = None
            return
        self.init_score = np.asarray(init_score, dtype=np.float64).ravel()

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1

    def query_ids(self) -> Optional[np.ndarray]:
        """Per-row query index [N] (derived; used by ranking objectives/metrics)."""
        if self.query_boundaries is None:
            return None
        sizes = np.diff(self.query_boundaries)
        return np.repeat(np.arange(len(sizes), dtype=np.int32), sizes)

    # -- side files (metadata.cpp LoadWeights/LoadQueryBoundaries/LoadInitialScore) --

    def load_side_files(self, data_path: str) -> None:
        wpath = data_path + ".weight"
        if os.path.exists(wpath):
            self.set_weight(np.loadtxt(wpath, dtype=np.float64).ravel())
            log.info("Loading weights from %s", wpath)
        qpath = data_path + ".query"
        if os.path.exists(qpath):
            self.set_query(np.loadtxt(qpath, dtype=np.int64).ravel())
            log.info("Loading query boundaries from %s", qpath)
        ipath = data_path + ".init"
        if os.path.exists(ipath):
            self.set_init_score(np.loadtxt(ipath, dtype=np.float64).ravel())
            log.info("Loading initial scores from %s", ipath)
