"""Exclusive Feature Bundling (EFB).

Re-designs the reference's greedy conflict-bounded bundling
(``FindGroups`` / ``FastFeatureBundling``, ``src/io/dataset.cpp:66-210``) for
the dense TPU layout: mutually-exclusive sparse features merge into ONE
physical uint8/16 column, so histogram width shrinks with the number of
*bundles*, not raw features — the same reduction the reference gets from
multi-feature ``FeatureGroup`` bins.

Layout per bundle column:
* slot 0 — every bundled feature at its default bin ("all zero");
* feature f with ``num_bin`` bins and default bin ``db`` owns the contiguous
  slot range ``[offset_f, offset_f + num_bin - 2]``: its non-default bins in
  ascending order with ``db`` skipped (``slot = offset + b - (b > db)``).

Rows where two bundled features are simultaneously non-default are conflicts;
the greedy packer bounds them by ``max_conflict_rate`` exactly like the
reference (later features overwrite earlier ones on conflicting rows).

Split finding never sees bundle columns directly: the grower's ``find``
expands a bundle histogram into per-subfeature histograms, reconstructing
each feature's default-bin entry as ``parent - sum(own slots)`` — the
reference's ``FixHistogram`` (``dataset.cpp:749-768``) in tensor form.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..utils import log


def find_bundles(nonzero: np.ndarray,            # [S, F] bool sample matrix
                 num_bins: Sequence[int],        # per feature
                 max_conflict_rate: float,
                 max_bundle_bins: int = 256,
                 max_sparse_rate: float = 0.8) -> List[List[int]]:
    """Greedy first-fit bundling (FindGroups, dataset.cpp:66-136 semantics).

    Returns a list of bundles (lists of feature indices into the input
    ordering); singleton lists are unbundled features.  Features denser than
    ``max_sparse_rate`` never bundle.
    """
    s, f = nonzero.shape
    nz_cnt = nonzero.sum(axis=0)
    budget = max_conflict_rate * s
    order = np.argsort(-nz_cnt, kind="mergesort")  # densest first (stable)

    bundles: List[List[int]] = []
    bundle_rows: List[np.ndarray] = []    # union of nonzero rows per bundle
    bundle_conflicts: List[float] = []
    bundle_bins: List[int] = []

    for j in order:
        nb = int(num_bins[j])
        sparse_ok = s == 0 or nz_cnt[j] <= max_sparse_rate * s
        placed = False
        if sparse_ok:
            for gi in range(len(bundles)):
                extra_bins = nb - 1
                if bundle_bins[gi] + extra_bins > max_bundle_bins:
                    continue
                conflicts = int(np.count_nonzero(bundle_rows[gi] & nonzero[:, j]))
                if bundle_conflicts[gi] + conflicts <= budget:
                    bundles[gi].append(int(j))
                    bundle_rows[gi] |= nonzero[:, j]
                    bundle_conflicts[gi] += conflicts
                    bundle_bins[gi] += extra_bins
                    placed = True
                    break
        if not placed:
            if sparse_ok and nb <= max_bundle_bins:
                bundles.append([int(j)])
                bundle_rows.append(nonzero[:, j].copy())
                bundle_conflicts.append(0.0)
                bundle_bins.append(1 + (nb - 1))
            else:
                # dense / oversized feature: its own column, never joined
                bundles.append([int(j)])
                bundle_rows.append(np.ones(s, dtype=bool))
                bundle_conflicts.append(float("inf"))
                bundle_bins.append(max_bundle_bins + 1)
    # restore deterministic order: bundles sorted by their first feature
    for b in bundles:
        b.sort()
    bundles.sort(key=lambda b: b[0])
    return bundles


class BundleLayout:
    """Per-logical-feature decode tables for a bundled dataset.

    Logical (sub)features are the original used features, in bundle order;
    physical columns are the binned matrix's columns (one per bundle).
    """

    def __init__(self, bundles: List[List[int]], mappers, used: List[int]):
        # bundles contain ORIGINAL feature ids; `used` lists them in logical
        # (expansion) order
        self.bundles = bundles
        self.sub_features: List[int] = []  # original id per logical feature
        self.sub_col: List[int] = []       # physical column
        self.sub_offset: List[int] = []    # first slot (-1: unbundled)
        self.col_num_bin: List[int] = []   # physical bins per column
        for col, bundle in enumerate(bundles):
            if len(bundle) == 1:
                j = bundle[0]
                self.sub_features.append(j)
                self.sub_col.append(col)
                self.sub_offset.append(-1)
                self.col_num_bin.append(mappers[j].num_bin)
            else:
                offset = 1
                for j in bundle:
                    self.sub_features.append(j)
                    self.sub_col.append(col)
                    self.sub_offset.append(offset)
                    offset += mappers[j].num_bin - 1
                self.col_num_bin.append(offset)

    @property
    def num_columns(self) -> int:
        return len(self.bundles)

    @property
    def has_bundles(self) -> bool:
        return any(len(b) > 1 for b in self.bundles)

    def max_col_bins(self) -> int:
        return max(self.col_num_bin) if self.col_num_bin else 1


def build_bundled_column(data, bundle: List[int], mappers,
                         offsets: List[int], dtype,
                         bin_buf: Optional[np.ndarray] = None) -> np.ndarray:
    """Bin + merge one bundle's features into a single column.

    ``data`` is either the raw ``[N, F]`` matrix or a mapping of feature
    index -> contiguous float64 column (the construction path pre-transposes
    column blocks for cache efficiency).  ``offsets[i]`` is the first slot of
    ``bundle[i]``; conflicting rows take the LAST feature's value (the
    reference also resolves conflicts by overwrite, PushData order)."""
    def column(j):
        if isinstance(data, dict):
            return data[j]
        return np.asarray(data[:, j], dtype=np.float64)

    n = len(column(bundle[0]))
    col = np.zeros(n, dtype=dtype)
    if bin_buf is None:
        bin_buf = np.empty(n, dtype=dtype)
    for j, off in zip(bundle, offsets):
        m = mappers[j]
        m.bin_into(column(j), bin_buf)
        b = bin_buf.astype(np.int32)
        db = m.default_bin
        nondef = b != db
        slot = off + b - (b > db)
        col[nondef] = slot[nondef].astype(dtype)
    return col
