"""Streamed out-of-core training data: host-side row blocks + the
double-buffered host->device transfer pipeline (``data_stream=chunked``).

The classic path uploads the whole binned matrix to HBM before iteration
0, so N_rows is bounded by device memory.  The block-distributed GBT
formulation (PAPERS.md) shows the natural out-of-core decomposition:
histogram accumulation is a sum over row blocks, so the quantized bins
can stay HOST-side and flow through the device one static-shape block at
a time — the reference's own OrderedBin / two-round loader exists for
exactly this "data never fits where the math runs" regime.

Two pieces, both placement-only (zero math):

* :class:`HostBlockStore` — the binned ``[N, F]`` matrix cut into
  ``chunk_rows``-row blocks, every block padded to ONE static shape
  (pad rows are bin 0 with a per-block ``valid`` count masking their
  weights), so the chunk loop adds zero recompiles.
* :class:`BlockStreamer` — the double-buffered async ``device_put``
  pipeline: block k+1's transfer is issued BEFORE block k is consumed,
  so the copy overlaps the grow step's histogram work.  Every wait on an
  incoming block is measured (``stream_wait_ms`` counter,
  ``chunks_in_flight`` gauge) and a blocking wait past the stall
  threshold lands as one structured ``stream_stall`` event — the stall
  fraction those feed is the bench rung's overlap evidence
  (docs/OBSERVABILITY.md).
"""
from __future__ import annotations

import time
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..obs.counters import counters
from ..utils import log

# a wait longer than this on an incoming block counts as a pipeline
# stall (the transfer did not hide behind the previous block's compute);
# sub-millisecond waits are dispatch noise, not serialization
STALL_THRESHOLD_MS = 1.0


class HostBlockStore:
    """The binned matrix as host-side static-shape row blocks.

    Full blocks are VIEWS of the source matrix (no host copy); only the
    short final block is materialized padded.  Pad rows are bin 0 —
    harmless because the streamed grower zeroes their (g, h, c) weights
    through the ``valid`` count before any histogram sees them."""

    def __init__(self, binned: np.ndarray, chunk_rows: int):
        binned = np.ascontiguousarray(binned)
        if binned.ndim != 2:
            raise ValueError("HostBlockStore needs a [N, F] binned matrix")
        n, f = binned.shape
        chunk = max(1, min(int(chunk_rows), n))
        self.num_rows = n
        self.num_cols = f
        self.dtype = binned.dtype
        self.chunk_rows = chunk
        self.num_blocks = -(-n // chunk)
        self.padded_rows = self.num_blocks * chunk
        self._binned = binned
        self._tail: Optional[np.ndarray] = None
        tail_valid = n - (self.num_blocks - 1) * chunk
        if tail_valid < chunk:
            tail = np.zeros((chunk, f), dtype=binned.dtype)
            tail[:tail_valid] = binned[(self.num_blocks - 1) * chunk:]
            self._tail = tail
        self.valid: List[int] = [chunk] * (self.num_blocks - 1) + [tail_valid]

    def block(self, k: int) -> np.ndarray:
        """Block ``k`` as a ``[chunk_rows, F]`` host array (padded)."""
        if self._tail is not None and k == self.num_blocks - 1:
            return self._tail
        start = k * self.chunk_rows
        return self._binned[start:start + self.chunk_rows]

    def nbytes(self) -> int:
        """Host bytes the store holds beyond the source matrix (the
        padded tail copy only)."""
        return int(self._tail.nbytes) if self._tail is not None else 0


class BlockStreamer:
    """Double-buffered async host->device pipeline over a
    :class:`HostBlockStore`.

    One :meth:`blocks` pass yields ``(k, device_block, valid)`` per
    block; before block k is handed out, block k+1's ``device_put`` has
    already been issued, so under an async-dispatch backend (TPU) the
    DMA runs while the caller computes on block k.  The wait for the
    incoming block is measured per block and accumulated — callers read
    :meth:`take_wait_ms` per tree/iteration to derive the stall
    fraction."""

    def __init__(self, store: HostBlockStore, device=None,
                 stall_threshold_ms: float = STALL_THRESHOLD_MS):
        import jax
        self.store = store
        self.device = device if device is not None else jax.devices()[0]
        self.stall_threshold_ms = float(stall_threshold_ms)
        self.wait_ms = 0.0          # cumulative across all passes
        self.stall_events = 0
        self.passes = 0
        self._wait_since_take = 0.0

    def _put(self, k: int):
        import jax
        return jax.device_put(self.store.block(k), self.device)

    def blocks(self) -> Iterator[Tuple[int, object, int]]:
        """One full pass over the store, double buffered."""
        nb = self.store.num_blocks
        if nb == 0:
            return
        inflight = self._put(0)
        for k in range(nb):
            nxt = self._put(k + 1) if k + 1 < nb else None
            counters.gauge("chunks_in_flight", 1 + (nxt is not None))
            t0 = time.perf_counter()
            was_ready = self._is_ready(inflight)
            try:
                inflight.block_until_ready()
            except AttributeError:      # non-jax array (test doubles)
                pass
            wait_ms = (time.perf_counter() - t0) * 1e3
            self.wait_ms += wait_ms
            self._wait_since_take += wait_ms
            counters.inc("stream_wait_ms", wait_ms)
            if was_ready is False and wait_ms > self.stall_threshold_ms:
                # the grow step is BLOCKED on this transfer: the copy of
                # block k did not hide behind block k-1's compute
                self.stall_events += 1
                counters.inc("stream_stalls")
                counters.event("stream_stall", block=k,
                               wait_ms=round(wait_ms, 3),
                               pass_index=self.passes,
                               chunk_rows=self.store.chunk_rows)
            yield k, inflight, self.store.valid[k]
            inflight = nxt
        counters.gauge("chunks_in_flight", 0)
        self.passes += 1

    @staticmethod
    def _is_ready(arr) -> Optional[bool]:
        """Whether the transfer already completed (None when the backend
        does not expose readiness — then only the measured wait
        decides)."""
        probe = getattr(arr, "is_ready", None)
        if probe is None:
            return None
        try:
            return bool(probe())
        except Exception:
            return None

    def take_wait_ms(self) -> float:
        """Wait accumulated since the last take (per-tree stall
        numerator; the caller supplies the wall-clock denominator)."""
        w, self._wait_since_take = self._wait_since_take, 0.0
        return w


def make_block_store(binned: np.ndarray, chunk_rows: int,
                     context: str = "") -> HostBlockStore:
    """Build the host block store and log the pipeline shape once."""
    store = HostBlockStore(binned, chunk_rows)
    log.info("Streamed data pipeline%s: %d rows x %d cols in %d block(s) "
             "of %d rows (%.1f MB/block, double-buffered)",
             f" ({context})" if context else "", store.num_rows,
             store.num_cols, store.num_blocks, store.chunk_rows,
             store.chunk_rows * store.num_cols
             * store._binned.dtype.itemsize / 1e6)
    return store
