"""Small-bin column packing — the TPU answer to dense 4-bit bins.

The reference stores features with <= 16 bins nibble-packed
(``src/io/dense_nbits_bin.hpp:12-405``) and its GPU learner packs 8
features per int32 (``gpu_tree_learner.cpp:234-556``) because histogram
building is bandwidth-bound.  Here the same observation holds — the
per-leaf row gather of the binned matrix is the HBM roofline
(docs/PERF.md) — but the packing is designed around the MXU histogram
kernel instead of translated:

two physical columns a (lo) and b (hi), both with <= 16 bins, share one
byte ``v = a | (b << 4)``.  The byte value IS the joint (a, b) bin index
over a 16 x 16 grid, so the EXISTING 256-wide one-hot histogram kernels
(pallas / einsum / segment) run on packed columns UNCHANGED; the two
16-bin feature histograms fall out of the joint [256]-bin histogram by
summing over each nibble axis (``unfold_packed_hist``).  Per packed
pair this HALVES the gather bytes AND the histogram compute relative to
two unpacked uint8 columns at a 256-wide one-hot.

The packed matrix is a SECOND device copy used only by the histogram
path; routing/partition and leaf traversal keep the unpacked matrix
(they read single columns — decode would buy nothing).  Packed-pair
datasets are narrow by construction (<= 16-bin columns), so the extra
copy is small exactly when it exists.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

PACK_MAX_BIN = 16          # nibble capacity
PACK_JOINT_BINS = 256      # joint (lo, hi) index space
FUSED_COL_GROUP = 8        # fused-kernel feature-group width (8 * 16 lanes)


def pack_gather_words(mat):
    """[N, C] uint8/uint16 -> ([N, W] uint32, lanes_per_word).

    On TPU a random row gather costs per ELEMENT, not per byte (measured
    ~12.6 ns/elem on v5e through XLA's gather); packing 4 uint8 (or 2
    uint16) bin columns into each uint32 word cuts the gathered element
    count 4x (2x), and the unpack after the gather is a handful of
    shift/mask vector ops that XLA fuses into the consumer.  The same
    word layout is what the fused histogram kernel's in-kernel row
    DMA reads (ops/pallas_hist.hist6_fused)."""
    import jax.numpy as jnp
    n, c = mat.shape
    assert mat.dtype.itemsize <= 2, mat.dtype   # u32 words hold 4 u8 or 2 u16
    per = 4 if mat.dtype.itemsize == 1 else 2
    w = -(-c // per)
    m = jnp.pad(mat, ((0, 0), (0, w * per - c))).astype(jnp.uint32)
    m = m.reshape(n, w, per)
    packed = m[:, :, 0]
    for k in range(1, per):
        packed = packed | (m[:, :, k] << (k * (32 // per)))
    return packed, per


def unpack_gather_words(words, c: int, per: int):
    """[M, W] uint32 -> [M, C] int32 (inverse of :func:`pack_gather_words`)."""
    import jax.numpy as jnp
    shift = 32 // per
    mask = jnp.uint32((1 << shift) - 1)
    parts = [(words >> (k * shift)) & mask for k in range(per)]
    stacked = jnp.stack(parts, axis=-1).reshape(words.shape[0], -1)
    return stacked[:, :c].astype(jnp.int32)


FUSED_PANEL_LANES = 128    # panel minor dim is padded to this multiple:
#                            Mosaic DMA row slices must span whole 128-lane
#                            tiles, so each in-kernel row gather is one
#                            aligned [1, 128k]-u32 burst (512 B — the HBM
#                            transaction class a random row read touches
#                            regardless of how few bytes it keeps)


def pack_fused_panel(bins_pad, gw_pad, hw_pad, cw_pad):
    """The u32 row layout the fused histogram kernel DMAs per row:
    [N(+1), C] uint8/uint16 bins + three f32 weight columns ->
    ([N(+1), ceil((W + 3) / 128) * 128] uint32, lanes_per_word).

    Columns are zero-padded up to a FUSED_COL_GROUP multiple BEFORE word
    packing so the kernel's phantom features (its feature loop runs in
    groups of 8) always read real, provably-zero words; the f32 weights
    ride as bitcast u32 columns after the words (pure bitcasts — values
    are bit-identical through the panel); the whole row is then padded to
    a FUSED_PANEL_LANES multiple (the Mosaic DMA alignment above — HBM
    footprint 512 B/row at narrow shapes, the price of an aligned
    single-burst gather).  Callers pass SENTINEL-padded inputs: the last
    row must carry zero bins and zero weights, because the kernel
    redirects every past-the-count position to it."""
    import jax.numpy as jnp
    from jax import lax
    c = bins_pad.shape[1]
    c_pad = -(-c // FUSED_COL_GROUP) * FUSED_COL_GROUP
    if c_pad > c:
        bins_pad = jnp.pad(bins_pad, ((0, 0), (0, c_pad - c)))
    words, per = pack_gather_words(bins_pad)
    panel = jnp.concatenate(
        [words] + [lax.bitcast_convert_type(w.astype(jnp.float32),
                                            jnp.uint32)[:, None]
                   for w in (gw_pad, hw_pad, cw_pad)], axis=1)
    wp = panel.shape[1]
    wp_pad = -(-wp // FUSED_PANEL_LANES) * FUSED_PANEL_LANES
    if wp_pad > wp:
        panel = jnp.pad(panel, ((0, 0), (0, wp_pad - wp)))
    return panel, per


class PackPlan(NamedTuple):
    """Static (host) description of the packed layout.

    Maps each PHYSICAL column f of the logical binned matrix to its
    storage: ``byte_col[f]`` is its column in the packed matrix,
    ``shift[f]`` is 0 (lo nibble / unpacked) or 4 (hi nibble), and
    ``is_packed[f]`` says whether f shares its byte with a partner.
    """
    byte_col: np.ndarray       # [Fp] i32
    shift: np.ndarray          # [Fp] i32, 0 or 4
    is_packed: np.ndarray      # [Fp] bool
    num_storage_cols: int
    num_phys_cols: int

    @property
    def num_packed(self) -> int:
        return int(self.is_packed.sum())


def build_pack_plan(col_num_bins) -> Optional[PackPlan]:
    """Pairing plan over physical columns: columns with <= 16 bins are
    packed two-per-byte (an odd leftover keeps a byte to itself in the
    lo nibble); wider columns pass through.

    Returns None when packing would not pay: fewer than 2 packable
    columns, or the joint-form histogram is WIDER than the unpacked one
    — ``storage_cols * 256 > phys_cols * B`` (B = the histogram width
    the unpacked layout needs, i.e. the max column bins).  The single
    inequality covers both degenerate regimes: a couple of narrow
    columns among thousands of wide ones (the full-matrix second copy
    would buy ~nothing), and an all-narrow dataset whose unpacked
    histograms are tiny (B <= 16: a 256-bin joint psum/einsum would
    move up to 8x MORE than the 2 x 16 bins it replaces)."""
    nb = np.asarray(col_num_bins, dtype=np.int64)
    fp = len(nb)
    narrow = np.flatnonzero(nb <= PACK_MAX_BIN)
    if len(narrow) < 2:
        return None
    n_storage = (fp - len(narrow)) + (len(narrow) + 1) // 2
    if n_storage * PACK_JOINT_BINS > fp * int(nb.max()):
        return None
    wide = np.flatnonzero(nb > PACK_MAX_BIN)
    byte_col = np.zeros(fp, dtype=np.int32)
    shift = np.zeros(fp, dtype=np.int32)
    is_packed = np.zeros(fp, dtype=bool)
    c = 0
    for f in wide:
        byte_col[f] = c
        c += 1
    for i in range(0, len(narrow) - 1, 2):
        a, b = narrow[i], narrow[i + 1]
        byte_col[a] = byte_col[b] = c
        shift[b] = 4
        is_packed[a] = is_packed[b] = True
        c += 1
    if len(narrow) % 2:
        f = narrow[-1]
        byte_col[f] = c
        c += 1
    return PackPlan(byte_col, shift, is_packed, c, fp)


def pack_columns(binned: np.ndarray, plan: PackPlan) -> np.ndarray:
    """[N, Fp] binned matrix -> [N, C] packed storage matrix (same
    dtype; nibble pairs merged, other columns copied)."""
    n = binned.shape[0]
    out = np.zeros((n, plan.num_storage_cols), dtype=binned.dtype)
    for f in range(plan.num_phys_cols):
        shifted = (binned[:, f].astype(np.int32)
                   << int(plan.shift[f])).astype(binned.dtype)
        np.bitwise_or(out[:, plan.byte_col[f]], shifted,
                      out=out[:, plan.byte_col[f]])
    return out


def unfold_packed_hist(hist_c, plan: PackPlan, out_bins: int):
    """Joint storage-column histograms -> physical-column histograms.

    hist_c [C, B_joint >= 256, S] -> [Fp, out_bins, S]: a packed
    column's joint histogram reshaped to [16, 16] grids sums over the
    partner's axis to give each nibble feature's 16-bin histogram (the
    FixHistogram-style reconstruction, but exact — no parent needed);
    unpacked columns pass through."""
    import jax.numpy as jnp
    c, bj, s = hist_c.shape
    h4 = hist_c[:, :PACK_JOINT_BINS].reshape(c, PACK_MAX_BIN, PACK_MAX_BIN, s)
    lo_h = h4.sum(axis=1)                      # [C, 16, S] lo-nibble feature
    hi_h = h4.sum(axis=2)                      # [C, 16, S] hi-nibble feature
    byte_col = jnp.asarray(plan.byte_col)
    nib = jnp.where((jnp.asarray(plan.shift) == 0)[:, None, None],
                    lo_h[byte_col], hi_h[byte_col])        # [Fp, 16, S]
    if out_bins > PACK_MAX_BIN:
        nib = jnp.pad(nib, ((0, 0), (0, out_bins - PACK_MAX_BIN), (0, 0)))
    else:
        nib = nib[:, :out_bins]
    wide = hist_c[byte_col, :out_bins]
    if out_bins > bj:
        wide = jnp.pad(wide, ((0, 0), (0, out_bins - bj), (0, 0)))
    return jnp.where(jnp.asarray(plan.is_packed)[:, None, None], nib, wide)
