"""Feature binning (quantization) on the host.

Re-implements the reference BinMapper semantics (``src/io/bin.cpp:72-344``,
``include/LightGBM/bin.h:60-208,451-483``) in vectorized numpy:

* ``greedy_find_bin``     — equal-count greedy bin boundaries (bin.cpp:72-141)
* ``find_bin_zero_as_missing`` — split around the zero range (bin.cpp:143-191)
* ``BinMapper.fit``       — missing-type resolution, categorical mapping,
                            trivial-feature detection (bin.cpp:193-344)
* ``BinMapper.value_to_bin`` — vectorized binary-search binning (bin.h:451-483)

Bins are dense: every feature maps to ``[0, num_bin)`` with the NaN bin (if
``missing_type == NAN``) at index ``num_bin - 1``.  There is no sparse/ordered
bin variant — the TPU data layout is a dense ``[num_rows, num_features]``
uint8/uint16 matrix (the reference's own GPU recipe: ``sparse_threshold=1``).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from ..utils import log

# |value| <= this is treated as "zero" for MissingType.ZERO (reference kZeroAsMissingValueRange)
ZERO_AS_MISSING_RANGE = 1e-35
K_EPSILON = 1e-15  # reference kEpsilon used in hessian guards

# MissingType encoding matches the reference decision_type bits ((dt >> 2) & 3)
MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

BIN_TYPE_NUMERICAL = 0
BIN_TYPE_CATEGORICAL = 1


def greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray,
                    max_bin: int, total_cnt: int, min_data_in_bin: int) -> List[float]:
    """Greedy equal-count bin boundary search (bin.cpp:72-141 semantics).

    The loop carries a sequential dependence (``mean_bin_size`` is
    re-derived every time a bin closes), so it cannot be vectorized
    without changing semantics.  The native library carries an identical
    C++ loop (``GBTN_GreedyFindBin``, ~300x faster on continuous
    features — the Python loop dominated wide-dataset construction);
    :func:`greedy_find_bin_py` is its oracle (``tests/test_native.py``).
    """
    if len(distinct_values) > 512:   # native payoff; tiny columns stay here
        from .. import native
        nb = native.greedy_find_bin(distinct_values, counts, max_bin,
                                    total_cnt, min_data_in_bin)
        if nb is not None:
            return nb
    return greedy_find_bin_py(distinct_values, counts, max_bin, total_cnt,
                              min_data_in_bin)


def greedy_find_bin_py(distinct_values: np.ndarray, counts: np.ndarray,
                       max_bin: int, total_cnt: int,
                       min_data_in_bin: int) -> List[float]:
    """Pure-Python reference body of :func:`greedy_find_bin`."""
    num_distinct = len(distinct_values)
    bounds: List[float] = []
    if max_bin <= 0:
        return [np.inf]
    if num_distinct <= max_bin:
        cur = 0
        for i in range(num_distinct - 1):
            cur += int(counts[i])
            if cur >= min_data_in_bin:
                bounds.append((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                cur = 0
        bounds.append(np.inf)
        return bounds
    # more distinct values than bins: greedy mean-size packing with
    # "big count" values pinned to their own bin
    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin
    is_big = counts >= mean_bin_size
    rest_bin_cnt = max_bin - int(is_big.sum())
    rest_sample_cnt = total_cnt - int(counts[is_big].sum())
    mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)
    upper = np.full(max_bin, np.inf)
    lower = np.full(max_bin, np.inf)
    bin_cnt = 0
    lower[0] = distinct_values[0]
    cur = 0
    for i in range(num_distinct - 1):
        if not is_big[i]:
            rest_sample_cnt -= int(counts[i])
        cur += int(counts[i])
        if (is_big[i] or cur >= mean_bin_size or
                (is_big[i + 1] and cur >= max(1.0, mean_bin_size * 0.5))):
            upper[bin_cnt] = distinct_values[i]
            bin_cnt += 1
            lower[bin_cnt] = distinct_values[i + 1]
            if bin_cnt >= max_bin - 1:
                break
            cur = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)
    bin_cnt += 1
    bounds = [(upper[i] + lower[i + 1]) / 2.0 for i in range(bin_cnt - 1)]
    bounds.append(np.inf)
    return bounds


def find_bin_zero_as_missing(distinct_values: np.ndarray, counts: np.ndarray,
                             max_bin: int, total_sample_cnt: int,
                             min_data_in_bin: int) -> List[float]:
    """Bin boundaries with the zero range isolated (bin.cpp:143-191 semantics).

    Negative values and positive values are binned independently with the
    near-zero range ``(-eps, eps]`` reserved as its own bin boundary pair, so
    zero always lands in a dedicated bin.
    """
    zero_l, zero_r = -ZERO_AS_MISSING_RANGE, ZERO_AS_MISSING_RANGE
    left_mask = distinct_values <= zero_l
    right_mask = distinct_values > zero_r
    left_cnt_data = int(counts[left_mask].sum())
    right_cnt_data = int(counts[right_mask].sum())
    cnt_missing = total_sample_cnt - left_cnt_data - right_cnt_data

    bounds: List[float] = []
    left_cnt = int(left_mask.sum())
    if left_cnt > 0:
        denom = max(total_sample_cnt - cnt_missing, 1)
        left_max_bin = int(left_cnt_data / denom * (max_bin - 1))
        lb = greedy_find_bin(distinct_values[:left_cnt], counts[:left_cnt],
                             left_max_bin, left_cnt_data, min_data_in_bin)
        lb[-1] = zero_l
        bounds.extend(lb)

    if right_cnt_data > 0:
        right_start = int(np.argmax(right_mask))
        right_max_bin = max_bin - 1 - len(bounds)
        rb = greedy_find_bin(distinct_values[right_start:], counts[right_start:],
                             right_max_bin, right_cnt_data, min_data_in_bin)
        bounds.append(zero_r)
        bounds.extend(rb)
    else:
        bounds.append(np.inf)
    return bounds


@dataclasses.dataclass
class BinMapper:
    """Per-feature value→bin mapping (bin.h:60-208 analogue)."""

    num_bin: int = 1
    bin_type: int = BIN_TYPE_NUMERICAL
    missing_type: int = MISSING_NONE
    is_trivial: bool = True
    bin_upper_bound: Optional[np.ndarray] = None     # numerical
    categorical_2_bin: Optional[Dict[int, int]] = None
    bin_2_categorical: Optional[List[int]] = None
    min_val: float = 0.0
    max_val: float = 0.0
    default_bin: int = 0   # bin of value 0.0 — the "most frequent" bin for sparse data

    @staticmethod
    def fit(values: np.ndarray, total_sample_cnt: int, max_bin: int,
            min_data_in_bin: int, min_split_data: int,
            bin_type: int = BIN_TYPE_NUMERICAL,
            use_missing: bool = True, zero_as_missing: bool = False) -> "BinMapper":
        """Build a BinMapper from sampled values (bin.cpp:193-344 semantics).

        ``values`` are the sampled *non-zero-filtered* values; rows absent from
        the sample are implicitly zero (``total_sample_cnt - len(values)``),
        matching the reference's sparse sampling convention.
        """
        m = BinMapper()
        values = np.asarray(values, dtype=np.float64)
        nan_mask = np.isnan(values)
        na_cnt = int(nan_mask.sum())
        vals = values[~nan_mask]

        if not use_missing:
            m.missing_type = MISSING_NONE
            na_cnt = 0
        elif zero_as_missing:
            m.missing_type = MISSING_ZERO
        else:
            m.missing_type = MISSING_NAN if na_cnt > 0 else MISSING_NONE

        # rows absent from the sample and (unless NaN-tracked) NaN rows count as zero
        zero_cnt = total_sample_cnt - len(vals)
        if m.missing_type == MISSING_NAN:
            zero_cnt -= na_cnt
        zero_cnt = max(int(zero_cnt), 0)
        # distinct values with zero injected at its sorted position carrying zero_cnt
        vals = np.sort(vals)
        distinct, counts = (np.unique(vals, return_counts=True)
                            if len(vals) else (np.empty(0), np.empty(0, dtype=np.int64)))
        if zero_cnt > 0 or len(distinct) == 0:
            if len(distinct) == 0 or 0.0 not in distinct:
                pos = int(np.searchsorted(distinct, 0.0))
                distinct = np.insert(distinct, pos, 0.0)
                counts = np.insert(counts, pos, zero_cnt)
            else:
                counts = counts.copy()
                counts[np.searchsorted(distinct, 0.0)] += zero_cnt
        distinct = distinct.astype(np.float64)
        counts = counts.astype(np.int64)
        m.min_val = float(distinct[0]) if len(distinct) else 0.0
        m.max_val = float(distinct[-1]) if len(distinct) else 0.0

        num_distinct = len(distinct)
        if num_distinct + (1 if na_cnt > 0 else 0) <= 2:
            bin_type = BIN_TYPE_NUMERICAL
        m.bin_type = bin_type

        if bin_type == BIN_TYPE_NUMERICAL:
            if m.missing_type == MISSING_ZERO:
                bounds = find_bin_zero_as_missing(distinct, counts, max_bin,
                                                  total_sample_cnt, min_data_in_bin)
                if len(bounds) == 2:
                    m.missing_type = MISSING_NONE
            elif m.missing_type == MISSING_NONE:
                bounds = find_bin_zero_as_missing(distinct, counts, max_bin,
                                                  total_sample_cnt, min_data_in_bin)
            else:  # NAN: reserve last bin for NaN
                bounds = find_bin_zero_as_missing(distinct, counts, max_bin - 1,
                                                  total_sample_cnt - na_cnt, min_data_in_bin)
                bounds.append(np.nan)
            m.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
            m.num_bin = len(bounds)
            # count per bin for the trivial/filter checks
            cnt_in_bin = np.zeros(m.num_bin, dtype=np.int64)
            effective_bins = m.num_bin - (1 if m.missing_type == MISSING_NAN else 0)
            if num_distinct:
                # value goes to the first bin whose upper bound is >= value
                idx = np.searchsorted(m.bin_upper_bound[:effective_bins - 1],
                                      distinct, side="left")
                np.add.at(cnt_in_bin, idx, counts)
            if m.missing_type == MISSING_NAN:
                cnt_in_bin[m.num_bin - 1] = na_cnt
            m.default_bin = int(m.value_to_bin_scalar(0.0))
        else:
            # categorical: ints sorted by count desc, keep top until 99% coverage
            ints = distinct.astype(np.int64)
            agg: Dict[int, int] = {}
            for v, c in zip(ints, counts):
                agg[int(v)] = agg.get(int(v), 0) + int(c)
            if any(k < 0 for k in agg):
                log.fatal("Cannot use negative numbers in categorical features")
            items = sorted(agg.items(), key=lambda kv: -kv[1])
            # avoid first bin being category 0 (reference bin.cpp:305-308)
            if len(items) > 1 and items[0][0] == 0:
                items[0], items[1] = items[1], items[0]
            cut_cnt = int((total_sample_cnt - na_cnt) * 0.99)
            m.bin_2_categorical = []
            m.categorical_2_bin = {}
            used_cnt = 0
            nb = 0
            mb = min(len(items), max_bin)
            while (used_cnt < cut_cnt or nb < mb) and nb < len(items):
                cat, c = items[nb]
                m.bin_2_categorical.append(cat)
                m.categorical_2_bin[cat] = nb
                used_cnt += c
                nb += 1
            m.num_bin = nb
            if nb == len(items) and na_cnt == 0:
                m.missing_type = MISSING_NONE
            elif na_cnt == 0:
                m.missing_type = MISSING_ZERO
            else:
                m.missing_type = MISSING_NAN
            cnt_in_bin = np.asarray([c for _, c in items[:nb]], dtype=np.int64)
            if nb > 0:
                cnt_in_bin[-1] += total_sample_cnt - used_cnt
            m.default_bin = 0

        m.is_trivial = m.num_bin <= 1
        if not m.is_trivial and _need_filter(cnt_in_bin, total_sample_cnt,
                                             min_split_data, m.bin_type):
            m.is_trivial = True
        return m

    # -- binning -----------------------------------------------------------

    def value_to_bin_scalar(self, value: float) -> int:
        return int(self.value_to_bin(np.asarray([value]))[0])

    def value_to_bin(self, values: np.ndarray) -> np.ndarray:
        """Vectorized ValueToBin (bin.h:451-483 semantics)."""
        values = np.asarray(values, dtype=np.float64)
        if self.bin_type == BIN_TYPE_NUMERICAL:
            nan_mask = np.isnan(values)
            v = np.where(nan_mask, 0.0, values)
            n_search = self.num_bin - (1 if self.missing_type == MISSING_NAN else 0)
            # first bin whose upper bound >= value  (upper bounds strictly increasing)
            bins = np.searchsorted(self.bin_upper_bound[:n_search - 1], v, side="left")
            if self.missing_type == MISSING_NAN:
                bins = np.where(nan_mask, self.num_bin - 1, bins)
            return bins.astype(np.int32)
        out = np.full(values.shape, self.num_bin - 1, dtype=np.int32)
        int_vals = values.astype(np.int64, copy=False)
        nan_mask = np.isnan(values)
        for i, v in enumerate(int_vals.ravel()):
            if not nan_mask.ravel()[i] and int(v) in self.categorical_2_bin:
                out.ravel()[i] = self.categorical_2_bin[int(v)]
        return out

    def bin_into(self, values: np.ndarray, out: np.ndarray) -> None:
        """value_to_bin into a preallocated uint8/uint16 buffer, using the
        native OpenMP binner when available (bin.h:451-483 either way)."""
        from .. import native
        values = np.asarray(values, dtype=np.float64)
        if self.bin_type == BIN_TYPE_NUMERICAL:
            n_search = self.num_bin - (1 if self.missing_type == MISSING_NAN else 0)
            nan_bin = self.num_bin - 1 if self.missing_type == MISSING_NAN else -1
            if native.bin_column(values, self.bin_upper_bound, n_search,
                                 nan_bin, out):
                return
        elif self.categorical_2_bin is not None:
            if native.bin_column_categorical(values, self.categorical_2_bin,
                                             self.num_bin - 1, out):
                return
        out[:] = self.value_to_bin(values).astype(out.dtype)

    def bin_to_value(self, bin_idx: int) -> float:
        """Representative real threshold for a bin (used in the model file)."""
        if self.bin_type == BIN_TYPE_CATEGORICAL:
            return float(self.bin_2_categorical[bin_idx])
        return float(self.bin_upper_bound[bin_idx])

    def feature_info_str(self) -> str:
        """Model-file feature_infos token (gbdt.cpp SaveModelToString)."""
        if self.is_trivial:
            return "none"
        if self.bin_type == BIN_TYPE_CATEGORICAL:
            return ":".join(str(c) for c in sorted(self.bin_2_categorical))
        return f"[{self.min_val:g}:{self.max_val:g}]"


def _need_filter(cnt_in_bin: np.ndarray, total_cnt: int, filter_cnt: int,
                 bin_type: int) -> bool:
    """True if no split of this feature can satisfy min_split_data (bin.cpp:48-70)."""
    if bin_type == BIN_TYPE_NUMERICAL:
        left = np.cumsum(cnt_in_bin[:-1])
        ok = (left >= filter_cnt) & (total_cnt - left >= filter_cnt)
        return not bool(ok.any())
    if len(cnt_in_bin) <= 2:
        for c in cnt_in_bin[:-1]:
            if c >= filter_cnt and total_cnt - c >= filter_cnt:
                return False
        return True
    return False
