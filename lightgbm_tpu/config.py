"""Parameter system.

Re-creates the reference's config surface (``include/LightGBM/config.h``):
the ~90-entry alias table (``config.h:353-483``), defaults, unknown-parameter
rejection, and the cross-field conflict checks (``src/io/config.cpp:188-240``)
— as one flat typed dataclass instead of the C++ struct hierarchy
``OverallConfig{IOConfig, BoostingConfig{TreeConfig}, ...}``.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Sequence, Union

from .utils import log

# Alias -> canonical name (reference config.h:353-483, KeyAliasTransform).
PARAM_ALIASES: Dict[str, str] = {
    "config": "config_file",
    "nthread": "num_threads",
    "num_thread": "num_threads",
    "random_seed": "seed",
    "boosting": "boosting_type",
    "boost": "boosting_type",
    "application": "objective",
    "app": "objective",
    "train_data": "data",
    "train": "data",
    "model_output": "output_model",
    "model_out": "output_model",
    "model_input": "input_model",
    "model_in": "input_model",
    "bin_packing": "enable_bin_packing",
    "predict_result": "output_result",
    "prediction_result": "output_result",
    "valid": "valid_data",
    "test_data": "valid_data",
    "test": "valid_data",
    "is_sparse": "is_enable_sparse",
    "enable_sparse": "is_enable_sparse",
    "pre_partition": "is_pre_partition",
    "training_metric": "is_training_metric",
    "train_metric": "is_training_metric",
    "ndcg_at": "ndcg_eval_at",
    "eval_at": "ndcg_eval_at",
    "min_data_per_leaf": "min_data_in_leaf",
    "min_data": "min_data_in_leaf",
    "min_child_samples": "min_data_in_leaf",
    "min_sum_hessian_per_leaf": "min_sum_hessian_in_leaf",
    "min_sum_hessian": "min_sum_hessian_in_leaf",
    "min_hessian": "min_sum_hessian_in_leaf",
    "min_child_weight": "min_sum_hessian_in_leaf",
    "num_leaf": "num_leaves",
    "sub_feature": "feature_fraction",
    "colsample_bytree": "feature_fraction",
    "num_iteration": "num_iterations",
    "num_tree": "num_iterations",
    "num_round": "num_iterations",
    "num_trees": "num_iterations",
    "num_rounds": "num_iterations",
    "num_boost_round": "num_iterations",
    "sub_row": "bagging_fraction",
    "subsample": "bagging_fraction",
    "subsample_freq": "bagging_freq",
    "shrinkage_rate": "learning_rate",
    "tree": "tree_learner",
    "num_machine": "num_machines",
    "local_port": "local_listen_port",
    "two_round_loading": "use_two_round_loading",
    "two_round": "use_two_round_loading",
    "mlist": "machine_list_file",
    "is_save_binary": "is_save_binary_file",
    "save_binary": "is_save_binary_file",
    "early_stopping_rounds": "early_stopping_round",
    "early_stopping": "early_stopping_round",
    "verbosity": "verbose",
    "header": "has_header",
    "label": "label_column",
    "weight": "weight_column",
    "group": "group_column",
    "query": "group_column",
    "query_column": "group_column",
    "ignore_feature": "ignore_column",
    "blacklist": "ignore_column",
    "categorical_feature": "categorical_column",
    "cat_column": "categorical_column",
    "cat_feature": "categorical_column",
    "predict_raw_score": "is_predict_raw_score",
    "predict_leaf_index": "is_predict_leaf_index",
    "raw_score": "is_predict_raw_score",
    "leaf_index": "is_predict_leaf_index",
    "min_split_gain": "min_gain_to_split",
    "topk": "top_k",
    "reg_alpha": "lambda_l1",
    "reg_lambda": "lambda_l2",
    "num_classes": "num_class",
    "unbalanced_sets": "is_unbalance",
    "bagging_fraction_seed": "bagging_seed",
}


@dataclasses.dataclass
class Config:
    """Flat parameter set with reference defaults (config.h:94-295)."""

    # task / infra
    task: str = "train"
    device: str = "tpu"            # reference: cpu|gpu; here: tpu|cpu (cpu = same XLA path on host)
    seed: int = 0
    num_threads: int = 0
    verbose: int = 1

    # objective / boosting
    objective: str = "regression"
    boosting_type: str = "gbdt"
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_class: int = 1
    tree_learner: str = "serial"  # serial|feature|data|voting|data_feature

    # tree
    num_leaves: int = 31
    max_depth: int = -1
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    feature_fraction: float = 1.0
    feature_fraction_seed: int = 2
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    bagging_seed: int = 3
    top_rate: float = 0.2          # GOSS
    other_rate: float = 0.1        # GOSS
    top_k: int = 20                # voting parallel
    histogram_pool_size: float = -1.0

    # categorical handling (feature_histogram.hpp:113-223)
    max_cat_group: int = 64
    max_cat_threshold: int = 256
    cat_smooth_ratio: float = 0.01
    min_cat_smooth: float = 5.0
    max_cat_smooth: float = 100.0

    # IO / binning
    max_bin: int = 255
    min_data_in_bin: int = 5
    bin_construct_sample_cnt: int = 200000
    data_random_seed: int = 1
    use_missing: bool = True
    zero_as_missing: bool = False
    enable_bundle: bool = True
    enable_bin_packing: bool = True  # nibble-pack <=16-bin column pairs
    is_enable_sparse: bool = True
    sparse_threshold: float = 0.8
    max_conflict_rate: float = 0.0
    is_pre_partition: bool = False
    use_two_round_loading: bool = False
    is_save_binary_file: bool = False
    has_header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""
    categorical_column: str = ""

    # objectives' knobs
    sigmoid: float = 1.0
    huber_delta: float = 1.0
    fair_c: float = 1.0
    poisson_max_delta_step: float = 0.7
    gaussian_eta: float = 1.0
    scale_pos_weight: float = 1.0
    is_unbalance: bool = False
    boost_from_average: bool = True
    max_position: int = 20
    label_gain: Optional[List[float]] = None

    # DART
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    xgboost_dart_mode: bool = False
    uniform_drop: bool = False
    drop_seed: int = 4

    # metric / eval
    metric: List[str] = dataclasses.field(default_factory=list)
    metric_freq: int = 1
    is_training_metric: bool = False
    ndcg_eval_at: List[int] = dataclasses.field(default_factory=lambda: [1, 2, 3, 4, 5])
    early_stopping_round: int = 0
    output_freq: int = 1

    # prediction
    num_iteration_predict: int = -1
    is_predict_raw_score: bool = False
    is_predict_leaf_index: bool = False
    pred_early_stop: bool = False
    pred_early_stop_freq: int = 10
    pred_early_stop_margin: float = 10.0

    # model io
    output_model: str = "LightGBM_model.txt"
    input_model: str = ""
    output_result: str = "LightGBM_predict_result.txt"
    snapshot_freq: int = -1
    snapshot_keep: int = -1        # retain only the K most-recent snapshot
                                   # checkpoints, pruned after each write
                                   # (-1 = keep all)
    snapshot_resume: bool = False  # resume training from the latest VALID
                                   # snapshot checkpoint of output_model
                                   # (torn tails fall back to the previous
                                   # good snapshot; docs/ROBUSTNESS.md)
    profile_dir: str = ""          # write a jax.profiler trace of training here
    device_profile: bool = False   # device-time attribution (obs/devprof.py,
                                   # docs/OBSERVABILITY.md "Device-time
                                   # attribution"): arm programmatic
                                   # jax.profiler windows over profile_iters
                                   # steady-state boosting iterations
                                   # (first firing/compile excluded), parse
                                   # the trace artifacts, and embed a
                                   # schema-versioned device_profile block
                                   # (per-phase device ms, top ops,
                                   # host/device overlap + idle-gap per
                                   # iteration) in the telemetry trace and
                                   # bench JSON.  Implies telemetry=true;
                                   # incompatible with profile_dir (both
                                   # own the one jax profiler session)
    profile_iters: int = 2         # steady-state iterations device_profile
                                   # captures (>= 1); each window is one
                                   # profiler start/stop around one
                                   # boosting iteration
    trace_path: str = ""           # write a Chrome-trace span file (.json or
                                   # .jsonl) of training here (lightgbm_tpu.obs
                                   # telemetry; implies telemetry=true; render
                                   # with `python -m lightgbm_tpu.obs <path>`)
    telemetry: bool = False        # enable the telemetry counters/spans (docs/OBSERVABILITY.md) without writing a trace file
    metrics_port: int = 0          # live metrics export (docs/OBSERVABILITY.md
                                   # "Live telemetry"): > 0 serves the
                                   # Prometheus text view of the telemetry
                                   # registry on GET /metrics from a
                                   # standalone exporter thread while
                                   # training runs.  Rank R of a
                                   # multi-process group binds
                                   # metrics_port + R; the supervisor binds
                                   # metrics_port itself and hands workers
                                   # metrics_port + 1.  Host-side reads
                                   # only — zero added collectives or
                                   # device syncs; 0 = off
    obs_stream_path: str = ""      # per-rank flight recorder
                                   # (obs/flight.py): write a bounded,
                                   # rotated JSONL event stream to
                                   # <path>.rank_R — one iteration-stamped
                                   # progress record per boosting
                                   # iteration (trees/s, observed kernel,
                                   # HBM peak, collective bytes) plus
                                   # every structured obs event as it
                                   # happens.  The supervisor tails all
                                   # ranks' streams for straggler
                                   # detection; "" = off
    straggler_factor: float = 4.0  # supervisor straggler verdict: a rank whose flight-stream progress rate falls this factor behind the group median raises a structured rank_straggler event (requires obs_stream_path; must be > 1)
    model_quality: str = "auto"    # model-quality observability plane
                                   # (obs/model_quality.py, docs/
                                   # OBSERVABILITY.md "Model quality"):
                                   # per-split audit records into the
                                   # flight stream, per-feature gain /
                                   # split-count metrics gauges, and eval
                                   # values on progress records.  auto =
                                   # armed whenever telemetry is armed;
                                   # on | off force it.  Pure host-side
                                   # folds over arrays the trainer already
                                   # fetched — zero added device syncs or
                                   # collectives (pinned)
    convert_model: str = "gbdt_prediction.cpp"  # convert_model task (cli.py) output path
    convert_model_language: str = ""
    saved_feature_importance_type: int = 0  # importance type written to the
                                   # "feature importances:" model-file
                                   # section: 0 = split counts (reference
                                   # default), 1 = total gain (written at
                                   # full float precision, not truncated
                                   # to int)

    # robustness (docs/ROBUSTNESS.md)
    nonfinite_policy: str = "raise"  # guard on non-finite grad/hess/leaf
                                     # values: raise | rollback | clamp.
                                     # raise fails naming the iteration;
                                     # rollback discards the poisoned
                                     # iteration (forces synchronous tree
                                     # materialization); clamp sanitizes
                                     # grad->0 / hess->1 on device.  Every
                                     # trip emits a structured `nonfinite`
                                     # obs event.
    hbm_budget: float = 0.0          # device-memory pre-flight budget in
                                     # BYTES (obs/memory.predict_hbm vs
                                     # docs/MEMORY.md): 0 warns only when
                                     # the predicted peak exceeds the
                                     # detected device capacity; > 0
                                     # raises BEFORE the grower compiles
                                     # when the predicted peak exceeds it
    data_stream: str = "auto"        # training-data placement: resident
                                     # keeps the binned matrix on device
                                     # (the classic path); chunked streams
                                     # host-side row blocks through a
                                     # double-buffered device_put pipeline
                                     # (data/stream.py + the streamed
                                     # grower) so N_rows is no longer
                                     # bounded by HBM; auto lets the
                                     # pre-flight planner walk resident ->
                                     # streamed -> sharded against
                                     # hbm_budget (parallel/mesh.
                                     # resolve_placement) before any
                                     # compile
    stream_chunk_rows: int = 0       # rows per streamed block when
                                     # data_stream resolves to chunked; 0
                                     # picks a default (262144 rows capped
                                     # at ceil(rows/2) so even small
                                     # datasets exercise >= 2 blocks).
                                     # All blocks pad to this one static
                                     # shape, so the chunk loop adds zero
                                     # recompiles
    fault_inject: str = ""           # deterministic fault-injection spec,
                                     # e.g. nan_grad@3,torn_checkpoint@4,
                                     # collective_fail_once (utils/faults.py;
                                     # also via LGBM_TPU_FAULT_INJECT env)
    heartbeat_interval: float = 0.0  # per-rank liveness heartbeats
                                     # (docs/ROBUSTNESS.md "Self-healing
                                     # training"): > 0 stamps iteration +
                                     # wall-time into
                                     # <output_model>.heartbeat.rank_R at
                                     # each iteration boundary, at most
                                     # once per this many seconds — pure
                                     # host-side file writes, zero added
                                     # collectives or device syncs.  The
                                     # supervisor reads the stamps for
                                     # hang detection; 0 = off
    hang_timeout: float = 0.0        # supervisor hang detection: a rank
                                     # whose heartbeat is older than this
                                     # many seconds is declared hung and
                                     # the group is restarted from the
                                     # last committed checkpoint.  Raised
                                     # automatically to exceed the
                                     # collective ladder's worst case so
                                     # an in-band CollectiveError gets a
                                     # chance to surface first; 0 = the
                                     # supervisor default (300 s)
    restart_limit: int = 3           # supervisor restart budget: give up
                                     # (restart_budget_exhausted) after
                                     # this many group restarts WITHOUT
                                     # forward progress — a restart after
                                     # a newer committed checkpoint
                                     # resets the budget
    restart_backoff: float = 1.0     # seconds before the first group
                                     # relaunch; doubles per restart
                                     # while no forward progress is made
    preempt_signal: str = ""         # preemption safety: signals that
                                     # request a coordinated checkpoint at
                                     # the next iteration boundary and a
                                     # clean training exit — "sigterm",
                                     # "sigint", or "sigterm,sigint"
                                     # ("" = off).  Multi-process ranks
                                     # agree on the request through the
                                     # hardened collective ladder (one
                                     # small allgather per iteration while
                                     # armed); snapshots land at
                                     # output_model like snapshot_freq ones
                                     # and resume with snapshot_resume.
    elastic_resume: bool = False     # elastic groups: accept a committed
                                     # snapshot set written by a DIFFERENT
                                     # process count (any W -> this job's
                                     # W'): each rank reassembles its new
                                     # row partition from the old shards
                                     # at global row boundaries and the
                                     # group re-verifies the manifest's
                                     # global dataset fingerprint.  Also
                                     # arms the supervisor's degraded-world
                                     # relaunch.  Default false: strict
                                     # topology matching (a mismatch stays
                                     # fatal)
    elastic_min_ranks: int = 1       # floor for the supervisor's
                                     # degraded-world relaunch: the group
                                     # is never shrunk below this many
                                     # ranks (budget exhaustion applies
                                     # instead)
    world_shrink_after: int = 2      # consecutive STARTUP failures (a rank
                                     # dying before its first heartbeat of
                                     # an incarnation) after which the
                                     # supervisor declares the rank's host
                                     # lost and relaunches the group one
                                     # rank smaller through the elastic
                                     # resume path (requires
                                     # elastic_resume=true)

    # serving (docs/SERVING.md): the high-QPS batched prediction engine
    latency_budget_ms: float = 2.0   # serving microbatcher coalescing
                                     # window: a dispatched request waits
                                     # at most this long for companions
                                     # before its microbatch runs (0 =
                                     # dispatch immediately, no
                                     # coalescing)
    serving_buckets: str = "1,8,64,512,4096"  # ascending microbatch row
                                     # ladder; every request batch is
                                     # padded up to the next bucket so the
                                     # predict executable set stays
                                     # bounded and pre-warmed
                                     # (predict_jit_entries gauge)
    model_watch: str = ""            # hot model swap: checkpoint prefix
                                     # (a trainer's output_model) whose
                                     # committed snapshots/manifests the
                                     # server watches; a newly committed
                                     # iteration is loaded, pre-warmed off
                                     # the serving path, and swapped in
                                     # atomically between microbatches
                                     # ("" = no watching)
    model_watch_interval: float = 1.0  # seconds between model_watch polls
    drift_threshold: float = 0.2     # serving feature-drift alarm level:
                                     # a feature whose PSI (population
                                     # stability index) between the
                                     # training-set bin distribution and
                                     # the current serving window exceeds
                                     # this fires one `feature_drift`
                                     # structured event per window and
                                     # moves the lgbm_tpu_feature_drift
                                     # gauge; <= 0 disables the event
                                     # (gauges still export)
    drift_window_rows: int = 4096    # serving rows accumulated per drift
                                     # comparison window before the PSI is
                                     # recomputed and the histograms reset
                                     # (must be > 0)
    serving_traversal: str = "auto"  # serving-engine tree traversal:
                                     # auto | xla | packed.  ``packed``
                                     # folds each node's fields into one
                                     # i32 word pair and walks a fixed
                                     # max-depth fori ladder (one fused
                                     # gather per step instead of eight) —
                                     # bit-identical raw margins; ``auto``
                                     # picks packed on XLA:CPU where the
                                     # scalar gather lowering makes it
                                     # ~1.6x, and the classic while-loop
                                     # traversal elsewhere

    # distributed (reference NetworkConfig -> JAX mesh knobs)
    num_machines: int = 1
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_file: str = ""
    # TPU additions: how many mesh devices to use per axis; 0 = all available
    mesh_devices: int = 0
    parallel_impl: str = "auto"    # distributed learner implementation
                                   # (docs/DISTRIBUTED.md): auto | gspmd |
                                   # shardmap.  ``gspmd`` writes the grow
                                   # program over global arrays with
                                   # NamedSharding annotations and lets the
                                   # XLA partitioner insert the collectives
                                   # (the histogram reduce-scatter included);
                                   # ``shardmap`` is the historical explicit
                                   # psum/all_gather choreography, kept as
                                   # the forced A/B partner.  ``auto``
                                   # resolves gspmd single- AND multi-
                                   # process; shardmap only for voting and
                                   # multi-process feature-parallel (whose
                                   # data contracts gspmd cannot express)
    mesh_shape: str = "auto"       # GSPMD (batch, feature) mesh extents:
                                   # auto (the memory-driven planner,
                                   # parallel/mesh.plan_mesh, sizes the mesh
                                   # from predicted per-device HBM) | data
                                   # (all devices on the batch axis) |
                                   # feature | DxF (e.g. 2x4)
    shard_axes: str = "auto"       # which mesh axes shard the BINNED
                                   # matrix under gspmd: auto (planner:
                                   # replicate over feature unless memory
                                   # pressure forces block sharding) |
                                   # batch | batch,feature (row x column
                                   # block sharding)
    gspmd_hist: str = "auto"       # histogram formulation inside the
                                   # gspmd program: flat (masked whole-
                                   # partition scatter-add — pure XLA,
                                   # any layout, the forced A/B partner)
                                   # | fused (the hybrid: each device
                                   # runs the fused Pallas gather-
                                   # histogram over its row shard inside
                                   # a shard_map island; unfusable
                                   # layouts downgrade loudly to flat)
                                   # | auto (flat until the on-chip A/B
                                   # flips it — capture-backlog
                                   # discipline, scripts/decide_flips.py)
    collective_timeout: float = 120.0  # seconds one host-object collective
                                       # attempt may block before it is
                                       # failed and retried (parallel/sync.py)
    collective_retries: int = 2        # bounded retries with exponential
                                       # backoff per host-object collective
                                       # before the error surfaces

    # compute backend knobs (TPU analogue of gpu_* params)
    gpu_platform_id: int = -1
    gpu_device_id: int = -1
    gpu_use_dp: bool = False
    hist_dtype: str = "float32"    # accumulator dtype for histograms
    use_pallas: bool = True        # Pallas hist kernel on TPU
    cpu_hist_method: str = "segment"   # off-TPU histogram: segment | einsum
    pallas_row_tile: int = 512     # kernel grid: rows per block
    pallas_bucket_min_log2: int = 6    # smallest pow2 gather bucket (64
                                       # rows: deep-tree tail splits pay
                                       # O(leaf) work, not kilobucket
                                       # padding; sub-512 buckets shrink
                                       # the Pallas row tile to match)
    gather_words: str = "auto"     # pack bin columns into u32 words for the
                                   # histogram row gather: auto | on | off
    gather_panel: str = "auto"     # fold the f32 weight columns into the
                                   # word matrix so each split's read is
                                   # ONE row gather: auto | on | off
    split_find: str = "fused"      # best-split scan formulation: fused
                                   # (gain scan fused onto the hot
                                   # histogram — per-direction reductions,
                                   # loop-invariant masks hoisted, no
                                   # packed candidate arrays) | chain (the
                                   # historical packed-argmax form, kept as
                                   # the forced A/B baseline).  Trees are
                                   # bit-identical either way (pinned)
    pallas_fused: str = "auto"     # fused-gather nibble histogram kernel
                                   # (in-kernel row DMA, no gather pass,
                                   # no pow2 staging buffer): auto | on
                                   # | off; the ONLY Pallas rung since
                                   # the gen-1 kernels were retired —
                                   # 'auto'/'on' run it on TPU, 'off'
                                   # forces the einsum reference oracle
    ordered_bins: str = "auto"     # leaf-ordered bin matrix (OrderedBin
                                   # analogue): auto | on | off; 'on' trades
                                   # wide partition scatters for contiguous
                                   # histogram reads (no row gathers)
    partition_impl: str = "auto"   # window partition: auto | scatter | sort
                                   # | compact (sort = stable 1-bit-key
                                   # payload sort; compact = Pallas two-pass
                                   # MXU compaction kernel, all-sequential
                                   # HBM traffic)
    bucket_scheme: str = "auto"    # gather-bucket sizes: auto | pow2 | pow15
                                   # (pow15 adds 1.5*2^k buckets: ~16% less
                                   # padded work, 2x the compiled branches)

    pipeline_trees: bool = True    # pipeline tree materialization: keep
    # freshly grown trees on device and pull them to host a few iterations
    # late (one batched async transfer per tree) so the training loop never
    # blocks on device->host latency.  Matters enormously when the
    # accelerator sits behind a high-latency link; synchronous fallback
    # happens automatically for DART/RF, multi-process meshes, and
    # custom-gradient training.  The final model is always bit-identical to
    # the synchronous path; the one observable difference is that a mid-run
    # "no more leaves" stop is DETECTED up to a few iterations late, so
    # per-iteration callbacks may see evals for iterations that are then
    # rewound (tests/test_pipeline.py pins the rewind to the exact
    # synchronous final state).

    # file-task fields (CLI)
    data: str = ""
    valid_data: List[str] = dataclasses.field(default_factory=list)
    config_file: str = ""

    def copy(self) -> "Config":
        return dataclasses.replace(self)


_FIELD_TYPES = {f.name: f.type for f in dataclasses.fields(Config)}
_LIST_FIELDS = {"metric", "ndcg_eval_at", "valid_data", "label_gain"}
_BOOL_TRUE = {"true", "1", "yes", "on", "+"}
_BOOL_FALSE = {"false", "0", "no", "off", "-"}


def _parse_value(name: str, value: Any) -> Any:
    """Coerce a raw (possibly string) value to the field's declared type."""
    ftype = str(_FIELD_TYPES[name])
    if name in _LIST_FIELDS:
        if value is None:
            return None
        if isinstance(value, str):
            parts = [p for p in value.replace(",", " ").split() if p]
        elif isinstance(value, (list, tuple, set, frozenset)):
            # sets arrive from user code like metric={'l2', 'auc'}
            # (python-guide simple_example.py); order them for
            # deterministic eval-log column order
            parts = (sorted(value, key=str)
                     if isinstance(value, (set, frozenset)) else list(value))
        else:
            parts = [value]
        if name == "ndcg_eval_at":
            ks = sorted(int(p) for p in parts)   # ascending, like the
            for k in ks:                         # reference (config.cpp:341)
                if k <= 0:
                    log.fatal("eval_at positions must be positive; got %d", k)
            return ks
        if name == "label_gain":
            return [float(p) for p in parts]
        return [str(p) for p in parts]
    if "bool" in ftype:
        if isinstance(value, bool):
            return value
        s = str(value).strip().lower()
        if s in _BOOL_TRUE:
            return True
        if s in _BOOL_FALSE:
            return False
        raise ValueError(f"cannot parse bool parameter {name}={value!r}")
    if "int" in ftype:
        return int(float(value)) if isinstance(value, str) else int(value)
    if "float" in ftype:
        return float(value)
    return str(value)


def canonicalize_params(params: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Alias-resolve a raw param dict; reject unknown keys (config.h:478-481).

    Explicit canonical keys win over aliased ones, mirroring the reference
    (aliases only fill in missing canonical entries).
    """
    params = dict(params or {})
    out: Dict[str, Any] = {}
    aliased: Dict[str, Any] = {}
    for key, value in params.items():
        k = key.strip().lower()
        if k in PARAM_ALIASES:
            aliased[PARAM_ALIASES[k]] = value
        elif k in _FIELD_TYPES:
            out[k] = value
        elif k in ("objective_seed",):
            continue  # tolerated no-ops
        else:
            raise ValueError(f"Unknown parameter: {key}")
    for k, v in aliased.items():
        out.setdefault(k, v)
    return out


def config_from_params(params: Optional[Dict[str, Any]] = None,
                       base: Optional[Config] = None) -> Config:
    cfg = (base.copy() if base is not None else Config())
    for k, v in canonicalize_params(params).items():
        setattr(cfg, k, _parse_value(k, v))
    check_param_conflicts(cfg)
    return cfg


def check_param_conflicts(cfg: Config) -> None:
    """Cross-field checks, following src/io/config.cpp:188-240."""
    if cfg.num_class <= 0:
        log.fatal("num_class must be positive")
    is_multiclass = cfg.objective in ("multiclass", "multiclassova", "softmax",
                                      "multiclass_ova", "ova", "ovr")
    if is_multiclass and cfg.num_class <= 1:
        log.fatal("Number of classes should be specified and greater than 1 for multiclass training")
    if not is_multiclass and cfg.num_class != 1:
        log.fatal("Number of classes must be 1 for non-multiclass training")
    if cfg.tree_learner not in ("serial", "feature", "data", "voting",
                                "data_feature"):
        log.fatal("Unknown tree learner type %s", cfg.tree_learner)
    if cfg.boosting_type not in ("gbdt", "gbrt", "dart", "goss", "rf", "random_forest"):
        log.fatal("Unknown boosting type %s", cfg.boosting_type)
    if cfg.boosting_type in ("rf", "random_forest"):
        if not (cfg.bagging_freq > 0 and 0.0 < cfg.bagging_fraction < 1.0):
            log.fatal("Random forest needs bagging (bagging_freq > 0 and 0 < bagging_fraction < 1)")
    if cfg.max_bin > 65535:
        log.fatal("max_bin too large (must fit uint16)")
    # parallel <-> learner coupling (config.cpp:212-225): a serial learner
    # forces single-machine; multiple machines with serial would otherwise
    # hang waiting for a network that no strategy uses
    if cfg.tree_learner == "serial" and cfg.num_machines > 1:
        log.warning("tree_learner=serial forces num_machines=1 "
                    "(config.cpp:222-225 semantics)")
        cfg.num_machines = 1
    if cfg.parallel_impl not in ("auto", "gspmd", "shardmap"):
        log.fatal("parallel_impl must be auto, gspmd, or shardmap; got %r",
                  cfg.parallel_impl)
    # mesh_shape syntax is validated here (the real device count is only
    # known at learner setup, where extents are checked against it)
    ms = str(cfg.mesh_shape or "auto").strip().lower()
    if ms not in ("auto", "data", "feature"):
        parts = ms.replace("*", "x").split("x")
        if len(parts) != 2 or not all(p.strip().isdigit() for p in parts) \
                or any(int(p) < 1 for p in parts):
            log.fatal("mesh_shape must be auto, data, feature, or DxF "
                      "(e.g. 2x4); got %r", cfg.mesh_shape)
    sa = str(cfg.shard_axes or "auto").strip().lower().replace(" ", "")
    if sa not in ("auto", "batch", "batch,feature", "feature,batch"):
        log.fatal("shard_axes must be auto, batch, or batch,feature; "
                  "got %r", cfg.shard_axes)
    # the 2-D hybrid shards data x feature over ONE process's mesh; fail at
    # parse time like the other conflicts instead of a late runtime fatal
    if cfg.tree_learner == "data_feature" and cfg.num_machines > 1:
        log.fatal("tree_learner=data_feature is single-process (it shards "
                  "data x feature over one process's device mesh); use "
                  "data, voting, or feature across machines")
    # Pallas grid knobs: catch bad values here with the real cause instead
    # of an opaque Mosaic layout error at trace/compile time
    if cfg.pallas_row_tile <= 0 or cfg.pallas_row_tile % 128 != 0:
        log.fatal("pallas_row_tile must be a positive multiple of 128 "
                  "(the TPU lane width); got %d", cfg.pallas_row_tile)
    if cfg.pallas_bucket_min_log2 < 0 or cfg.pallas_bucket_min_log2 > 26:
        log.fatal("pallas_bucket_min_log2 must be in [0, 26]; got %d",
                  cfg.pallas_bucket_min_log2)
    if cfg.gather_words not in ("auto", "on", "off"):
        log.fatal("gather_words must be auto, on, or off; got %r",
                  cfg.gather_words)
    if cfg.gather_panel not in ("auto", "on", "off"):
        log.fatal("gather_panel must be auto, on, or off; got %r",
                  cfg.gather_panel)
    if cfg.pallas_fused not in ("auto", "on", "off"):
        log.fatal("pallas_fused must be auto, on, or off; got %r",
                  cfg.pallas_fused)
    if cfg.gspmd_hist not in ("auto", "fused", "flat"):
        log.fatal("gspmd_hist must be auto, fused, or flat; got %r",
                  cfg.gspmd_hist)
    if cfg.split_find not in ("fused", "chain"):
        log.fatal("split_find must be fused or chain; got %r",
                  cfg.split_find)
    if cfg.serving_traversal not in ("auto", "xla", "packed"):
        log.fatal("serving_traversal must be auto, xla, or packed; got %r",
                  cfg.serving_traversal)
    if cfg.model_quality not in ("auto", "on", "off"):
        log.fatal("model_quality must be auto, on, or off; got %r",
                  cfg.model_quality)
    if cfg.drift_window_rows <= 0:
        log.fatal("drift_window_rows must be > 0 serving rows per PSI "
                  "window; got %d", cfg.drift_window_rows)
    if cfg.saved_feature_importance_type not in (0, 1):
        log.fatal("saved_feature_importance_type must be 0 (split) or "
                  "1 (gain); got %d", cfg.saved_feature_importance_type)
    if cfg.ordered_bins not in ("auto", "on", "off"):
        log.fatal("ordered_bins must be auto, on, or off; got %r",
                  cfg.ordered_bins)
    if cfg.partition_impl not in ("auto", "scatter", "sort", "compact"):
        log.fatal("partition_impl must be auto, scatter, sort, or compact; "
                  "got %r", cfg.partition_impl)
    if cfg.bucket_scheme not in ("auto", "pow2", "pow15"):
        log.fatal("bucket_scheme must be auto, pow2, or pow15; got %r",
                  cfg.bucket_scheme)
    if cfg.nonfinite_policy not in ("raise", "rollback", "clamp"):
        log.fatal("nonfinite_policy must be raise, rollback, or clamp; "
                  "got %r", cfg.nonfinite_policy)
    if cfg.fault_inject:
        # fail at parse time with the real cause, not at the injection point
        from .utils.faults import parse_spec
        try:
            entries = parse_spec(cfg.fault_inject)
        except ValueError as e:
            log.fatal("%s", e)
        else:
            world = max(1, cfg.num_machines)
            for e in entries:
                # a rank qualifier naming a rank the job does not run
                # would silently inject nothing — reject it here.  Skipped
                # under an elastic relaunch (LGBM_TPU_WORLD set): the spec
                # was written for the LAUNCH topology, and a shrunk world
                # legitimately no longer runs the evicted rank
                if e.rank is not None and e.rank >= world \
                        and "LGBM_TPU_WORLD" not in os.environ:
                    log.fatal("fault_inject: rank=%d targets a rank this "
                              "job does not run (num_machines=%d)",
                              e.rank, world)
    if cfg.preempt_signal:
        for tok in str(cfg.preempt_signal).replace(",", " ").split():
            if tok.strip().lower() not in ("sigterm", "sigint", "term",
                                           "int"):
                log.fatal("preempt_signal must name sigterm and/or sigint "
                          "(comma-separated); got %r", cfg.preempt_signal)
    if cfg.hbm_budget < 0:
        log.fatal("hbm_budget must be >= 0 bytes (0 = warn-only pre-flight "
                  "against the detected device capacity); got %r",
                  cfg.hbm_budget)
    if cfg.data_stream not in ("auto", "resident", "chunked"):
        log.fatal("data_stream must be auto, resident, or chunked; got %r",
                  cfg.data_stream)
    if cfg.stream_chunk_rows < 0:
        log.fatal("stream_chunk_rows must be >= 0 rows (0 = auto block "
                  "size); got %r", cfg.stream_chunk_rows)
    if cfg.data_stream == "chunked" \
            and cfg.boosting_type in ("dart", "goss"):
        log.fatal("data_stream=chunked is incompatible with "
                  "boosting_type=%s: dart's drop/rescale and goss's top-k "
                  "sampling assume the resident row layout; use "
                  "data_stream=resident or boosting_type=gbdt",
                  cfg.boosting_type)
    if cfg.collective_timeout <= 0:
        log.fatal("collective_timeout must be positive; got %r",
                  cfg.collective_timeout)
    if cfg.collective_retries < 0:
        log.fatal("collective_retries must be >= 0; got %d",
                  cfg.collective_retries)
    if cfg.heartbeat_interval < 0:
        log.fatal("heartbeat_interval must be >= 0 seconds (0 = off); "
                  "got %r", cfg.heartbeat_interval)
    if cfg.hang_timeout < 0:
        log.fatal("hang_timeout must be >= 0 seconds (0 = the supervisor "
                  "default); got %r", cfg.hang_timeout)
    if cfg.hang_timeout and cfg.heartbeat_interval \
            and cfg.hang_timeout <= cfg.heartbeat_interval:
        log.fatal("hang_timeout (%g s) must exceed heartbeat_interval "
                  "(%g s): every rank would look hung between two stamps",
                  cfg.hang_timeout, cfg.heartbeat_interval)
    if cfg.metrics_port < 0 or cfg.metrics_port > 65535:
        log.fatal("metrics_port must be in [0, 65535] (0 = off); got %d",
                  cfg.metrics_port)
    if cfg.profile_iters < 1:
        log.fatal("profile_iters must be >= 1 (steady-state iterations "
                  "the device_profile plane captures); got %d",
                  cfg.profile_iters)
    if cfg.device_profile and cfg.profile_dir:
        log.fatal("device_profile cannot be combined with profile_dir: "
                  "both arm the one process-wide jax profiler session; "
                  "use device_profile for attributed per-phase accounting "
                  "or profile_dir for a raw whole-run XProf trace")
    if cfg.straggler_factor <= 1:
        log.fatal("straggler_factor must be > 1 (a rank is a straggler "
                  "when its progress rate falls that factor behind the "
                  "group median); got %r", cfg.straggler_factor)
    if cfg.latency_budget_ms < 0:
        log.fatal("latency_budget_ms must be >= 0 (0 = dispatch "
                  "immediately); got %r", cfg.latency_budget_ms)
    if cfg.model_watch_interval <= 0:
        log.fatal("model_watch_interval must be positive seconds; got %r",
                  cfg.model_watch_interval)
    try:
        parse_serving_buckets(cfg.serving_buckets)
    except ValueError as e:
        log.fatal("%s", e)
    if cfg.restart_limit < 0:
        log.fatal("restart_limit must be >= 0; got %d", cfg.restart_limit)
    if cfg.restart_backoff < 0:
        log.fatal("restart_backoff must be >= 0 seconds; got %r",
                  cfg.restart_backoff)
    if cfg.elastic_min_ranks < 1:
        log.fatal("elastic_min_ranks must be >= 1; got %d",
                  cfg.elastic_min_ranks)
    if cfg.world_shrink_after < 1:
        log.fatal("world_shrink_after must be >= 1 consecutive startup "
                  "failures; got %d", cfg.world_shrink_after)
def parse_serving_buckets(spec) -> tuple:
    """``serving_buckets`` ("1,8,64,512,4096") -> ascending int tuple;
    raises ValueError on empty/non-positive/non-ascending specs so config
    parsing fails with the real cause (docs/SERVING.md)."""
    if isinstance(spec, (tuple, list)):
        vals = [int(v) for v in spec]
    else:
        vals = [int(v) for v in str(spec).replace(",", " ").split()]
    if not vals:
        raise ValueError("serving_buckets must name at least one batch size")
    if any(v <= 0 for v in vals):
        raise ValueError(f"serving_buckets must be positive; got {vals}")
    if sorted(vals) != vals or len(set(vals)) != len(vals):
        raise ValueError(
            f"serving_buckets must be strictly ascending; got {vals}")
    return tuple(vals)


def parse_config_file(path: str) -> Dict[str, str]:
    """key=value config file, '#' comments (application.cpp:48-104)."""
    params: Dict[str, str] = {}
    with open(path, "r") as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            k, v = line.split("=", 1)
            params[k.strip()] = v.strip()
    return params
