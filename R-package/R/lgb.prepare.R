# lgb.prepare family: convert character/factor columns of a data.frame
# to numeric codes (R-package/R/lgb.prepare*.R surface in base R;
# data.table inputs are handled through their data.frame interface —
# the package takes no data.table dependency, so conversion is
# copy-based rather than by-reference).

lgb.prepare <- function(data) {
  .lgbtpu_prepare(data, as.numeric)
}

# Integer variant (reference lgb.prepare2: "integer is smaller than
# numeric"); same conversion, integer storage.
lgb.prepare2 <- function(data) {
  .lgbtpu_prepare(data, as.integer)
}

.lgbtpu_prepare <- function(data, cast) {
  data <- as.data.frame(data)
  cls <- vapply(data, function(x) class(x)[1], character(1))
  fix <- which(cls %in% c("character", "factor"))
  for (i in fix) {
    data[[i]] <- cast(as.factor(data[[i]]))
  }
  data
}

# Conversion WITH reusable rules: returns list(data = , rules = );
# pass the rules back in to convert validation/test data identically
# (unknown levels become 0 — "excellent for sparse datasets", the
# reference's words).
lgb.prepare_rules <- function(data, rules = NULL) {
  .lgbtpu_prepare_rules(data, rules, as.numeric)
}

lgb.prepare_rules2 <- function(data, rules = NULL) {
  .lgbtpu_prepare_rules(data, rules, as.integer)
}

.lgbtpu_prepare_rules <- function(data, rules, cast) {
  data <- as.data.frame(data)
  if (!is.null(rules)) {
    for (col in names(rules)) {
      mapped <- unname(rules[[col]][as.character(data[[col]])])
      mapped[is.na(mapped)] <- 0          # unknown levels -> 0
      data[[col]] <- cast(mapped)
    }
    return(list(data = data, rules = rules))
  }
  cls <- vapply(data, function(x) class(x)[1], character(1))
  fix <- which(cls %in% c("character", "factor"))
  rules <- list()
  for (i in fix) {
    col <- data[[i]]
    if (is.factor(col)) {
      lev <- levels(col)                  # respect ordinality
    } else {
      lev <- levels(as.factor(unique(col)))
    }
    map <- seq_along(lev)
    names(map) <- lev
    rules[[colnames(data)[i]]] <- map
    mapped <- unname(map[as.character(col)])
    mapped[is.na(mapped)] <- 0
    data[[i]] <- cast(mapped)
  }
  list(data = data, rules = rules)
}
